"""Host-boundary lint: device→host syncs, uploads-in-loops, tracer flow.

The serving stack's throughput story is a host-boundary budget — ONE
packed ``np.asarray`` fetch and ZERO steady-state uploads per chunk
dispatch (serving.py module docstring; asserted at runtime by
``make perf-smoke``).  That budget is easy to regress silently: a stray
``np.asarray`` on a device value, a ``float()`` on a tracer, or a
``jnp.*`` construction inside a per-token loop each re-introduce the
~100 ms/dispatch tunnel stall chunked decode exists to amortize — and
nothing fails until a bench round notices.

This checker makes every crossing explicit.  It walks each audited
module's AST with a simple per-function taint analysis:

  * **taint sources** — ``self.<attr>`` for attributes in the module's
    device-state registry (:data:`DEVICE_SELF_ATTRS`) or with the
    ``d_`` device-twin prefix (any base object: ``pf.d_off``), results
    of ``jnp.*`` / ``jax.*`` / ``lax.*`` calls and of the registered
    jitted serving programs (:data:`DEVICE_RETURNING`), and parameters
    with conventional device names (:data:`DEVICE_PARAM_NAMES`);
    taint propagates through assignment (tuple unpacks taint every
    target), subscripts, attribute chains and arithmetic;
  * **sinks** — ``np.asarray``/``np.array`` on a tainted value,
    ``float``/``int``/``bool`` on a tainted value, ``.item()`` /
    ``.tolist()`` on a tainted value, and ``jax.device_get`` /
    ``block_until_ready`` unconditionally (rule ``host-fetch``);
    ``if``/``while`` tests referencing a tainted value (rule
    ``device-flow`` — Python truthiness on a device value is both a
    sync and a latent tracer error); ``jnp.*`` array construction /
    ``jax.device_put`` lexically inside a ``for``/``while`` loop
    (rule ``host-upload`` — a per-iteration H2D upload).

Each sanctioned crossing carries an ``# audit: <kind>(<reason>)``
pragma (common.py) — the allowlist IS the documentation: grep for
``audit: host-fetch`` and you have every device→host sync the serving
stack performs, with its justification.

Functions that only execute at trace time (the jitted programs
themselves, and module-level helpers reachable ONLY from them) skip
the ``host-upload`` rule: a ``jnp.*`` call in a Python loop there is
loop unrolling inside one compiled program, not a runtime upload.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .common import (
    Finding, Pragmas, def_line_span, dotted_name as _dotted,
    iter_package_sources, jit_decorations, node_span, parse_module,
    pragma_findings,
)

CHECKER = "host-boundary"

# Modules under audit: the serving stack, where the host-boundary
# budget is load-bearing.  (Model/ops/engine code is device-side or
# offline; extend this list when a new module joins the serving path.)
AUDITED_MODULES = (
    "serving", "kvcache", "server", "obs", "degrade", "faults",
)

# Per-module device-state registry: ``self.<attr>`` names that hold
# jax arrays (device residency).  The generic ``d_`` prefix rule covers
# the device twins on ANY object; these are the exceptions that don't
# carry the prefix.  NOTE: ``tau_lp`` (no prefix) is the NUMPY mirror
# and is deliberately absent.
DEVICE_SELF_ATTRS: Dict[str, Set[str]] = {
    "serving": {
        "pool", "draft_pool", "tau", "keys", "params", "draft_params",
    },
    "kvcache": set(),
    "server": set(),
    "obs": set(),
    "degrade": set(),
    "faults": set(),
}

# Attribute names that hold device values on ANY base object
# (dataclass carriers like serving._Prefill / _Restore).
DEVICE_ANY_ATTRS = frozenset({"staged", "pool", "draft_pool"})

# Parameters with these names seed taint (module-level device helpers:
# kvcache.fetch_slab(pool, ...), adopt_into_pool(pool, staged), ...).
DEVICE_PARAM_NAMES = frozenset({
    "pool", "draft_pool", "t_pool", "d_pool", "params", "draft_params",
    "t_params", "d_params", "staged", "pool_arrays",
})

# Module-level callables whose results live on device (the jitted
# serving programs plus the device-returning kvcache helpers).  The
# lowering auditor's contract registry is the authority for the jitted
# subset; this adds the non-jit wrappers.
DEVICE_RETURNING = frozenset({
    "_paged_decode_step", "_paged_decode_chunk", "_fused_chunk",
    "_spec_round", "_spec_rounds_chunk", "_paged_insert",
    "_paged_suffix_insert", "_scatter_rows", "_release_blocks",
    "_adopt_jit", "adopt_into_pool", "stage_restore", "init_pool",
    "_gather_cache", "_scatter_back", "_pool_as_cache",
})

# Metadata attributes of device arrays — host-resident, never a sync.
_METADATA_ATTRS = frozenset({
    "shape", "dtype", "ndim", "size", "sharding", "block_size",
    "n_blocks", "quantized",
})

_FETCH_NP_FUNCS = frozenset({"asarray", "array"})
_FETCH_BUILTINS = frozenset({"float", "int", "bool"})
_FETCH_METHODS = frozenset({"item", "tolist"})
_UPLOAD_JNP_FUNCS = frozenset({
    "asarray", "array", "zeros", "ones", "full", "arange", "eye",
    "zeros_like", "ones_like", "full_like",
})


def _jit_function_names(tree: ast.Module) -> Set[str]:
    """Module-level defs wrapped in jax.jit (common.jit_decorations —
    shared with the lowering auditor's coverage gate)."""
    return set(jit_decorations(tree))


def _trace_time_functions(tree: ast.Module, jitted: Set[str]) -> Set[str]:
    """Module-level functions whose EVERY intra-module caller is
    trace-time — their bodies run at trace time, so ``jnp.*``-in-a-loop
    there is unrolling, not a runtime upload.

    Fixpoint over the caller relation: a function is trace-time iff it
    is jitted, or it has at least one caller and all of them are
    trace-time (so two-level helper chains under a jitted program stay
    exempt).  Calls from class methods / nested defs count as HOST
    callers, and an uncalled function is host by default (it may be an
    external entry point)."""
    funcs: Dict[str, ast.FunctionDef] = {
        n.name: n for n in tree.body if isinstance(n, ast.FunctionDef)
    }

    # callers[f] = module-function names calling f; None marks a call
    # from host context (a method or a nested/class scope).
    callers: Dict[str, Set[Optional[str]]] = {n: set() for n in funcs}

    def record(caller: Optional[str], fn: ast.AST) -> None:
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                name = _dotted(node.func)
                if name in callers:
                    callers[name].add(caller)

    for node in tree.body:
        if isinstance(node, ast.FunctionDef):
            record(node.name, node)
        elif isinstance(node, ast.ClassDef):
            record(None, node)

    trace_time = set(jitted)
    changed = True
    while changed:
        changed = False
        for name in funcs:
            if name in trace_time:
                continue
            cs = callers[name]
            if cs and all(c is not None and c in trace_time
                          for c in cs):
                trace_time.add(name)
                changed = True
    return trace_time


class _FunctionLint(ast.NodeVisitor):
    """Taint + sink walk of one function body."""

    def __init__(self, module: str, path: str, fn: ast.FunctionDef,
                 pragmas: Pragmas, trace_time: bool):
        self.module = module
        self.path = path
        self.fn = fn
        self.pragmas = pragmas
        self.trace_time = trace_time
        self.findings: List[Finding] = []
        self.tainted: Set[str] = {
            a.arg for a in (
                list(fn.args.posonlyargs) + list(fn.args.args)
                + list(fn.args.kwonlyargs)
            )
            if a.arg in DEVICE_PARAM_NAMES
        }
        self.loop_depth = 0
        self._stmt_stack: List[ast.stmt] = []

    # -- taint ---------------------------------------------------------------

    def _is_tainted(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Attribute):
            if node.attr in _METADATA_ATTRS:
                return False
            if node.attr.startswith("d_") or node.attr in DEVICE_ANY_ATTRS:
                return True
            base = _dotted(node.value)
            if base == "self":
                return node.attr in DEVICE_SELF_ATTRS.get(
                    self.module, set()
                )
            return self._is_tainted(node.value)
        if isinstance(node, ast.Call):
            return self._call_returns_device(node)
        if isinstance(node, ast.Subscript):
            return self._is_tainted(node.value)
        if isinstance(node, (ast.BinOp, ast.BoolOp, ast.Compare,
                             ast.UnaryOp, ast.IfExp, ast.Starred,
                             ast.Tuple, ast.List)):
            return any(
                self._is_tainted(c) for c in ast.iter_child_nodes(node)
                if isinstance(c, ast.expr)
            )
        return False

    def _call_returns_device(self, call: ast.Call) -> bool:
        name = _dotted(call.func) or ""
        head = name.split(".", 1)[0]
        leaf = name.rsplit(".", 1)[-1]
        if name == "getattr" and call.args and self._is_tainted(
            call.args[0]
        ):
            return True
        if head in ("jnp", "lax"):
            return True
        if head == "jax" and leaf not in ("device_get",):
            return True
        if leaf in DEVICE_RETURNING:
            return True
        if isinstance(call.func, ast.Attribute):
            # method chains on device values (x.at[i].set(...), .astype)
            return self._is_tainted(call.func.value)
        return False

    # -- findings ------------------------------------------------------------

    def _spans(self, node: ast.AST) -> Tuple[Tuple[int, int], ...]:
        spans = [node_span(node), def_line_span(self.fn)]
        if self._stmt_stack:
            spans.append(node_span(self._stmt_stack[-1]))
        return tuple(spans)

    def _flag(self, node: ast.AST, rule: str, message: str) -> None:
        if self.pragmas.allows(rule, *self._spans(node)):
            return
        self.findings.append(Finding(
            checker=CHECKER, rule=rule, path=self.path,
            line=getattr(node, "lineno", 0), message=message,
            sanctionable=True,
        ))

    # -- visitors ------------------------------------------------------------

    def visit(self, node: ast.AST):
        if isinstance(node, ast.stmt):
            self._stmt_stack.append(node)
            try:
                return super().visit(node)
            finally:
                self._stmt_stack.pop()
        return super().visit(node)

    def visit_FunctionDef(self, node: ast.FunctionDef):
        if node is self.fn:
            self.generic_visit(node)
        # nested defs are linted separately (fresh scope)

    visit_AsyncFunctionDef = visit_FunctionDef

    @staticmethod
    def _target_names(target: ast.AST) -> List[str]:
        """Plain-Name assignment targets only: ``pf.d_off = ...`` must
        not taint ``pf`` itself."""
        if isinstance(target, ast.Name):
            return [target.id]
        if isinstance(target, (ast.Tuple, ast.List)):
            out: List[str] = []
            for elt in target.elts:
                out.extend(_FunctionLint._target_names(elt))
            return out
        if isinstance(target, ast.Starred):
            return _FunctionLint._target_names(target.value)
        return []

    def visit_Assign(self, node: ast.Assign):
        self.generic_visit(node)
        tainted = self._is_tainted(node.value)
        for target in node.targets:
            for name in self._target_names(target):
                if tainted:
                    self.tainted.add(name)
                else:
                    self.tainted.discard(name)

    def visit_AugAssign(self, node: ast.AugAssign):
        self.generic_visit(node)
        if isinstance(node.target, ast.Name) and self._is_tainted(
            node.value
        ):
            self.tainted.add(node.target.id)

    def visit_For(self, node: ast.For):
        self.loop_depth += 1
        try:
            self.generic_visit(node)
        finally:
            self.loop_depth -= 1

    @staticmethod
    def _identity_test(test: ast.AST) -> bool:
        """``x is None`` / ``x is not None`` never call ``__bool__`` on
        the operand — host-safe even on a device value."""
        return isinstance(test, ast.Compare) and all(
            isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops
        )

    def visit_While(self, node: ast.While):
        if not self._identity_test(node.test) and self._is_tainted(
            node.test
        ):
            self._flag(
                node.test, "device-flow",
                "while-loop condition evaluates a device value on the "
                "host (implicit sync; tracer error under jit)",
            )
        self.loop_depth += 1
        try:
            self.generic_visit(node)
        finally:
            self.loop_depth -= 1

    def visit_If(self, node: ast.If):
        if not self._identity_test(node.test) and self._is_tainted(
            node.test
        ):
            self._flag(
                node.test, "device-flow",
                "branch condition evaluates a device value on the host "
                "(implicit sync; tracer error under jit)",
            )
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        self.generic_visit(node)
        name = _dotted(node.func) or ""
        head, _, rest = name.partition(".")
        leaf = name.rsplit(".", 1)[-1]

        # Unconditional syncs.
        if name == "jax.device_get" or leaf == "block_until_ready":
            self._flag(
                node, "host-fetch",
                f"{leaf}() is an unconditional device sync",
            )
            return
        # np.asarray / np.array on a device value.
        if head in ("np", "numpy") and rest in _FETCH_NP_FUNCS:
            if any(self._is_tainted(a) for a in node.args):
                self._flag(
                    node, "host-fetch",
                    f"np.{rest}() on a device value is a blocking "
                    "device->host fetch",
                )
            return
        # float()/int()/bool() on a device value.
        if name in _FETCH_BUILTINS and node.args and self._is_tainted(
            node.args[0]
        ):
            self._flag(
                node, "host-fetch",
                f"{name}() on a device value is a blocking scalar "
                "device->host fetch",
            )
            return
        # .item() / .tolist() on a device value.
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in _FETCH_METHODS
            and self._is_tainted(node.func.value)
        ):
            self._flag(
                node, "host-fetch",
                f".{node.func.attr}() on a device value is a blocking "
                "device->host fetch",
            )
            return
        # jnp construction / device_put inside a host loop.
        is_upload = (
            (head == "jnp" and rest in _UPLOAD_JNP_FUNCS)
            or name == "jax.device_put"
        )
        if is_upload and self.loop_depth > 0 and not self.trace_time:
            self._flag(
                node, "host-upload",
                f"{name}() inside a loop is a per-iteration "
                "host->device upload",
            )


class HostBoundaryChecker:
    """Run the lint over source text / the audited package modules."""

    def check_source(self, path: str, source: str,
                     module: Optional[str] = None) -> List[Finding]:
        module = module or path.rsplit("/", 1)[-1].replace(".py", "")
        tree, findings = parse_module(path, source, CHECKER)
        if tree is None:
            return findings
        pragmas = Pragmas.scan(source)
        findings.extend(pragma_findings(path, pragmas, CHECKER))
        jitted = _jit_function_names(tree)
        trace_time = _trace_time_functions(tree, jitted)

        def lint_fn(fn: ast.FunctionDef, in_class: bool) -> None:
            is_trace = (not in_class) and fn.name in trace_time
            # Pass 1 computes the function's final taint set (so taint
            # assigned late in a loop body still covers early sinks on
            # the next iteration); pass 2 reports with it pre-seeded.
            seed = _FunctionLint(
                module, path, fn, pragmas, trace_time=is_trace
            )
            seed.visit(fn)
            walker = _FunctionLint(
                module, path, fn, pragmas, trace_time=is_trace
            )
            walker.tainted |= seed.tainted
            walker.visit(fn)
            findings.extend(walker.findings)

        def lint_tree(fn: ast.FunctionDef, in_class: bool) -> None:
            lint_fn(fn, in_class)
            # Nested defs (closures, handler classes defined inside
            # methods) get their own fresh scope — host-side always.
            for sub in ast.walk(fn):
                if sub is not fn and isinstance(sub, ast.FunctionDef):
                    lint_fn(sub, in_class=True)

        for node in tree.body:
            if isinstance(node, ast.FunctionDef):
                lint_tree(node, in_class=False)
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, ast.FunctionDef):
                        lint_tree(sub, in_class=True)
        return findings

    def check_package(
        self, modules: Sequence[str] = AUDITED_MODULES
    ) -> List[Finding]:
        out: List[Finding] = []
        for path, source in iter_package_sources(only=modules):
            out.extend(self.check_source(path, source))
        return out
