"""Metrics-registry lint: obs.METRICS <-> emission sites, both ways.

PR 7 replaced the ``"total" in name`` type heuristic with the explicit
``obs.METRICS`` registry and a loud UNREGISTERED help line for names
that show up at scrape time without a registration — a RUNTIME check
that only fires for metrics the exercised configuration actually
emits.  This pass closes the loop statically:

  * **unemitted-metric**: every name registered in ``obs.METRICS``
    must be emitted somewhere in the package — as an exact string
    constant, or via an f-string whose constant parts match (the
    generated per-site/per-feature families).  A registered name with
    no emission site is dashboard rot: the family renders HELP/TYPE
    never followed by a sample, or nothing at all.
  * **unregistered-metric**: every scalar key the metric PROVIDERS
    build (``ContinuousBatcher.stats``, ``DegradeManager.stats``,
    ``Observability.metrics``, ``OverloadController.stats``,
    ``FaultInjector.stats``, ``LLMServer._metrics_text``'s update
    dict) must be registered — statically, for every configuration,
    not just the ones the /metrics parse test happens to serve.

String constants inside statements that ASSIGN into ``METRICS`` are
registration, not emission, and are excluded from the evidence.

The ROUTER's exposition (router.py renders ``llm_router_*`` /
``llm_fleet_*`` / ``llm_replica_*`` families itself, outside the
obs.METRICS pipeline, off its own ``ROUTER_METRICS`` registry) gets
the same two-way audit via :func:`check_router_registry` —
``router-unemitted-metric`` / ``router-unregistered-metric`` findings,
run as part of the package pass.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .common import Finding, iter_package_sources, parse_module

CHECKER = "metrics"

# (module basename, class or None, function) whose built dicts are
# rendered into /metrics verbatim — their keys ARE metric names.
PROVIDERS: Tuple[Tuple[str, Optional[str], str], ...] = (
    ("serving", "ContinuousBatcher", "stats"),
    ("degrade", "DegradeManager", "stats"),
    ("obs", "Observability", "metrics"),
    ("overload", "OverloadController", "stats"),
    ("faults", "FaultInjector", "stats"),
    ("server", "LLMServer", "_metrics_scalars"),
)

_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")

# Family names the ROUTER's own exposition mints (full names — the
# router renders outside the obs.METRICS pipeline, with its own
# ``ROUTER_METRICS`` registry in router.py).
_ROUTER_FAMILY_RE = re.compile(
    r"llm_(?:router|fleet|replica)_[a-z0-9_]+"
)


def _is_metrics_assign(stmt: ast.stmt) -> bool:
    """Does ``stmt`` assign into the METRICS registry?"""
    targets: List[ast.AST] = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    for t in targets:
        for leaf in ast.walk(t):
            if isinstance(leaf, ast.Name) and leaf.id == "METRICS":
                return True
    return False


def _joined_pattern(node: ast.JoinedStr) -> Optional[re.Pattern]:
    """Regex matching the f-string's constant skeleton, or None when
    the constant parts are too thin to mean anything (< 4 chars)."""
    parts: List[str] = []
    const_len = 0
    for v in node.values:
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            parts.append(re.escape(v.value))
            const_len += len(v.value)
        else:
            parts.append("[a-z0-9_]+")
    if const_len < 4:
        return None
    return re.compile("^" + "".join(parts) + "$")


def _collect_evidence(
    sources: Sequence[Tuple[str, str]],
) -> Tuple[Set[str], List[re.Pattern]]:
    """(exact string constants, f-string patterns) outside METRICS
    registration statements, package-wide."""
    exact: Set[str] = set()
    patterns: List[re.Pattern] = []
    for path, source in sources:
        tree, _ = parse_module(path, source, CHECKER)
        if tree is None:
            continue
        skip_spans: List[Tuple[int, int]] = []
        for stmt in ast.walk(tree):
            if isinstance(stmt, ast.stmt) and _is_metrics_assign(stmt):
                skip_spans.append(
                    (stmt.lineno, stmt.end_lineno or stmt.lineno)
                )
            # Docstrings DOCUMENT metrics by name (the /metrics schema
            # tables) — they are not emission evidence; counting them
            # would let a deleted emission hide behind its own docs.
            if isinstance(stmt, (ast.Module, ast.ClassDef,
                                 ast.FunctionDef, ast.AsyncFunctionDef)):
                body = getattr(stmt, "body", [])
                if body and isinstance(body[0], ast.Expr) and isinstance(
                    body[0].value, ast.Constant
                ) and isinstance(body[0].value.value, str):
                    doc = body[0]
                    skip_spans.append(
                        (doc.lineno, doc.end_lineno or doc.lineno)
                    )

        def skipped(node: ast.AST) -> bool:
            line = getattr(node, "lineno", 0)
            return any(lo <= line <= hi for lo, hi in skip_spans)

        for node in ast.walk(tree):
            if skipped(node):
                continue
            if isinstance(node, ast.Constant) and isinstance(
                node.value, str
            ):
                exact.add(node.value)
            elif isinstance(node, ast.JoinedStr):
                pat = _joined_pattern(node)
                if pat is not None:
                    patterns.append(pat)
    return exact, patterns


def _provider_keys(
    tree: ast.Module, cls: Optional[str], func: str,
) -> List[Tuple[str, int, bool]]:
    """(key, line, is_template) for every metric-name key the provider
    function builds: dict-literal keys, ``out[...] =`` string
    subscripts, and f-string keys (templates)."""
    fn: Optional[ast.FunctionDef] = None
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and (
            cls is None or node.name == cls
        ):
            for sub in node.body:
                if isinstance(sub, ast.FunctionDef) and sub.name == func:
                    fn = sub
        elif (
            cls is None and isinstance(node, ast.FunctionDef)
            and node.name == func
        ):
            fn = node
    if fn is None:
        return []
    # Dicts that are elements of a tuple literal are LABEL dicts
    # (("family", {label: value}, v) rows), not metric-name dicts.
    label_dicts: Set[ast.Dict] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Tuple):
            for elt in node.elts:
                if isinstance(elt, ast.Dict):
                    label_dicts.add(elt)
    out: List[Tuple[str, int, bool]] = []
    for node in ast.walk(fn):
        keys: Iterable[ast.AST] = ()
        if isinstance(node, ast.Dict) and node not in label_dicts:
            keys = [k for k in node.keys if k is not None]
        elif isinstance(node, ast.Assign):
            keys = [
                t.slice for t in node.targets
                if isinstance(t, ast.Subscript)
            ]
        for key in keys:
            if isinstance(key, ast.Constant) and isinstance(
                key.value, str
            ):
                if _NAME_RE.match(key.value):
                    out.append((key.value, key.lineno, False))
            elif isinstance(key, ast.JoinedStr):
                pat = _joined_pattern(key)
                if pat is not None:
                    out.append((pat.pattern, key.lineno, True))
    return out


def _is_named_assign(stmt: ast.stmt, name: str) -> bool:
    """Does ``stmt`` assign into the variable ``name``?"""
    targets: List[ast.AST] = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    for t in targets:
        for leaf in ast.walk(t):
            if isinstance(leaf, ast.Name) and leaf.id == name:
                return True
    return False


def check_router_registry(
    registry: Optional[Dict[str, Tuple[str, str]]] = None,
    source: Optional[str] = None,
    path: str = "jax_llama_tpu/router.py",
) -> List[Finding]:
    """Router-exposition audit: the ReplicaRouter renders its own
    Prometheus text (``llm_router_*`` / ``llm_fleet_*`` /
    ``llm_replica_*`` families) outside the obs.METRICS pipeline,
    driven by the ``ROUTER_METRICS`` registry in router.py — so it
    gets the same two-way contract:

      * **router-unemitted-metric**: every registered family must be
        emitted in router.py — a ``fam("name")`` header call or a
        sample-line string mentioning the full name (registry
        assignment and docstrings are not evidence).
      * **router-unregistered-metric**: every family router.py emits
        — a ``fam()`` first argument, or any family-shaped token
        inside a non-docstring string constant / f-string constant
        part — must be registered.
    """
    findings: List[Finding] = []
    if registry is None:
        from .. import router

        registry = router.ROUTER_METRICS
    if source is None:
        for p, src in iter_package_sources():
            if p.replace("\\", "/").endswith("/router.py"):
                path, source = p, src
                break
    if source is None:
        return [Finding(
            checker=CHECKER, rule="stale-registry", path=path, line=0,
            message="router.py not found in the audited package",
        )]
    tree, errs = parse_module(path, source, CHECKER)
    findings.extend(errs)
    if tree is None:
        return findings
    skip_spans: List[Tuple[int, int]] = []
    for stmt in ast.walk(tree):
        if isinstance(stmt, ast.stmt) and _is_named_assign(
            stmt, "ROUTER_METRICS"
        ):
            skip_spans.append(
                (stmt.lineno, stmt.end_lineno or stmt.lineno)
            )
        if isinstance(stmt, (ast.Module, ast.ClassDef,
                             ast.FunctionDef, ast.AsyncFunctionDef)):
            body = getattr(stmt, "body", [])
            if body and isinstance(body[0], ast.Expr) and isinstance(
                body[0].value, ast.Constant
            ) and isinstance(body[0].value.value, str):
                doc = body[0]
                skip_spans.append(
                    (doc.lineno, doc.end_lineno or doc.lineno)
                )

    def skipped(node: ast.AST) -> bool:
        line = getattr(node, "lineno", 0)
        return any(lo <= line <= hi for lo, hi in skip_spans)

    emitted: Dict[str, int] = {}  # family -> first evidence line
    fam_args: List[Tuple[str, int]] = []
    for node in ast.walk(tree):
        if skipped(node):
            continue
        texts: List[str] = []
        if isinstance(node, ast.Constant) and isinstance(
            node.value, str
        ):
            texts.append(node.value)
        elif isinstance(node, ast.JoinedStr):
            texts.extend(
                v.value for v in node.values
                if isinstance(v, ast.Constant)
                and isinstance(v.value, str)
            )
        for text in texts:
            for name in _ROUTER_FAMILY_RE.findall(text):
                emitted.setdefault(name, getattr(node, "lineno", 0))
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "fam"
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            fam_args.append((node.args[0].value, node.lineno))
            emitted.setdefault(node.args[0].value, node.lineno)
    for name in sorted(registry):
        if name not in emitted:
            findings.append(Finding(
                checker=CHECKER, rule="router-unemitted-metric",
                path=path, line=0,
                message=(
                    f"ROUTER_METRICS registers {name!r} but router.py "
                    "never emits it (no fam() header, no sample line) "
                    "— dead registration; emit it or delete it"
                ),
            ))
    flagged: set = set()
    for name, line in fam_args:
        if name not in registry and name not in flagged:
            flagged.add(name)
            findings.append(Finding(
                checker=CHECKER, rule="router-unregistered-metric",
                path=path, line=line,
                message=(
                    f"router.py declares family {name!r} via fam() "
                    "but ROUTER_METRICS has no entry — register "
                    "type + help or the exposition KeyErrors"
                ),
            ))
    for name, line in sorted(emitted.items()):
        if name not in registry and name not in flagged:
            flagged.add(name)
            findings.append(Finding(
                checker=CHECKER, rule="router-unregistered-metric",
                path=path, line=line,
                message=(
                    f"router.py emits family {name!r} (sample-line "
                    "string) with no ROUTER_METRICS entry — it "
                    "renders without HELP/TYPE; register it"
                ),
            ))
    return findings


def check_package(
    registry: Optional[Dict[str, Tuple[str, str]]] = None,
    sources: Optional[Sequence[Tuple[str, str]]] = None,
    providers: Tuple[Tuple[str, Optional[str], str], ...] = PROVIDERS,
) -> List[Finding]:
    # Package mode (no fixture registry/sources): the router's own
    # registry is audited alongside obs.METRICS.
    package_mode = registry is None and sources is None
    findings: List[Finding] = []
    if registry is None:
        from .. import obs

        registry = obs.METRICS
    if sources is None:
        sources = list(iter_package_sources())
    exact, patterns = _collect_evidence(sources)

    # -- registered -> emitted ----------------------------------------------
    for name in sorted(registry):
        if name in exact:
            continue
        if any(p.match(name) for p in patterns):
            continue
        findings.append(Finding(
            checker=CHECKER, rule="unemitted-metric",
            path="jax_llama_tpu/obs.py", line=0,
            message=(
                f"obs.METRICS registers {name!r} but nothing in the "
                "package emits it (no exact string constant, no "
                "matching f-string) — dead registration; emit it or "
                "delete it"
            ),
        ))

    # -- emitted -> registered ----------------------------------------------
    by_module: Dict[str, Tuple[str, ast.Module]] = {}
    for path, source in sources:
        modname = path.rsplit("/", 1)[-1][:-3]
        tree, errs = parse_module(path, source, CHECKER)
        findings.extend(errs)
        if tree is not None:
            by_module[modname] = (path, tree)
    registered = set(registry)
    for modname, cls, func in providers:
        if modname not in by_module:
            findings.append(Finding(
                checker=CHECKER, rule="stale-registry",
                path=f"jax_llama_tpu/{modname}.py", line=0,
                message=(
                    f"metrics PROVIDERS names module {modname!r} which "
                    "is not in the audited package"
                ),
            ))
            continue
        path, tree = by_module[modname]
        keys = _provider_keys(tree, cls, func)
        if not keys:
            findings.append(Finding(
                checker=CHECKER, rule="stale-registry",
                path=path, line=0,
                message=(
                    f"metrics PROVIDERS names {cls or modname}.{func} "
                    "but no dict keys were found there — provider "
                    "moved or renamed; update PROVIDERS"
                ),
            ))
            continue
        for key, line, is_template in keys:
            if is_template:
                pat = re.compile(key)
                if any(pat.match(r) for r in registered):
                    continue
                findings.append(Finding(
                    checker=CHECKER, rule="unregistered-metric",
                    path=path, line=line,
                    message=(
                        f"{cls or modname}.{func} emits templated "
                        f"metric {key!r} matching no registered name "
                        "— add the family to obs.METRICS"
                    ),
                ))
            elif key not in registered:
                findings.append(Finding(
                    checker=CHECKER, rule="unregistered-metric",
                    path=path, line=line,
                    message=(
                        f"{cls or modname}.{func} emits {key!r} which "
                        "is not registered in obs.METRICS — the "
                        "exposition will render the loud UNREGISTERED "
                        "help line; register type + help"
                    ),
                ))

    # -- router exposition (its own registry, both directions) ---------------
    if package_mode:
        findings.extend(check_router_registry())
    return findings
