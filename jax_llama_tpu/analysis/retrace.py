"""Retrace auditor: bounded jit-cache-key domains for every program.

A jitted program's executable cache is keyed by its static arguments
and the shapes/dtypes of its traced arguments.  The serving stack's
latency story assumes each program compiles O(log) variants — pow2
buckets for chunk sizes, row counts and padded widths; bools and
ctor-stable objects for everything else.  Nothing enforced that: one
un-bucketed width at one call site re-specializes a program per
request, and the first symptom is a production latency cliff (PR 11's
``llm_jit_cache_entries`` gauge would only DETECT it after shipping).

This pass promotes the discipline to a lint-time proof plus a runtime
drill:

  1. **Static layer** (:func:`check_static`): for every registered
     :class:`~.contracts.ProgramContract`, find each dispatch call
     site in its module and prove every value that enters the jit
     cache key flows through a *bounded-domain constructor*:

       * the program's ``static_argnames`` keyword values, and
       * the dims of every locally-constructed array argument (the
         admission-path uploads whose shapes key the cache), and
       * the registered :data:`SHAPE_SOURCES` — host buffers built
         elsewhere (e.g. the fused-prefill token buffer) whose shapes
         reach a dispatch through object attributes.

     Bounded means: literals and bools; ``self.<attr>`` assigned only
     in ``__init__`` (ctor-stable — one value per serving config);
     calls to :data:`BOUNDED_CALLS` / :data:`BOUNDED_METHODS`
     (``engine.pow2_bucket`` and the documented bucketing helpers);
     ``min(...)`` clamps against a bounded bound; boolean
     expressions; and compositions thereof.  Anything else is an
     ``unbounded-trace-domain`` finding, sanctionable with
     ``# audit: trace-domain(<why the domain is bounded anyway>)``.

     A registered program without a ``max_cache_keys`` budget is a
     finding too — new programs must declare their domain size.

  2. **Runtime drill** (:func:`check_runtime`): build real batchers at
     the contracts' tiny geometry, sweep the admission surface (prompt
     lengths across block buckets, greedy + sampled, stop sets, fused
     + classic + speculative lanes) and assert the DELTA in
     ``serving.jit_cache_entries()`` per program stays within each
     contract's ``max_cache_keys``.  The static proof says every key
     is bucketed; the drill says the buckets are as few as declared.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .common import (
    Finding, Pragmas, def_line_span, iter_package_sources,
    jit_decorations, node_span, parse_module,
)
from .contracts import REGISTRY, ProgramContract

CHECKER = "retrace"

# Free functions / constructors whose RESULT has a bounded domain by
# documented contract (the "bounded-domain constructors" the static
# proof accepts).  ``pow2_bucket`` is THE bucketing primitive; the
# others return bools or clamped pow2 values (their docstrings carry
# the argument; the runtime drill backstops them).
BOUNDED_CALLS = frozenset({
    "pow2_bucket", "bool", "frozenset",
})

# Methods of the serving classes with the same property.  Each returns
# a pow2-bucketed / flag-clamped value (``_pick_chunk``, ``_suffix_pad``,
# ``_pf_chunk``, ``_row_bucket``) or a bool (``_spec_kernel_ok`` — also
# provable from its ``-> bool`` annotation, listed for robustness).
BOUNDED_METHODS = frozenset({
    "_pick_chunk", "_suffix_pad", "_pf_chunk", "_row_bucket",
    "_spec_kernel_ok", "_fused_scheduling",
})

# Attribute names that carry bounded values ACROSS object boundaries:
# reading ``<obj>.<name>`` is bounded because the only writer is a
# bounded constructor (checked where it is constructed; see
# SHAPE_SOURCES for the array-shaped ones).  ``chunk`` is
# ``_Prefill.chunk`` = ``_pf_chunk``'s pow2 result.
BOUNDED_ATTRS = frozenset({"chunk"})

# Array constructors whose first argument is the shape to audit.
_SHAPE_CTORS = frozenset({
    "zeros", "ones", "full", "empty",
})
# Wrappers to look through when resolving an array argument.
_PASSTHROUGH = frozenset({"asarray", "array"})

# Host buffers whose SHAPES reach a dispatch indirectly (through
# ``pf.d_toks``-style attributes or device twins): per program, the
# (defining function, local variable) pairs whose constructor dims the
# static layer must prove bounded.  This is the contract for "shape
# dims flowing in from admission": the buffer is built once on the
# admission path, and its width is a jit cache key of the program.
SHAPE_SOURCES: Dict[str, List[Tuple[str, str]]] = {
    # the fused-prefill token buffer: n_chunks (pow2) * C (_pf_chunk)
    "_fused_chunk": [("_setup_fused_prefill", "toks")],
    # the per-slot stop table: width pow2-bucketed on regrowth; its
    # shape keys every chunk/spec-chunk/scatter program
    "_paged_decode_chunk": [("_ensure_stop_width", "tab")],
    "_spec_rounds_chunk": [("_ensure_stop_width", "tab")],
    "_scatter_rows": [("_ensure_stop_width", "tab")],
}


def _static_argnames(dec: Optional[ast.Call]) -> Set[str]:
    if dec is None:
        return set()
    out: Set[str] = set()
    for kw in dec.keywords:
        if kw.arg == "static_argnames":
            for elt in ast.walk(kw.value):
                if isinstance(elt, ast.Constant) and isinstance(
                    elt.value, str
                ):
                    out.add(elt.value)
    return out


def _ctor_stable_attrs(cls: ast.ClassDef) -> Set[str]:
    """self-attributes assigned ONLY inside ``__init__`` — one value
    per instance lifetime, so they contribute exactly one cache key."""
    init_writes: Set[str] = set()
    other_writes: Set[str] = set()
    for node in cls.body:
        if not isinstance(node, ast.FunctionDef):
            continue
        sink = init_writes if node.name == "__init__" else other_writes
        for sub in ast.walk(node):
            targets: List[ast.AST] = []
            if isinstance(sub, ast.Assign):
                targets = list(sub.targets)
            elif isinstance(sub, (ast.AugAssign, ast.AnnAssign)):
                targets = [sub.target]
            for t in targets:
                for leaf in ast.walk(t):
                    if (
                        isinstance(leaf, ast.Attribute)
                        and isinstance(leaf.ctx, (ast.Store, ast.Del))
                        and isinstance(leaf.value, ast.Name)
                        and leaf.value.id == "self"
                    ):
                        sink.add(leaf.attr)
    return init_writes - other_writes


def _dotted(node: ast.AST) -> str:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


class _BoundedProver:
    """Backward boundedness proof for expressions inside one function
    (single-function dataflow: a Name is bounded iff every assignment
    to it in the function is bounded)."""

    def __init__(self, fn: ast.FunctionDef, cls: Optional[ast.ClassDef],
                 ctor_stable: Set[str]):
        self.fn = fn
        self.cls = cls
        self.ctor_stable = ctor_stable
        self._assigns: Dict[str, List[ast.AST]] = {}
        self._bool_methods: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    self._index_target(t, node.value)
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                self._index_target(node.target, node.value)
        if cls is not None:
            for node in cls.body:
                if isinstance(node, ast.FunctionDef) and isinstance(
                    node.returns, ast.Name
                ) and node.returns.id == "bool":
                    self._bool_methods.add(node.name)

    def _index_target(self, target: ast.AST, value: ast.AST) -> None:
        if isinstance(target, ast.Name):
            self._assigns.setdefault(target.id, []).append(value)
        elif isinstance(target, (ast.Tuple, ast.List)):
            # tuple unpack: if the value is a bounded call
            # (e.g. _row_bucket), every element inherits boundedness;
            # record the whole RHS for each name and let the call rule
            # decide.
            for elt in target.elts:
                if isinstance(elt, ast.Name):
                    self._assigns.setdefault(elt.id, []).append(value)

    # -- the proof -----------------------------------------------------------

    def why_unbounded(self, node: ast.AST,
                      seen: Optional[Set[str]] = None) -> Optional[str]:
        """None if ``node`` provably has a bounded domain, else a short
        reason naming the unprovable leaf."""
        seen = seen if seen is not None else set()
        if isinstance(node, ast.Constant):
            return None
        if isinstance(node, (ast.Compare, ast.BoolOp)):
            return None  # bool domain
        if isinstance(node, ast.UnaryOp):
            return self.why_unbounded(node.operand, seen)
        if isinstance(node, ast.BinOp):
            return (self.why_unbounded(node.left, seen)
                    or self.why_unbounded(node.right, seen))
        if isinstance(node, ast.IfExp):
            return (self.why_unbounded(node.body, seen)
                    or self.why_unbounded(node.orelse, seen))
        if isinstance(node, (ast.Tuple, ast.List)):
            for elt in node.elts:
                why = self.why_unbounded(elt, seen)
                if why:
                    return why
            return None
        if isinstance(node, ast.Starred):
            return self.why_unbounded(node.value, seen)
        if isinstance(node, ast.Subscript):
            # x.shape[...] and bounded-tuple indexing
            return self.why_unbounded(node.value, seen)
        if isinstance(node, ast.Attribute):
            dotted = _dotted(node)
            if node.attr == "shape":
                # Shapes of INSTANCE state (self.<attr>.shape — device
                # twins, pool planes) are stable-or-bucketed where
                # built; a bare parameter's .shape is request-shaped
                # laundering (width=toks.shape[0]) and stays flagged.
                base = node.value
                while isinstance(base, ast.Attribute):
                    base = base.value
                if isinstance(base, ast.Name) and base.id == "self":
                    return None
                return (
                    f"{dotted!r}: .shape of a non-instance value is "
                    "request-shaped unless its constructor is checked"
                )
            if node.attr in BOUNDED_ATTRS:
                return None
            if (
                isinstance(node.value, ast.Name)
                and node.value.id == "self"
            ):
                if node.attr in self.ctor_stable:
                    return None
                return (
                    f"self.{node.attr} is not ctor-stable (assigned "
                    "outside __init__)"
                )
            return f"attribute {dotted!r} has no bounded-domain proof"
        if isinstance(node, ast.Name):
            if node.id in seen:
                return None  # cycle: judged by the other assignments
            if node.id not in self._assigns:
                return (
                    f"name {node.id!r} is not assigned in this "
                    "function (parameter or outer binding)"
                )
            seen = seen | {node.id}
            for value in self._assigns[node.id]:
                why = self.why_unbounded(value, seen)
                if why:
                    return why
            return None
        if isinstance(node, ast.Call):
            fname = _dotted(node.func)
            leaf = fname.rsplit(".", 1)[-1]
            if leaf in BOUNDED_CALLS:
                return None
            if fname.startswith("self.") and (
                leaf in BOUNDED_METHODS or leaf in self._bool_methods
            ):
                return None
            if leaf == "min":
                # a clamp: bounded if ANY operand is bounded above
                for a in node.args:
                    if self.why_unbounded(a, seen) is None:
                        return None
                return "min() with no bounded operand"
            if leaf == "max":
                for a in node.args:
                    why = self.why_unbounded(a, seen)
                    if why:
                        return why
                if not node.args:
                    return "max() over a generator is unbounded"
                return None
            if leaf == "len":
                return (
                    "len(...) is request-shaped — bucket it "
                    "(pow2_bucket / a declared clamp)"
                )
            return f"call to {fname!r} is not a bounded-domain constructor"
        return f"expression {type(node).__name__} has no boundedness rule"


def _resolve_array_ctor(
    expr: ast.AST, prover: _BoundedProver,
) -> Optional[ast.Call]:
    """The ``np.zeros``-class constructor call an argument expression
    resolves to (through ``asarray`` wrappers and local names), or
    None when the arg is not locally constructed (attribute loads /
    device twins — shape-stable, audited where built)."""
    for _ in range(6):
        if isinstance(expr, ast.Call):
            leaf = _dotted(expr.func).rsplit(".", 1)[-1]
            if leaf in _SHAPE_CTORS:
                return expr
            if leaf in _PASSTHROUGH and expr.args:
                expr = expr.args[0]
                continue
            return None
        if isinstance(expr, ast.Name):
            assigns = prover._assigns.get(expr.id)
            if not assigns or len(assigns) != 1:
                return None
            expr = assigns[0]
            continue
        return None
    return None


def _call_sites(
    tree: ast.Module, name: str,
) -> List[Tuple[ast.Call, ast.FunctionDef, Optional[ast.ClassDef]]]:
    out = []

    def walk(node, fn, cls):
        for child in ast.iter_child_nodes(node):
            f, c = fn, cls
            if isinstance(child, ast.ClassDef):
                c = child
            elif isinstance(child, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                f = child
            if (
                isinstance(child, ast.Call)
                and _dotted(child.func).rsplit(".", 1)[-1] == name
                and fn is not None
                and fn.name != name
            ):
                out.append((child, fn, cls))
            walk(child, f, c)

    walk(tree, None, None)
    return out


def check_module_source(
    path: str,
    source: str,
    registry: Dict[str, ProgramContract] = REGISTRY,
    module: Optional[str] = None,
) -> List[Finding]:
    """Static retrace audit of one module's dispatch call sites."""
    modname = module or path.rsplit("/", 1)[-1][:-3]
    tree, findings = parse_module(path, source, CHECKER)
    if tree is None:
        return findings
    pragmas = Pragmas.scan(source)
    jits = jit_decorations(tree)
    classes = {
        n.name: n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)
    }
    stable_by_class = {
        name: _ctor_stable_attrs(cls) for name, cls in classes.items()
    }

    def sanctioned(node: ast.AST, fn: ast.FunctionDef) -> bool:
        return pragmas.allows(
            "trace-domain", node_span(node), def_line_span(fn)
        )

    def report(node, fn, program, what, why):
        findings.append(Finding(
            checker=CHECKER, rule="unbounded-trace-domain",
            path=path, line=getattr(node, "lineno", fn.lineno),
            message=(
                f"{program}: {what} is not provably bounded — {why}. "
                "Every jit-cache-key value must pass through a "
                "bounded-domain constructor (pow2_bucket, a clamp "
                "against a flag, a bool, a ctor-stable attribute); "
                "sanction a provably-bounded-anyway case with "
                "# audit: trace-domain(<argument>)"
            ),
            sanctionable=True,
        ))

    for name, contract in sorted(registry.items()):
        prog_module = contract.module.rsplit(".", 1)[-1]
        if prog_module != modname:
            continue
        dec = jits.get(name)
        statics = _static_argnames(dec[1]) if dec else set()
        for call, fn, cls in _call_sites(tree, name):
            stable = stable_by_class.get(cls.name, set()) if cls else set()
            prover = _BoundedProver(fn, cls, stable)
            if sanctioned(call, fn):
                continue
            for kw in call.keywords:
                if kw.arg not in statics:
                    continue
                why = prover.why_unbounded(kw.value)
                if why and not sanctioned(kw.value, fn):
                    report(kw.value, fn, name,
                           f"static arg {kw.arg!r} at {fn.name}", why)
            for arg in list(call.args) + [
                kw.value for kw in call.keywords if kw.arg not in statics
            ]:
                ctor = _resolve_array_ctor(arg, prover)
                if ctor is None or not ctor.args:
                    continue
                why = prover.why_unbounded(ctor.args[0])
                if why and not sanctioned(ctor, fn) and not sanctioned(
                    arg, fn
                ):
                    report(
                        ctor, fn, name,
                        f"shape of a constructed array argument at "
                        f"{fn.name}", why,
                    )
    # -- registered shape sources -------------------------------------------
    fns_by_name: Dict[str, List[Tuple[ast.FunctionDef,
                                      Optional[ast.ClassDef]]]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, ast.FunctionDef):
                    fns_by_name.setdefault(sub.name, []).append(
                        (sub, node)
                    )
    for node in tree.body:
        if isinstance(node, ast.FunctionDef):
            fns_by_name.setdefault(node.name, []).append((node, None))

    for name, contract in sorted(registry.items()):
        if contract.module.rsplit(".", 1)[-1] != modname:
            continue
        for fn_name, var in SHAPE_SOURCES.get(name, ()):
            hits = fns_by_name.get(fn_name)
            if not hits:
                findings.append(Finding(
                    checker=CHECKER, rule="stale-registry", path=path,
                    line=0,
                    message=(
                        f"retrace SHAPE_SOURCES names "
                        f"{fn_name!r}/{var!r} for {name} but the "
                        "function no longer exists"
                    ),
                ))
                continue
            for fn, cls in hits:
                stable = (
                    stable_by_class.get(cls.name, set()) if cls else set()
                )
                prover = _BoundedProver(fn, cls, stable)
                assigns = prover._assigns.get(var, [])
                if not assigns:
                    findings.append(Finding(
                        checker=CHECKER, rule="stale-registry",
                        path=path, line=fn.lineno,
                        message=(
                            f"retrace SHAPE_SOURCES names local "
                            f"{var!r} in {fn_name} (for {name}) but "
                            "no such assignment exists"
                        ),
                    ))
                for value in assigns:
                    ctor = (
                        value if isinstance(value, ast.Call)
                        and _dotted(value.func).rsplit(".", 1)[-1]
                        in _SHAPE_CTORS else None
                    )
                    target = (
                        ctor.args[0] if ctor is not None and ctor.args
                        else value
                    )
                    why = prover.why_unbounded(target)
                    if why and not sanctioned(value, fn):
                        report(
                            value, fn, name,
                            f"shape source {fn_name}.{var}", why,
                        )
    return findings


def check_static(
    registry: Dict[str, ProgramContract] = REGISTRY,
) -> List[Finding]:
    """Static retrace audit over every contract module, plus the
    budget-coverage gate (every program declares ``max_cache_keys``)."""
    findings: List[Finding] = []
    for name, contract in sorted(registry.items()):
        if contract.max_cache_keys is None:
            findings.append(Finding(
                checker=CHECKER, rule="no-cache-key-budget",
                path=contract.module.replace(".", "/") + ".py", line=0,
                message=(
                    f"{name}: contract declares no max_cache_keys — "
                    "every registered program must bound its jit-cache "
                    "domain (see ProgramContract.max_cache_keys)"
                ),
            ))
    modules = sorted({
        c.module.rsplit(".", 1)[-1] for c in registry.values()
    })
    for path, source in iter_package_sources(only=modules):
        findings.extend(
            check_module_source(path, source, registry=registry)
        )
    return findings


# ---------------------------------------------------------------------------
# Runtime drill
# ---------------------------------------------------------------------------

def _sweep_batcher(cb, lengths: Sequence[int], vocab: int) -> None:
    import numpy as np

    rng = np.random.RandomState(7)
    for i, n in enumerate(lengths):
        toks = list(rng.randint(1, vocab, n))
        sampled = i % 2 == 1
        cb.submit(
            toks,
            max_new_tokens=3 + (i % 3),
            temperature=0.8 if sampled else 0.0,
            seed=17 + i if sampled else None,
            stop_tokens=(
                list(rng.randint(1, vocab, 1 + 2 * (i % 2)))
                if i % 2 else None
            ),
        )
    for _ in range(200):
        if not cb.step() and not cb.pending():
            break
    cb.run_to_completion()


def check_runtime(
    registry: Dict[str, ProgramContract] = REGISTRY,
) -> List[Finding]:
    """The jit-cache drill: sweep the admission surface on real
    batchers and assert per-program cache-entry DELTAS stay within
    each contract's ``max_cache_keys``.  Deltas, not totals: the jit
    cache is process-wide, and only this sweep's growth is this
    configuration's footprint."""
    import numpy as np  # noqa: F401  (parity with contracts' builders)

    from .. import serving
    from ..serving import ContinuousBatcher
    from .contracts import _BLOCK, _MAXLEN, _VOCAB, _tiny_config_params

    findings: List[Finding] = []
    before = serving.jit_cache_entries()
    cfg, params = _tiny_config_params()

    # One fused+chunked batcher takes the classic, suffix/prefix,
    # fused-prefill, scatter and release programs across prompt
    # lengths spanning several block buckets...
    cb = ContinuousBatcher(
        params, cfg, n_slots=2, max_len=_MAXLEN, block_size=_BLOCK,
        decode_chunk=4, prefill_budget=_BLOCK,
    )
    _sweep_batcher(
        cb, [3, 9, 17, 21, 33, 40, 18, 5], _VOCAB
    )
    # ...a classic-admission batcher widens the _paged_insert sweep
    # (prefill_budget=0 keeps every admission on the whole-prompt
    # path)...
    cb2 = ContinuousBatcher(
        params, cfg, n_slots=2, max_len=_MAXLEN, block_size=_BLOCK,
        decode_chunk=2, prefix_cache=False,
    )
    _sweep_batcher(cb2, [4, 12, 20, 35, 44], _VOCAB)
    # ...a speculative batcher drives the spec programs...
    cb3 = ContinuousBatcher(
        params, cfg, n_slots=2, max_len=_MAXLEN, block_size=_BLOCK,
        spec_rounds=2, draft_params=params, draft_config=cfg, n_draft=2,
    )
    _sweep_batcher(cb3, [6, 14, 26], _VOCAB)
    # ...and a classic prefix-cache batcher replays shared prefixes so
    # the grouped suffix-insert path compiles its buckets too.
    cb4 = ContinuousBatcher(
        params, cfg, n_slots=2, max_len=_MAXLEN, block_size=_BLOCK,
        decode_chunk=2,
    )
    base = list(range(1, 37))  # two full blocks + a suffix
    for tail in ([40, 41], list(range(50, 60)), [70]):
        cb4.submit(base + tail, max_new_tokens=2)
        cb4.run_to_completion()

    after = serving.jit_cache_entries()
    for name, contract in sorted(registry.items()):
        if contract.max_cache_keys is None:
            continue  # check_static reports it
        if name not in after:
            continue
        if after[name] < 0 or before.get(name, 0) < 0:
            continue  # this jax hides the cache; the gauge says -1 too
        delta = after[name] - before.get(name, 0)
        if delta > contract.max_cache_keys:
            findings.append(Finding(
                checker=CHECKER, rule="cache-key-overrun",
                path=contract.module.replace(".", "/") + ".py", line=0,
                message=(
                    f"{name}: the admission sweep created {delta} jit "
                    f"cache entries (contract: "
                    f"{contract.max_cache_keys}) — a cache-key value "
                    "is escaping its bucket; see llm_jit_cache_entries "
                    "and the retrace static findings"
                ),
            ))
    return findings
