"""Shared core of the invariant auditor: findings, pragmas, sources.

Every checker reports :class:`Finding` records and honors ``# audit:``
pragmas — the explicit, greppable allowlist that turns a sanctioned
violation into documentation instead of noise:

    # audit: host-fetch(the one packed fetch per chunk)
    # audit: host-upload(admission-time prompt upload, not per-token)
    # audit: device-flow(static eligibility flag, not a tracer)
    # audit: locked(called under self._lock by every public method)
    # audit: racy-read(snapshot gauge; single-writer loop, GIL-atomic)
    # audit: unguarded(single-writer: watchdog thread only)

A pragma suppresses findings of its kind on the STATEMENT it annotates
(any line of a multi-line statement works) — or on the whole function
when placed on its ``def`` line.  The reason is mandatory: a bare
``# audit: host-fetch`` does not parse and the crossing stays flagged.
An unknown pragma kind is itself a finding (typo defense — a
misspelled allowlist entry must not silently sanction anything).
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

# Pragma kinds, by checker:
#   host-fetch / host-upload / device-flow  -> hostsync.py
#   locked / racy-read / unguarded          -> lockcheck.py
#   trace-domain                            -> retrace.py
PRAGMA_KINDS = frozenset({
    "host-fetch", "host-upload", "device-flow",
    "locked", "racy-read", "unguarded",
    "trace-domain",
})

_PRAGMA_OPEN_RE = re.compile(r"#\s*audit:\s*([A-Za-z-]+)\s*\((.*)$")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One invariant violation (or registry inconsistency)."""

    checker: str    # "host-boundary" | "lowering" | "lock-discipline"
                    # | "retrace" | "comms" | "schedules" | "metrics"
    rule: str       # short kebab-case rule id, e.g. "host-fetch"
    path: str       # repo-relative or synthetic module path
    line: int       # 1-based line of the offending node (0 = module)
    message: str
    # "error" findings gate lint-invariants; "warn" is reserved for
    # advisory output (--report surfaces), never emitted by the gating
    # passes today.  Machine consumers read it from --json.
    severity: str = "error"
    # Whether a pragma of the sanctioning kind could suppress this
    # finding (the --json "pragma" field: tooling distinguishes
    # annotate-to-sanction findings from hard structural ones).
    sanctionable: bool = False

    def render(self) -> str:
        return (
            f"{self.path}:{self.line}: [{self.checker}/{self.rule}] "
            f"{self.message}"
        )


class Pragmas:
    """``# audit:`` pragmas of one source file, indexed by line."""

    def __init__(self, by_line: Dict[int, List[Tuple[str, str]]],
                 bad_lines: List[Tuple[int, str]],
                 records: Optional[List[Tuple[int, str, str]]] = None):
        self._by_line = by_line
        self.bad_lines = bad_lines  # [(line, raw kind)] unknown kinds
        # One (first_line, kind, reason) per pragma — the --report
        # surface (by_line duplicates multi-line pragmas per line).
        self.records = records if records is not None else []

    @classmethod
    def scan(cls, source: str) -> "Pragmas":
        """Collect pragmas.  A reason may wrap across CONSECUTIVE
        comment lines (``# audit: kind(start of reason`` ... ``# end)``);
        the pragma then covers every line it spans."""
        by_line: Dict[int, List[Tuple[str, str]]] = {}
        bad: List[Tuple[int, str]] = []
        records: List[Tuple[int, str, str]] = []

        def record(kind: str, reason: str, lines: List[int]) -> None:
            reason = reason.strip()
            if kind not in PRAGMA_KINDS or not reason:
                bad.append((lines[0], kind))
                return
            records.append((lines[0], kind, reason))
            for line in lines:
                by_line.setdefault(line, []).append((kind, reason))

        open_kind: Optional[str] = None
        open_reason = ""
        open_lines: List[int] = []
        try:
            toks = tokenize.generate_tokens(io.StringIO(source).readline)
            for tok in toks:
                if tok.type != tokenize.COMMENT:
                    if tok.type in (tokenize.NL, tokenize.NEWLINE,
                                    tokenize.INDENT, tokenize.DEDENT):
                        continue
                    if open_kind is not None:
                        # real code interrupted an unclosed pragma
                        bad.append((open_lines[0], open_kind))
                        open_kind = None
                    continue
                text = tok.string
                if open_kind is not None:
                    open_lines.append(tok.start[0])
                    body = text.lstrip("#").strip()
                    if body.endswith(")"):
                        record(open_kind, open_reason + " " + body[:-1],
                               open_lines)
                        open_kind = None
                    else:
                        open_reason += " " + body
                    continue
                m = _PRAGMA_OPEN_RE.search(text)
                if m is None:
                    if "audit:" in text:
                        bad.append((tok.start[0], text.strip()))
                    continue
                kind, rest = m.group(1), m.group(2)
                if rest.rstrip().endswith(")"):
                    record(kind, rest.rstrip()[:-1], [tok.start[0]])
                else:
                    open_kind, open_reason = kind, rest
                    open_lines = [tok.start[0]]
            if open_kind is not None:
                bad.append((open_lines[0], open_kind))
        except tokenize.TokenError:
            pass  # syntactically broken file: the AST parse reports it
        return cls(by_line, bad, records)

    def kinds_in_span(self, lo: int, hi: int) -> Set[str]:
        out: Set[str] = set()
        for line in range(lo, hi + 1):
            for kind, _ in self._by_line.get(line, ()):
                out.add(kind)
        return out

    def allows(self, kind: str, *spans: Tuple[int, int]) -> bool:
        """Is a ``kind`` pragma present on any of the line spans?  A
        span includes the line directly above it, so a pragma on its
        own comment line annotates the statement that follows."""
        for lo, hi in spans:
            if kind in self.kinds_in_span(max(1, lo - 1), hi):
                return True
        return False


def dotted_name(node: ast.AST) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_jit_name(name: str) -> bool:
    # `jax.jit`, aliased `from jax import jit`, or a re-export suffix.
    return name == "jit" or name.endswith("jax.jit") or name.endswith(".jit")


def jit_decorations(
    tree: ast.Module,
) -> Dict[str, Tuple[ast.FunctionDef, Optional[ast.Call]]]:
    """Module-level defs wrapped in jax.jit — directly, via
    ``functools.partial(jax.jit, ...)``, or through a ``jit`` alias —
    as ``{name: (fn, decorator Call or None for a bare decorator)}``.
    The single recognizer shared by the host-boundary lint and the
    lowering auditor's coverage gate, so the two can never disagree on
    what counts as a jitted program."""
    out: Dict[str, Tuple[ast.FunctionDef, Optional[ast.Call]]] = {}
    for node in tree.body:
        if not isinstance(node, ast.FunctionDef):
            continue
        for dec in node.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            name = dotted_name(target) or ""
            if _is_jit_name(name):
                out[node.name] = (
                    node, dec if isinstance(dec, ast.Call) else None
                )
            elif isinstance(dec, ast.Call) and name.endswith("partial"):
                if any(
                    _is_jit_name(dotted_name(a) or "") for a in dec.args
                ):
                    out[node.name] = (node, dec)
    return out


def node_span(node: ast.AST) -> Tuple[int, int]:
    lo = getattr(node, "lineno", 0)
    hi = getattr(node, "end_lineno", lo) or lo
    return lo, hi


def def_line_span(fn: ast.AST) -> Tuple[int, int]:
    """The ``def`` line (after decorators) of a FunctionDef — a pragma
    there covers the whole function body for its kind."""
    lo = getattr(fn, "lineno", 0)
    return lo, lo


def package_root() -> str:
    """Directory of the ``jax_llama_tpu`` package this module lives in."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def iter_package_sources(
    root: Optional[str] = None,
    only: Optional[Sequence[str]] = None,
) -> Iterable[Tuple[str, str]]:
    """Yield ``(path, source)`` for package modules.

    ``only`` restricts to module basenames (no ``.py``); default is
    every ``.py`` file under the package (analysis/ itself included —
    the auditor holds its own code to its rules).
    """
    root = root or package_root()
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(
            d for d in dirnames if d != "__pycache__"
        )
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            if only is not None and fname[:-3] not in only:
                continue
            path = os.path.join(dirpath, fname)
            with open(path, "r", encoding="utf-8") as f:
                yield path, f.read()


def parse_module(
    path: str, source: str, checker: str
) -> Tuple[Optional[ast.Module], List[Finding]]:
    """Parse ``source``; a syntax error becomes a finding, not a crash."""
    try:
        return ast.parse(source), []
    except SyntaxError as e:
        return None, [Finding(
            checker=checker, rule="syntax-error", path=path,
            line=e.lineno or 0, message=f"unparseable module: {e.msg}",
        )]


def pragma_findings(path: str, pragmas: Pragmas,
                    checker: str) -> List[Finding]:
    """Unknown/malformed pragmas are findings (typo defense)."""
    return [
        Finding(
            checker=checker, rule="bad-pragma", path=path, line=line,
            message=(
                f"unrecognized audit pragma {raw!r}: known kinds are "
                f"{sorted(PRAGMA_KINDS)} and a (reason) is mandatory"
            ),
        )
        for line, raw in pragmas.bad_lines
    ]
