"""CLI: ``python -m jax_llama_tpu.analysis`` — run the invariant
auditor over the package (or explicit files) and exit non-zero on any
finding.

    python -m jax_llama_tpu.analysis                  # all three checkers
    python -m jax_llama_tpu.analysis --checker host   # one checker
    python -m jax_llama_tpu.analysis --no-trace       # skip the (slower)
                                                      # abstract-trace layer
    python -m jax_llama_tpu.analysis path/to/file.py  # lint given files
                                                      # (host + lock only)
    python -m jax_llama_tpu.analysis --contracts pkg.mod
                                                      # audit an external
                                                      # REGISTRY (tests)

Exit codes: 0 clean, 1 findings, 2 usage/internal error.
"""

from __future__ import annotations

import argparse
import importlib
import json
import os
import sys
from typing import List, Optional, Sequence

# The serving-mesh contract pass lowers sharded program variants on
# forced host devices — the flag must land before ANY jax import (the
# checkers import jax lazily, so setting it here covers them all).
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

from .common import Finding
from .hostsync import HostBoundaryChecker
from .lockcheck import LockDisciplineChecker
from .lowering import LoweringAuditor


def _file_findings(paths: Sequence[str], checker: str) -> List[Finding]:
    out: List[Finding] = []
    host, lock = HostBoundaryChecker(), LockDisciplineChecker()
    for path in paths:
        with open(path, "r", encoding="utf-8") as f:
            source = f.read()
        if checker in ("all", "host"):
            out.extend(host.check_source(path, source))
        if checker in ("all", "lock"):
            out.extend(lock.check_source(path, source))
    return out


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m jax_llama_tpu.analysis",
        description="Invariant auditor for the serving stack "
                    "(host-boundary lint, lowering contracts, lock "
                    "discipline).",
    )
    parser.add_argument(
        "--checker", choices=("all", "host", "lowering", "lock"),
        default="all",
    )
    parser.add_argument(
        "--no-trace", action="store_true",
        help="lowering auditor: static (AST) layer only — skip the "
             "abstract trace of each registered program",
    )
    parser.add_argument(
        "--contracts", metavar="MODULE",
        help="import MODULE and audit its REGISTRY instead of the "
             "built-in one (fixture/testing hook)",
    )
    parser.add_argument("--json", action="store_true",
                        help="machine-readable findings")
    parser.add_argument(
        "paths", nargs="*",
        help="explicit .py files to lint (host + lock checkers only); "
             "default: the audited package modules",
    )
    args = parser.parse_args(argv)

    if args.contracts and args.no_trace:
        # An external registry has ONLY the trace layer — static-only
        # would silently audit nothing.
        print(
            "--contracts audits an external registry's lowerings; "
            "--no-trace would skip the only layer it has",
            file=sys.stderr,
        )
        return 2
    if args.paths and args.checker == "lowering":
        # The lowering auditor works from the contract registry, not
        # from source paths — "clean" here would mean "never ran".
        print(
            "--checker lowering audits the contract registry and does "
            "not take file paths (use --checker host/lock/all with "
            "paths)",
            file=sys.stderr,
        )
        return 2

    findings: List[Finding] = []
    try:
        if args.paths:
            findings.extend(_file_findings(args.paths, args.checker))
        else:
            if args.checker in ("all", "host"):
                findings.extend(HostBoundaryChecker().check_package())
            if args.checker in ("all", "lock"):
                findings.extend(LockDisciplineChecker().check_package())
        if args.checker in ("all", "lowering") and not args.paths:
            if args.contracts:
                # External registry: audit ITS programs' lowerings only
                # (the static coverage layer is about the package's own
                # modules and would mis-fire against a fixture registry).
                from .lowering import check_traces

                registry = importlib.import_module(args.contracts).REGISTRY
                findings.extend(check_traces(registry))
            else:
                findings.extend(
                    LoweringAuditor().check_package(
                        trace=not args.no_trace
                    )
                )
    except Exception as e:  # noqa: BLE001 - CLI boundary
        print(f"analysis failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2

    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    if args.json:
        print(json.dumps([vars(f) for f in findings], indent=2))
    else:
        for f in findings:
            print(f.render())
        n = len(findings)
        print(
            f"invariant audit: {n} finding{'s' if n != 1 else ''}"
            + ("" if n else " — clean")
        )
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
