"""CLI: ``python -m jax_llama_tpu.analysis`` — run the invariant
auditor over the package (or explicit files) and exit non-zero on any
finding.

    python -m jax_llama_tpu.analysis                  # all seven passes
    python -m jax_llama_tpu.analysis --checker host   # one pass
    python -m jax_llama_tpu.analysis --no-trace       # skip the compile-
                                                      # heavy layers (trace
                                                      # lowering, comms,
                                                      # the jit-cache drill)
    python -m jax_llama_tpu.analysis path/to/file.py  # lint given files
                                                      # (host + lock only)
    python -m jax_llama_tpu.analysis --contracts pkg.mod
                                                      # audit an external
                                                      # REGISTRY (tests)
    python -m jax_llama_tpu.analysis --json           # machine-readable
                                                      # findings + per-pass
                                                      # exit codes
    python -m jax_llama_tpu.analysis --report         # dump the sanctioned
                                                      # pragma surface +
                                                      # schedule models

Exit codes: 0 clean, 1 findings, 2 usage/internal error.  Under
``--json`` the findings code is per-pass stable instead of 1 — CI can
route failures without parsing:

    9  findings in more than one pass
    10 host-boundary   11 lowering   12 lock-discipline
    13 retrace         14 comms      15 schedules        16 metrics
"""

from __future__ import annotations

import argparse
import importlib
import json
import os
import sys
from typing import List, Optional, Sequence

# The serving-mesh contract + comms passes lower sharded program
# variants on forced host devices — the flag must land before ANY jax
# import (the checkers import jax lazily, so setting it here covers
# them all).
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

from .common import Finding, Pragmas, iter_package_sources
from .hostsync import HostBoundaryChecker
from .lockcheck import LockDisciplineChecker
from .lowering import LoweringAuditor

# Pass order is the exit-code order (module docstring).
PASS_CODES = {
    "host-boundary": 10, "lowering": 11, "lock-discipline": 12,
    "retrace": 13, "comms": 14, "schedules": 15, "metrics": 16,
}

_CHECKER_CHOICES = (
    "all", "host", "lowering", "lock", "retrace", "comms",
    "schedules", "metrics",
)


def _file_findings(paths: Sequence[str], checker: str) -> List[Finding]:
    out: List[Finding] = []
    host, lock = HostBoundaryChecker(), LockDisciplineChecker()
    for path in paths:
        with open(path, "r", encoding="utf-8") as f:
            source = f.read()
        if checker in ("all", "host"):
            out.extend(host.check_source(path, source))
        if checker in ("all", "lock"):
            out.extend(lock.check_source(path, source))
    return out


def _report() -> dict:
    """The sanctioned-surface dump: every audit pragma in the package
    with kind, site and justification, plus the schedule models (name,
    site, claim) the cross-thread pragmas resolve to."""
    from .schedules import MODELS, pragma_sites

    pragmas = []
    for path, source in iter_package_sources():
        for line, kind, reason in Pragmas.scan(source).records:
            pragmas.append({
                "path": path, "line": line, "kind": kind,
                "reason": reason,
            })
    sites = {(s.module, s.func) for s in pragma_sites()}
    models = []
    for mk in MODELS:
        m = mk()
        models.append({
            "model": m.name, "site": f"{m.module}.{m.func}",
            "claim": m.claim,
            "pragma_site_exists": (m.module, m.func) in sites,
        })
    by_kind: dict = {}
    for p in pragmas:
        by_kind[p["kind"]] = by_kind.get(p["kind"], 0) + 1
    return {
        "pragmas": sorted(
            pragmas, key=lambda p: (p["kind"], p["path"], p["line"])
        ),
        "pragma_counts": by_kind,
        "schedule_models": models,
    }


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m jax_llama_tpu.analysis",
        description="Invariant auditor for the serving stack "
                    "(host-boundary lint, lowering contracts, lock "
                    "discipline, retrace domains, comms budgets, "
                    "schedule models, metrics registry).",
    )
    parser.add_argument(
        "--checker", choices=_CHECKER_CHOICES, default="all",
    )
    parser.add_argument(
        "--no-trace", action="store_true",
        help="skip the compile-heavy layers: the lowering auditor's "
             "abstract-trace + mesh passes, the comms-budget compile, "
             "and the retrace jit-cache drill (static layers still "
             "run)",
    )
    parser.add_argument(
        "--contracts", metavar="MODULE",
        help="import MODULE and audit its REGISTRY instead of the "
             "built-in one (fixture/testing hook)",
    )
    parser.add_argument(
        "--json", action="store_true",
        help="machine-readable findings (checker, rule, path, line, "
             "message, severity, sanctionable) and per-pass stable "
             "exit codes",
    )
    parser.add_argument(
        "--report", action="store_true",
        help="dump the sanctioned surface (every audit pragma with "
             "its justification + the schedule-model table) as JSON "
             "and exit 0",
    )
    parser.add_argument(
        "paths", nargs="*",
        help="explicit .py files to lint (host + lock checkers only); "
             "default: the audited package modules",
    )
    args = parser.parse_args(argv)

    if args.report:
        print(json.dumps(_report(), indent=2))
        return 0
    if args.contracts and args.no_trace:
        # An external registry has ONLY the trace layer — static-only
        # would silently audit nothing.
        print(
            "--contracts audits an external registry's lowerings; "
            "--no-trace would skip the only layer it has",
            file=sys.stderr,
        )
        return 2
    if args.no_trace and args.checker == "comms":
        # The comms pass IS a compile-time audit — "clean" under
        # --no-trace would mean "never ran".
        print(
            "--checker comms has only the compiled-lowering layer; "
            "--no-trace would skip it and report a vacuous clean",
            file=sys.stderr,
        )
        return 2
    if args.contracts and args.checker == "retrace":
        # The retrace static layer audits the PACKAGE's dispatch call
        # sites and the jit-cache drill is package-batcher-driven —
        # neither can audit an external fixture registry, so "clean"
        # here would mean "never looked at your registry".
        print(
            "--checker retrace audits the package's own call sites "
            "and cache drill; it cannot audit an external --contracts "
            "registry (use --checker lowering/comms with --contracts)",
            file=sys.stderr,
        )
        return 2
    if args.paths and args.checker not in ("all", "host", "lock"):
        # Registry-driven passes audit the contract registry / the
        # package, not source paths — "clean" would mean "never ran".
        print(
            f"--checker {args.checker} audits the package registries "
            "and does not take file paths (use --checker host/lock/"
            "all with paths)",
            file=sys.stderr,
        )
        return 2

    findings: List[Finding] = []
    try:
        if args.paths:
            findings.extend(_file_findings(args.paths, args.checker))
        else:
            if args.checker in ("all", "host"):
                findings.extend(HostBoundaryChecker().check_package())
            if args.checker in ("all", "lock"):
                findings.extend(LockDisciplineChecker().check_package())
            if args.checker in ("all", "retrace"):
                from . import retrace

                findings.extend(retrace.check_static())
                if not args.no_trace and not args.contracts:
                    findings.extend(retrace.check_runtime())
            if args.checker in ("all", "schedules"):
                from . import schedules

                findings.extend(schedules.check_package())
            if args.checker in ("all", "metrics"):
                from . import metricscheck

                findings.extend(metricscheck.check_package())
        if args.checker in ("all", "lowering", "comms") and not args.paths:
            if args.contracts:
                # External registry: audit ITS programs' lowerings only
                # (the static coverage layer is about the package's own
                # modules and would mis-fire against a fixture registry).
                registry = importlib.import_module(
                    args.contracts
                ).REGISTRY
                if args.checker in ("all", "lowering"):
                    from .lowering import check_traces

                    findings.extend(check_traces(registry))
                if args.checker in ("all", "comms"):
                    from . import comms

                    findings.extend(comms.check_package(registry))
            else:
                if args.checker in ("all", "lowering"):
                    findings.extend(
                        LoweringAuditor().check_package(
                            trace=not args.no_trace
                        )
                    )
                if args.checker in ("all", "comms") and not args.no_trace:
                    from . import comms

                    findings.extend(comms.check_package())
    except Exception as e:  # noqa: BLE001 - CLI boundary
        print(f"analysis failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2

    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    if args.json:
        print(json.dumps([vars(f) for f in findings], indent=2))
    else:
        for f in findings:
            print(f.render())
        n = len(findings)
        print(
            f"invariant audit: {n} finding{'s' if n != 1 else ''}"
            + ("" if n else " — clean")
        )
    if not findings:
        return 0
    if args.json:
        passes = {f.checker for f in findings}
        if len(passes) == 1:
            return PASS_CODES.get(passes.pop(), 1)
        return 9
    return 1


if __name__ == "__main__":
    sys.exit(main())
