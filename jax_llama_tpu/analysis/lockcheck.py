"""Lock-discipline checker: guarded fields and thread confinement.

The serving stack has exactly two concurrency disciplines, and both
were previously enforced by comments alone:

  * **Lock-guarded classes** (``obs.Observability``,
    ``degrade.DegradeManager``, the ``LLMServer`` profiler state):
    every access to the registered fields must happen inside a
    ``with self.<lock>:`` block, in a method whose name ends in
    ``_locked`` (the repo's existing convention for
    called-with-lock-held helpers), or on a line / ``def`` carrying an
    ``# audit: locked(<why the lock is held>)`` pragma.
  * **Owner-thread confinement** (``ContinuousBatcher``,
    ``LLMServer``): the batcher has NO lock by design — one serving
    loop thread owns it and the jitted dispatch path stays lock-free
    (server.py module docstring).  The registry therefore declares the
    confined fields and the *foreign* methods (code that provably runs
    on HTTP-handler / watchdog threads); any access to a confined
    field from a foreign method — or through a holder attribute like
    ``server.batcher`` / the handler closure's ``server`` from another
    class — must carry ``# audit: racy-read(<why a stale/ torn view is
    acceptable>)`` or ``# audit: unguarded(<single-writer argument>)``.

The pragma is the point: every cross-thread touch of batcher state is
greppable, with its safety argument attached, and a new unannotated
one fails ``make lint-invariants`` (and tier-1) instead of waiting for
a race to reproduce.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

from .common import (
    Finding, Pragmas, def_line_span, iter_package_sources, node_span,
    parse_module,
)

CHECKER = "lock-discipline"


@dataclasses.dataclass(frozen=True)
class LockGuard:
    """Fields of ``cls`` that may only be touched under ``self.<lock>``."""

    module: str                  # module basename, e.g. "obs"
    cls: str
    lock: str                    # e.g. "_lock"
    fields: frozenset
    exempt_methods: frozenset = frozenset({"__init__"})


@dataclasses.dataclass(frozen=True)
class ThreadConfinement:
    """Fields of ``cls`` owned by one thread (the serving loop).

    ``fields``: reads AND writes are owner-only.
    ``write_fields``: only writes are owner-only (snapshot reads of
    single-writer counters/gauges are the /metrics contract).
    ``foreign_methods``: methods of ``cls`` that run on non-owner
    threads; confined-field accesses there need a pragma.
    ``holders``: expressions that alias the instance from OTHER
    classes/modules ("batcher" = ``<x>.batcher.<field>``, "server" =
    the handler closure's ``server.<field>``); accesses through them
    need a pragma anywhere they appear.
    """

    module: str
    cls: str
    owner: str                   # prose: who owns it
    fields: frozenset
    write_fields: frozenset = frozenset()
    foreign_methods: frozenset = frozenset()
    holders: frozenset = frozenset()
    exempt_methods: frozenset = frozenset({"__init__"})


# ---------------------------------------------------------------------------
# The serving stack's registry
# ---------------------------------------------------------------------------

LOCK_GUARDS: Tuple[LockGuard, ...] = (
    LockGuard(
        module="obs", cls="Observability", lock="_lock",
        fields=frozenset({
            "_seq", "dispatches", "events", "_timelines", "_by_rid",
            "hist", "hist_dispatch", "_slo_window", "_util",
            "compiles", "compiles_total", "compiles_by_program",
            "requests_finished_total", "requests_failed_total",
            "requests_cancelled_total", "requests_slo_ok_total",
            "goodput_tokens_total", "metric_snapshots",
        }),
    ),
    # Decision audit log (obs.py): serving-loop / poller / canary /
    # handler threads record while /debug/decisions snapshots — a
    # leaf lock never held while calling out.
    LockGuard(
        module="obs", cls="DecisionLog", lock="_lock",
        fields=frozenset({"_ring", "_seq", "counts"}),
    ),
    # Structured logger (obs.py): every thread that logs appends to
    # the flight-recorder tail ring; /debug/bundle snapshots it.
    LockGuard(
        module="obs", cls="StructuredLogger", lock="_lock",
        fields=frozenset({"_ring"}),
    ),
    # Static cost-model cache (obs.py): serving-loop threads of
    # DIFFERENT batchers share the one module-level instance
    # (serving._COST_MODELS) — lookups and inserts go under its lock;
    # the cost analysis itself deliberately runs outside it.
    LockGuard(
        module="obs", cls="CostModelCache", lock="_lock",
        fields=frozenset({"_cache"}),
    ),
    LockGuard(
        module="degrade", cls="DegradeManager", lock="_lock",
        fields=frozenset({"_features"}),
    ),
    LockGuard(
        module="server", cls="LLMServer", lock="_profiler_lock",
        fields=frozenset({"_profiler_dir", "_profiler_last_dir"}),
    ),
    # Overload controller (overload.py): HTTP handler threads call
    # admit() while the serving loop pushes/pops/ticks — every access
    # to the queues, EWMAs, ladder state, and counters goes under the
    # one lock (its dispatch-record ingest is called OUTSIDE the obs
    # lock, so the two locks never nest in either order).
    LockGuard(
        module="overload", cls="OverloadController", lock="_lock",
        fields=frozenset({
            "_queues", "_queued_tokens", "_inflight_tokens",
            "_prefill_tps", "_decode_tps",
            "_rung", "_rung_since", "_pressure_since", "_calm_since",
            "_slo_windows", "_wait_window",
            "transitions_total", "sheds_total",
            "refused_backlog_total", "refused_deadline_total",
            "refused_batch_total", "ttft_estimate_last_ms",
        }),
    ),
    # Replica router (router.py): HTTP handler threads (forward /
    # metrics / healthz), the health-poller thread, and the handoff
    # worker share the replica table, sticky-session map, routing
    # counters, the router-local trace ring, the request-id routing
    # record, the handoff scheduler's dedup/bounds/outcome state, and
    # the cached fleet cache view — every access goes under the one
    # lock.  The router holds no jax state.
    LockGuard(
        module="router", cls="ReplicaRouter", lock="_lock",
        fields=frozenset({
            "_replicas", "_affinity", "routed_by_policy",
            "reroutes_total", "replica_failures_total",
            "kv_handoffs_total", "_trace", "_routes",
            "affinity_stale_routes_total", "_fleet_kv",
            "cache_stale_routes_total",
            "cache_hit_depth_blocks_total",
            "_handoff_chains", "_handoff_bytes_inflight",
            "handoffs_scheduled_total", "handoffs_completed_total",
            "handoffs_aborted_total", "handoffs_skipped_total",
            "handoffs_empty_total", "handoff_blocks_total",
            "handoff_bytes_total", "_role_handoffs_pending",
            "canary_probes_total", "canary_failures_total",
            "canary_mismatches_total", "canary_oracle_repins_total",
            "_canary_oracle", "_canary_seq",
        }),
    ),
    # Elastic-fleet controller (router.py): the background control
    # loop, operator HTTP handlers (drain/rollout entries), and the
    # /metrics + /debug/fleet renderers share the counters and
    # hysteresis state — all under the controller's own leaf lock
    # (compute under it, act outside it: never held while calling the
    # router or a replica, so it never nests with router._lock in
    # either order).
    LockGuard(
        module="router", cls="FleetController", lock="_lock",
        fields=frozenset({
            "_scale_events", "sessions_migrated_total",
            "sessions_migrate_failed_total",
            "drains_total", "drains_failed_total",
            "rollouts_total", "rollbacks_total", "rollout_rung",
            "_pressure_since", "_calm_since", "_last_action_t",
            "_busy", "_last_signals", "_owned", "_rollout_oracle",
        }),
    ),
    # Per-replica health sentinel (router.py): the canary prober and
    # the health poller feed observations while handler threads read
    # /debug/fleet and /metrics — all state under the sentinel's own
    # leaf lock (never held while calling out; the router lock is
    # never taken inside).
    LockGuard(
        module="router", cls="HealthSentinel", lock="_lock",
        fields=frozenset({"_states", "anomalies_total"}),
    ),
    # Router-side global radix index (router.py): the health poller
    # writes syncs, handler threads read lookups at pick time, the
    # handoff worker applies optimistic post-migration updates — all
    # under the index's own leaf lock (lock order router -> index,
    # never inverted: the sync/lookup paths take only this lock).
    LockGuard(
        module="router", cls="RouterRadixIndex", lock="_lock",
        fields=frozenset({
            "_by_replica", "_synced", "_epoch", "_block_bytes",
            "syncs_total", "resyncs_total", "events_applied_total",
        }),
    ),
    # KV chain digest (kvcache.py): the serving loop mutates it at
    # every prefix-store content mutation while HTTP handler threads
    # read /debug/kv, /healthz kv.digest, and the stats() gauges — the
    # ONE piece of KV-cache state that is legitimately cross-thread,
    # so every field lives under its own leaf lock (taken nowhere else
    # while another lock is held).
    LockGuard(
        module="kvcache", cls="KvDigest", lock="_lock",
        fields=frozenset({
            "_entries", "_seq", "_hash", "_hbm", "_host", "_idle",
            "version", "loss_version", "depth_max",
            "publishes_total", "evictions_total", "demotions_total",
            "restores_total", "host_evictions_total", "_journal",
        }),
    ),
)

CONFINEMENTS: Tuple[ThreadConfinement, ...] = (
    ThreadConfinement(
        module="serving", cls="ContinuousBatcher",
        owner="the serving-loop thread (single owner; no lock by "
              "design — the dispatch path stays lock-free)",
        fields=frozenset({
            # block-table / per-slot decode state + their device twins
            "table", "fill", "pos", "active", "tau", "tau_lp", "keys",
            "remaining", "stop_tab", "pool", "draft_pool",
            "_dirty_rows",
            # admission machinery
            "slots", "queue", "free_blocks", "_block_refs", "_store",
            "_pf", "_restoring", "_restored_ready", "failed",
            "_accept_window",
        }),
        # /metrics snapshot-reads single-writer counters; only WRITES
        # are confined for them.
        write_fields=frozenset({
            "host_syncs_total", "state_uploads_total", "emitted_total",
            "steps_total", "decode_dispatches_total",
        }),
        # Methods documented/observed to run on HTTP-handler threads.
        foreign_methods=frozenset({
            "stats", "_window_acceptance", "acceptance_rate",
            "kv_debug_json", "_kv_summary",
            # Ctor-stable config snapshot for /debug/bundle — touches
            # no confined field by construction.
            "describe",
        }),
        holders=frozenset({"batcher"}),
    ),
    ThreadConfinement(
        module="server", cls="LLMServer",
        owner="the serving-loop thread",
        fields=frozenset({
            "_active", "_pending_success", "_recovery_times",
        }),
        write_fields=frozenset({
            "batcher", "ttft_ms_ewma", "itl_ms_ewma",
            "recoveries_total",
            "quarantine_rebuilds_total", "probe_rebuilds_total",
            "nonfinite_failed_total", "watchdog_stalls_total",
            "_stalled", "_heartbeat", "canary_requests_total",
            "_last_flight_t",
        }),
        foreign_methods=frozenset({
            "_watchdog", "_health", "_metrics_text",
            "_metrics_scalars",
            "_handle_profiler", "_retry_after_s", "begin_drain",
            "wait_drained", "draining", "address", "stop", "start",
            # The handoff scheduler's control path: queues work for
            # the loop thread (thread-safe queue) and waits on the
            # call's own event — no confined field is touched.
            "call_on_loop",
            # Flight-recorder artifact assembly (handler threads):
            # snapshot reads through the same racy-read surfaces
            # /metrics and /healthz already use.
            "bundle_json", "_config_snapshot",
        }),
        holders=frozenset({"server"}),
    ),
)


# ---------------------------------------------------------------------------
# Checker
# ---------------------------------------------------------------------------

def _self_attr(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


class _WithLockTracker(ast.NodeVisitor):
    """Visit a method body tracking ``with self.<lock>:`` nesting and
    reporting guarded-field accesses outside it."""

    def __init__(self, guard: LockGuard, path: str, method: str,
                 fn: ast.FunctionDef, pragmas: Pragmas,
                 findings: List[Finding]):
        self.guard = guard
        self.path = path
        self.method = method
        self.fn = fn
        self.pragmas = pragmas
        self.findings = findings
        self.lock_depth = 0
        self._stmt_stack: List[ast.stmt] = []

    def visit(self, node: ast.AST):
        if isinstance(node, ast.stmt):
            self._stmt_stack.append(node)
            try:
                return super().visit(node)
            finally:
                self._stmt_stack.pop()
        return super().visit(node)

    def _holds_lock(self, item: ast.withitem) -> bool:
        return _self_attr(item.context_expr) == self.guard.lock

    def visit_With(self, node: ast.With):
        held = any(self._holds_lock(i) for i in node.items)
        if held:
            self.lock_depth += 1
        try:
            self.generic_visit(node)
        finally:
            if held:
                self.lock_depth -= 1

    def visit_FunctionDef(self, node: ast.FunctionDef):
        if node is self.fn:
            self.generic_visit(node)
        # nested defs inherit the surrounding analysis conservatively:
        # skip (they are closures invoked who-knows-where; accesses in
        # them would need their own pragma anyway)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Attribute(self, node: ast.Attribute):
        attr = _self_attr(node)
        if (
            attr in self.guard.fields
            and self.lock_depth == 0
            and not self.method.endswith("_locked")
        ):
            spans = [node_span(node), def_line_span(self.fn)]
            if self._stmt_stack:
                spans.append(node_span(self._stmt_stack[-1]))
            if not (
                self.pragmas.allows("locked", *spans)
                or self.pragmas.allows("unguarded", *spans)
            ):
                self.findings.append(Finding(
                    checker=CHECKER, rule="unlocked-access",
                    sanctionable=True,
                    path=self.path, line=node.lineno,
                    message=(
                        f"{self.guard.cls}.{self.method} touches "
                        f"self.{attr} outside `with self."
                        f"{self.guard.lock}` (annotate with # audit: "
                        "locked(...) if the caller holds it, or "
                        "rename the method *_locked)"
                    ),
                ))
        self.generic_visit(node)


class LockDisciplineChecker:
    """Registry-driven lock/confinement audit (module docstring)."""

    def __init__(
        self,
        lock_guards: Sequence[LockGuard] = LOCK_GUARDS,
        confinements: Sequence[ThreadConfinement] = CONFINEMENTS,
    ):
        self.lock_guards = tuple(lock_guards)
        self.confinements = tuple(confinements)

    # -- per-source ----------------------------------------------------------

    def check_source(self, path: str, source: str,
                     module: Optional[str] = None) -> List[Finding]:
        module = module or path.rsplit("/", 1)[-1].replace(".py", "")
        tree, findings = parse_module(path, source, CHECKER)
        if tree is None:
            return findings
        pragmas = Pragmas.scan(source)

        classes: Dict[str, ast.ClassDef] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                classes[node.name] = node

        for guard in self.lock_guards:
            if guard.module != module or guard.cls not in classes:
                continue
            self._check_lock_guard(
                guard, path, classes[guard.cls], pragmas, findings
            )
        for conf in self.confinements:
            if conf.module == module and conf.cls in classes:
                self._check_confinement_intra(
                    conf, path, classes[conf.cls], pragmas, findings
                )
        # Holder accesses apply to EVERY audited module (the handler
        # closure's ``server`` lives inside server.py itself; the
        # batcher holder is reached from server.py).
        self._check_holders(path, tree, pragmas, findings, module)
        return findings

    def _check_lock_guard(self, guard: LockGuard, path: str,
                          cls: ast.ClassDef, pragmas: Pragmas,
                          findings: List[Finding]) -> None:
        for node in cls.body:
            if not isinstance(node, ast.FunctionDef):
                continue
            if node.name in guard.exempt_methods:
                continue
            _WithLockTracker(
                guard, path, node.name, node, pragmas, findings
            ).visit(node)

    def _check_confinement_intra(
        self, conf: ThreadConfinement, path: str, cls: ast.ClassDef,
        pragmas: Pragmas, findings: List[Finding],
    ) -> None:
        declared_missing = conf.foreign_methods - {
            n.name for n in cls.body if isinstance(n, ast.FunctionDef)
        }
        for name in sorted(declared_missing):
            findings.append(Finding(
                checker=CHECKER, rule="stale-registry", path=path,
                line=cls.lineno,
                message=(
                    f"{conf.cls} registry lists foreign method "
                    f"{name!r} which no longer exists"
                ),
            ))
        for node in cls.body:
            if not isinstance(node, ast.FunctionDef):
                continue
            foreign = node.name in conf.foreign_methods
            if not foreign:
                continue
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Attribute):
                    continue
                attr = _self_attr(sub)
                if attr is None:
                    continue
                is_write = isinstance(sub.ctx, (ast.Store, ast.Del))
                relevant = attr in conf.fields or (
                    attr in conf.write_fields and is_write
                )
                if not relevant:
                    continue
                spans = (node_span(sub), def_line_span(node),
                         self._stmt_span(node, sub))
                if pragmas.allows("racy-read", *spans) or \
                        pragmas.allows("unguarded", *spans):
                    continue
                findings.append(Finding(
                    checker=CHECKER, rule="foreign-thread-access",
                    sanctionable=True,
                    path=path, line=sub.lineno,
                    message=(
                        f"{conf.cls}.{node.name} (runs off the owner "
                        f"thread) {'writes' if is_write else 'reads'} "
                        f"self.{attr}, which is confined to "
                        f"{conf.owner} (annotate # audit: "
                        "racy-read(...) / unguarded(...) with the "
                        "safety argument, or move it onto the loop)"
                    ),
                ))

    def _check_holders(self, path: str, tree: ast.Module,
                       pragmas: Pragmas, findings: List[Finding],
                       module: str) -> None:
        # find the enclosing statement for span-level pragmas
        parents: Dict[ast.AST, ast.stmt] = {}

        def index(node: ast.AST, stmt: Optional[ast.stmt]):
            if isinstance(node, ast.stmt):
                stmt = node
            for child in ast.iter_child_nodes(node):
                if stmt is not None:
                    parents[child] = stmt
                index(child, stmt)

        index(tree, None)

        for conf in self.confinements:
            if not conf.holders:
                continue
            confined = conf.fields | conf.write_fields
            for node in ast.walk(tree):
                if not isinstance(node, ast.Attribute):
                    continue
                if node.attr not in confined:
                    continue
                base = node.value
                via_holder = (
                    isinstance(base, ast.Name)
                    and base.id in conf.holders
                ) or (
                    isinstance(base, ast.Attribute)
                    and base.attr in conf.holders
                )
                if not via_holder:
                    continue
                is_write = isinstance(node.ctx, (ast.Store, ast.Del))
                if node.attr in conf.write_fields and not is_write:
                    continue
                stmt = parents.get(node)
                spans = [node_span(node)]
                if stmt is not None:
                    spans.append(node_span(stmt))
                if pragmas.allows("racy-read", *spans) or \
                        pragmas.allows("unguarded", *spans):
                    continue
                holder_name = (
                    base.id if isinstance(base, ast.Name) else base.attr
                )
                findings.append(Finding(
                    checker=CHECKER, rule="foreign-thread-access",
                    sanctionable=True,
                    path=path, line=node.lineno,
                    message=(
                        f"access to {conf.cls} state "
                        f"`{holder_name}.{node.attr}`: the field is "
                        f"confined to {conf.owner} (annotate "
                        "# audit: racy-read(...) or route through "
                        "the owner)"
                    ),
                ))

    @staticmethod
    def _stmt_span(fn: ast.FunctionDef, node: ast.AST) -> Tuple[int, int]:
        """Span of the smallest simple statement in ``fn`` containing
        ``node`` — the unit one pragma comment covers."""
        target = getattr(node, "lineno", 0)
        best = node_span(node)
        best_width = None
        for stmt in ast.walk(fn):
            if not isinstance(stmt, ast.stmt) or isinstance(
                stmt, (ast.If, ast.For, ast.While, ast.With, ast.Try,
                       ast.FunctionDef, ast.AsyncFunctionDef,
                       ast.ClassDef)
            ):
                continue
            lo, hi = node_span(stmt)
            if lo <= target <= hi and (
                best_width is None or hi - lo < best_width
            ):
                best, best_width = (lo, hi), hi - lo
        return best

    # -- package -------------------------------------------------------------

    def check_package(self) -> List[Finding]:
        modules = sorted({
            g.module for g in self.lock_guards
        } | {c.module for c in self.confinements})
        out: List[Finding] = []
        for path, source in iter_package_sources(only=modules):
            out.extend(self.check_source(path, source))
        return out
