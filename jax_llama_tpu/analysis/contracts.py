"""Declarative lowering contracts for the serving stack's jitted programs.

``tests/test_tpu_compiled.py`` pins two programs' lowerings by hand
(no-full-pool-copy, donated carries).  This registry generalizes those
pins: EVERY jitted program the ``ContinuousBatcher`` dispatches declares

  * ``donated``      — the argnames the jit decorator must donate
                       (a dropped ``donate_argnames`` entry silently
                       doubles KV HBM and re-uploads state per dispatch);
  * ``max_live_outputs`` / ``max_fetch_bytes_per_row``
                     — the host-fetch surface: how many outputs are NOT
                       aliased onto donated inputs, and how many bytes
                       per batch row they may total at the example shape
                       (the "1 packed fetch" contract; a [B, V] logits
                       leak blows the per-row budget immediately);
  * ``forbid_pool_shapes``
                     — no copy-class jaxpr equation (broadcast, gather,
                       dynamic-slice, concat, transpose, convert, ...)
                       may produce a full-pool-sized or one-plane-sized
                       array (the regression class the TPU pins catch in
                       optimized HLO; here caught abstractly on any
                       backend);
  * ``build``        — a callable producing concrete example arguments
                       at a tiny geometry, so the auditor can
                       ``.lower()`` the program on CPU in seconds.

New programs MUST join this registry before the batcher dispatches
them — the auditor's coverage check fails on any jit-decorated
module-level function in serving.py / kvcache.py / ops/kernels.py
without a contract (allowlist: :data:`NON_DISPATCHED`).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

# Example geometry: small enough that tracing all programs on CPU costs
# seconds, real enough that every shape class (pool, plane, state row,
# packed fetch) is present.
_DIM, _LAYERS, _HEADS, _KVH = 64, 2, 4, 2
_VOCAB, _MAXLEN, _BLOCK, _SLOTS = 128, 64, 16, 2


@dataclasses.dataclass(frozen=True)
class CommsBudget:
    """Per-program collective-communication budget, declared at the
    contract's tiny mesh example geometry (data=2 x tensor=2 over 4
    forced host devices) and checked by :mod:`.comms` against the
    COMPILED sharded lowering (GSPMD inserts collectives at partition
    time — they exist nowhere earlier) plus the traced jaxpr (explicit
    ``psum``/``all_gather``-class primitives from shard_map code).

      * ``max_count``: collective kind -> max instruction count in the
        compiled module (a kind absent from the dict allows ZERO).
        Counts are per compiled-module text — an op inside a scan body
        counts once but executes per iteration, which is exactly the
        per-dispatch cost class the budget bounds.
      * ``max_bytes``: result bytes of the largest single collective.
        The legit per-layer tensor-axis reductions the matmul sharding
        implies are activation-sized; a pool-sized reshard is 1-2
        orders larger at any geometry, so the byte bar separates the
        two robustly even as XLA's exact op counts drift.

    Full-pool / one-plane shaped collective RESULTS are a hard finding
    regardless of budget (not declarable here on purpose)."""

    max_count: Dict[str, int]
    max_bytes: int


@dataclasses.dataclass(frozen=True)
class ProgramContract:
    name: str
    module: str                       # import path of the owning module
    donated: Tuple[str, ...]          # argnames (or argnums' param names)
    max_live_outputs: int             # outputs not aliased to donations
    max_fetch_bytes_per_row: int      # live-output bytes / batch rows
    forbid_pool_shapes: bool = True
    build: Optional[Callable[[], Tuple[Tuple[str, ...], tuple, dict]]] = None
    # build() -> (positional argnames, positional args, static kwargs)
    # Forbidden-shape derivation: default scans the example args for
    # BlockPool-shaped leaves (pool_shapes).  A program whose pool
    # state arrives in another form (e.g. _adopt_jit's bare array
    # tuple) declares its own — the rule lives with the contract, so a
    # new pool carrier cannot silently derive an empty shape set and
    # pass the full-pool-copy check vacuously.
    forbidden_shapes: Optional[Callable[[tuple], List[Tuple[int, ...]]]] = None
    # Serving-mesh variant (parallel/serve_mesh.py): ``mesh_build``
    # produces the SAME program's example arguments placed on a small
    # forced-host-device serving mesh (sharded pool + row-sharded
    # state + sharded params, mesh static kwarg set).  The auditor's
    # mesh pass then proves donated-leaf aliasing still RESOLVES under
    # the sharded lowering, and — via ``mesh_aliases`` (donated
    # argname -> output position in the program's return tuple) —
    # executes the program once and asserts each donated input's
    # sharding equals its carried output's (sharding drift between a
    # donated input and its output is exactly how "donated" state
    # silently starts copying/resharding per dispatch on a mesh).
    mesh_build: Optional[
        Callable[[], Tuple[Tuple[str, ...], tuple, dict]]
    ] = None
    mesh_aliases: Optional[Dict[str, int]] = None
    # Jit-cache-key budget (analysis/retrace.py): the maximum number of
    # NEW executable-cache entries ONE serving configuration may create
    # for this program across its whole admission surface — the product
    # of the bounded domains its static args and admission-shaped dims
    # may take (pow2 buckets are O(log), bools are 2, ctor-stable args
    # are 1).  Checked two ways: the static pass proves every cache-key
    # value at every dispatch call site flows through a bounded-domain
    # constructor, and the runtime drill sweeps the admission surface
    # asserting ``serving.jit_cache_entries()`` stays within this
    # budget.  REQUIRED: a registered program without one is a finding.
    max_cache_keys: Optional[int] = None
    # Collective-comms budget (analysis/comms.py) for the SHARDED
    # lowering; required whenever ``mesh_build`` is set.
    comms: Optional[CommsBudget] = None


# -- example-argument factories ---------------------------------------------

_CACHE: Dict[str, Any] = {}


def _tiny_config_params():
    if "cfg" not in _CACHE:
        import jax

        import jax_llama_tpu as jlt

        cfg = jlt.get_config(
            "tiny", dim=_DIM, n_layers=_LAYERS, n_heads=_HEADS,
            n_kv_heads=_KVH, vocab_size=_VOCAB, max_seq_len=_MAXLEN,
            multiple_of=16,
        )
        _CACHE["cfg"] = cfg
        _CACHE["params"] = jlt.init_params(jax.random.PRNGKey(0), cfg)
    return _CACHE["cfg"], _CACHE["params"]


def _plain_batcher():
    if "plain" not in _CACHE:
        import numpy as np

        from ..serving import ContinuousBatcher

        cfg, params = _tiny_config_params()
        cb = ContinuousBatcher(
            params, cfg, n_slots=_SLOTS, max_len=_MAXLEN,
            block_size=_BLOCK, decode_chunk=2,
        )
        rng = np.random.RandomState(0)
        for _ in range(_SLOTS):
            cb.submit(list(rng.randint(1, _VOCAB, 20)), max_new_tokens=4)
        cb.step()
        _CACHE["plain"] = cb
    return _CACHE["plain"]


def _fused_batcher():
    if "fused" not in _CACHE:
        import numpy as np

        from ..serving import ContinuousBatcher

        cfg, params = _tiny_config_params()
        cb = ContinuousBatcher(
            params, cfg, n_slots=_SLOTS, max_len=_MAXLEN,
            block_size=_BLOCK, decode_chunk=2, prefill_budget=_BLOCK,
        )
        rng = np.random.RandomState(1)
        cb.submit(list(rng.randint(1, _VOCAB, 20)), max_new_tokens=8)
        cb.step()  # cold classic admission
        cb.step()
        cb.submit(list(rng.randint(1, _VOCAB, 40)), max_new_tokens=8)
        cb.step()  # fused prefill starts (40-token suffix > one chunk)
        assert cb._pf is not None, "fused example failed to enter prefill"
        _CACHE["fused"] = cb
    return _CACHE["fused"]


def _spec_batcher():
    if "spec" not in _CACHE:
        import numpy as np

        from ..serving import ContinuousBatcher

        cfg, params = _tiny_config_params()
        cb = ContinuousBatcher(
            params, cfg, n_slots=_SLOTS, max_len=_MAXLEN,
            block_size=_BLOCK, spec_rounds=2, draft_params=params,
            draft_config=cfg, n_draft=2,
        )
        rng = np.random.RandomState(2)
        for _ in range(_SLOTS):
            cb.submit(list(rng.randint(1, _VOCAB, 20)),
                      max_new_tokens=8)
        cb.step()
        _CACHE["spec"] = cb
    return _CACHE["spec"]


def _serve_mesh4():
    """A data=2 x tensor=2 serving mesh over 4 of the forced host
    devices (conftest / the analysis CLI force 8): tensor=2 divides
    the tiny config's 2 KV heads, data=2 divides the 2 example slots."""
    if "mesh" not in _CACHE:
        import jax

        from ..parallel.serve_mesh import ServeMeshSpec, build_serve_mesh

        if len(jax.devices()) < 4:
            raise RuntimeError(
                "serving-mesh contract pass needs >= 4 host devices "
                "(set XLA_FLAGS=--xla_force_host_platform_device_count=8)"
            )
        _CACHE["mesh"] = build_serve_mesh(
            ServeMeshSpec(data=2, tensor=2),
            devices=jax.devices()[:4],
        )
    return _CACHE["mesh"]


def _mesh_params():
    if "params_mesh" not in _CACHE:
        from ..parallel.partition import shard_params

        cfg, params = _tiny_config_params()
        _CACHE["params_mesh"] = shard_params(
            params, _serve_mesh4(), cfg
        )
    return _CACHE["params_mesh"]


def _plain_batcher_mesh():
    if "plain_mesh" not in _CACHE:
        import numpy as np

        from ..serving import ContinuousBatcher

        cfg, _ = _tiny_config_params()
        cb = ContinuousBatcher(
            _mesh_params(), cfg, n_slots=_SLOTS, max_len=_MAXLEN,
            block_size=_BLOCK, decode_chunk=2, mesh=_serve_mesh4(),
        )
        assert cb._mesh_placed, "mesh example fell outside placement"
        rng = np.random.RandomState(0)
        for _ in range(_SLOTS):
            cb.submit(list(rng.randint(1, _VOCAB, 20)), max_new_tokens=4)
        cb.step()
        _CACHE["plain_mesh"] = cb
    return _CACHE["plain_mesh"]


def _fused_batcher_mesh():
    if "fused_mesh" not in _CACHE:
        import numpy as np

        from ..serving import ContinuousBatcher

        cfg, _ = _tiny_config_params()
        cb = ContinuousBatcher(
            _mesh_params(), cfg, n_slots=_SLOTS, max_len=_MAXLEN,
            block_size=_BLOCK, decode_chunk=2, prefill_budget=_BLOCK,
            mesh=_serve_mesh4(),
        )
        rng = np.random.RandomState(1)
        cb.submit(list(rng.randint(1, _VOCAB, 20)), max_new_tokens=8)
        cb.step()
        cb.step()
        cb.submit(list(rng.randint(1, _VOCAB, 40)), max_new_tokens=8)
        cb.step()
        assert cb._pf is not None, "fused mesh example missed prefill"
        _CACHE["fused_mesh"] = cb
    return _CACHE["fused_mesh"]


def clear_examples() -> None:
    """Drop the cached example batchers (tests)."""
    _CACHE.clear()


_STATE_NAMES = (
    "table", "n_alloc", "fill", "tau", "tau_lp", "pos", "active",
    "remaining", "stops", "keys", "temperature", "top_p", "top_k",
)


def _chunk_state(cb) -> tuple:
    return (
        cb.d_table, cb.d_n_alloc, cb.d_fill, cb.tau, cb.d_tau_lp,
        cb.d_pos, cb.d_active, cb.d_remaining, cb.d_stops, cb.keys,
        cb.d_temps, cb.d_top_ps, cb.d_top_ks,
    )


def _build_paged_decode_step():
    import jax.numpy as jnp

    cb = _plain_batcher()
    names = ("params", "pool", "table", "n_alloc", "fill", "tau",
             "pos", "active", "keys", "temperature", "top_p", "top_k")
    args = (
        cb.params, cb.pool, jnp.asarray(cb.table),
        jnp.asarray(cb.n_alloc), jnp.asarray(cb.fill), cb.tau,
        jnp.asarray(cb.pos), jnp.asarray(cb.active), cb.keys,
        jnp.asarray(cb.temp_arr), jnp.asarray(cb.top_p_arr),
        jnp.asarray(cb.top_k_arr),
    )
    kwargs = dict(config=cb.config, all_greedy=True, mesh=None,
                  allow_kernel=True, with_logprobs=False)
    return names, args, kwargs


def _build_paged_decode_chunk():
    cb = _plain_batcher()
    names = ("params", "pool") + _STATE_NAMES
    args = (cb.params, cb.pool) + _chunk_state(cb)
    kwargs = dict(config=cb.config, n_iter=2, all_greedy=True,
                  mesh=None, allow_kernel=True, with_logprobs=False)
    return names, args, kwargs


def _build_fused_chunk():
    cb = _fused_batcher()
    pf = cb._pf
    names = ("params", "pool") + _STATE_NAMES + (
        "pf_row", "pf_toks", "pf_len", "pf_base", "pf_off", "pf_key",
    )
    args = (cb.params, cb.pool) + _chunk_state(cb) + (
        pf.d_row, pf.d_toks, pf.d_len, pf.d_base, pf.d_off, pf.d_key,
    )
    kwargs = dict(config=cb.config, n_iter=2, pf_chunk=pf.chunk,
                  all_greedy=True, mesh=None, allow_kernel=True,
                  with_logprobs=False)
    return names, args, kwargs


def _build_paged_decode_chunk_mesh():
    cb = _plain_batcher_mesh()
    names = ("params", "pool") + _STATE_NAMES
    args = (cb.params, cb.pool) + _chunk_state(cb)
    kwargs = dict(config=cb.config, n_iter=2, all_greedy=True,
                  mesh=cb.mesh, allow_kernel=True, with_logprobs=False,
                  placed=True)
    return names, args, kwargs


def _build_fused_chunk_mesh():
    cb = _fused_batcher_mesh()
    pf = cb._pf
    names = ("params", "pool") + _STATE_NAMES + (
        "pf_row", "pf_toks", "pf_len", "pf_base", "pf_off", "pf_key",
    )
    args = (cb.params, cb.pool) + _chunk_state(cb) + (
        pf.d_row, pf.d_toks, pf.d_len, pf.d_base, pf.d_off, pf.d_key,
    )
    kwargs = dict(config=cb.config, n_iter=2, pf_chunk=pf.chunk,
                  all_greedy=True, mesh=cb.mesh, allow_kernel=True,
                  with_logprobs=False, placed=True)
    return names, args, kwargs


# Donated argname -> position in the chunk programs' return tuple
# (packed, tau, tau_lp, fill, pos, active, remaining, keys, pool[,
# pf_off]) — the mesh pass's sharding-stability map.
_CHUNK_ALIASES = {
    "tau": 1, "tau_lp": 2, "fill": 3, "pos": 4, "active": 5,
    "remaining": 6, "keys": 7, "pool": 8,
}


def _build_spec_round():
    import jax.numpy as jnp

    cb = _spec_batcher()
    names = ("t_params", "d_params", "t_pool", "d_pool", "table",
             "n_alloc", "fill", "tau", "pos", "active", "keys",
             "temperature", "top_p", "top_k")
    args = (
        cb.params, cb.draft_params, cb.pool, cb.draft_pool,
        jnp.asarray(cb.table), jnp.asarray(cb.n_alloc),
        jnp.asarray(cb.fill), cb.tau, jnp.asarray(cb.pos),
        jnp.asarray(cb.active), cb.keys, jnp.asarray(cb.temp_arr),
        jnp.asarray(cb.top_p_arr), jnp.asarray(cb.top_k_arr),
    )
    kwargs = dict(t_config=cb.config, d_config=cb.draft_config,
                  n_draft=cb.n_draft, all_greedy=True, use_kernel=True,
                  mesh=None, with_logprobs=False)
    return names, args, kwargs


def _build_spec_rounds_chunk():
    cb = _spec_batcher()
    names = ("t_params", "d_params", "t_pool", "d_pool") + _STATE_NAMES
    args = (cb.params, cb.draft_params, cb.pool,
            cb.draft_pool) + _chunk_state(cb)
    kwargs = dict(t_config=cb.config, d_config=cb.draft_config,
                  n_draft=cb.n_draft, n_rounds=2, all_greedy=True,
                  use_kernel=True, mesh=None, with_logprobs=False)
    return names, args, kwargs


def _build_paged_insert():
    import jax.numpy as jnp
    import numpy as np

    cb = _plain_batcher()
    k, P = 2, 2 * _BLOCK
    rng = np.random.RandomState(3)
    names = ("params", "pool", "block_ids", "prompt_tokens",
             "prompt_mask", "keys", "temperature", "top_p", "top_k")
    args = (
        cb.params, cb.pool,
        jnp.asarray(np.full((k, P // _BLOCK), cb.n_blocks, np.int32)),
        jnp.asarray(rng.randint(1, _VOCAB, (k, P)).astype(np.int32)),
        jnp.asarray(np.ones((k, P), bool)),
        jnp.asarray(np.zeros((k, 2), np.uint32)),
        jnp.asarray(np.zeros((k,), np.float32)),
        jnp.asarray(np.ones((k,), np.float32)),
        jnp.asarray(np.zeros((k,), np.int32)),
    )
    kwargs = dict(config=cb.config, prefill_chunk=None, mesh=None,
                  with_logprobs=False)
    return names, args, kwargs


def _build_paged_suffix_insert():
    import jax.numpy as jnp
    import numpy as np

    cb = _plain_batcher()
    k, T = 2, _BLOCK
    rng = np.random.RandomState(4)
    names = ("params", "pool", "table_rows", "n_alloc", "fill0",
             "suffix_tokens", "suffix_mask", "keys", "temperature",
             "top_p", "top_k")
    args = (
        cb.params, cb.pool,
        jnp.asarray(np.full((k, cb.blocks_per_slot), cb.n_blocks,
                            np.int32)),
        jnp.asarray(np.full((k,), 2, np.int32)),
        jnp.asarray(np.full((k,), _BLOCK, np.int32)),
        jnp.asarray(rng.randint(1, _VOCAB, (k, T)).astype(np.int32)),
        jnp.asarray(np.ones((k, T), bool)),
        jnp.asarray(np.zeros((k, 2), np.uint32)),
        jnp.asarray(np.zeros((k,), np.float32)),
        jnp.asarray(np.ones((k,), np.float32)),
        jnp.asarray(np.zeros((k,), np.int32)),
    )
    kwargs = dict(config=cb.config, prefill_chunk=None, mesh=None,
                  with_logprobs=False)
    return names, args, kwargs


def _build_scatter_rows():
    import jax.numpy as jnp
    import numpy as np

    cb = _plain_batcher()
    state = (cb.d_table, cb.d_n_alloc, cb.d_fill, cb.d_pos,
             cb.d_active, cb.d_temps, cb.d_top_ps, cb.d_top_ks,
             cb.d_remaining, cb.d_stops)
    rows = tuple(
        jnp.asarray(np.zeros((1,) + tuple(a.shape[1:]),
                             np.asarray(a).dtype))
        for a in state
    )
    idx = jnp.asarray(np.zeros((1,), np.int32))
    return ("state", "idx", "rows"), (state, idx, rows), {}


def _build_release_blocks():
    import jax.numpy as jnp
    import numpy as np

    cb = _plain_batcher()
    return (
        ("pos", "block_ids"),
        (cb.pool.pos, jnp.asarray(np.zeros((2,), np.int32))),
        {},
    )


def _build_splash_prefill():
    import jax.numpy as jnp
    import numpy as np

    # Splash's own lane geometry, not the tiny model's: the kernel
    # requires head_dim / q_len / kv_len % 128 == 0 (splash_eligible
    # gates real dispatches the same way), so the example is the
    # smallest legal splash shape.  interpret=True pins the CPU-
    # lowerable variant — the kernel body is identical on TPU.
    rng = np.random.RandomState(5)
    B, T, S, H, KVH, D = 1, 128, 128, 2, 1, 128
    names = ("q", "k", "v")
    args = (
        jnp.asarray(rng.randn(B, T, H, D).astype(np.float32)),
        jnp.asarray(rng.randn(B, S, KVH, D).astype(np.float32)),
        jnp.asarray(rng.randn(B, S, KVH, D).astype(np.float32)),
    )
    kwargs = dict(chunk_offset=0, interpret=True)
    return names, args, kwargs


def _build_stock_paged_decode():
    import jax.numpy as jnp
    import numpy as np

    # Tiny-pool geometry (mirrors the registry's example scale); the
    # stock kernel body has no lane-alignment requirement in interpret
    # mode, so the pool example matches the serving tests' shapes.
    rng = np.random.RandomState(6)
    B, H, KVH, D = 2, 4, 2, 16
    L, NB, BLK, MB = _LAYERS, 8, _BLOCK, 4
    names = ("q", "k_new", "v_new", "k_pool", "v_pool", "table",
             "q_pos", "layer")
    args = (
        jnp.asarray(rng.randn(B, 1, H, D).astype(np.float32)),
        jnp.asarray(rng.randn(B, 1, KVH, D).astype(np.float32)),
        jnp.asarray(rng.randn(B, 1, KVH, D).astype(np.float32)),
        jnp.asarray(rng.randn(L, KVH, NB, BLK, D).astype(np.float32)),
        jnp.asarray(rng.randn(L, KVH, NB, BLK, D).astype(np.float32)),
        jnp.asarray(
            np.array([[0, 1, NB, NB], [2, NB, NB, NB]], np.int32)
        ),
        jnp.asarray(np.array([17, 9], np.int32)),
        jnp.asarray(np.int32(1)),
    )
    kwargs = dict(interpret=True)
    return names, args, kwargs


def _build_adopt_jit():
    import numpy as np

    from ..kvcache import _pool_names, stage_restore

    cb = _plain_batcher()
    pool = cb.pool
    names = _pool_names(pool)
    slab = {
        n: (np.zeros((pool.pos.shape[1],), np.int32) if n == "pos"
            else np.zeros(
                (pool.k.shape[0], pool.k.shape[1], pool.k.shape[3],
                 pool.k.shape[4]), np.asarray(pool.k).dtype))
        for n in names
    }
    staged = stage_restore([slab], [0], cb.n_blocks)
    arrays = tuple(getattr(pool, n) for n in names)
    return (
        ("pool_arrays", "ids", "staged"),
        (arrays, staged["ids"], tuple(staged[n] for n in names)),
        {},
    )


# -- the registry ------------------------------------------------------------

_CHUNK_DONATED = (
    "pool", "fill", "tau", "tau_lp", "pos", "active", "remaining",
    "keys",
)

# Comms budgets (see CommsBudget): counts measured on this image's XLA
# at the tiny data=2 x tensor=2 geometry after the gathered-view /
# pool-plane sharding pins landed, with ~50% headroom.  The all-reduce
# populations are the per-layer tensor-axis reductions the Megatron
# matmul sharding implies (attn out + mlp down per layer, per scan
# iteration) plus scalar control reductions; the only all-gathers are
# slab-/row-/[1, V]-logits-sized.  ``max_bytes`` sits an order of
# magnitude below the full-pool byte size at the same geometry (64 KiB)
# so a pool-scale reshard can never hide inside the count budget.
_DECODE_CHUNK_COMMS = CommsBudget(
    max_count={
        "all-gather": 8, "all-reduce": 36, "collective-permute": 12,
        "reduce-scatter": 4,
    },
    max_bytes=4096,
)
_FUSED_CHUNK_COMMS = CommsBudget(
    max_count={
        "all-gather": 24, "all-reduce": 280, "collective-permute": 24,
        "reduce-scatter": 8, "all-to-all": 4,
    },
    max_bytes=16384,
)

REGISTRY: Dict[str, ProgramContract] = {
    c.name: c for c in (
        ProgramContract(
            name="_paged_decode_step", module="jax_llama_tpu.serving",
            donated=("pool",), max_live_outputs=2,
            max_fetch_bytes_per_row=16,
            build=_build_paged_decode_step,
            # all_greedy (bool); config/mesh/allow_kernel/with_logprobs
            # are ctor-stable per batcher.
            max_cache_keys=4,
        ),
        ProgramContract(
            name="_paged_decode_chunk", module="jax_llama_tpu.serving",
            donated=_CHUNK_DONATED, max_live_outputs=1,
            max_fetch_bytes_per_row=16,
            build=_build_paged_decode_chunk,
            mesh_build=_build_paged_decode_chunk_mesh,
            mesh_aliases=dict(_CHUNK_ALIASES),
            # n_iter pow2 <= decode_chunk (log2 K + 1 <= 6) x all_greedy
            # (2) x stop-table width pow2 regrowth (O(log max stops)).
            max_cache_keys=24,
            comms=_DECODE_CHUNK_COMMS,
        ),
        ProgramContract(
            name="_fused_chunk", module="jax_llama_tpu.serving",
            donated=_CHUNK_DONATED + ("pf_off",), max_live_outputs=1,
            max_fetch_bytes_per_row=16,
            build=_build_fused_chunk,
            mesh_build=_build_fused_chunk_mesh,
            mesh_aliases=dict(_CHUNK_ALIASES, pf_off=9),
            # n_iter pow2 (<= 6) x pf_chunk pow2-down from the budget
            # flag (<= 5) x pf_toks buffer in pow2 chunk counts
            # (<= 5) x all_greedy (2) — the admission sweep touches a
            # sparse corner of that product, and every axis is O(log).
            max_cache_keys=48,
            comms=_FUSED_CHUNK_COMMS,
        ),
        ProgramContract(
            name="_spec_round", module="jax_llama_tpu.serving",
            donated=("t_pool", "d_pool"), max_live_outputs=4,
            max_fetch_bytes_per_row=64,
            build=_build_spec_round,
            # all_greedy (2) x use_kernel (2).
            max_cache_keys=6,
        ),
        ProgramContract(
            name="_spec_rounds_chunk", module="jax_llama_tpu.serving",
            donated=("t_pool", "d_pool", "fill", "tau", "tau_lp",
                     "pos", "active", "remaining", "keys"),
            max_live_outputs=1, max_fetch_bytes_per_row=64,
            build=_build_spec_rounds_chunk,
            # n_rounds pow2 <= spec_rounds (<= 5) x all_greedy (2) x
            # use_kernel (2) x stop-width regrowth.
            max_cache_keys=24,
        ),
        ProgramContract(
            name="_paged_insert", module="jax_llama_tpu.serving",
            donated=("pool",), max_live_outputs=4,
            max_fetch_bytes_per_row=32,
            build=_build_paged_insert,
            # row count kb pow2 (log2 n_slots + 1) x group width P in
            # pow2 block counts (log2 blocks_per_slot + 1).
            max_cache_keys=32,
        ),
        ProgramContract(
            name="_paged_suffix_insert", module="jax_llama_tpu.serving",
            donated=("pool",), max_live_outputs=3,
            max_fetch_bytes_per_row=32,
            build=_build_paged_suffix_insert,
            # row count kb pow2 x suffix width T in pow2 block counts
            # (_suffix_pad).
            max_cache_keys=32,
        ),
        ProgramContract(
            name="_scatter_rows", module="jax_llama_tpu.serving",
            donated=("state",), max_live_outputs=0,
            max_fetch_bytes_per_row=0,
            build=_build_scatter_rows,
            # No pool rides this program — it scatters the small
            # per-slot state twins; its whole contract is the
            # donation/zero-live-output check above.
            forbid_pool_shapes=False,
            # dirty-row count Rb pow2 (log2 n_slots + 1) x stop-table
            # width pow2 regrowth.
            max_cache_keys=16,
        ),
        ProgramContract(
            name="_release_blocks", module="jax_llama_tpu.serving",
            donated=("pos",), max_live_outputs=0,
            max_fetch_bytes_per_row=0,
            build=_build_release_blocks,
            # Only the pool's [NB, BLK] pos plane rides along — that
            # is the shape no copy-class equation may produce.
            forbidden_shapes=lambda args: [tuple(args[0].shape)],
            # id batches are padded to the FIXED blocks_per_slot width
            # (_invalidate_evicted): one key per batcher geometry.
            max_cache_keys=2,
        ),
        ProgramContract(
            name="splash_prefill", module="jax_llama_tpu.ops.kernels",
            donated=(), max_live_outputs=1,
            # NOT a host-fetch surface: this program is an attention
            # primitive called INSIDE the serving programs' traces (its
            # jit only caches per static chunk_offset under the outer
            # trace); the one "live" output is the chunk's activation,
            # handed to the surrounding jitted program, never the host.
            # Budget = the example output [1, 128, 2, 128] fp32 exactly,
            # so any second escaping output still trips the check.
            max_fetch_bytes_per_row=131072,
            build=_build_splash_prefill,
            # No pool rides this program — it sees gathered activation
            # views only ([B, T/S, heads, d]); the no-full-pool-copy
            # invariant is the CALLING insert program's contract.
            forbid_pool_shapes=False,
            # chunk_offset: multiples of the fixed prefill chunk inside
            # the pow2-bucketed group width (<= blocks_per_slot values)
            # x q_len in {chunk, P-pow2} x kv_len pow2 — all O(log) or
            # flag-bounded; interpret is platform-derived (1 value).
            max_cache_keys=64,
            # In-op shard_map places heads over "tensor" and rows over
            # the batch axes with ZERO collectives (every (row, head)
            # is independent; the o-projection all-reduce belongs to
            # the calling program's budget) — declared as an explicit
            # all-zero budget rather than omitted.
            comms=CommsBudget(max_count={}, max_bytes=0),
        ),
        ProgramContract(
            name="stock_paged_decode", module="jax_llama_tpu.ops.kernels",
            donated=(), max_live_outputs=1,
            # Same internal-primitive story as splash_prefill: the one
            # output is the step's [B, 1, H, d] activation (512 B at
            # the example geometry), consumed by the calling decode
            # program's trace, not the host.
            max_fetch_bytes_per_row=512,
            build=_build_stock_paged_decode,
            # The pool arrives as bare [L, KVH, NB, BLK, d] arrays (the
            # flat-page reshape is a free row-major view, not a copy) —
            # derive the forbidden full-pool/one-plane shapes from them.
            forbidden_shapes=lambda args: [
                tuple(args[3].shape), tuple(args[3].shape[1:]),
            ],
            # Every array shape is ctor-stable per batcher (full-width
            # state rows, fixed pool geometry); layer is traced, and
            # interpret is platform-derived — target + draft pool
            # geometries are the only multiplier.
            max_cache_keys=8,
            # Zero-collective for the same reason as splash_prefill:
            # KV heads shard over "tensor", rows over the batch axes,
            # and the softmax merge is per-(row, head).
            comms=CommsBudget(max_count={}, max_bytes=0),
        ),
        ProgramContract(
            name="_adopt_jit", module="jax_llama_tpu.kvcache",
            donated=("pool_arrays",), max_live_outputs=0,
            max_fetch_bytes_per_row=0,
            build=_build_adopt_jit,
            # pool arrays arrive as a bare tuple (arg 0), not a
            # BlockPool — derive the forbidden shapes from them
            forbidden_shapes=lambda args: [
                tuple(a.shape) for a in args[0]
            ],
            # staged block count pow2-bucketed (kvcache.stage_restore):
            # log2 n_blocks + 1 buckets.
            max_cache_keys=12,
        ),
    )
}

# jit-decorated module-level functions that the batcher never
# dispatches and which therefore need no contract (currently none —
# every jitted program in serving.py/kvcache.py is on a dispatch path).
NON_DISPATCHED: frozenset = frozenset()

# Modules whose jitted programs must be registered.
CONTRACT_MODULES = ("serving", "kvcache", "kernels")


def pool_shapes(pool) -> List[Tuple[int, ...]]:
    """Full-pool and one-plane shapes of a BlockPool example — the
    shapes no copy-class equation may produce."""
    shapes: List[Tuple[int, ...]] = []
    for arr in (pool.k, pool.v, pool.k_scale, pool.v_scale):
        if arr is None:
            continue
        shapes.append(tuple(arr.shape))        # [L, KVH, NB, BLK, ...]
        shapes.append(tuple(arr.shape[1:]))    # one-layer plane
    return shapes
