"""Schedule explorer: checked models for every cross-thread pragma.

The lock-discipline checker (lockcheck.py) lets a cross-thread access
through on the strength of an ``# audit: racy-read(<argument>)`` /
``# audit: unguarded(<argument>)`` pragma — trusted PROSE.  This pass
elevates each such pragma to a CHECKED claim: a small deterministic
model that drives the declared thread pair through instrumented
schedules over the real classes (the real ``ContinuousBatcher.stats``
/ ``LLMServer._health`` methods run against stub instances built from
real stores, deques and events) under a virtual clock, asserting the
annotated access really is snapshot-safe / single-writer under
exhaustive interleavings of the declared critical regions.  A pragma
with no model — or a model whose exploration finds a counterexample —
fails ``make lint-invariants``.

Two explorers, matched to the two claim shapes:

  * **Preemption explorer** (``snapshot`` claims, real reader
    methods): the reader runs in its own thread under a
    ``sys.settrace`` line hook; for every line boundary ``cut`` and
    every split of the writer's atomic ops, the schedule pauses the
    reader at ``cut``, runs the op prefix, resumes the reader to
    completion, then runs the suffix.  That explores every placement
    of the writer's critical regions against every intra-reader
    preemption point — exactly the TOCTOU class the ``stats()``
    ``self._pf`` bug (PR 8) lived in: a reader that dereferences
    loop-owned state twice fails the schedule where the writer's
    nulling op lands between the two lines.
  * **Atomic explorer** (``single-writer`` / ``happens-before``
    claims): threads are lists of named atomic ops with declared
    write-sets; every interleaving (honoring declared happens-before
    edges) runs against fresh state, and the write-sets are checked
    structurally — a field written by two threads voids a
    single-writer claim no schedule needs to find.

``owner-thread`` claims (loop-thread code reading through its own
holder alias) run their accesses sequentially on one thread — the
model documents WHY there is no concurrency to explore, and keeps the
pragma's claim in a place the checker can fail when the claim rots
(e.g. the method disappears).

Models register in :data:`MODELS`, keyed by the pragma's enclosing
``(module, function)``.  The site scan finds every ``racy-read`` /
``unguarded`` pragma in the package; a site without a model is an
``unmodeled-pragma`` finding, a model without a site is
``stale-model``.
"""

from __future__ import annotations

import ast
import dataclasses
import sys
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from .common import Finding, Pragmas, iter_package_sources, parse_module

CHECKER = "schedules"

_MAX_SCHEDULES = 20000
_MAX_CUTS = 160


@dataclasses.dataclass(frozen=True)
class Op:
    """One atomic step of a modeled thread (a declared critical
    region: everything inside runs without preemption, matching the
    GIL-atomicity the pragmas' arguments lean on)."""

    name: str
    fn: Callable[[Any, int], None]       # (state, virtual clock)
    writes: frozenset = frozenset()      # state fields this op writes


@dataclasses.dataclass(frozen=True)
class ScheduleModel:
    """A checked safety argument for one pragma site."""

    name: str
    module: str                           # pragma site: module basename
    func: str                             # pragma site: enclosing def
    claim: str                            # snapshot | single-writer |
                                          # happens-before | owner-thread
    make: Callable[[], Any]               # fresh shared state
    writers: Dict[str, Tuple[Op, ...]]    # thread -> atomic ops
    reader: Optional[Callable[[Any], Any]] = None   # preemptible
    check: Optional[Callable[[Any, Any], None]] = None
    # Name of the function whose LINES are the preemption points
    # (default: the site function).  Only that frame is traced — a
    # pause inside a nested call could sit on a C-level mutex (e.g.
    # queue.qsize) and deadlock the writer instead of racing it; the
    # annotated code's own lines are the TOCTOU surface under audit.
    trace_fn: Optional[str] = None
    # happens-before edges: thread -> (other thread, op name) that
    # must complete before the keyed thread's first op may run.
    after: Dict[str, Tuple[str, str]] = dataclasses.field(
        default_factory=dict
    )


# ---------------------------------------------------------------------------
# Explorers
# ---------------------------------------------------------------------------

def _make_tracer(model: ScheduleModel, on_line: Callable[[], None]):
    """A settrace handler firing ``on_line`` only inside the frame(s)
    of the model's traced function (see ScheduleModel.trace_fn)."""
    name = model.trace_fn or model.func

    def line_tracer(frame, event, arg):
        if event == "line":
            on_line()
        return line_tracer

    def global_tracer(frame, event, arg):
        if event == "call" and frame.f_code.co_name == name:
            return line_tracer
        return None

    return global_tracer


def _reader_line_count(model: ScheduleModel) -> int:
    """Dry-run the reader counting line events (the preemption points)."""
    state = model.make()
    count = [0]

    def bump():
        count[0] += 1

    tracer = _make_tracer(model, bump)

    def run():
        sys.settrace(tracer)
        try:
            model.reader(state)
        except BaseException:  # noqa: BLE001 - schedules judge errors
            pass  # the cut=0 schedule reports it with context
        finally:
            sys.settrace(None)

    t = threading.Thread(target=run, daemon=True)
    t.start()
    t.join(timeout=10)
    return count[0]


def _preempt_once(
    model: ScheduleModel, ops: Sequence[Op], cut: int, split: int,
) -> Optional[str]:
    """One schedule: reader runs to line ``cut``, pauses; ops[:split]
    run; reader resumes to completion; ops[split:] run.  Returns a
    failure description or None."""
    state = model.make()
    paused = threading.Event()
    resume = threading.Event()
    err: Dict[str, BaseException] = {}
    out: Dict[str, Any] = {}
    count = [0]

    def on_line():
        count[0] += 1
        if count[0] == cut:
            paused.set()
            resume.wait(timeout=5)

    tracer = _make_tracer(model, on_line)

    def run():
        sys.settrace(tracer)
        try:
            out["v"] = model.reader(state)
        except BaseException as e:  # noqa: BLE001 - the verdict itself
            err["e"] = e
        finally:
            sys.settrace(None)
            paused.set()

    t = threading.Thread(target=run, daemon=True)
    if cut == 0:
        # writer prefix strictly before the reader starts
        for clock, op in enumerate(ops[:split]):
            op.fn(state, clock)
        t.start()
    else:
        t.start()
        if not paused.wait(timeout=5):
            return f"reader hung before line {cut}"
        for clock, op in enumerate(ops[:split]):
            op.fn(state, cut + clock)
        resume.set()
    t.join(timeout=10)
    if t.is_alive():
        return f"reader hung (cut={cut}, split={split})"
    for clock, op in enumerate(ops[split:]):
        op.fn(state, cut + split + clock)
    schedule = (
        f"cut@line{cut} after "
        f"[{', '.join(o.name for o in ops[:split])}]"
    )
    if "e" in err:
        e = err["e"]
        return (
            f"reader raised {type(e).__name__}: {e} under schedule "
            f"{schedule}"
        )
    if model.check is not None:
        try:
            model.check(state, out.get("v"))
        except AssertionError as e:
            return f"check failed ({e}) under schedule {schedule}"
    return None


def _explore_preempt(model: ScheduleModel) -> List[str]:
    failures: List[str] = []
    lines = min(_reader_line_count(model), _MAX_CUTS)
    for thread, ops in sorted(model.writers.items()):
        for cut in range(0, lines + 1):
            for split in range(0, len(ops) + 1):
                fail = _preempt_once(model, ops, cut, split)
                if fail:
                    failures.append(f"[{thread}] {fail}")
                    if len(failures) >= 3:
                        return failures
    return failures


def _explore_atomic(model: ScheduleModel) -> List[str]:
    """Exhaustive interleavings of the threads' atomic op lists,
    honoring happens-before edges."""
    threads = sorted(model.writers.items())
    failures: List[str] = []
    counted = [0]

    def run_schedule(order: List[Tuple[str, Op]]) -> Optional[str]:
        state = model.make()
        try:
            for clock, (tname, op) in enumerate(order):
                op.fn(state, clock)
        except BaseException as e:  # noqa: BLE001 - the verdict
            return (
                f"{type(e).__name__}: {e} under schedule "
                f"[{', '.join(t + ':' + o.name for t, o in order)}]"
            )
        if model.check is not None:
            try:
                model.check(state, None)
            except AssertionError as e:
                return (
                    f"check failed ({e}) under schedule "
                    f"[{', '.join(t + ':' + o.name for t, o in order)}]"
                )
        return None

    def gen(pos: Dict[str, int], order: List[Tuple[str, Op]],
            done: Dict[str, set]):
        if counted[0] > _MAX_SCHEDULES or len(failures) >= 3:
            return
        complete = True
        for tname, ops in threads:
            i = pos[tname]
            if i >= len(ops):
                continue
            complete = False
            edge = model.after.get(tname)
            if edge is not None and i == 0:
                other, opname = edge
                if opname not in done.get(other, set()):
                    continue  # not enabled yet
            pos[tname] += 1
            order.append((tname, ops[i]))
            done.setdefault(tname, set()).add(ops[i].name)
            gen(pos, order, done)
            done[tname].discard(ops[i].name) if ops[i].name not in [
                o.name for o in ops[:i]
            ] else None
            order.pop()
            pos[tname] -= 1
        if complete:
            counted[0] += 1
            fail = run_schedule(order)
            if fail:
                failures.append(fail)

    gen({t: 0 for t, _ in threads}, [], {})
    if counted[0] == 0 and not failures:
        # An unsatisfiable after-edge (typo'd op/thread name, or a
        # renamed op) would otherwise make the claim pass VACUOUSLY.
        failures.append(
            "no complete schedule could be generated — an `after` "
            "happens-before edge names a thread/op that never runs "
            "(typo or renamed op?)"
        )
    return failures


def _single_writer_violations(model: ScheduleModel) -> List[str]:
    owners: Dict[str, set] = {}
    for tname, ops in model.writers.items():
        for op in ops:
            for field in op.writes:
                owners.setdefault(field, set()).add(tname)
    return [
        f"field {field!r} is written by threads {sorted(ts)} — the "
        "single-writer claim is structurally void"
        for field, ts in sorted(owners.items()) if len(ts) > 1
    ]


def explore(model: ScheduleModel) -> List[str]:
    """Run a model's exploration; [] means the claim held."""
    failures: List[str] = []
    if model.claim in ("single-writer", "snapshot"):
        failures.extend(_single_writer_violations(model))
    if model.claim == "owner-thread":
        # no concurrency by claim: one thread, program order
        state = model.make()
        clock = 0
        try:
            for _, ops in sorted(model.writers.items()):
                for op in ops:
                    op.fn(state, clock)
                    clock += 1
            if model.reader is not None:
                result = model.reader(state)
                if model.check is not None:
                    model.check(state, result)
        except BaseException as e:  # noqa: BLE001 - the verdict
            failures.append(f"owner-thread run raised {e}")
        return failures
    if model.reader is not None:
        failures.extend(_explore_preempt(model))
    else:
        failures.extend(_explore_atomic(model))
    return failures


# ---------------------------------------------------------------------------
# Pragma-site scan
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Site:
    module: str
    func: str
    path: str
    line: int
    kind: str


def pragma_sites(
    sources: Optional[Sequence[Tuple[str, str]]] = None,
) -> List[Site]:
    """Every ``racy-read`` / ``unguarded`` pragma in the package,
    resolved to its innermost enclosing function."""
    out: List[Site] = []
    if sources is None:
        sources = list(iter_package_sources())
    for path, source in sources:
        pragmas = Pragmas.scan(source)
        hits = [
            (line, kind) for line, kind, _ in pragmas.records
            if kind in ("racy-read", "unguarded")
        ]
        if not hits:
            continue
        tree, _ = parse_module(path, source, CHECKER)
        if tree is None:
            continue
        fns = [
            n for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        modname = path.rsplit("/", 1)[-1][:-3]
        for line, kind in hits:
            best = None
            for fn in fns:
                hi = fn.end_lineno or fn.lineno
                # a pragma on its own comment line annotates the
                # STATEMENT BELOW it, so let the span reach one past
                if fn.lineno <= line <= hi + 1:
                    if best is None or hi - fn.lineno < (
                        best.end_lineno or best.lineno
                    ) - best.lineno:
                        best = fn
            out.append(Site(
                module=modname,
                func=best.name if best is not None else "<module>",
                path=path, line=line, kind=kind,
            ))
    return out


# ---------------------------------------------------------------------------
# The serving stack's models
# ---------------------------------------------------------------------------

def _make_batcher_stub():
    """A ContinuousBatcher stand-in carrying every field ``stats()`` /
    ``_window_acceptance()`` reads, with the REAL methods resolved
    from the real class (so the model exercises the code under audit,
    not a copy) over real container/store instances."""
    import collections

    from ..kvcache import RadixPrefixStore
    from ..serving import ContinuousBatcher

    class _StubBatcher:
        stats = ContinuousBatcher.stats
        _window_acceptance = ContinuousBatcher._window_acceptance
        acceptance_rate = ContinuousBatcher.acceptance_rate
        kv_debug_json = ContinuousBatcher.kv_debug_json
        _kv_summary = ContinuousBatcher._kv_summary

    s = _StubBatcher()
    s.fault_injector = None
    s.emitted_total = 0
    s.steps_total = 0
    s.slots = {0: None, 1: None}
    s.queue = []
    s.free_blocks = list(range(8))
    s.n_blocks = 8
    s.drafts_proposed = 0
    s.drafts_accepted = 0
    s._store = RadixPrefixStore(host_blocks=0)
    s.prefix_requests_hit = 0
    s.prefix_blocks_reused = 0
    s.prefix_hit_tokens_total = 0
    s.prompt_tokens_total = 0
    s.host_kv_blocks = 0
    s._restoring = []
    s._restored_ready = []
    s.swap_ins_total = 0
    s.swap_in_blocks_total = 0
    s.swap_out_blocks_total = 0
    s.swap_in_ms_total = 0.0
    s.swap_failures_total = 0
    s.kv_export_blocks_total = 0
    s.kv_import_blocks_total = 0
    s.mesh = None
    s._mesh_placed = False
    s.nonfinite_rows_total = 0
    s.decode_chunk_last = 1
    s.decode_dispatches_total = 0
    s.host_syncs_total = 0
    s.state_uploads_total = 0
    s.spec_rounds_last = 0
    s.spec_dispatches_total = 0
    s.spec_host_syncs_total = 0
    s.spec_emitted_total = 0
    s._accept_window = collections.deque(maxlen=64)
    s.prefill_budget = 16
    s._pf = None
    s.prefill_chunks_total = 0
    s.fused_admissions_total = 0
    s.decode_stall_ms_total = 0.0
    s.prefix_index = "radix"
    s.n_slots = 2
    # KV chain-digest surface (PR 13): the REAL store's real digest
    # (its own leaf lock), plus the ctor-stable geometry scalars
    # stats()/kv_debug_json read.
    s.kv_digest = s._store.digest
    s.block_bytes = 4096
    s.block_size = 16
    s.kv_export_events_total = 0
    s.kv_import_events_total = 0
    # Handoff hardening (r14): the abort/demote ledger stats() reads.
    s.kv_handoff_aborted_total = 0
    s.kv_export_demoted_blocks_total = 0
    return s


def _make_prefill():
    from ..serving import _Prefill

    return _Prefill(
        slot=0, req=None, chain=[], n_share=0, base=0, suffix_len=8,
        chunk=4,
    )


def _loop_admit(s, clock):
    s.slots[0] = object()
    s.queue.append(object())
    s.free_blocks.pop()
    s._pf = _make_prefill()
    s._restoring.append(object())


def _loop_dispatch(s, clock):
    s.emitted_total += 1
    s.steps_total += 1
    s.host_syncs_total += 1
    s.decode_dispatches_total += 1
    s._accept_window.append((4, 3))
    if s._pf is not None:
        s._pf.off += s._pf.chunk


def _loop_finish(s, clock):
    s._pf = None
    s.slots[0] = None
    s.queue.clear()
    s.free_blocks.append(9)
    s._restoring.clear()
    s._accept_window.append((4, 0))


_LOOP_OPS = (
    Op("admit", _loop_admit, frozenset({
        "slots", "queue", "free_blocks", "_pf", "_restoring",
    })),
    Op("dispatch", _loop_dispatch, frozenset({
        "emitted_total", "steps_total", "host_syncs_total",
        "decode_dispatches_total", "_accept_window", "_pf",
    })),
    Op("finish", _loop_finish, frozenset({
        "_pf", "slots", "queue", "free_blocks", "_restoring",
        "_accept_window",
    })),
)


def _check_stats(state, result):
    assert isinstance(result, dict) and result, "stats() returned junk"
    for k, v in result.items():
        assert isinstance(v, (int, float)), f"non-scalar stat {k!r}"


def _model_stats() -> ScheduleModel:
    return ScheduleModel(
        name="batcher-stats-snapshot",
        module="serving", func="stats", claim="snapshot",
        make=_make_batcher_stub,
        writers={"loop": _LOOP_OPS},
        reader=lambda s: s.stats(),
        check=_check_stats,
    )


def _model_window_acceptance() -> ScheduleModel:
    def check(state, result):
        assert 0.0 <= result <= 1.0, f"acceptance {result} out of range"

    return ScheduleModel(
        name="spec-window-snapshot",
        module="serving", func="_window_acceptance", claim="snapshot",
        make=_make_batcher_stub,
        writers={"loop": (
            Op("append", lambda s, c: s._accept_window.append((4, 2)),
               frozenset({"_accept_window"})),
            Op("append2", lambda s, c: s._accept_window.append((4, 4)),
               frozenset({"_accept_window"})),
        )},
        reader=lambda s: s._window_acceptance(),
        check=check,
    )


def _model_kv_debug() -> ScheduleModel:
    """``kv_debug_json``'s racy-read (the /debug/kv endpoint, handler
    threads): the digest reads go through KvDigest's own leaf lock and
    the two hit-token counters are single-writer point-in-time reads.
    The writer ops drive the REAL RadixPrefixStore (publish / retain /
    evict), so every digest mutation hook runs under preemption."""
    def loop_publish(s, clock):
        key = (b"chain-%d" % clock) * 2
        s._store.publish([key], [clock % 8])
        s.prefix_hit_tokens_total += 16
        s.prompt_tokens_total += 32

    def loop_retain_evict(s, clock):
        blk = clock % 8
        if s._store.is_keyed(blk):
            s._store.retain([blk])
        s._store.pop_evictable()

    def check(state, result):
        assert isinstance(result, dict), "kv_debug_json returned junk"
        assert "summary" in result and "nodes" in result
        for node in result["nodes"]:
            assert {"key", "depth", "tier", "refcount", "seq"} <= set(
                node
            ), f"malformed digest node {node!r}"
        assert result["summary"]["nodes"] >= 0

    return ScheduleModel(
        name="kv-debug-digest-snapshot",
        # The pragma site lives in _kv_summary (the factored summary
        # helper kv_debug_json and the incremental ?since= reply both
        # call); the reader still drives the full public entry point.
        module="serving", func="_kv_summary", claim="snapshot",
        make=_make_batcher_stub,
        writers={"loop": (
            Op("publish", loop_publish, frozenset({
                "_store", "kv_digest", "prefix_hit_tokens_total",
                "prompt_tokens_total",
            })),
            Op("retain_evict", loop_retain_evict, frozenset({
                "_store", "kv_digest",
            })),
        )},
        reader=lambda s: s.kv_debug_json(),
        check=check,
    )


def _make_server_stub():
    """An LLMServer stand-in for the ``_health`` snapshot model: the
    REAL ``_health`` runs against real Events/threads/containers, a
    real DegradeManager and a real OverloadController, with the
    batcher stub above behind the holder alias."""
    import queue
    import time

    from ..degrade import DegradeManager
    from ..overload import OverloadController
    from ..server import LLMServer

    class _StubServer:
        _health = LLMServer._health

    s = _StubServer()
    s._loop_thread = threading.Thread(target=lambda: None)
    s._closed = threading.Event()
    s._draining = threading.Event()
    s._drain_deadline = None
    s.degrade = DegradeManager()
    s._stalled = False
    s._heartbeat = time.monotonic()
    s.recoveries_total = 0
    s.watchdog_stalls_total = 0
    s.batcher = _make_batcher_stub()
    s._inbox = queue.Queue()
    s._active = {}
    s.overload = OverloadController(enabled=False)
    s.replica_id = None
    # Control-plane observability (r15): _health's replica section
    # reports the ITL EWMA the router's sentinel z-scores.
    s.itl_ms_ewma = None
    return s


def _model_health() -> ScheduleModel:
    def loop_mutate(s, clock):
        s.batcher._restoring.append(object())
        s.batcher._restored_ready.append(object())
        s.batcher.slots[0] = object()
        s._heartbeat = clock * 0.001
        s._active[clock] = object()

    def loop_settle(s, clock):
        s.batcher._restoring.clear()
        s.batcher._restored_ready.clear()
        s.batcher.slots[0] = None
        s._active.clear()

    def watchdog_trip(s, clock):
        s._stalled = True

    def check(state, result):
        assert isinstance(result, dict) and "ok" in result, (
            "_health returned junk"
        )

    return ScheduleModel(
        name="healthz-snapshot",
        module="server", func="_health", claim="snapshot",
        make=_make_server_stub,
        writers={
            "loop": (
                Op("mutate", loop_mutate, frozenset({
                    "batcher._restoring", "batcher._restored_ready",
                    "batcher.slots", "_heartbeat", "_active",
                })),
                Op("settle", loop_settle, frozenset({
                    "batcher._restoring", "batcher._restored_ready",
                    "batcher.slots", "_active",
                })),
            ),
            "watchdog": (
                Op("trip", watchdog_trip, frozenset({"_stalled"})),
            ),
        },
        reader=lambda s: s._health(),
        check=check,
    )


def _model_do_post_depth() -> ScheduleModel:
    """do_POST's admission-depth estimate (the ``# audit: racy-read``
    at the overload gate): ``_inbox.qsize() + len(_active) +
    overload.queued_total()`` over loop-mutated state.  The model
    mirrors the handler expression over the real container types; the
    claim is that an off-by-a-few depth is the worst outcome."""
    def reader(s):
        return (
            s._inbox.qsize() + len(s._active)
            + s.overload.queued_total()
        )

    def check(state, result):
        assert 0 <= result <= 6, f"depth estimate {result} impossible"

    return ScheduleModel(
        name="admission-depth-snapshot",
        module="server", func="do_POST", claim="snapshot",
        make=_make_server_stub,
        writers={"loop": (
            Op("take", lambda s, c: (
                s._inbox.put(object()), s._active.update({c: object()}),
            ), frozenset({"_inbox", "_active"})),
            Op("drain", lambda s, c: (
                s._inbox.get_nowait() if not s._inbox.empty() else None,
                s._active.clear(),
            ), frozenset({"_inbox", "_active"})),
        )},
        reader=reader,
        check=check,
        trace_fn="reader",
    )


def _model_start_happens_before() -> ScheduleModel:
    """LLMServer.start's heartbeat write precedes every thread start —
    the loop/watchdog can never read an unset heartbeat."""
    def make():
        class _S:
            pass

        s = _S()
        s.heartbeat = None
        s.started = False
        return s

    def set_heartbeat(s, clock):
        s.heartbeat = float(clock)

    def start_threads(s, clock):
        s.started = True

    def loop_read(s, clock):
        assert s.heartbeat is not None, (
            "loop read the heartbeat before start() wrote it"
        )

    return ScheduleModel(
        name="start-heartbeat-happens-before",
        module="server", func="start", claim="happens-before",
        make=make,
        writers={
            "main": (
                Op("set_heartbeat", set_heartbeat,
                   frozenset({"heartbeat"})),
                Op("start_threads", start_threads,
                   frozenset({"started"})),
            ),
            "loop": (Op("read_heartbeat", loop_read),),
        },
        after={"loop": ("main", "start_threads")},
    )


def _model_watchdog_single_writer() -> ScheduleModel:
    """_watchdog's ``_stalled`` / ``watchdog_stalls_total`` writes:
    single-writer (only the watchdog thread mutates them); /healthz
    and /metrics readers see GIL-atomic bool/int snapshots."""
    def make():
        class _S:
            pass

        s = _S()
        s._stalled = False
        s.watchdog_stalls_total = 0
        s._heartbeat = 0.0
        return s

    def trip(s, clock):
        if not s._stalled:
            s._stalled = True
            s.watchdog_stalls_total += 1

    def clear(s, clock):
        s._stalled = False

    def read(s, clock):
        assert isinstance(s._stalled, bool)
        assert s.watchdog_stalls_total in (0, 1)

    return ScheduleModel(
        name="watchdog-single-writer",
        module="server", func="_watchdog", claim="single-writer",
        make=make,
        writers={
            "watchdog": (
                Op("trip", trip, frozenset({
                    "_stalled", "watchdog_stalls_total",
                })),
                Op("clear", clear, frozenset({"_stalled"})),
            ),
            "health-reader": (Op("read", read), Op("read2", read)),
        },
    )


def _model_loop_owner() -> ScheduleModel:
    """_loop's reads through its own holder alias (``self.batcher.
    slots`` / ``.queue`` at the interactive-first submit gate): the
    loop thread OWNS the batcher, so there is no concurrency — the
    model runs the exact access shapes in program order and exists so
    the pragma's claim fails loudly if the loop stops being the
    owner-thread home of this code."""
    def submit_gate(s, clock):
        free = sum(v is None for v in s.slots.values())
        while len(s.queue) < free:
            s.queue.append(object())

    return ScheduleModel(
        name="loop-owner-submit-gate",
        module="server", func="_loop", claim="owner-thread",
        make=_make_batcher_stub,
        writers={"loop": (
            Op("admit", _loop_admit, frozenset({
                "slots", "queue", "free_blocks", "_pf", "_restoring",
            })),
            Op("gate", submit_gate, frozenset({"queue"})),
            Op("finish", _loop_finish, frozenset({
                "_pf", "slots", "queue", "free_blocks", "_restoring",
                "_accept_window",
            })),
        )},
    )


MODELS: Tuple[Callable[[], ScheduleModel], ...] = (
    _model_stats,
    _model_window_acceptance,
    _model_kv_debug,
    _model_health,
    _model_do_post_depth,
    _model_start_happens_before,
    _model_watchdog_single_writer,
    _model_loop_owner,
)


# ---------------------------------------------------------------------------
# The pass
# ---------------------------------------------------------------------------

def check_package(
    models: Optional[Sequence[ScheduleModel]] = None,
    sources: Optional[Sequence[Tuple[str, str]]] = None,
) -> List[Finding]:
    """Match every racy-read/unguarded pragma to a model and run every
    model's exploration."""
    findings: List[Finding] = []
    if models is None:
        models = [m() for m in MODELS]
    sites = pragma_sites(sources)
    by_key: Dict[Tuple[str, str], List[ScheduleModel]] = {}
    for m in models:
        by_key.setdefault((m.module, m.func), []).append(m)

    covered: set = set()
    for site in sites:
        key = (site.module, site.func)
        if key in by_key:
            covered.add(key)
            continue
        findings.append(Finding(
            checker=CHECKER, rule="unmodeled-pragma",
            path=site.path, line=site.line,
            message=(
                f"# audit: {site.kind}(...) in {site.module}."
                f"{site.func} has no schedule model — register a "
                "ScheduleModel in analysis/schedules.py MODELS (the "
                "safety argument must be checked, not trusted prose)"
            ),
        ))
    for m in models:
        if sources is None and (m.module, m.func) not in {
            (s.module, s.func) for s in sites
        }:
            findings.append(Finding(
                checker=CHECKER, rule="stale-model",
                path=f"jax_llama_tpu/{m.module}.py", line=0,
                message=(
                    f"schedule model {m.name!r} targets {m.module}."
                    f"{m.func} but no racy-read/unguarded pragma "
                    "lives there anymore — delete or retarget it"
                ),
            ))
            continue
        for fail in explore(m):
            findings.append(Finding(
                checker=CHECKER, rule="schedule-model-failed",
                path=f"jax_llama_tpu/{m.module}.py", line=0,
                message=(
                    f"model {m.name!r} ({m.claim}) found a "
                    f"counterexample: {fail} — the pragma's safety "
                    "argument does not hold; fix the code or the model"
                ),
            ))
    return findings
