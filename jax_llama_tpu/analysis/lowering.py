"""Lowering auditor: jaxpr/StableHLO contract checks for jitted programs.

Three layers, cheapest first (contracts.py holds the registry):

  1. **Coverage + decorator audit (static, AST).**  Every jit-decorated
     module-level function in serving.py / kvcache.py must have a
     registered :class:`~.contracts.ProgramContract` (new programs must
     JOIN the registry to be dispatched), the registry must not hold
     stale names, and each program's ``donate_argnames`` /
     ``donate_argnums`` decorator must match its contract exactly —
     in BOTH directions (a dropped donation silently doubles KV HBM; an
     undeclared one silently invalidates the host's buffer reuse).
  2. **Donation resolution (abstract trace).**  The program is
     ``.lower()``-ed at the contract's tiny example shape (CPU-safe:
     lowering records ``tf.aliasing_output`` even on backends that drop
     donation at compile time).  Every leaf of every donated argument
     must actually resolve to an input-output alias — donated-but-
     unusable buffers (shape/dtype drift between an input and its
     carried output) are exactly how "donated" state quietly starts
     copying.
  3. **Host-fetch surface + forbidden equations (abstract trace).**
     The outputs NOT aliased onto donations are what the host can
     fetch: their count must not exceed ``max_live_outputs`` (the
     "1 packed fetch" discipline) and their bytes-per-batch-row must
     fit ``max_fetch_bytes_per_row`` (a [B, V] logits leak fails
     immediately).  Finally the traced jaxpr — recursively through
     scan/cond/while sub-jaxprs — must contain no copy-class equation
     (broadcast, gather, dynamic-slice, concatenate, transpose,
     convert, copy) producing a full-pool-sized or one-plane-sized
     array: the abstract version of test_tpu_compiled.py's
     no-full-pool-copy HLO pins, enforceable on any backend.
"""

from __future__ import annotations

import ast
import math
import re
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from .common import (
    Finding, iter_package_sources, jit_decorations, parse_module,
)
from .contracts import (
    CONTRACT_MODULES, NON_DISPATCHED, REGISTRY, ProgramContract,
    pool_shapes,
)

CHECKER = "lowering"

# Copy-class primitives: producing a pool-sized result through any of
# these means XLA will materialize a full-pool copy (scatter /
# dynamic_update_slice are the sanctioned in-place writes and are NOT
# listed).
FORBIDDEN_PRIMITIVES = frozenset({
    "broadcast_in_dim", "gather", "dynamic_slice", "concatenate",
    "transpose", "rev", "copy", "convert_element_type", "select_n",
    "pad", "iota",
})

_ALIAS_RE = re.compile(r"tf\.aliasing_output\s*=\s*(\d+)")
# Sharded lowerings record donation as a buffer-donor attribute and
# defer the alias RESOLUTION to compile time — the mesh pass accepts
# either spelling (and proves the resolution's precondition, sharding
# stability, by running the program).
_DONOR_RE = re.compile(r"jax\.buffer_donor")


# ---------------------------------------------------------------------------
# Static layer
# ---------------------------------------------------------------------------

def _declared_donations(
    fn: ast.FunctionDef, dec: Optional[ast.Call]
) -> Tuple[str, ...]:
    """donate_argnames (or argnums mapped through the signature) the
    decorator declares."""
    if dec is None:
        return ()
    names: List[str] = []
    params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    for kw in dec.keywords:
        if kw.arg == "donate_argnames":
            for elt in ast.walk(kw.value):
                if isinstance(elt, ast.Constant) and isinstance(
                    elt.value, str
                ):
                    names.append(elt.value)
        elif kw.arg == "donate_argnums":
            for elt in ast.walk(kw.value):
                if isinstance(elt, ast.Constant) and isinstance(
                    elt.value, int
                ):
                    if elt.value < len(params):
                        names.append(params[elt.value])
                    else:
                        names.append(f"<argnum {elt.value} OOB>")
    return tuple(names)


def check_static(
    registry: Dict[str, ProgramContract] = REGISTRY,
    modules: Sequence[str] = CONTRACT_MODULES,
    non_dispatched: frozenset = NON_DISPATCHED,
) -> List[Finding]:
    """Coverage + decorator audit over the contract modules' sources."""
    findings: List[Finding] = []
    seen: Dict[str, Tuple[str, ast.FunctionDef, Optional[ast.Call]]] = {}
    for path, source in iter_package_sources(only=modules):
        tree, errs = parse_module(path, source, CHECKER)
        findings.extend(errs)
        if tree is None:
            continue
        for name, (fn, dec) in jit_decorations(tree).items():
            seen[name] = (path, fn, dec)

    for name, (path, fn, dec) in sorted(seen.items()):
        if name in non_dispatched:
            continue
        contract = registry.get(name)
        if contract is None:
            findings.append(Finding(
                checker=CHECKER, rule="unregistered-program",
                path=path, line=fn.lineno,
                message=(
                    f"jitted program {name!r} has no lowering contract "
                    "— register it in analysis/contracts.py (donated "
                    "args, fetch budget, forbidden shapes) before the "
                    "batcher may dispatch it"
                ),
            ))
            continue
        declared = _declared_donations(fn, dec)
        if tuple(sorted(declared)) != tuple(sorted(contract.donated)):
            findings.append(Finding(
                checker=CHECKER, rule="donation-mismatch",
                path=path, line=fn.lineno,
                message=(
                    f"{name}: decorator donates {sorted(declared)} but "
                    f"the contract declares {sorted(contract.donated)} "
                    "— update whichever is wrong (both are load-"
                    "bearing: donation drops double HBM silently)"
                ),
            ))
    for name, contract in sorted(registry.items()):
        if name not in seen:
            findings.append(Finding(
                checker=CHECKER, rule="stale-contract",
                path=contract.module.replace(".", "/") + ".py", line=0,
                message=(
                    f"contract {name!r} names a jitted program that no "
                    "longer exists in its module"
                ),
            ))
    return findings


# ---------------------------------------------------------------------------
# Trace layer
# ---------------------------------------------------------------------------

def _aval_bytes(aval) -> int:
    return int(math.prod(aval.shape)) * aval.dtype.itemsize


def _walk_jaxprs(jaxpr) -> Iterable[Any]:
    """Yield every equation in a (Closed)Jaxpr, recursing into
    sub-jaxprs (scan/while/cond/pjit bodies)."""
    inner = getattr(jaxpr, "jaxpr", jaxpr)
    for eqn in inner.eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in (v if isinstance(v, (list, tuple)) else (v,)):
                if hasattr(sub, "eqns") or hasattr(sub, "jaxpr"):
                    yield from _walk_jaxprs(sub)


def _resolve_program(contract: ProgramContract):
    import importlib

    mod = importlib.import_module(contract.module)
    return getattr(mod, contract.name)


def _batch_rows(args: tuple, argnames: Tuple[str, ...]) -> int:
    """Example batch size: rows of the first per-row state array."""
    for name, arg in zip(argnames, args):
        if name in ("tau", "fill", "active", "prompt_tokens",
                    "suffix_tokens"):
            return int(arg.shape[0])
    return 1


def check_lowering(
    contract: ProgramContract,
    path_hint: Optional[str] = None,
) -> List[Finding]:
    """Trace ``contract``'s program at its example shape and audit the
    donation resolution, host-fetch surface and forbidden equations."""
    import jax.tree_util as jtu

    findings: List[Finding] = []
    path = path_hint or contract.module.replace(".", "/") + ".py"
    if contract.build is None:
        return findings
    program = _resolve_program(contract)
    argnames, args, kwargs = contract.build()
    # ONE abstract trace serves both layers: the Traced carries the
    # jaxpr (forbidden-equation walk) and lowers into the StableHLO
    # whose arg attributes carry the alias resolution.
    traced = program.trace(*args, **kwargs)
    lowered = traced.lower()

    # -- donation resolution -------------------------------------------------
    # args_info is ((per-positional-arg trees...), kwargs-dict); each
    # leaf records whether jit will donate it.
    donated_leaves = 0
    for name, info in zip(argnames, lowered.args_info[0]):
        leaves = jtu.tree_leaves(info)
        want = name in contract.donated
        got = [bool(leaf.donated) for leaf in leaves]
        donated_leaves += sum(got)
        if want and not all(got):
            findings.append(Finding(
                checker=CHECKER, rule="donation-not-applied",
                path=path, line=0,
                message=(
                    f"{contract.name}: contract donates {name!r} but "
                    f"{len(got) - sum(got)}/{len(got)} of its leaves "
                    "are not donated at trace time"
                ),
            ))
        elif not want and any(got):
            findings.append(Finding(
                checker=CHECKER, rule="donation-not-applied",
                path=path, line=0,
                message=(
                    f"{contract.name}: argument {name!r} is donated at "
                    "trace time but the contract does not declare it"
                ),
            ))

    text = lowered.as_text()
    aliased_outputs = {int(m) for m in _ALIAS_RE.findall(text)}
    if len(aliased_outputs) != donated_leaves:
        findings.append(Finding(
            checker=CHECKER, rule="donation-unresolved",
            path=path, line=0,
            message=(
                f"{contract.name}: {donated_leaves} leaves are donated "
                f"but only {len(aliased_outputs)} resolve to an "
                "input-output alias — a donated buffer with no aliased "
                "output is silently copied instead of reused"
            ),
        ))

    # -- host-fetch surface --------------------------------------------------
    out_avals = traced.jaxpr.out_avals
    live = [
        (i, aval) for i, aval in enumerate(out_avals)
        if i not in aliased_outputs
    ]
    if len(live) > contract.max_live_outputs:
        findings.append(Finding(
            checker=CHECKER, rule="fetch-count",
            path=path, line=0,
            message=(
                f"{contract.name}: {len(live)} outputs are not aliased "
                f"onto donated inputs (contract allows "
                f"{contract.max_live_outputs}) — every live output is "
                "host-fetchable surface; pack or donate it"
            ),
        ))
    rows = _batch_rows(args, argnames)
    live_bytes = sum(_aval_bytes(a) for _, a in live)
    budget = contract.max_fetch_bytes_per_row * rows
    if live_bytes > budget:
        findings.append(Finding(
            checker=CHECKER, rule="fetch-bytes",
            path=path, line=0,
            message=(
                f"{contract.name}: live outputs total {live_bytes} B "
                f"for {rows} rows (contract: "
                f"{contract.max_fetch_bytes_per_row} B/row = {budget} "
                "B) — something vocab-sized or per-position is "
                "escaping to the host"
            ),
        ))

    # -- forbidden pool-shaped equations -------------------------------------
    if contract.forbid_pool_shapes:
        shapes = set()
        if contract.forbidden_shapes is not None:
            shapes.update(
                tuple(s) for s in contract.forbidden_shapes(args)
            )
        else:
            for name, arg in zip(argnames, args):
                for leaf in jtu.tree_leaves(
                    arg, is_leaf=lambda x: hasattr(x, "block_size")
                    and hasattr(x, "k")
                ):
                    if hasattr(leaf, "block_size") and hasattr(
                        leaf, "k"
                    ):
                        shapes.update(pool_shapes(leaf))
        if not shapes:
            # An empty forbidden set would make the full-pool-copy
            # check pass vacuously — the silent-cap failure mode.
            findings.append(Finding(
                checker=CHECKER, rule="no-forbidden-shapes",
                path=path, line=0,
                message=(
                    f"{contract.name}: forbid_pool_shapes is set but "
                    "no pool shapes are derivable from the example "
                    "args — give the contract a forbidden_shapes "
                    "callable (or set forbid_pool_shapes=False with "
                    "justification)"
                ),
            ))
        hits: List[str] = []
        if shapes:
            for eqn in _walk_jaxprs(traced.jaxpr):
                prim = getattr(eqn.primitive, "name", str(eqn.primitive))
                if prim not in FORBIDDEN_PRIMITIVES:
                    continue
                for outvar in eqn.outvars:
                    shape = tuple(getattr(outvar.aval, "shape", ()))
                    if shape in shapes:
                        hits.append(f"{prim} -> {shape}")
        for hit in hits[:8]:
            findings.append(Finding(
                checker=CHECKER, rule="full-pool-copy",
                path=path, line=0,
                message=(
                    f"{contract.name}: copy-class equation {hit} "
                    "materializes a pool-sized array — the no-full-"
                    "pool-copy invariant (doubles KV HBM, ms-class "
                    "per-dispatch regression)"
                ),
            ))
    return findings


def check_traces(
    registry: Dict[str, ProgramContract] = REGISTRY,
) -> List[Finding]:
    findings: List[Finding] = []
    for name in sorted(registry):
        findings.extend(check_lowering(registry[name]))
    return findings


# ---------------------------------------------------------------------------
# Serving-mesh pass
# ---------------------------------------------------------------------------

def check_mesh_lowering(
    contract: ProgramContract,
    path_hint: Optional[str] = None,
) -> List[Finding]:
    """Audit ``contract``'s SHARDED variant (``mesh_build`` example:
    sharded pool / row-sharded state / sharded params on a small
    forced-host-device serving mesh):

      1. the sharded lowering must still resolve EVERY donated leaf to
         an input-output alias (donation that survives single-chip but
         not the mesh is the silent-copy failure mode this PR's
         placement layer exists to prevent);
      2. sharding STABILITY (``mesh_aliases``): the program runs once
         and each donated input's sharding must be equivalent to its
         carried output's — drift means the next dispatch reshards
         (and un-aliases) the "donated" buffer every time."""
    import jax.tree_util as jtu

    findings: List[Finding] = []
    path = path_hint or contract.module.replace(".", "/") + ".py"
    if contract.mesh_build is None:
        return findings
    program = _resolve_program(contract)
    argnames, args, kwargs = contract.mesh_build()
    traced = program.trace(*args, **kwargs)
    lowered = traced.lower()
    donated_leaves = sum(
        sum(bool(leaf.donated) for leaf in jtu.tree_leaves(info))
        for info in lowered.args_info[0]
    )
    text = lowered.as_text()
    carried = len({int(m) for m in _ALIAS_RE.findall(text)}) + len(
        _DONOR_RE.findall(text)
    )
    if carried != donated_leaves:
        findings.append(Finding(
            checker=CHECKER, rule="mesh-donation-unresolved",
            path=path, line=0,
            message=(
                f"{contract.name} [mesh]: {donated_leaves} leaves are "
                f"donated but only {carried} carry an alias/buffer-"
                "donor attribute under the SHARDED lowering — donation "
                "that holds single-chip but not on the mesh silently "
                "copies the pool/state every dispatch"
            ),
        ))
    if not contract.mesh_aliases:
        return findings
    in_shardings: Dict[str, list] = {}
    for name, arg in zip(argnames, args):
        if name in contract.mesh_aliases:
            in_shardings[name] = [
                leaf.sharding for leaf in jtu.tree_leaves(arg)
            ]
    out = program(*args, **kwargs)
    for name, idx in sorted(contract.mesh_aliases.items()):
        want = in_shardings.get(name)
        if want is None:
            findings.append(Finding(
                checker=CHECKER, rule="mesh-alias-map",
                path=path, line=0,
                message=(
                    f"{contract.name} [mesh]: mesh_aliases names "
                    f"{name!r} but the mesh example has no such "
                    "argument"
                ),
            ))
            continue
        leaves = jtu.tree_leaves(out[idx])
        drift = [
            i for i, (a, b) in enumerate(zip(want, leaves))
            if not a.is_equivalent_to(b.sharding, b.ndim)
        ]
        if len(leaves) != len(want) or drift:
            findings.append(Finding(
                checker=CHECKER, rule="mesh-sharding-drift",
                path=path, line=0,
                message=(
                    f"{contract.name} [mesh]: donated {name!r} leaves "
                    f"{drift or 'shape-mismatched'} leave the program "
                    "with a DIFFERENT sharding than they entered with "
                    "— the next dispatch reshards (and un-aliases) the "
                    "donated buffer every time; pin the output with "
                    "serve_mesh.constrain_pool/constrain_rows"
                ),
            ))
    return findings


def check_mesh_traces(
    registry: Dict[str, ProgramContract] = REGISTRY,
) -> List[Finding]:
    findings: List[Finding] = []
    for name in sorted(registry):
        findings.extend(check_mesh_lowering(registry[name]))
    return findings


class LoweringAuditor:
    """Facade bundling the static, trace, and serving-mesh layers."""

    def __init__(self, registry: Dict[str, ProgramContract] = REGISTRY):
        self.registry = registry

    def check_package(self, trace: bool = True) -> List[Finding]:
        findings = check_static(self.registry)
        if trace and not any(
            f.rule in ("unregistered-program", "stale-contract",
                       "syntax-error")
            for f in findings
        ):
            findings.extend(check_traces(self.registry))
            findings.extend(check_mesh_traces(self.registry))
        return findings
