"""Comms-budget contracts: collective counts for sharded lowerings.

PR 10's mesh pass proves donated buffers keep their sharding across a
dispatch (no reshard of the CARRIES), but it is blind to what GSPMD
does INSIDE the program: a gathered-view or write-back slab whose
sharding propagation loses the KV-head axis compiles to a full-pool
``all-gather`` in every scan iteration — token-identical, invisible to
every parity test, and it silently eats the tensor-sharding win on a
real interconnect.  (Exactly this was live when this pass landed: the
paged write-back replicated the pool 4x per decode body and 36x per
fused body until the view/plane sharding pins in serving.py /
models/llama.py fixed it.)

This pass walks each mesh-registered program's SHARDED lowering at two
levels and checks the contract's :class:`~.contracts.CommsBudget`:

  * the traced **jaxpr** (recursing into scan/while/cond bodies) for
    explicit collective primitives — ``psum``/``all_gather``-class ops
    that shard_map kernels (the splash/paged kernels of ROADMAP item
    1) emit directly; and
  * the **compiled module** text — GSPMD inserts the partition-time
    collectives nowhere earlier, so the compiled HLO is the only
    ground truth for propagation-chosen reshards.

Checks, hardest first:

  * ``pool-collective``: any collective whose RESULT is full-pool- or
    one-plane-shaped (the contract's forbidden shapes) is a hard
    finding — never budgetable.
  * ``comms-bytes``: the largest single collective result must fit
    ``max_bytes`` (activation-sized per-layer reductions pass; a
    pool-scale reshard is 1-2 orders larger at any geometry).
  * ``comms-count``: per-kind instruction counts within
    ``max_count`` (a kind absent from the budget allows zero).
  * ``no-comms-budget``: a mesh-registered program without a declared
    budget.
"""

from __future__ import annotations

import math
import re
from typing import Any, Dict, List, Optional, Tuple

from .common import Finding
from .contracts import REGISTRY, ProgramContract, pool_shapes
from .lowering import _resolve_program, _walk_jaxprs

CHECKER = "comms"

COLLECTIVE_KINDS = (
    "all-gather", "all-reduce", "reduce-scatter", "collective-permute",
    "all-to-all",
)

# jaxpr primitive name -> collective kind (explicit shard_map-style
# collectives; GSPMD's own live only in the compiled module).
JAXPR_COLLECTIVES = {
    "all_gather": "all-gather",
    "all_gather_invariant": "all-gather",
    "psum": "all-reduce",
    "psum2": "all-reduce",
    "all_reduce": "all-reduce",
    "psum_scatter": "reduce-scatter",
    "reduce_scatter": "reduce-scatter",
    "ppermute": "collective-permute",
    "all_to_all": "all-to-all",
}

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2,
    "bf16": 2, "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8,
    "f64": 8, "c64": 8, "c128": 16,
}

# `%name = f32[2,8,16]{...} all-gather(...)` — single-array result.
_COLLECTIVE_RE = re.compile(
    r"=\s*([a-z0-9]+)\[([0-9,]*)\][^=]*?\s"
    r"(all-gather|all-reduce|reduce-scatter|collective-permute|"
    r"all-to-all)(?:-start)?\(",
)
# `%name = (f32[2,8,16]{...}, s32[4]{0}) all-gather(...)` — variadic/
# combined and async collectives carry TUPLE results; missing them
# would let a full-pool reshard hide inside a combiner-merged op.
_TUPLE_COLLECTIVE_RE = re.compile(
    r"=\s*\(([^)]*)\)[^=]*?\s"
    r"(all-gather|all-reduce|reduce-scatter|collective-permute|"
    r"all-to-all)(?:-start)?\(",
)
_SHAPE_RE = re.compile(r"([a-z0-9]+)\[([0-9,]*)\]")


def _entry(dtype: str, dims: str) -> Tuple[Tuple[int, ...], int]:
    shape = tuple(int(d) for d in dims.split(",") if d)
    return shape, int(math.prod(shape)) * _DTYPE_BYTES.get(dtype, 4)


def collectives_in_text(
    text: str,
) -> List[Tuple[str, List[Tuple[Tuple[int, ...], int]]]]:
    """[(kind, [(result shape, result bytes), ...])] — one entry per
    collective INSTRUCTION in a compiled HLO module text, with every
    element of a tuple result listed.  Async pairs count the
    ``-start`` only (the ``-done`` carries no new transfer)."""
    out: List[Tuple[str, List[Tuple[Tuple[int, ...], int]]]] = []
    for line in text.splitlines():
        if "-done(" in line:
            continue
        m = _COLLECTIVE_RE.search(line)
        if m is not None:
            out.append((m.group(3), [_entry(m.group(1), m.group(2))]))
            continue
        m = _TUPLE_COLLECTIVE_RE.search(line)
        if m is not None:
            results = [
                _entry(d, dims)
                for d, dims in _SHAPE_RE.findall(m.group(1))
            ]
            if results:
                out.append((m.group(2), results))
    return out


def collectives_in_jaxpr(
    jaxpr: Any,
) -> List[Tuple[str, Tuple[int, ...], int]]:
    """Explicit collective equations in a (Closed)Jaxpr, recursing
    into scan/while/cond bodies.  Used ONLY for the pool-shape hard
    finding, never for budget counts: every jaxpr collective appears
    in the compiled module too (counting both would double-charge
    shard_map kernels), but a Pallas/custom-call body can hide its
    collectives from the HLO text — the jaxpr walk is the safety net
    for those."""
    out: List[Tuple[str, Tuple[int, ...], int]] = []
    for eqn in _walk_jaxprs(jaxpr):
        prim = getattr(eqn.primitive, "name", str(eqn.primitive))
        kind = JAXPR_COLLECTIVES.get(prim)
        if kind is None:
            continue
        for outvar in eqn.outvars:
            aval = getattr(outvar, "aval", None)
            shape = tuple(getattr(aval, "shape", ()))
            itemsize = getattr(
                getattr(aval, "dtype", None), "itemsize", 4
            )
            out.append(
                (kind, shape, int(math.prod(shape)) * int(itemsize))
            )
    return out


def _forbidden_shapes(
    contract: ProgramContract, argnames: Tuple[str, ...], args: tuple,
) -> set:
    import jax.tree_util as jtu

    shapes = set()
    if contract.forbidden_shapes is not None:
        shapes.update(tuple(s) for s in contract.forbidden_shapes(args))
    for name, arg in zip(argnames, args):
        for leaf in jtu.tree_leaves(
            arg,
            is_leaf=lambda x: hasattr(x, "block_size") and hasattr(x, "k"),
        ):
            if hasattr(leaf, "block_size") and hasattr(leaf, "k"):
                shapes.update(pool_shapes(leaf))
    return shapes


def check_comms(
    contract: ProgramContract,
    path_hint: Optional[str] = None,
) -> List[Finding]:
    """Audit one contract's sharded lowering against its comms budget."""
    findings: List[Finding] = []
    path = path_hint or contract.module.replace(".", "/") + ".py"
    if contract.mesh_build is None:
        return findings
    if contract.comms is None:
        findings.append(Finding(
            checker=CHECKER, rule="no-comms-budget", path=path, line=0,
            message=(
                f"{contract.name}: mesh-registered program declares no "
                "CommsBudget — every sharded program must bound its "
                "collective footprint (see ProgramContract.comms)"
            ),
        ))
        return findings
    program = _resolve_program(contract)
    argnames, args, kwargs = contract.mesh_build()
    traced = program.trace(*args, **kwargs)
    compiled = traced.lower().compile()
    texts = compiled.as_text()
    text = "\n".join(texts) if isinstance(texts, (list, tuple)) else texts

    forbidden = _forbidden_shapes(contract, argnames, args)
    budget = contract.comms
    counts: Dict[str, int] = {}
    worst: Dict[str, Tuple[int, Tuple[int, ...]]] = {}

    def check_result(kind: str, shape: Tuple[int, ...],
                     nbytes: int) -> None:
        if shape in forbidden:
            findings.append(Finding(
                checker=CHECKER, rule="pool-collective",
                path=path, line=0,
                message=(
                    f"{contract.name} [mesh]: {kind} produces the "
                    f"pool shape {shape} — a full-pool reshard inside "
                    "the program (hard finding; never budgetable). "
                    "Pin the operand's sharding "
                    "(serve_mesh.constrain_view / "
                    "llama._constrain_heads) instead"
                ),
            ))
        elif nbytes > budget.max_bytes:
            findings.append(Finding(
                checker=CHECKER, rule="comms-bytes",
                path=path, line=0,
                message=(
                    f"{contract.name} [mesh]: {kind} of {shape} moves "
                    f"{nbytes} B (budget: {budget.max_bytes} B per "
                    "collective) — bigger than any per-layer reduction "
                    "the matmul sharding implies; a reshard is hiding "
                    "in the lowering"
                ),
            ))

    # Budget counts come from the COMPILED text only (one count per
    # instruction, tuple results included); the jaxpr walk below adds
    # only the pool-shape hard finding for collectives a custom-call
    # body might hide from the HLO text.
    for kind, results in collectives_in_text(text):
        counts[kind] = counts.get(kind, 0) + 1
        for shape, nbytes in results:
            if kind not in worst or nbytes > worst[kind][0]:
                worst[kind] = (nbytes, shape)
            check_result(kind, shape, nbytes)
    for kind, shape, nbytes in collectives_in_jaxpr(traced.jaxpr):
        if shape in forbidden:
            check_result(kind, shape, nbytes)
    for kind, n in sorted(counts.items()):
        allowed = budget.max_count.get(kind, 0)
        if n > allowed:
            findings.append(Finding(
                checker=CHECKER, rule="comms-count",
                path=path, line=0,
                message=(
                    f"{contract.name} [mesh]: {n} {kind} instructions "
                    f"in the compiled module (budget: {allowed}) — "
                    "the sharded lowering grew collectives beyond the "
                    "per-layer set the contract sanctions (worst "
                    f"operand: {worst[kind][1]}, {worst[kind][0]} B)"
                ),
            ))
    return findings


def check_package(
    registry: Dict[str, ProgramContract] = REGISTRY,
) -> List[Finding]:
    findings: List[Finding] = []
    for name in sorted(registry):
        findings.extend(check_comms(registry[name]))
    return findings
