"""Speculative decoding: draft-model proposal + single-pass greedy verify.

Beyond the reference's capability surface (its decode is strictly
one-token-at-a-time through HF's mixin, SURVEY.md §1) — speculative decoding
trades cheap draft-model FLOPs for target-model HBM bandwidth, the binding
resource of TPU decode: the target runs ONE forward over ``n_draft + 1``
positions per round (weights stream once) instead of one forward per token.

Greedy verification (temperature 0) is exact: the emitted sequence equals
plain greedy decode of the target model token-for-token, regardless of the
draft model's quality — the draft only controls speed (acceptance rate),
never content.  This invariant is what the tests assert.

TPU-native mechanics worth noting:
  * **No cache rollback.**  Attention masking in this framework is purely
    positional (``KVCache.pos``; -1 = invalid), so rejected draft entries
    are simply re-marked ``pos=-1`` after verification — the slots are
    wasted, never rolled back, and the whole round stays inside one jitted
    ``lax.while_loop`` with static shapes.
  * **Per-row acceptance with a shared cache index.**  Rows accept
    different prefix lengths; each row's surviving slots keep their own
    absolute positions, everything else is masked.  Batch rows never
    synchronize on acceptance.
  * Memory trade-off: caches are sized for the worst case (every round
    accepts 0 drafts): ``P + max_new * (n_draft + 1)`` target slots.  Use
    for latency-bound serving (small batch, good draft), not max-batch
    throughput.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .config import LLaMAConfig
from .engine import GenerationConfig, _is_stop, prompt_positions
from .models.llama import KVCache, forward, init_cache
from .parallel.mesh import use_mesh


@functools.partial(
    jax.jit,
    static_argnames=("target_config", "draft_config", "gen_config",
                     "n_draft", "mesh"),
)
def generate_speculative(
    target_params,
    draft_params,
    prompt_tokens: jnp.ndarray,
    prompt_mask: jnp.ndarray,
    *,
    target_config: LLaMAConfig,
    draft_config: LLaMAConfig,
    gen_config: GenerationConfig,
    n_draft: int = 4,
    mesh=None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Greedy speculative decode.

    Args:
      target_params / draft_params: param trees; models must share the
        vocabulary (draft proposes token ids the target verifies).
      prompt_tokens: [B, P] int32, left-padded.
      prompt_mask: [B, P] bool.
      gen_config: sampling policy — temperature must be 0.0 (greedy); the
        stop-token / pad semantics match ``engine.generate``.
      n_draft: draft tokens proposed per round (>= 1).
    Returns:
      (tokens [B, P + max_new_tokens] int32 — prompt then generated, pad
       after stop; accept_counts [B] int32 — total accepted draft tokens
       per row, for observability/acceptance-rate monitoring).
    """
    gc = gen_config
    if gc.temperature != 0.0:
        raise NotImplementedError(
            "speculative decoding is greedy-only (temperature 0.0); "
            "distribution-preserving sampled verification is future work"
        )
    if n_draft < 1:
        raise ValueError("n_draft must be >= 1")
    if target_config.vocab_size != draft_config.vocab_size:
        raise ValueError("target and draft must share a vocabulary")
    from .parallel.mesh import current_mesh

    if mesh is None and current_mesh() is not None:
        # Same trap engine.generate guards: an ambient use_mesh(...) is not
        # part of the jit cache key, so silently tracing under use_mesh(None)
        # here would disable every sharding constraint.
        raise ValueError(
            "generate_speculative: pass mesh= explicitly (it is part of "
            "the jit cache key); an ambient use_mesh(...) context is not "
            "seen by the compiled executable on later calls"
        )
    with use_mesh(mesh):
        return _spec_impl(
            target_params, draft_params, prompt_tokens, prompt_mask,
            target_config, draft_config, gc, n_draft,
        )


def _greedy(logits: jnp.ndarray) -> jnp.ndarray:
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


def _spec_impl(tp, dp, prompt_tokens, prompt_mask, tc, dc, gc, G):
    B, P = prompt_tokens.shape
    N = gc.max_new_tokens
    total = P + N
    positions = prompt_positions(prompt_mask)
    prompt_lens = jnp.sum(prompt_mask.astype(jnp.int32), axis=-1)  # [B]

    # Worst case: every round accepts 0 drafts -> N rounds, G+1 (target) /
    # G (draft) slots burned per round.
    t_cache = init_cache(tc, B, max_len=P + N * (G + 1))
    d_cache = init_cache(dc, B, max_len=P + N * (G + 1))

    t_logits, t_cache = forward(
        tp, prompt_tokens, positions, tc, cache=t_cache, attn_mask=prompt_mask
    )
    _, d_cache = forward(
        dp, prompt_tokens, positions, dc, cache=d_cache, attn_mask=prompt_mask
    )
    tau = _greedy(t_logits[:, -1])  # [B] first generated token

    buf = jnp.full((B, total), gc.pad_id, dtype=jnp.int32)
    buf = lax.dynamic_update_slice(buf, prompt_tokens.astype(jnp.int32), (0, 0))
    buf = buf.at[jnp.arange(B), P].set(
        jnp.where(prompt_lens > 0, tau, gc.pad_id)
    )
    done = _is_stop(tau, gc.stop_tokens)  # [B]
    count = jnp.ones((B,), jnp.int32)     # generated tokens so far (tau)
    accepted_total = jnp.zeros((B,), jnp.int32)

    # (round, buf, t_cache, d_cache, tau, count, done, accepted_total)
    init = (jnp.zeros((), jnp.int32), buf, t_cache, d_cache, tau, count,
            done, accepted_total)

    def cond(state):
        rnd, _, _, _, _, count, done, _ = state
        return jnp.logical_and(
            rnd < N, ~jnp.all(jnp.logical_or(done, count >= N))
        )

    def body(state):
        rnd, buf, t_cache, d_cache, tau, count, done, accepted_total = state
        # tau sits at per-row position p = prompt_len + count - 1.
        p = prompt_lens + count - 1  # [B]

        # --- 1. draft G tokens autoregressively ---
        def draft_one(carry, j):
            d_cache, tok = carry
            pos = (p + j)[:, None]
            lg, d_cache = forward(
                dp, tok[:, None], pos, dc, cache=d_cache,
                attn_mask=jnp.ones((B, 1), bool),
            )
            nxt = _greedy(lg[:, -1])
            return (d_cache, nxt), nxt

        (d_cache, d_last), drafts = lax.scan(
            draft_one, (d_cache, tau), jnp.arange(G, dtype=jnp.int32)
        )
        drafts = jnp.swapaxes(drafts, 0, 1)  # [B, G]
        # Feed d_G once more (logits discarded) so its KV lands in the
        # draft cache: the scan only cached inputs [tau, d_1..d_{G-1}], and
        # on a fully-accepted round the next tau is the *bonus* token at
        # p+G+1 — without this, position p+G stays a permanent hole that
        # corrupts every later draft forward and collapses acceptance in
        # exactly the high-acceptance regime.
        _, d_cache = forward(
            dp, d_last[:, None], (p + G)[:, None], dc, cache=d_cache,
            attn_mask=jnp.ones((B, 1), bool),
        )

        # --- 2. one target pass over [tau, d_1 .. d_G] ---
        block = jnp.concatenate([tau[:, None], drafts], axis=1)  # [B, G+1]
        block_pos = p[:, None] + jnp.arange(G + 1, dtype=jnp.int32)[None, :]
        t_idx = t_cache.index
        t_logits, t_cache = forward(
            tp, block, block_pos, tc, cache=t_cache,
            attn_mask=jnp.ones((B, G + 1), bool),
        )
        outs = _greedy(t_logits)  # [B, G+1]; outs[:, j] follows block[:, j]

        # --- 3. accept the matching draft prefix (+1 correction/bonus) ---
        match = (drafts == outs[:, :G])                       # [B, G]
        acc = jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=1), axis=1)
        # Emitted candidates this round: outs[:, 0..acc] (acc+1 tokens).
        j = jnp.arange(G + 1, dtype=jnp.int32)[None, :]       # [1, G+1]
        in_prefix = j <= acc[:, None]
        stopped_before = jnp.cumsum(
            _is_stop(outs, gc.stop_tokens).astype(jnp.int32), axis=1
        ) - _is_stop(outs, gc.stop_tokens).astype(jnp.int32) > 0
        emit = (
            in_prefix
            & ~stopped_before
            & ~done[:, None]
            & ((count[:, None] + j) < N)
        )

        # --- 4. write emitted tokens at per-row columns ---
        cols = jnp.where(emit, P + count[:, None] + j, total)  # OOB -> drop
        buf = buf.at[jnp.arange(B)[:, None], cols].set(outs, mode="drop")

        n_emit = jnp.sum(emit.astype(jnp.int32), axis=1)       # [B]
        # Last emitted token per row becomes the next tau.
        last_j = jnp.maximum(n_emit - 1, 0)
        new_tau = jnp.take_along_axis(outs, last_j[:, None], axis=1)[:, 0]
        tau = jnp.where(n_emit > 0, new_tau, tau)

        stopped = jnp.any(_is_stop(outs, gc.stop_tokens) & emit, axis=1)
        count = count + n_emit
        done = done | stopped | (count >= N)
        accepted_total = accepted_total + jnp.minimum(acc, jnp.maximum(n_emit - 1, 0))

        # --- 5. invalidate rejected slots (positional masking: no rollback)
        # Target wrote G+1 slots at t_idx: tau (always valid) + G drafts,
        # valid iff accepted.  (Validity beyond emission is harmless for
        # done rows — their buf writes are suppressed.)
        t_valid = j <= acc[:, None]                            # [B, G+1]
        t_patch = jnp.where(t_valid, block_pos, -1).astype(jnp.int32)
        t_cache = KVCache(
            k=t_cache.k, v=t_cache.v,
            pos=lax.dynamic_update_slice(t_cache.pos, t_patch, (0, t_idx)),
            index=t_cache.index,
        )
        # Draft wrote G+1 slots: [tau, d_1 .. d_G] — slot j holds the token
        # at position p+j, valid iff j <= acc (d_G survives exactly on a
        # fully-accepted round, when the next round needs it).
        d_idx = d_cache.index - (G + 1)
        jd = jnp.arange(G + 1, dtype=jnp.int32)[None, :]
        d_valid = jd <= acc[:, None]
        d_patch = jnp.where(
            d_valid, p[:, None] + jd, -1
        ).astype(jnp.int32)
        d_cache = KVCache(
            k=d_cache.k, v=d_cache.v,
            pos=lax.dynamic_update_slice(d_cache.pos, d_patch, (0, d_idx)),
            index=d_cache.index,
        )

        return (rnd + 1, buf, t_cache, d_cache, tau, count, done,
                accepted_total)

    _, buf, _, _, _, _, _, accepted_total = lax.while_loop(cond, body, init)
    return buf, accepted_total
