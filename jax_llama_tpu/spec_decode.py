"""Speculative decoding: draft-model proposal + single-pass greedy verify.

Beyond the reference's capability surface (its decode is strictly
one-token-at-a-time through HF's mixin, SURVEY.md §1) — speculative decoding
trades cheap draft-model FLOPs for target-model HBM bandwidth, the binding
resource of TPU decode: the target runs ONE forward over ``n_draft + 1``
positions per round (weights stream once) instead of one forward per token.

Greedy verification (temperature 0) is exact: the emitted sequence equals
plain greedy decode of the target model token-for-token, regardless of the
draft model's quality — the draft only controls speed (acceptance rate),
never content.  Sampled verification (temperature > 0) is Leviathan-style
rejection sampling and is distribution-preserving: the emitted tokens are
drawn from exactly the target's (warped) sampling distribution.  Both
invariants are what the tests assert.

TPU-native mechanics worth noting:
  * **No cache rollback.**  Attention masking in this framework is purely
    positional (``KVCache.pos``; -1 = invalid), so rejected draft entries
    are simply re-marked ``pos=-1`` after verification — the slots are
    wasted, never rolled back, and the whole round stays inside one jitted
    ``lax.while_loop`` with static shapes.
  * **Per-row acceptance with a shared cache index.**  Rows accept
    different prefix lengths; each row's surviving slots keep their own
    absolute positions, everything else is masked.  Batch rows never
    synchronize on acceptance.
  * Memory trade-off: caches are sized for the worst case (every round
    accepts 0 drafts): ``P + max_new * (n_draft + 1)`` target slots.  Use
    for latency-bound serving (small batch, good draft), not max-batch
    throughput.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .config import LLaMAConfig
from .engine import GenerationConfig, _is_stop, prompt_positions
from .models.llama import forward, init_cache
from .ops.sampling import sample, warped_probs
from .parallel.mesh import use_mesh

def _maybe_fault() -> None:
    """Chaos-drill hook: fires faults.py's trace-time registry (site
    "spec_decode") at ``generate_speculative``'s trace time.  The
    serving batcher's per-round injection is the batcher-side site of
    the same name (serving.ContinuousBatcher.step)."""
    from .faults import fire_trace

    fire_trace("spec_decode")


@functools.partial(
    jax.jit,
    static_argnames=("target_config", "draft_config", "gen_config",
                     "n_draft", "mesh"),
)
def generate_speculative(
    target_params,
    draft_params,
    prompt_tokens: jnp.ndarray,
    prompt_mask: jnp.ndarray,
    rng: Optional[jax.Array] = None,
    *,
    target_config: LLaMAConfig,
    draft_config: LLaMAConfig,
    gen_config: GenerationConfig,
    n_draft: int = 4,
    mesh=None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Speculative decode — greedy or sampled verification.

    temperature == 0.0: exact greedy verification; output is token-for-token
    identical to plain greedy decode of the target.  temperature > 0:
    Leviathan-style rejection sampling — draft token ``d ~ q`` is accepted
    with probability ``min(1, p(d)/q(d))``; on rejection the replacement is
    drawn from ``norm(relu(p - q))``; a fully-accepted round draws a bonus
    token from ``p``.  Both p and q carry the SAME temperature/top-p/top-k
    warping as ``ops.sampling.sample``, so the emitted distribution equals
    plain sampled decode of the target (the draft only changes speed).

    Args:
      target_params / draft_params: param trees; models must share the
        vocabulary (draft proposes token ids the target verifies).
      prompt_tokens: [B, P] int32, left-padded.
      prompt_mask: [B, P] bool.
      rng: PRNG key — required when temperature > 0.
      gen_config: sampling/stopping policy (matches ``engine.generate``).
      n_draft: draft tokens proposed per round (>= 1).
    Returns:
      (tokens [B, P + max_new_tokens] int32 — prompt then generated, pad
       after stop; accept_counts [B] int32 — total accepted draft tokens
       per row, for observability/acceptance-rate monitoring).
    """
    _maybe_fault()
    gc = gen_config
    if gc.temperature != 0.0 and rng is None:
        raise ValueError(
            "generate_speculative: rng is required when temperature > 0"
        )
    if n_draft < 1:
        raise ValueError("n_draft must be >= 1")
    if target_config.vocab_size != draft_config.vocab_size:
        raise ValueError("target and draft must share a vocabulary")
    from .parallel.mesh import current_mesh

    if mesh is None and current_mesh() is not None:
        # Same trap engine.generate guards: an ambient use_mesh(...) is not
        # part of the jit cache key, so silently tracing under use_mesh(None)
        # here would disable every sharding constraint.
        raise ValueError(
            "generate_speculative: pass mesh= explicitly (it is part of "
            "the jit cache key); an ambient use_mesh(...) context is not "
            "seen by the compiled executable on later calls"
        )
    if rng is None:
        rng = jax.random.PRNGKey(0)  # unused on the greedy path
    with use_mesh(mesh):
        return _spec_impl(
            target_params, draft_params, prompt_tokens, prompt_mask, rng,
            target_config, draft_config, gc, n_draft,
        )


def _greedy(logits: jnp.ndarray) -> jnp.ndarray:
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


# ---------------------------------------------------------------------------
# Shared speculative math
#
# ONE implementation of the Leviathan draft-draw / accept / residual rules,
# traced into both jit contexts that need it: the standalone engine below
# (B-wide keys, static policies) and the serving batcher's ``_spec_round``
# (per-row key chains, traced per-row policies, vmapped draws).  Sharing the
# math is what makes a sampled serving slot emit bit-identically to a
# standalone B=1 seeded ``generate_speculative`` of the same request — the
# equivalence is pinned by tests/test_serving_spec.py.
# ---------------------------------------------------------------------------

def draft_categorical(key, probs):
    """One categorical draw from a post-warp distribution — the draft
    proposal and replacement/bonus draw.  ``log(probs + 1e-30)`` keeps
    zero-probability (warped-out) tokens unreachable without -inf NaN
    traps.  Works B-wide (probs [B, V], one key) and under vmap (probs
    [V], per-row key) — ``jax.random.categorical`` draws the same bits
    for both shapes, which the serving bit-identity relies on."""
    return jax.random.categorical(
        key, jnp.log(probs + 1e-30), axis=-1
    ).astype(jnp.int32)


def leviathan_verify(pprobs, qprobs, drafts, u):
    """Leviathan-style rejection of a drafted block.

    pprobs: [B, G+1, V] post-warp target distributions (position j is the
      distribution AFTER consuming block token j, i.e. the one draft j+1
      was checked against; position G is the bonus distribution).
    qprobs: [B, G, V] post-warp draft distributions.
    drafts: [B, G] proposed tokens.  u: [B, G] uniforms.

    Draft ``d ~ q`` is accepted iff ``u * q(d) < p(d)`` (probability
    min(1, p/q)); ``acc`` is the length of the accepted prefix.  Returns
    (acc [B], dist [B, V]) where ``dist`` is the distribution for the
    token at offset ``acc``: the residual ``norm(relu(p - q))`` at the
    first rejection, or the bonus ``p_G`` on full acceptance.  Residual
    mass 0 means p <= q everywhere (p == q): rejection was probability-0
    but float rounding can reach it — fall back to p.
    """
    G = drafts.shape[1]
    p_d = jnp.take_along_axis(
        pprobs[:, :G], drafts[..., None], axis=-1
    )[..., 0]  # [B, G]
    q_d = jnp.take_along_axis(qprobs, drafts[..., None], axis=-1)[..., 0]
    accept = u * q_d < p_d
    acc = jnp.sum(jnp.cumprod(accept.astype(jnp.int32), axis=1), axis=1)
    resid = jnp.maximum(pprobs[:, :G] - qprobs, 0.0)  # [B, G, V]
    cand = jnp.concatenate([resid, pprobs[:, G:]], axis=1)
    dist = jnp.take_along_axis(cand, acc[:, None, None], axis=1)[:, 0]
    mass = jnp.sum(dist, axis=-1, keepdims=True)
    p_at = jnp.take_along_axis(pprobs, acc[:, None, None], axis=1)[:, 0]
    dist = jnp.where(mass > 1e-12, dist, p_at)
    return acc, dist


def place_extra(drafts, acc, extra):
    """Emitted block [B, G+1]: accepted drafts at offsets j < acc, the
    replacement/bonus token at offset acc (offsets past acc are dead —
    callers only consume outs[:, :acc+1])."""
    B = drafts.shape[0]
    outs = jnp.concatenate(
        [drafts, jnp.zeros((B, 1), jnp.int32)], axis=1
    )
    return outs.at[jnp.arange(B), acc].set(extra)


def accepted_emit_counts(acc, stop_hits, remaining):
    """How many of a round's accepted tokens the serving host's emit
    scan would actually deliver — the ON-DEVICE mirror of the classic
    per-round loop's token-by-token stop/budget walk over ``outs[:acc]``
    (``serving.ContinuousBatcher._spec_tail``), so the fused R-round
    chunk program can fold slot completion mid-chunk without a host
    round-trip.

    acc: [B] int32 accepted-prefix lengths (clipped to >= 0).
    stop_hits: [B, G] bool, per-position stop-set membership of the
      round's ``outs[:, :G]`` (``ops.sampling.stop_token_hits``).
    remaining: [B] int32 generation budget AFTER the round's
      pending-tau emit (the host checks ``len(emitted) >= max_new``
      after appending each token; emitting outs token i makes that
      ``i + 1 >= remaining``).
    Returns (e [B], done [B]): tokens ``outs[0..e-1]`` are emitted —
    ``e == acc`` when the row sails through, ``first_done + 1`` when
    token ``first_done`` hits a stop or exhausts the budget — and
    ``done`` marks rows whose request finished mid-prefix (their slot
    frees; fill never advances for them, exactly as on the host)."""
    G = stop_hits.shape[1]
    i = jnp.arange(G, dtype=jnp.int32)[None, :]
    cand = i < acc[:, None]
    done_at = cand & (stop_hits | ((i + 1) >= remaining[:, None]))
    done = jnp.any(done_at, axis=1)
    first = jnp.argmax(done_at, axis=1)
    return jnp.where(done, first + 1, acc), done


def _spec_impl(tp, dp, prompt_tokens, prompt_mask, rng, tc, dc, gc, G):
    B, P = prompt_tokens.shape
    N = gc.max_new_tokens
    total = P + N
    positions = prompt_positions(prompt_mask)
    prompt_lens = jnp.sum(prompt_mask.astype(jnp.int32), axis=-1)  # [B]

    # Worst case: every round accepts 0 drafts -> N rounds, G+1 (target) /
    # G (draft) slots burned per round.
    t_cache = init_cache(tc, B, max_len=P + N * (G + 1))
    d_cache = init_cache(dc, B, max_len=P + N * (G + 1))

    sampled = gc.temperature != 0.0  # static: picked at trace time
    t_logits, t_cache = forward(
        tp, prompt_tokens, positions, tc, cache=t_cache, attn_mask=prompt_mask
    )
    _, d_cache = forward(
        dp, prompt_tokens, positions, dc, cache=d_cache, attn_mask=prompt_mask
    )
    if sampled:
        rng, sub = jax.random.split(rng)
        tau = sample(sub, t_logits[:, -1], gc.temperature, gc.top_p, gc.top_k)
    else:
        tau = _greedy(t_logits[:, -1])  # [B] first generated token

    buf = jnp.full((B, total), gc.pad_id, dtype=jnp.int32)
    buf = lax.dynamic_update_slice(buf, prompt_tokens.astype(jnp.int32), (0, 0))
    buf = buf.at[jnp.arange(B), P].set(
        jnp.where(prompt_lens > 0, tau, gc.pad_id)
    )
    done = _is_stop(tau, gc.stop_tokens)  # [B]
    count = jnp.ones((B,), jnp.int32)     # generated tokens so far (tau)
    accepted_total = jnp.zeros((B,), jnp.int32)

    # (round, buf, t_cache, d_cache, tau, count, done, accepted_total, rng)
    init = (jnp.zeros((), jnp.int32), buf, t_cache, d_cache, tau, count,
            done, accepted_total, rng)

    def cond(state):
        rnd, _, _, _, _, count, done, _, _ = state
        return jnp.logical_and(
            rnd < N, ~jnp.all(jnp.logical_or(done, count >= N))
        )

    def body(state):
        (rnd, buf, t_cache, d_cache, tau, count, done, accepted_total,
         rng) = state
        rng, k_draft, k_accept, k_extra = jax.random.split(rng, 4)
        # tau sits at per-row position p = prompt_len + count - 1.
        p = prompt_lens + count - 1  # [B]

        # --- 1. draft G tokens autoregressively ---
        def draft_one(carry, j):
            d_cache, tok, key = carry
            pos = (p + j)[:, None]
            lg, d_cache = forward(
                dp, tok[:, None], pos, dc, cache=d_cache,
                attn_mask=jnp.ones((B, 1), bool),
            )
            if sampled:
                key, sub = jax.random.split(key)
                q = warped_probs(lg[:, -1], gc.temperature, gc.top_p, gc.top_k)
                nxt = draft_categorical(sub, q)
            else:
                q = jnp.zeros((B, dc.vocab_size), jnp.float32)  # unused
                nxt = _greedy(lg[:, -1])
            return (d_cache, nxt, key), (nxt, q)

        (d_cache, d_last, _), (drafts, qprobs) = lax.scan(
            draft_one, (d_cache, tau, k_draft), jnp.arange(G, dtype=jnp.int32)
        )
        drafts = jnp.swapaxes(drafts, 0, 1)   # [B, G]
        qprobs = jnp.swapaxes(qprobs, 0, 1)   # [B, G, V]
        # Feed d_G once more (logits discarded) so its KV lands in the
        # draft cache: the scan only cached inputs [tau, d_1..d_{G-1}], and
        # on a fully-accepted round the next tau is the *bonus* token at
        # p+G+1 — without this, position p+G stays a permanent hole that
        # corrupts every later draft forward and collapses acceptance in
        # exactly the high-acceptance regime.
        _, d_cache = forward(
            dp, d_last[:, None], (p + G)[:, None], dc, cache=d_cache,
            attn_mask=jnp.ones((B, 1), bool),
        )

        # --- 2. one target pass over [tau, d_1 .. d_G] ---
        block = jnp.concatenate([tau[:, None], drafts], axis=1)  # [B, G+1]
        block_pos = p[:, None] + jnp.arange(G + 1, dtype=jnp.int32)[None, :]
        t_idx = t_cache.index
        t_logits, t_cache = forward(
            tp, block, block_pos, tc, cache=t_cache,
            attn_mask=jnp.ones((B, G + 1), bool),
        )
        # --- 3. verification ---
        if sampled:
            # Leviathan rejection sampling (shared core).  pprobs/qprobs
            # are both post-warp, so acceptance min(1, p/q) + residual
            # resampling reproduce the target's sampled distribution
            # exactly.
            pprobs = warped_probs(
                t_logits, gc.temperature, gc.top_p, gc.top_k
            )  # [B, G+1, V]
            u = jax.random.uniform(k_accept, (B, G))
            acc, dist = leviathan_verify(pprobs, qprobs, drafts, u)
            extra = draft_categorical(k_extra, dist)
            outs = place_extra(drafts, acc, extra)
        else:
            outs = _greedy(t_logits)  # [B, G+1]; outs[:, j] follows block[:, j]
            # Accept the matching draft prefix (+1 correction/bonus).
            match = (drafts == outs[:, :G])                   # [B, G]
            acc = jnp.sum(
                jnp.cumprod(match.astype(jnp.int32), axis=1), axis=1
            )
        # Emitted candidates this round: outs[:, 0..acc] (acc+1 tokens).
        j = jnp.arange(G + 1, dtype=jnp.int32)[None, :]       # [1, G+1]
        in_prefix = j <= acc[:, None]
        stopped_before = jnp.cumsum(
            _is_stop(outs, gc.stop_tokens).astype(jnp.int32), axis=1
        ) - _is_stop(outs, gc.stop_tokens).astype(jnp.int32) > 0
        emit = (
            in_prefix
            & ~stopped_before
            & ~done[:, None]
            & ((count[:, None] + j) < N)
        )

        # --- 4. write emitted tokens at per-row columns ---
        cols = jnp.where(emit, P + count[:, None] + j, total)  # OOB -> drop
        buf = buf.at[jnp.arange(B)[:, None], cols].set(outs, mode="drop")

        n_emit = jnp.sum(emit.astype(jnp.int32), axis=1)       # [B]
        # Last emitted token per row becomes the next tau.
        last_j = jnp.maximum(n_emit - 1, 0)
        new_tau = jnp.take_along_axis(outs, last_j[:, None], axis=1)[:, 0]
        tau = jnp.where(n_emit > 0, new_tau, tau)

        stopped = jnp.any(_is_stop(outs, gc.stop_tokens) & emit, axis=1)
        count = count + n_emit
        done = done | stopped | (count >= N)
        accepted_total = accepted_total + jnp.minimum(acc, jnp.maximum(n_emit - 1, 0))

        # --- 5. invalidate rejected slots (positional masking: no rollback)
        # Target wrote G+1 slots at t_idx: tau (always valid) + G drafts,
        # valid iff accepted.  (Validity beyond emission is harmless for
        # done rows — their buf writes are suppressed.)
        t_valid = j <= acc[:, None]                            # [B, G+1]
        t_patch = jnp.where(t_valid, block_pos, -1).astype(jnp.int32)
        t_cache = dataclasses.replace(
            t_cache,
            pos=lax.dynamic_update_slice(t_cache.pos, t_patch, (0, t_idx)),
        )
        # Draft wrote G+1 slots: [tau, d_1 .. d_G] — slot j holds the token
        # at position p+j, valid iff j <= acc (d_G survives exactly on a
        # fully-accepted round, when the next round needs it).
        d_idx = d_cache.index - (G + 1)
        jd = jnp.arange(G + 1, dtype=jnp.int32)[None, :]
        d_valid = jd <= acc[:, None]
        d_patch = jnp.where(
            d_valid, p[:, None] + jd, -1
        ).astype(jnp.int32)
        d_cache = dataclasses.replace(
            d_cache,
            pos=lax.dynamic_update_slice(d_cache.pos, d_patch, (0, d_idx)),
        )

        return (rnd + 1, buf, t_cache, d_cache, tau, count, done,
                accepted_total, rng)

    _, buf, _, _, _, _, _, accepted_total, _ = lax.while_loop(
        cond, body, init
    )
    return buf, accepted_total
