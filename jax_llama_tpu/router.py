"""Data-parallel replica routing: N serving replicas behind one door.

The scale-out serving subsystem's outermost layer (ROADMAP item 2; the
in-replica mesh sharding lives in ``parallel/serve_mesh.py``): a
:class:`ReplicaRouter` fronts N **independent** serving replicas — each
an ``LLMServer`` with its own ``ContinuousBatcher``, KV pool, radix
prefix index and (optionally) its own mesh slice — and routes each POST
to one of them:

  * **least-loaded** (default): the healthy replica with the fewest
    router-tracked in-flight requests (ties rotate by routed count), so
    a long-generation pileup on one replica never queues new arrivals
    behind it.
  * **affinity**: sticky sessions by prompt-prefix key — a revisited
    session routes to the replica already holding its radix chain, so
    multi-turn chats keep their prefix-cache hits (and host-tier slabs)
    local instead of re-prefilling cold on a random replica.  New
    sessions fall back to least-loaded; a dead replica's sessions
    re-pin wherever their next turn lands.
  * **cache-aware**: GLOBALLY cache-aware routing off the router-side
    radix index (below) — each request routes to the replica holding
    the DEEPEST matching chain prefix fleet-wide, spilling to
    least-loaded past the occupancy watermark (``spill_occupancy``,
    in-flight/slots).  Replaces affinity's single-pinned-replica LRU
    with exact fleet-wide knowledge: N replicas behave as ONE
    coherent prefix cache, so fleet TTFT tracks the global hit depth,
    not per-replica luck.

**Global radix index** (:class:`RouterRadixIndex`, cache-aware
policy): every replica's chain digest folded into one map
``chain-prefix key -> {replica: (depth, tier)}``.  Kept fresh
INCREMENTALLY off the /healthz poller — a digest ``version`` delta in
the scrape triggers ``GET /debug/kv?since=<synced>``, whose journaled
events (publish/remove/demote/restore) replay into the index at
O(changes); the bounded journal falling short (rebuild reset, poller
too far behind) falls back to one full node-walk replace.  The
request's own chain keys come from :func:`chain_keys` (the ONE shared
key schema; tokenization happens on the router thread OUTSIDE the
routing lock, mirroring the replica's own /generate-/chat encoding).
A hit whose holder's LIVE digest version has moved past the synced
one routes anyway but counts ``llm_router_cache_stale_routes_total``
— it degrades to a cold prefill, never to wrong tokens.

**Handoff scheduler**: when the deepest-prefix replica sits past the
occupancy watermark the request spills to least-loaded, and — when
``depth x (occupancy gap)`` clears ``handoff_threshold`` (and depth
>= ``handoff_min_depth``) — the chain MIGRATES to where the request
landed: a background worker drives ``export_prefix`` (with
``demote_after_export``, so the move deduplicates fleet HBM) on the
source's serving-loop thread and ``import_prefix`` on the
destination's, through ``LLMServer.call_on_loop`` (the batchers stay
thread-confined).  Bounds: at most ONE in-flight handoff per chain,
``handoff_max_bytes_inflight`` total estimated bytes moving,
``handoff_timeout_s`` wall budget per job (timeouts unwind cleanly on
both sides and count as aborted; the serving side owns the
no-partial-publish contract).  The triggering request NEVER waits —
its first token rides a cold prefill on the spill target; the next
turn hits warm.

**Prefill/decode disaggregation** (``roles=("prefill", "decode",
...)``, run.py ``--replica-roles``; requires cache-aware): cold
prompts route to the least-loaded PREFILL replica; a request
completing there streams its freshly published chain to the
least-loaded decode replica via the same export->import path, and the
session's routing record re-pins at the destination — so first turns
prefill on the prefill pool and every revisit decodes warm on the
decode pool.  Deep index hits route to the holding replica regardless
of role (the KV is there).

**Health / quarantine.**  A poller thread scrapes each replica's
``/healthz`` (the server's own ok/draining/degraded verdict — a replica
in drain or with a dead loop stops receiving new work while its
in-flight requests finish); a forward-time connection failure (or an
injected ``router_replica`` fault) marks the replica unhealthy
immediately.  Requests that have not yet streamed a byte RE-ROUTE to a
surviving replica losslessly; requests in flight on a genuinely crashed
replica are that replica's own crash-recovery problem (rebuild + replay
— the PR-1 machinery), not the router's: the router never duplicates a
request it may have half-delivered.

:func:`handoff_prefix` remains the direct two-batcher handoff helper
(tests/drills drive it on the owning threads); live traffic goes
through the scheduler above, which reaches each batcher via its
server's control path (``call_on_loop``).

HTTP surface (the router speaks the same protocol as a single server,
so clients need no changes):

    POST /generate, /chat    routed + proxied (streaming NDJSON relays
                             line-by-line); the response carries
                             X-Replica-Id, and the replica's request
                             timeline records the routing decision
                             (X-Routed-By -> /debug/requests/<id>)
    GET  /healthz            aggregate: ok = any replica routable, plus
                             a ``replicas`` section (per-replica
                             health/occupancy/mesh snapshot)
    GET  /metrics            router gauges + per-replica labeled series
    GET  /debug/kv/fleet     FLEET CACHE VIEW (schema below)
    GET  /debug/trace        FLEET-MERGED Perfetto trace (schema below)
    GET  /debug/fleet        the health sentinel's fleet view: per-
                             replica health score in [0,1], verdict
                             (healthy/suspect/critical), per-signal
                             subscores + active anomalies, last canary
                             result, edge-triggered anomaly counters,
                             and the fleet verdict (worst replica) —
                             what ROADMAP item 3's autoscaler consults
                             before killing or draining a replica
    GET  /debug/decisions    the router's decision audit log
                             (obs.DecisionLog): route decisions WITH
                             their candidate sets/scores/hit depths,
                             reroutes, handoff outcomes, canary
                             results, anomalies, verdict flips —
                             ?n=/?kind=/?request_id= filter; the
                             request_id filter joins a decision trail
                             to its request timeline
    GET  /debug/bundle       flight-recorder postmortem artifact:
                             router config + aggregate health + fleet
                             sentinel view + last-N decisions + log
                             tail + fleet-merged trace + (default)
                             every healthy replica's own bundle
                             (?replicas=0 / ?trace=0 to slim)

**Synthetic canary probes** (``canary_interval_s > 0``; manual
``run_canaries_now()`` otherwise): the router periodically POSTs a
tiny deterministic greedy probe (fixed token prompt/seed, the RESERVED
``"canary"`` priority class — replicas serve it but exclude it from
SLO attainment, goodput, latency histograms and the brownout ladder's
inputs) DIRECTLY to every replica, routable or not.  Each sweep
probes EVERY replica first and only then judges tokens against the
fleet ORACLE — the plurality token sequence among the sweep's
successful probes (same weights + greedy decode ⇒ replica-
independent, so healthy fleets are unanimous); a pinned oracle
RE-PINS when a strict majority later agrees on a different sequence
(counted ``canary_oracle_repins_total`` — a corrupt replica probed
first, or a legitimate fleet-wide output change), and
``reset_canary_oracle()`` is the operator hook for planned rollouts.
A probe disagreeing with the settled oracle is a counted token
MISMATCH — the wrong-output failure latency metrics cannot see.
Probe success/latency feeds the per-replica **health sentinel**
(:class:`HealthSentinel`): EWMA/z-score detectors over canary
latency, replica ITL EWMA, queue-wait p90, SLO attainment and scrape
staleness produce a [0,1] health score and a healthy/suspect/critical
verdict per replica, raising edge-triggered, counted, logged
``anomaly`` events into the decision log.  The sentinel never acts —
it is the trustworthy sensor the future autoscaler reads.
    GET  /debug/requests     index aggregated across ALL healthy
                             replicas, each entry tagged ``replica``
    GET  /debug/requests/<id>  resolved through the ROUTING RECORD
                             first (the bounded request-id -> replica
                             map the relay fills from each reply's
                             X-Request-Id), then healthy-replica
                             fan-out — never first-to-answer guessing
    GET  /debug/*            (everything else) tried against each
                             healthy replica until one answers non-404

Fleet-merged tracing (``GET /debug/trace[?window_s=S]``): ONE
Chrome/Perfetto ``trace_event`` document containing

  * the router's own span track (pid 0, process_name ``router``):
    ``route`` (decision; args replica/policy/request_id), ``forward``
    (relay wall time; timeout/client-disconnect flagged), ``reroute``
    (a failed replica's lossless re-route) and ``handoff``
    (cross-replica prefix-KV moves, args request_id/blocks) spans,
    recorded in a bounded ring under ``_lock``;
  * every healthy replica's own ``/debug/trace`` export re-tagged to
    pid ``1+index`` (process_name ``replica-<index>``) with its
    timestamps shifted into the router's frame via the ``t0_unix_s``
    wall-clock anchor each Observability ring publishes (clock-offset
    normalization — replica monotonic clocks share no epoch);
  * handoff linkage: the router's ``handoff`` span and both replicas'
    ``prefix_export`` / ``prefix_import`` instants carry the same
    external request id, so a prefill-on-A / decode-on-B session
    reads as one timeline across three tracks.

**Fleet cache view** (``GET /debug/kv/fleet[?depth=D]``, r13): the
router-side aggregation of every healthy replica's chain digest
(``GET /debug/kv``, scraped on demand with probe-class timeouts —
never from the poller; the poller's ``/healthz`` scrape already
carries each replica's O(1) digest summary under ``kv.digest``)::

    {"fleet": {
       "prefix_hit_ratio": float,        # sum(hit tokens)/sum(prompt)
       "prefix_hit_tokens_total": int, "prompt_tokens_total": int,
       "duplicate_chains": int,          # chain keys HBM-resident on
                                         # >= 2 replicas
       "duplicate_kv_blocks": int,       # copies beyond the first
       "duplicate_kv_bytes": int,        # ... priced per replica's
                                         # block_bytes — the HBM a
                                         # cache-aware scheduler
                                         # (ROADMAP item 2) reclaims
       "replicas_scraped": [int, ...],
       "truncated_replicas": [int, ...], # digests cut at the node cap
                                         # (duplicates = LOWER bound)
       "scrape_ms": float},
     "replicas": [{"replica": int, "summary": {<replica /debug/kv
                   summary>}, "hit_ratio": float,
                   "hbm_bytes": int}, ...]}

The computed aggregate is cached for ``/metrics``:
``llm_fleet_duplicate_kv_blocks`` / ``llm_fleet_duplicate_kv_bytes`` /
``llm_fleet_prefix_hit_ratio`` / ``llm_fleet_kv_age_s`` (samples
appear after the first fleet-view computation).  Per-replica labeled
cache gauges ride every scrape of the health poller:
``llm_router_replica_kv_{nodes,hbm_blocks,host_blocks,idle_blocks,
digest_version,hit_ratio}`` — qualified by
``llm_replica_health_age_s`` (seconds since that replica's labeled
values were last refreshed; -1 = never scraped; an unroutable
replica's gauges persist STALE, so dashboards gate on the age).
Digest freshness also feeds the affinity policy: an affinity hit onto
a replica whose digest ``loss_version`` changed since the session
pinned (evictions/demotions — or a rebuild, which resets versions)
still routes there, but as a counted, logged stale event
(``llm_router_affinity_stale_routes_total``; the pin refreshes to the
observed version so one loss event counts once) instead of a silent
cache miss.

**Elastic fleet** (:class:`FleetController`, r16): the control loop
that closes ROADMAP item 2 — scaling, draining and rolling the fleet
with zero dropped sessions on any PLANNED event.  Three actuators,
every action a ``kind="scale"`` / ``"drain"`` / ``"rollout"`` decision
record carrying the signals that drove it:

  * **autoscaler** (``tick()``; periodic when ``interval_s > 0``):
    windowed fleet pressure — mean interactive attainment below
    ``attainment_floor`` or queue-wait p90 above ``queue_wait_high_ms``
    — must HOLD for ``dwell_s`` before a scale-up, calm must hold for
    ``dwell_s`` before a scale-down, and every action starts a
    ``cooldown_s`` refractory window (the PR 9 brownout ladder's
    hysteresis shape, fleet-sized).  A scale-down victim must carry a
    ``healthy`` sentinel verdict: the controller NEVER kills a replica
    the sentinel can't explain (a suspect/critical replica defers the
    action into a recorded ``hold``).
  * **live session migration** (``drain_replica(idx)``, also the
    operator entry): the victim stops admitting (router-side
    ``retiring`` flag — excluded from every pick, /healthz untouched
    so its loop stays alive for export), in-flight requests finish,
    then every HBM-resident chain (``resident_chain_keys`` on the
    victim's loop) moves to a survivor through the SAME
    export -> import -> residency-probe -> demote path the handoff
    scheduler uses (``_execute_migration``; demote gated on proven
    destination residency, so an aborted move never costs the fleet
    its only copy), the global index re-pins optimistically and
    affinity pins / routing records re-point — revisits continue
    token-identically on the destination.  A failed drain RESUMES the
    victim (sessions keep serving at the source; nothing dropped).
  * **zero-downtime rollout** (``rollout(factory)``): replica by
    replica — drain, swap in the factory's new-weights server
    (``swap_replica``: sentinel + index state forgotten, retired old
    server via ``shutdown_for_restart``), then the rung GATE:
    ``reset_canary_oracle()`` + a full canary sweep, and the restarted
    replica's probe must be transport-clean AND token-match the
    ROLLOUT oracle (pinned from the first rung's probe — the fleet
    majority is still old weights mid-rollout, so the fleet oracle
    would misjudge a legitimate output change).  A failed gate
    auto-rolls the rung back onto ``rollback_factory``'s server and
    aborts.  After the last rung: one more reset + sweep over the now
    homogeneous fleet, which must be unanimously clean before the
    rollout reports complete.

Fault sites ``scale_event`` (fired at each action start — injected
fault aborts the whole action cleanly, fleet membership unchanged) and
``session_migrate`` (fired per migrating session — injected fault
aborts that session's move only; the source copy stays, the session
keeps serving there) make every step chaos-drillable.  ``/metrics``
gains ``llm_fleet_scale_events_total{action=up|down|deferred|aborted}``,
``llm_sessions_migrated_total`` and ``llm_rollout_rung`` (current rung,
-1 idle); ``GET /debug/fleet`` gains a ``controller`` section
(state/signals/counters) when a controller is attached.

Thread discipline: handler threads (forward), the health poller, and
the handoff worker share the replica table, counters, routing record,
trace ring, the handoff scheduler's dedup/bounds state, and the
cached fleet cache view — every access goes under ``_lock``
(registered in analysis/lockcheck.py).  The global radix index keeps
its own leaf lock (lock order router -> index, never inverted).  The
fleet controller keeps its own leaf lock over its counters/ladder
state and NEVER holds it while calling into the router or a replica
(compute-under-lock, act-outside — same shape as the overload
ladder).  The router holds no jax state at all; it is pure host-side
HTTP — batcher work it schedules runs on the replicas' own
serving-loop threads via ``LLMServer.call_on_loop``."""

from __future__ import annotations

import hashlib
import http.client
import json
import queue
import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple
from urllib.parse import parse_qs, unquote, urlsplit

import numpy as np

from .faults import FaultInjector, InjectedFault
from .obs import DecisionLog, EwmaDetector, StructuredLogger
from .overload import CANARY

POLICIES = ("least-loaded", "affinity", "cache-aware")
ROLES = ("prefill", "decode")

# ---------------------------------------------------------------------------
# Router-side Prometheus registry (analysis/metricscheck.py audits it)
# ---------------------------------------------------------------------------

# FULL metric name -> (type, help).  The router renders its own text
# exposition (no obs.METRICS pipeline out here), so this registry is
# its HELP/TYPE source — and the metrics-registry lint checks it both
# ways: every registered family must be emitted somewhere in this
# module, and every emitted ``llm_router_*`` / ``llm_fleet_*`` /
# ``llm_replica_*`` family must be registered.
ROUTER_METRICS: Dict[str, Tuple[str, str]] = {
    "llm_router_replicas": (
        "gauge", "Replicas behind this router"),
    "llm_router_replicas_healthy": (
        "gauge", "Replicas currently routable"),
    "llm_router_routed_requests_total": (
        "counter", "Requests routed, by decision policy"),
    "llm_router_reroutes_total": (
        "counter", "Requests re-routed off a failed replica"),
    "llm_router_replica_failures_total": (
        "counter", "Forward-time replica failures observed"),
    "llm_router_kv_handoffs_total": (
        "counter", "Cross-replica prefix-KV handoffs brokered"),
    "llm_router_affinity_sessions": (
        "gauge", "Sticky sessions currently pinned"),
    "llm_router_affinity_stale_routes_total": (
        "counter",
        "Affinity routes taken onto a replica whose chain digest "
        "changed since the session pinned (possible cache miss — "
        "counted, no longer silent)"),
    "llm_router_cache_index_nodes": (
        "gauge", "Chain-prefix keys in the router's global radix "
                 "index, summed over replicas"),
    "llm_router_cache_index_replicas_synced": (
        "gauge", "Replicas whose chain digest has been folded into "
                 "the global index"),
    "llm_router_cache_index_syncs_total": (
        "counter", "Digest syncs applied to the global index "
                   "(incremental + full)"),
    "llm_router_cache_index_resyncs_total": (
        "counter", "Full node-walk resyncs (journal could not prove "
                   "completeness — rebuilds, or a poller too far "
                   "behind)"),
    "llm_router_cache_index_events_applied_total": (
        "counter", "Journaled digest events applied incrementally"),
    "llm_router_cache_stale_routes_total": (
        "counter",
        "Cache-aware routes taken onto a holder whose live digest "
        "version moved past the index's synced one (possible cold "
        "prefill — counted, never wrong tokens)"),
    "llm_router_cache_hit_depth_blocks_total": (
        "counter", "Cumulative matched prefix depth (blocks) over "
                   "cache-aware routed requests"),
    "llm_router_handoffs_scheduled_total": (
        "counter", "Chain migrations admitted into the handoff queue"),
    "llm_router_handoffs_completed_total": (
        "counter", "Chain migrations that landed blocks on the "
                   "destination"),
    "llm_router_handoffs_aborted_total": (
        "counter", "Chain migrations that failed or timed out "
                   "(unwound cleanly; chain re-eligible)"),
    "llm_router_handoffs_skipped_total": (
        "counter", "Chain migrations refused at admission "
                   "(bytes-in-flight bound, or an out-of-process "
                   "replica)"),
    "llm_router_handoff_bytes_inflight": (
        "gauge", "Estimated slab bytes currently moving between "
                 "replicas"),
    "llm_router_handoff_bytes_total": (
        "counter", "Slab bytes landed on destinations by completed "
                   "handoffs"),
    # -- fleet cache aggregate (last GET /debug/kv/fleet computation) --
    "llm_fleet_duplicate_kv_blocks": (
        "gauge", "HBM blocks holding chain prefixes duplicated on "
                 ">= 2 replicas (copies beyond the first; last "
                 "fleet-view computation)"),
    "llm_fleet_duplicate_kv_bytes": (
        "gauge", "HBM bytes behind the duplicate chain blocks — the "
                 "disaggregation scheduler's reclaimable redundancy"),
    "llm_fleet_prefix_hit_ratio": (
        "gauge", "Fleet-wide fraction of admitted prompt tokens "
                 "served from cached prefix blocks (last fleet-view "
                 "computation)"),
    "llm_fleet_kv_age_s": (
        "gauge", "Seconds since the fleet cache view was last "
                 "computed"),
    # -- control-plane observability (decision log, canaries, sentinel) --
    "llm_router_decisions_total": (
        "counter", "Control-plane decisions recorded in the router "
                   "audit log, by kind (GET /debug/decisions)"),
    "llm_router_canary_probes_total": (
        "counter", "Synthetic canary probes sent (reserved canary "
                   "request class; every replica, routable or not)"),
    "llm_router_canary_failures_total": (
        "counter", "Canary probes that failed (connect error, "
                   "non-200, timeout)"),
    "llm_router_canary_mismatches_total": (
        "counter", "Canary probes whose greedy tokens disagreed with "
                   "the fleet oracle (the wrong-output detector)"),
    "llm_router_canary_oracle_repins_total": (
        "counter", "Canary oracle re-pins: a strict majority of a "
                   "sweep's successful probes agreed on a DIFFERENT "
                   "token sequence than the pinned oracle (the pin "
                   "was wrong, or the fleet's output legitimately "
                   "changed)"),
    "llm_router_anomalies_total": (
        "counter", "Health-sentinel anomaly events by signal "
                   "(edge-triggered: one event per healthy -> "
                   "anomalous transition per replica)"),
    # -- elastic fleet controller (FleetController; zeros until one
    #    is attached — families always exposed for discovery) --------
    "llm_fleet_scale_events_total": (
        "counter", "Fleet controller scale actions by outcome "
                   "(up / down / deferred / aborted; every one is a "
                   "kind=scale decision record with its signals)"),
    "llm_sessions_migrated_total": (
        "counter", "Live sessions moved to a survivor by drain "
                   "migration (export -> import -> residency-gated "
                   "demote; zero dropped by contract)"),
    "llm_rollout_rung": (
        "gauge", "Replica index the in-progress rollout is restarting "
                 "(-1 = no rollout in progress)"),
    "llm_router_fleet_verdict": (
        "gauge", "Worst replica health verdict (0 healthy / 1 "
                 "suspect / 2 critical) — the GET /debug/fleet "
                 "verdict an autoscaler consumes"),
    # -- per-replica labeled gauges (qualified by health age) ----------
    "llm_router_replica_healthy": (
        "gauge", "Replica routable (per replica)"),
    "llm_router_replica_inflight": (
        "gauge", "Router-tracked in-flight requests (per replica)"),
    "llm_router_replica_routed_total": (
        "counter", "Requests routed to this replica"),
    "llm_router_replica_active_slots": (
        "gauge", "Replica batcher slots holding a live request (last "
                 "health scrape)"),
    "llm_router_replica_mesh_devices": (
        "gauge", "Devices in the replica's serving mesh (last health "
                 "scrape)"),
    "llm_replica_health_age_s": (
        "gauge", "Seconds since this replica's labeled gauges were "
                 "last refreshed from a successful /healthz scrape "
                 "(-1 = never scraped; stale values persist for "
                 "unroutable replicas — gate on this)"),
    "llm_router_replica_kv_nodes": (
        "gauge", "Chain-digest nodes (keyed blocks) on this replica "
                 "(last health scrape)"),
    "llm_router_replica_kv_hbm_blocks": (
        "gauge", "HBM-resident chain blocks on this replica (last "
                 "health scrape)"),
    "llm_router_replica_kv_host_blocks": (
        "gauge", "Host-tier-resident chain blocks on this replica "
                 "(last health scrape)"),
    "llm_router_replica_kv_idle_blocks": (
        "gauge", "Idle (refcount-0, evictable) chain blocks on this "
                 "replica (last health scrape)"),
    "llm_router_replica_kv_digest_version": (
        "gauge", "Chain-digest content version on this replica (last "
                 "health scrape)"),
    "llm_router_replica_kv_hit_ratio": (
        "gauge", "Replica fraction of admitted prompt tokens served "
                 "from cached prefix blocks (last health scrape)"),
    "llm_router_replica_health_score": (
        "gauge", "Sentinel health score in [0, 1] (per replica: "
                 "blends canary success, canary-latency / ITL / "
                 "queue-wait z-scores, SLO attainment, and scrape "
                 "staleness)"),
    "llm_router_replica_verdict": (
        "gauge", "Sentinel verdict per replica (0 healthy / 1 "
                 "suspect / 2 critical)"),
    "llm_router_replica_canary_latency_ms": (
        "gauge", "Last canary probe round-trip latency (per replica; "
                 "-1 = never probed)"),
    "llm_router_replica_canary_ok": (
        "gauge", "Last canary probe outcome (1 ok / 0 failed or "
                 "mismatched / -1 never probed)"),
}


def chain_keys(tokens: Sequence[int], block_size: int) -> List[bytes]:
    """Chain hash per FULL prompt block: ``key_j = H(key_{j-1},
    block-j tokens)``, so a hit at block j certifies the whole prefix
    up to it.  Only blocks strictly before the last token are keyed
    (at least one token must run through the model to produce the
    first sample).

    THE one shared key schema of the prefix-cache stack: the batcher's
    radix index, the KvDigest journal, and the router's global radix
    index all speak these keys — ``ContinuousBatcher._chain_keys``
    delegates here (the helper lives in this module because the
    router must stay jax-free)."""
    m = (len(tokens) - 1) // block_size
    keys: List[bytes] = []
    h = hashlib.blake2b(digest_size=16)
    for j in range(m):
        h.update(
            np.asarray(
                tokens[j * block_size:(j + 1) * block_size], np.int32
            ).tobytes()
        )
        keys.append(h.digest())  # digest() is non-destructive
    return keys


class RouterRadixIndex:
    """The router-side GLOBAL radix index: every replica's published
    chain digest folded into one map ``chain-prefix key -> {replica:
    (depth, tier)}``, so the cache-aware policy can route each request
    to the replica holding the DEEPEST matching prefix fleet-wide.

    Kept fresh INCREMENTALLY off the health poller: each successful
    ``/healthz`` scrape carries the replica's O(1) digest summary;
    when its ``version`` differs from the index's last synced version
    the poller fetches ``GET /debug/kv?since=<synced>`` and applies
    the journaled events (``publish``/``remove``/``demote``/
    ``restore`` — ``host_evict`` is a counter-only bump), falling back
    to a full node-walk replace when the bounded journal cannot prove
    completeness (consumer too far behind, or a crash-recovery rebuild
    reset the digest).  O(changes) per poll, not O(nodes).

    Thread discipline: own leaf ``_lock`` (registered in
    analysis/lockcheck.py) — the health poller writes, handler threads
    read at pick time, the handoff worker applies optimistic updates.
    The router's ``_lock`` may be held while calling in (lock order
    router -> index, never inverted: sync paths take only this
    lock)."""

    def __init__(self):
        self._lock = threading.Lock()
        # replica -> {key_hex: (depth, tier)}
        self._by_replica: Dict[int, Dict[str, Tuple[int, str]]] = {}
        # replica -> last applied digest version
        self._synced: Dict[int, int] = {}
        # replica -> digest epoch the synced version belongs to: a
        # rebuild resets versions AND mints a new epoch, so version
        # arithmetic across epochs is meaningless (a replay can
        # re-advance past the synced version — version aliasing) and
        # the consumer must full-resync on any epoch change.
        self._epoch: Dict[int, Any] = {}
        # replica -> block_bytes (handoff byte-budget pricing)
        self._block_bytes: Dict[int, int] = {}
        self.syncs_total = 0
        self.resyncs_total = 0
        self.events_applied_total = 0

    def synced_version(self, replica: int) -> Optional[int]:
        with self._lock:
            return self._synced.get(replica)

    def synced_epoch(self, replica: int) -> Optional[Any]:
        with self._lock:
            return self._epoch.get(replica)

    def block_bytes(self, replica: int) -> int:
        with self._lock:
            return self._block_bytes.get(replica, 0)

    def replace(self, replica: int, nodes: Sequence[Dict[str, Any]],
                version: int, block_bytes: int = 0,
                epoch: Any = None) -> None:
        """Full resync: adopt a replica's complete node walk."""
        table = {
            str(n["key"]): (int(n.get("depth", 0)),
                            str(n.get("tier", "hbm")))
            for n in nodes if isinstance(n, dict) and n.get("key")
        }
        with self._lock:
            self._by_replica[replica] = table
            self._synced[replica] = int(version)
            if epoch is not None:
                self._epoch[replica] = epoch
            if block_bytes:
                self._block_bytes[replica] = int(block_bytes)
            self.syncs_total += 1
            self.resyncs_total += 1

    def apply_events(self, replica: int,
                     events: Sequence[Dict[str, Any]],
                     version: int, block_bytes: int = 0,
                     epoch: Any = None) -> None:
        """Incremental sync: apply journaled digest mutations in
        order (idempotent per event — optimistic handoff updates may
        have pre-applied some)."""
        with self._lock:
            table = self._by_replica.setdefault(replica, {})
            for ev in events:
                op = ev.get("op")
                key = str(ev.get("key"))
                if op == "publish":
                    table[key] = (int(ev.get("depth", 0)), "hbm")
                elif op == "remove":
                    table.pop(key, None)
                elif op in ("demote", "restore"):
                    ent = table.get(key)
                    depth = (
                        ent[0] if ent is not None
                        else int(ev.get("depth", 0))
                    )
                    table[key] = (
                        depth, "host" if op == "demote" else "hbm"
                    )
                # host_evict: counter-only (removal journals itself)
            self._synced[replica] = int(version)
            if epoch is not None:
                self._epoch[replica] = epoch
            if block_bytes:
                self._block_bytes[replica] = int(block_bytes)
            self.syncs_total += 1
            self.events_applied_total += len(events)

    def note_handoff(self, src: int, dst: int,
                     keys_hex: Sequence[str]) -> None:
        """Optimistic post-handoff update so the NEXT request routes
        to the chain's new home immediately (the poller's sync
        confirms/corrects at the next scrape): the destination gains
        the chain HBM-resident, the demoted-after-export source drops
        to host tier."""
        with self._lock:
            dmap = self._by_replica.setdefault(dst, {})
            smap = self._by_replica.setdefault(src, {})
            for i, k in enumerate(keys_hex):
                ent = smap.get(k)
                depth = ent[0] if ent is not None else i + 1
                dmap[k] = (depth, "hbm")
                if ent is not None:
                    smap[k] = (depth, "host")

    def lookup(
        self, keys_hex: Sequence[str], replicas: Set[int],
    ) -> Optional[Tuple[int, List[Tuple[int, str]]]]:
        """Deepest fleet-wide prefix match: walk the chain keys from
        the leaf back toward the root; the first key held by any of
        ``replicas`` wins.  Returns ``(depth, [(replica, tier),...])``
        — depth in blocks (1-based), holders of that deepest key —
        or None on a fleet-wide miss."""
        with self._lock:
            for i in range(len(keys_hex) - 1, -1, -1):
                k = keys_hex[i]
                holders = [
                    (r, self._by_replica[r][k][1])
                    for r in replicas
                    if k in self._by_replica.get(r, {})
                ]
                if holders:
                    return i + 1, holders
        return None

    def drop_replica(self, replica: int) -> None:
        """Forget everything synced from ``replica`` (retirement or a
        rollout swap): its table, synced version, epoch and block
        pricing — the swapped-in instance starts from a full resync,
        and a retired one stops contributing phantom holders to
        lookups."""
        with self._lock:
            self._by_replica.pop(replica, None)
            self._synced.pop(replica, None)
            self._epoch.pop(replica, None)
            self._block_bytes.pop(replica, None)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "nodes": sum(
                    len(t) for t in self._by_replica.values()
                ),
                "replicas_synced": len(self._synced),
                "syncs_total": self.syncs_total,
                "resyncs_total": self.resyncs_total,
                "events_applied_total": self.events_applied_total,
            }


# ---------------------------------------------------------------------------
# Per-replica health score + anomaly sentinel
# ---------------------------------------------------------------------------

# Sentinel signals, each contributing one [0, 1] subscore per replica:
#   canary      EWMA of canary probe success; a token MISMATCH pins 0
#   latency     canary round-trip latency z-score vs its own baseline
#   itl         replica inter-token-latency EWMA z-score (healthz)
#   queue_wait  replica queue-wait p90 z-score (healthz overload)
#   attainment  smoothed interactive SLO attainment (healthz overload)
#   staleness   age of the last successful health scrape
SENTINEL_SIGNALS = (
    "canary", "latency", "itl", "queue_wait", "attainment", "staleness",
)

VERDICTS = ("healthy", "suspect", "critical")
VERDICT_INDEX = {v: i for i, v in enumerate(VERDICTS)}


class _SentinelState:
    """One replica's sentinel state (mutated under the sentinel lock)."""

    __slots__ = (
        "sub", "anomalous", "latency", "itl", "queue_wait",
        "canary_ok_ewma", "score", "verdict", "last_canary",
    )

    def __init__(self, alpha: float, min_samples: int,
                 floor_ms: float):
        self.sub: Dict[str, float] = {s: 1.0 for s in SENTINEL_SIGNALS}
        self.anomalous: Dict[str, bool] = {
            s: False for s in SENTINEL_SIGNALS
        }
        # All three z-scored signals are MILLISECOND latencies: the
        # absolute divisor floor (floor_ms) keeps a sub-ms-baseline
        # replica's harmless single-digit-ms blip from scoring as a
        # 500-sigma anomaly.
        self.latency = EwmaDetector(
            alpha=alpha, min_samples=min_samples, floor=floor_ms
        )
        self.itl = EwmaDetector(
            alpha=alpha, min_samples=min_samples, floor=floor_ms
        )
        self.queue_wait = EwmaDetector(
            alpha=alpha, min_samples=min_samples, floor=floor_ms
        )
        self.canary_ok_ewma = 1.0
        self.score = 1.0
        self.verdict = "healthy"
        self.last_canary: Optional[Dict[str, Any]] = None


class HealthSentinel:
    """Per-replica health score + anomaly detector (module docstring).

    Pure host bookkeeping over the signals the router already has —
    canary probe results (success, token match, latency) and /healthz
    scrape values (ITL EWMA, queue-wait p90, interactive attainment,
    scrape age).  Each signal keeps a [0, 1] subscore (z-scored
    signals via :class:`~jax_llama_tpu.obs.EwmaDetector` against the
    replica's OWN baseline, so a uniformly slow fleet is not five
    anomalies); the replica's health score blends them MIN-biased
    (``0.5 * min + 0.5 * mean`` — one collapsed signal must drag the
    score even while five others read 1.0) and maps to a verdict:
    ``healthy`` / ``suspect`` / ``critical``.

    Anomaly events are EDGE-triggered per (replica, signal): one
    counted event on the healthy -> anomalous transition (plus a
    cleared event on recovery), never one per poll — the counters
    count incidents, not samples.  The sentinel never ACTS: it is the
    trustworthy sensor layer the future autoscaler (ROADMAP item 3)
    reads via ``GET /debug/fleet`` before it is allowed to kill or
    drain a replica; routing keeps its own health/quarantine rules.

    Thread discipline: observe_* are called by the canary prober and
    the health poller while handler threads read fleet_json — every
    access goes under the sentinel's own leaf lock (registered in
    analysis/lockcheck.py; never held while calling out)."""

    def __init__(
        self,
        z_threshold: float = 3.0,
        alpha: float = 0.2,
        min_samples: int = 5,
        suspect_below: float = 0.8,
        critical_below: float = 0.5,
        attainment_floor: float = 0.75,
        staleness_allowance_s: float = 10.0,
        z_floor_ms: float = 5.0,
    ):
        self.z_threshold = float(z_threshold)
        self.alpha = float(alpha)
        self.min_samples = int(min_samples)
        # Absolute z-divisor floor for the ms-scale signals (canary
        # latency / ITL / queue wait): deviations under ~z_threshold x
        # this never flag, however tight the healthy baseline was.
        self.z_floor_ms = float(z_floor_ms)
        self.suspect_below = float(suspect_below)
        self.critical_below = float(critical_below)
        self.staleness_allowance_s = float(staleness_allowance_s)
        # Per-signal anomaly bars on the subscore: canary needs a few
        # consecutive successes to clear (EWMA alpha 0.5 -> ~3 probes
        # back above 0.9); attainment uses the SLO-ish floor.
        self._bars: Dict[str, float] = {
            "canary": 0.9, "latency": 0.5, "itl": 0.5,
            "queue_wait": 0.5, "attainment": float(attainment_floor),
            "staleness": 0.5,
        }
        self._lock = threading.Lock()
        self._states: Dict[int, _SentinelState] = {}
        self.anomalies_total: Dict[str, int] = {
            s: 0 for s in SENTINEL_SIGNALS
        }

    # audit: locked(every caller holds self._lock)
    def _state_locked(self, replica: int) -> _SentinelState:
        st = self._states.get(replica)
        if st is None:
            st = self._states[replica] = _SentinelState(
                self.alpha, self.min_samples, self.z_floor_ms
            )
        return st

    def _z_subscore(self, z: Optional[float]) -> float:
        """[0, 1] subscore from a one-sided z-score: 1.0 inside the
        threshold (or during warmup — no baseline, no verdict),
        decaying linearly to 0 at twice the threshold.  Only HIGH
        values are anomalous for every z-scored signal here (latency /
        ITL / queue wait dropping is good news)."""
        if z is None or z <= self.z_threshold:
            return 1.0
        return max(
            0.0, 1.0 - (z - self.z_threshold) / self.z_threshold
        )

    # audit: locked(every caller holds self._lock)
    def _signal_locked(
        self, st: _SentinelState, signal: str, sub: float,
        events: List[Dict[str, Any]], **fields,
    ) -> None:
        st.sub[signal] = round(max(0.0, min(1.0, float(sub))), 4)
        bad = st.sub[signal] < self._bars[signal]
        if bad and not st.anomalous[signal]:
            st.anomalous[signal] = True
            self.anomalies_total[signal] += 1
            events.append(dict(
                {"kind": "anomaly", "signal": signal,
                 "subscore": st.sub[signal]},
                **{k: v for k, v in fields.items() if v is not None},
            ))
        elif st.anomalous[signal] and not bad:
            st.anomalous[signal] = False
            events.append({
                "kind": "anomaly_cleared", "signal": signal,
                "subscore": st.sub[signal],
            })

    # audit: locked(every caller holds self._lock)
    def _rescore_locked(
        self, st: _SentinelState,
    ) -> List[Dict[str, Any]]:
        vals = list(st.sub.values())
        st.score = round(
            0.5 * min(vals) + 0.5 * (sum(vals) / len(vals)), 4
        )
        v = (
            "critical" if st.score < self.critical_below
            else "suspect" if st.score < self.suspect_below
            else "healthy"
        )
        if v == st.verdict:
            return []
        prev, st.verdict = st.verdict, v
        return [{
            "kind": "verdict", "verdict": v, "previous": prev,
            "score": st.score,
        }]

    def observe_canary(
        self, replica: int, ok: bool,
        latency_ms: Optional[float] = None, mismatch: bool = False,
        error: Optional[str] = None,
    ) -> List[Dict[str, Any]]:
        """Feed one canary probe result; returns the emitted events
        (anomaly / anomaly_cleared / verdict) for the caller to log,
        count and record into its decision log."""
        events: List[Dict[str, Any]] = []
        with self._lock:
            st = self._state_locked(replica)
            st.last_canary = {
                "ok": bool(ok), "mismatch": bool(mismatch),
                "latency_ms": (
                    round(float(latency_ms), 3)
                    if latency_ms is not None else None
                ),
                "error": error,
                "unix_s": round(time.time(), 3),
            }
            st.canary_ok_ewma = (
                0.5 * st.canary_ok_ewma + 0.5 * (1.0 if ok else 0.0)
            )
            sub = 0.0 if mismatch else st.canary_ok_ewma
            self._signal_locked(
                st, "canary", sub, events,
                mismatch=mismatch or None, error=error,
            )
            if ok and latency_ms is not None:
                z = st.latency.update(float(latency_ms))
                self._signal_locked(
                    st, "latency", self._z_subscore(z), events,
                    z=round(z, 3) if z is not None else None,
                    latency_ms=round(float(latency_ms), 3),
                )
            events.extend(self._rescore_locked(st))
        return events

    def observe_health(
        self, replica: int, reachable: bool,
        attainment: Optional[float] = None,
        queue_wait_ms: Optional[float] = None,
        itl_ms: Optional[float] = None,
        age_s: Optional[float] = None,
    ) -> List[Dict[str, Any]]:
        """Feed one /healthz scrape (or scrape failure): attainment /
        queue-wait p90 / ITL EWMA from the payload, ``age_s`` = time
        since the last SUCCESSFUL scrape (0 on success; grows while a
        replica stays unreachable — the digest/telemetry staleness
        signal)."""
        events: List[Dict[str, Any]] = []
        with self._lock:
            st = self._state_locked(replica)
            if age_s is not None:
                allow = self.staleness_allowance_s
                sub = (
                    1.0 if age_s <= allow
                    else max(0.0, 1.0 - (age_s - allow) / (3.0 * allow))
                )
                self._signal_locked(
                    st, "staleness", sub, events,
                    age_s=round(age_s, 3), reachable=reachable,
                )
            if reachable:
                if attainment is not None:
                    a = self.alpha
                    sub = (
                        (1.0 - a) * st.sub["attainment"]
                        + a * float(attainment)
                    )
                    self._signal_locked(
                        st, "attainment", sub, events,
                        attainment=round(float(attainment), 4),
                    )
                if queue_wait_ms is not None:
                    z = st.queue_wait.update(float(queue_wait_ms))
                    self._signal_locked(
                        st, "queue_wait", self._z_subscore(z), events,
                        z=round(z, 3) if z is not None else None,
                        queue_wait_ms=round(float(queue_wait_ms), 3),
                    )
                if itl_ms is not None:
                    z = st.itl.update(float(itl_ms))
                    self._signal_locked(
                        st, "itl", self._z_subscore(z), events,
                        z=round(z, 3) if z is not None else None,
                        itl_ms=round(float(itl_ms), 3),
                    )
            events.extend(self._rescore_locked(st))
        return events

    def forget(self, replica: int) -> None:
        """Drop a replica's sentinel state (retirement, or a rollout
        swapping a fresh instance into its slot): the new occupant
        starts from clean baselines — inheriting the predecessor's
        EWMA latency baselines would z-flag a legitimately different
        instance, and inheriting its anomalies would block the
        autoscaler's sentinel gate on ghosts.  The edge-triggered
        anomaly counters keep their history (incidents happened)."""
        with self._lock:
            self._states.pop(replica, None)

    def score(self, replica: int) -> float:
        with self._lock:
            st = self._states.get(replica)
            return st.score if st is not None else 1.0

    def verdict(self, replica: int) -> str:
        with self._lock:
            st = self._states.get(replica)
            return st.verdict if st is not None else "healthy"

    def fleet_json(self) -> Dict[str, Any]:
        """Per-replica scores/verdicts/signals + the fleet verdict
        (worst replica) — the core of ``GET /debug/fleet``."""
        with self._lock:
            replicas = {
                i: {
                    "score": st.score,
                    "verdict": st.verdict,
                    "signals": dict(st.sub),
                    "anomalous": sorted(
                        s for s, bad in st.anomalous.items() if bad
                    ),
                    "last_canary": (
                        dict(st.last_canary)
                        if st.last_canary is not None else None
                    ),
                }
                for i, st in self._states.items()
            }
            worst = max(
                (VERDICT_INDEX[st.verdict]
                 for st in self._states.values()),
                default=0,
            )
            anomalies = dict(self.anomalies_total)
        return {
            "verdict": VERDICTS[worst],
            "verdict_index": worst,
            "replicas": replicas,
            "anomalies_total": anomalies,
        }


class _ClientDisconnect(Exception):
    """The CLIENT's socket died while relaying — the replica is fine.
    Distinct from replica-side OSErrors so a disconnecting client never
    marks a healthy replica unhealthy; ``relayed`` records whether any
    bytes reached the client before the drop."""

    def __init__(self, relayed: bool):
        super().__init__("client disconnected")
        self.relayed = relayed

# Hop-by-hop / recomputed headers never relayed from a replica reply.
_SKIP_HEADERS = frozenset({
    "connection", "transfer-encoding", "content-length", "server",
    "date",
})

# Prompt-prefix length (tokens or characters) the affinity key hashes:
# long enough to separate sessions with a shared system prompt short
# of one block, short enough that appending turns to a chat keeps the
# key (and therefore the replica holding the chain) stable.
_AFFINITY_PREFIX = 64


@dataclass
class _Replica:
    """Router-side view of one serving replica."""

    index: int
    host: str
    port: int
    server: Any = None            # in-process LLMServer (caller-owned)
    healthy: bool = True
    inflight: int = 0
    routed_total: int = 0
    failures_total: int = 0
    last_health: Dict[str, Any] = field(default_factory=dict)
    # Monotonic instant of the last SUCCESSFUL health scrape (0.0 =
    # never scraped).  A replica that goes unroutable keeps its last
    # scraped values in ``last_health`` — the per-replica labeled
    # /metrics gauges would silently serve stale numbers, so the
    # exposition emits ``llm_replica_health_age_s`` alongside them and
    # dashboards gate on it.
    last_health_t: float = 0.0
    # Elastic-fleet lifecycle (FleetController).  ``retiring``: drain
    # in progress — excluded from every routing pick but still alive
    # (scraped, canaried, /healthz ok) so its serving loop can run the
    # session-migration exports; cleared by resume or retirement.
    # ``retired``: permanently out of the fleet — never picked,
    # scraped or canaried again.  Retired entries KEEP their list slot
    # (``self._replicas[i].index == i`` is a structural invariant the
    # handoff scheduler and the labeled /metrics series rely on); new
    # replicas only ever append.
    retiring: bool = False
    retired: bool = False

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def kv_digest(self) -> Dict[str, Any]:
        """The chain-digest summary of the last health scrape (empty
        dict before the first scrape / from pre-digest replicas)."""
        return (self.last_health.get("kv") or {}).get("digest") or {}

    def snapshot(self) -> Dict[str, Any]:
        h = self.last_health
        return {
            "index": self.index,
            "address": self.address,
            "healthy": self.healthy,
            "inflight": self.inflight,
            "routed_total": self.routed_total,
            "failures_total": self.failures_total,
            "retiring": self.retiring,
            "retired": self.retired,
            "draining": h.get("draining"),
            "degraded": h.get("degraded"),
            "overload_state": (h.get("overload") or {}).get("state"),
            "replica": h.get("replica"),
            "health_age_s": (
                round(time.monotonic() - self.last_health_t, 3)
                if self.last_health_t > 0 else None
            ),
            "kv": h.get("kv"),
        }


def _parse_address(addr: str) -> Tuple[str, int]:
    """Accepts ``host:port`` or ``http://host:port`` (LLMServer's own
    ``.address`` spelling)."""
    if addr.startswith("http://"):
        addr = addr[len("http://"):]
    addr = addr.rstrip("/")
    host, _, port = addr.rpartition(":")
    return host or "127.0.0.1", int(port)


class ReplicaRouter:
    """HTTP front-end routing requests across serving replicas
    (module docstring).  ``replicas`` mixes in-process ``LLMServer``
    instances (must already be started; their lifecycle stays with the
    caller) and ``"host:port"`` strings for out-of-process ones."""

    def __init__(
        self,
        replicas: Sequence[Any],
        host: str = "127.0.0.1",
        port: int = 0,
        policy: str = "least-loaded",
        health_interval_s: float = 0.5,  # <= 0: manual (tests) —
        #                                  check_health_now() only
        proxy_timeout_s: float = 300.0,
        affinity_max_sessions: int = 4096,
        fault_injector: Optional[FaultInjector] = None,
        logger: Optional[StructuredLogger] = None,
        # -- cache-aware routing (policy="cache-aware") -----------------
        tokenizer: Any = None,
        block_size: Optional[int] = None,
        chat_format: Any = None,
        roles: Optional[Sequence[str]] = None,
        spill_occupancy: float = 1.0,
        # -- handoff scheduler ------------------------------------------
        handoff_threshold: float = 1.0,
        handoff_min_depth: int = 1,
        handoff_max_bytes: int = 256 << 20,
        handoff_max_bytes_inflight: int = 64 << 20,
        handoff_timeout_s: float = 30.0,
        demote_after_export: bool = True,
        # -- control-plane observability --------------------------------
        canary_interval_s: float = 0.0,  # <= 0: manual (tests) —
        #                                  run_canaries_now() only
        canary_prompt: Optional[Sequence[int]] = None,
        canary_max_new: int = 4,
        canary_timeout_s: float = 10.0,
        sentinel: Optional[HealthSentinel] = None,
        decision_ring: int = 1024,
    ):
        if policy not in POLICIES:
            raise ValueError(
                f"unknown route policy {policy!r}; have {POLICIES}"
            )
        if not replicas:
            raise ValueError("ReplicaRouter needs at least one replica")
        if roles is not None:
            roles = tuple(str(r) for r in roles)
            if len(roles) != len(replicas):
                raise ValueError(
                    f"roles ({len(roles)}) must name every replica "
                    f"({len(replicas)})"
                )
            bad = sorted(set(roles) - set(ROLES))
            if bad:
                raise ValueError(
                    f"unknown replica roles {bad}; have {ROLES}"
                )
            if not ("prefill" in roles and "decode" in roles):
                raise ValueError(
                    "prefill/decode disaggregation needs at least one "
                    "replica of EACH role"
                )
            if policy != "cache-aware":
                raise ValueError(
                    "replica roles require the cache-aware policy "
                    "(the disaggregation scheduler routes off the "
                    "global radix index)"
                )
        if policy == "cache-aware" and block_size is None:
            raise ValueError(
                "cache-aware routing needs block_size (the chain-key "
                "granularity every replica's radix index uses)"
            )
        self.policy = policy
        self.fault_injector = fault_injector
        # No logger supplied -> a QUIET one (ring only): stdout stays
        # silent but the /debug/bundle flight-recorder log tail still
        # records every router lifecycle line.
        self.logger = (
            logger if logger is not None
            else StructuredLogger(quiet=True)
        )
        self.health_interval_s = float(health_interval_s)
        self.proxy_timeout_s = float(proxy_timeout_s)
        self.affinity_max_sessions = int(affinity_max_sessions)
        # Cache-aware routing + handoff scheduling knobs (ctor-stable).
        self.tokenizer = tokenizer
        self.block_size = block_size
        self.chat_format = chat_format
        self.roles = roles
        self.spill_occupancy = float(spill_occupancy)
        self.handoff_threshold = float(handoff_threshold)
        self.handoff_min_depth = int(handoff_min_depth)
        self.handoff_max_bytes = int(handoff_max_bytes)
        self.handoff_max_bytes_inflight = int(handoff_max_bytes_inflight)
        self.handoff_timeout_s = float(handoff_timeout_s)
        self.demote_after_export = bool(demote_after_export)
        self.index = RouterRadixIndex()
        # Control-plane observability: the decision audit log (own
        # leaf lock; GET /debug/decisions), the per-replica health
        # sentinel (own leaf lock; GET /debug/fleet), and the
        # synthetic canary prober's knobs/counters (counters under
        # self._lock below).
        self.decisions = DecisionLog(ring=decision_ring)
        self.sentinel = (
            sentinel if sentinel is not None else HealthSentinel()
        )
        # Elastic-fleet controller (attach_controller): written once
        # at attach time before any scale action runs; /debug/fleet
        # and /metrics read it to render the controller section.
        self.controller: Optional["FleetController"] = None
        self.canary_interval_s = float(canary_interval_s)
        self.canary_prompt = [
            int(t) for t in (canary_prompt or (1, 2, 3))
        ]
        self.canary_max_new = int(canary_max_new)
        self.canary_timeout_s = float(canary_timeout_s)
        self._lock = threading.Lock()
        self._replicas: List[_Replica] = []
        for i, rep in enumerate(replicas):
            if isinstance(rep, str):
                h, p = _parse_address(rep)
                self._replicas.append(_Replica(index=i, host=h, port=p))
            else:  # in-process LLMServer
                h, p = _parse_address(rep.address)
                self._replicas.append(
                    _Replica(index=i, host=h, port=p, server=rep)
                )
        # Sticky-session map: affinity key -> [replica index, the
        # replica's chain-digest loss_version at pin time] (bounded
        # LRU — hits refresh recency, so long-lived active sessions
        # are not the eviction victims; a dead replica's entries
        # re-pin on next use).  The loss_version is the digest-
        # freshness check: a later hit whose replica has since evicted
        # or demoted chains (loss_version changed) is routed anyway —
        # affinity is a locality HINT, not a correctness contract —
        # but as a COUNTED, logged stale-route event instead of a
        # silent cache miss (affinity_stale_routes_total; the entry
        # re-pins at the observed version so one loss event counts
        # once, not on every subsequent turn).
        self._affinity: "OrderedDict[bytes, List[Any]]" = OrderedDict()
        self.routed_by_policy: Dict[str, int] = {
            "least-loaded": 0, "affinity": 0, "reroute": 0,
            "cache-aware": 0, "spill": 0, "prefill-role": 0,
        }
        self.reroutes_total = 0
        self.replica_failures_total = 0
        self.kv_handoffs_total = 0
        self.affinity_stale_routes_total = 0
        # Canary prober state: the oracle is the FIRST successful
        # probe's greedy tokens — every replica serves the same
        # weights, and greedy decode is replica-independent (mesh
        # parity pins tokens exact), so later disagreement means a
        # replica is producing WRONG OUTPUT, the failure no latency
        # metric can see.
        self.canary_probes_total = 0
        self.canary_failures_total = 0
        self.canary_mismatches_total = 0
        self.canary_oracle_repins_total = 0
        self._canary_oracle: Optional[List[int]] = None
        self._canary_seq = 0
        # Cache-aware routing counters: stale = the index said HIT but
        # the holder's live digest version moved past the synced one
        # (eviction / rebuild mid-flight) — routed anyway, counted,
        # degrades to a cold prefill, never to wrong tokens.
        self.cache_stale_routes_total = 0
        self.cache_hit_depth_blocks_total = 0
        # Handoff scheduler state: per-chain in-flight dedup (at most
        # ONE in-flight handoff per chain), bytes-in-flight bound, and
        # the outcome ledger.  The job queue itself is a thread-safe
        # queue drained by the router-handoff worker.
        self._handoff_chains: Set[str] = set()
        self._handoff_bytes_inflight = 0
        # Role-handoff intents registered at route time, cleared once
        # _maybe_role_handoff ran (or the attempt failed) — lets
        # wait_handoffs() see a migration that a just-completed reply
        # is about to schedule.
        self._role_handoffs_pending = 0
        self.handoffs_scheduled_total = 0
        self.handoffs_completed_total = 0
        self.handoffs_aborted_total = 0
        self.handoffs_skipped_total = 0
        self.handoffs_empty_total = 0
        self.handoff_blocks_total = 0
        self.handoff_bytes_total = 0
        self._handoff_q: "queue.Queue[Dict[str, Any]]" = queue.Queue()
        # Last computed fleet cache view (fleet_kv_json fills it; the
        # /metrics fleet gauges read it) — None until the first
        # GET /debug/kv/fleet.
        self._fleet_kv: Optional[Dict[str, Any]] = None
        # Router-local trace ring (fleet-merged /debug/trace): bounded
        # span dicts, appended under _lock by handler threads.  The
        # monotonic/wall anchors are captured at the same instant —
        # the same clock-offset contract obs.Observability publishes.
        self._t0 = time.monotonic()
        self.t0_unix = time.time()
        self._trace: "deque[Dict[str, Any]]" = deque(maxlen=1024)
        # Routing record: external request id -> replica index
        # (bounded LRU, filled by the relay from each reply's
        # X-Request-Id header) — /debug/requests/<id> consults it
        # before any fan-out.
        self._routes: "OrderedDict[str, int]" = OrderedDict()
        self.route_record_max = 4096
        self._closed = threading.Event()
        router = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet test output
                pass

            def do_GET(self):
                router._handle_get(self)

            def do_POST(self):
                router._handle_post(self)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="router-http",
        )
        self._health_thread = threading.Thread(
            target=self._health_loop, daemon=True, name="router-health",
        )
        self._handoff_thread = threading.Thread(
            target=self._handoff_loop, daemon=True,
            name="router-handoff",
        )
        self._canary_thread = threading.Thread(
            target=self._canary_loop, daemon=True,
            name="router-canary",
        )

    # -- lifecycle -----------------------------------------------------------

    @property
    def address(self) -> str:
        h, p = self._httpd.server_address[:2]
        return f"http://{h}:{p}"

    def start(self) -> "ReplicaRouter":
        self._http_thread.start()
        self._health_thread.start()
        self._handoff_thread.start()
        if self.canary_interval_s > 0:
            self._canary_thread.start()
        return self

    def stop(self) -> None:
        """Stop the router (replica lifecycles stay with the caller)."""
        self._closed.set()
        self._httpd.shutdown()
        self._httpd.server_close()
        self._health_thread.join(timeout=5)
        if self._handoff_thread.is_alive():
            self._handoff_thread.join(timeout=5)
        if self._canary_thread.is_alive():
            self._canary_thread.join(timeout=5)

    def __enter__(self) -> "ReplicaRouter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _log(self, event: str, message: str = "", **fields) -> None:
        # self.logger is never None (the ctor substitutes a quiet
        # ring-only logger), so every event reaches the bundle tail.
        self.logger.log(event, message, **fields)

    # -- router-local tracing / routing record -------------------------------

    def _now_ms(self) -> float:
        return (time.monotonic() - self._t0) * 1000.0

    def _span(self, name: str, t0_ms: float, **args) -> None:
        """Close a router span started at ``t0_ms`` (None-valued args
        drop, so absent request ids don't litter the trace)."""
        dur = max(0.0, self._now_ms() - t0_ms)
        rec = {
            "name": name, "t0_ms": round(t0_ms, 3),
            "dur_ms": round(dur, 3),
            "args": {k: v for k, v in args.items() if v is not None},
        }
        with self._lock:
            self._trace.append(rec)

    def _note_route(self, request_id: Optional[str],
                    index: int) -> None:
        """Record which replica served ``request_id`` (bounded LRU) —
        the /debug/requests/<id> resolution path."""
        if not request_id:
            return
        with self._lock:
            self._routes[request_id] = index
            self._routes.move_to_end(request_id)
            while len(self._routes) > self.route_record_max:
                self._routes.popitem(last=False)

    # -- health --------------------------------------------------------------

    def _probe(self, rep: _Replica) -> Tuple[bool, Dict[str, Any]]:
        """One /healthz scrape; (routable, payload).  A 503 body still
        parses (draining replicas report their state)."""
        conn = http.client.HTTPConnection(
            rep.host, rep.port, timeout=2.0
        )
        try:
            conn.request("GET", "/healthz")
            resp = conn.getresponse()
            payload = json.loads(resp.read() or b"{}")
            return bool(payload.get("ok")), payload
        finally:
            conn.close()

    def _health_loop(self) -> None:
        if self.health_interval_s <= 0:
            # Manual mode (deterministic drills/tests): health moves
            # only through check_health_now() and forward failures.
            return
        while not self._closed.is_set():
            with self._lock:
                reps = [r for r in self._replicas if not r.retired]
            for rep in reps:
                self._scrape_replica(rep)
            self._closed.wait(self.health_interval_s)

    def check_health_now(self) -> None:
        """Synchronous health sweep (tests / deterministic drills) —
        the SAME per-replica step as the poller, so manual-mode drills
        and production produce identical audit trails.  Retired
        replicas are skipped (their servers are gone; scraping them
        would only burn probe timeouts and pollute the sentinel)."""
        with self._lock:
            reps = [r for r in self._replicas if not r.retired]
        for rep in reps:
            self._scrape_replica(rep)

    def _scrape_replica(self, rep: _Replica) -> None:
        """One replica's health step, shared by the poller thread and
        ``check_health_now``: /healthz probe, lock-held health flip,
        global radix index sync (digest-version delta only), sentinel
        feed, and — on a flip — the health log line + the
        ``replica_health`` decision record."""
        try:
            ok, payload = self._probe(rep)
        except (OSError, ValueError, http.client.HTTPException):
            ok, payload = False, {}
        with self._lock:
            was = rep.healthy
            rep.healthy = ok
            if payload:
                rep.last_health = payload
                rep.last_health_t = time.monotonic()
        if payload:
            # Global radix index sync rides the poll for free: only a
            # digest-version DELTA triggers the (mostly incremental)
            # /debug/kv fetch.
            self._sync_index(rep, payload)
        self._sentinel_scrape(rep, payload)
        if was != ok:
            self._log(
                "router_replica_health",
                replica=rep.index, healthy=ok,
            )
            self.decisions.record(
                "replica_health", replica=rep.index, healthy=ok,
            )

    def _sentinel_scrape(self, rep: _Replica,
                         payload: Dict[str, Any]) -> None:
        """Feed one /healthz scrape outcome into the health sentinel
        (runs on the poller thread / check_health_now's caller, never
        under the router lock): attainment + queue-wait p90 from the
        overload section, the ITL EWMA from the replica section, and
        the scrape age as the staleness signal (0 on success, growing
        while the replica stays unreachable)."""
        if payload:
            age: Optional[float] = 0.0
        else:
            with self._lock:
                lt = rep.last_health_t
            # Never-scraped replicas have no baseline to be stale
            # against — the canary (connect failure) covers them.
            age = (time.monotonic() - lt) if lt > 0 else None
        ov = (payload.get("overload") or {}) if payload else {}
        repl = (payload.get("replica") or {}) if payload else {}
        events = self.sentinel.observe_health(
            rep.index, reachable=bool(payload),
            attainment=ov.get("interactive_attainment"),
            queue_wait_ms=ov.get("queue_wait_ms_p90"),
            itl_ms=repl.get("itl_ms_ewma"),
            age_s=age,
        )
        self._ingest_sentinel_events(rep.index, events)

    def _ingest_sentinel_events(
        self, replica: int, events: Sequence[Dict[str, Any]],
    ) -> None:
        """Record sentinel-emitted events (anomaly / anomaly_cleared /
        verdict) into the decision audit log + structured log — the
        counted, logged ``anomaly_*`` trail the acceptance drill
        asserts."""
        for ev in events:
            kind = ev.get("kind", "anomaly")
            fields = {k: v for k, v in ev.items() if k != "kind"}
            self.decisions.record(kind, replica=replica, **fields)
            self._log(f"router_{kind}", replica=replica, **fields)

    def _sync_index(self, rep: _Replica,
                    payload: Dict[str, Any]) -> None:
        """Fold ``rep``'s chain digest into the global radix index
        when its version moved past the last synced one.  Runs on the
        poller thread (or check_health_now's caller) OUTSIDE the
        router lock — the /debug/kv fetch is an HTTP round-trip.
        Incremental (``?since=``, journal replay) whenever the
        replica's bounded journal covers the gap; full node-walk
        replace otherwise."""
        if self.policy != "cache-aware":
            return  # least-loaded/affinity never read the index
        dig = (payload.get("kv") or {}).get("digest") or {}
        ver = dig.get("version")
        if ver is None:
            return  # pre-digest replica
        since = self.index.synced_version(rep.index)
        if dig.get("epoch") != self.index.synced_epoch(rep.index):
            # A rebuild minted a new digest instance: versions live in
            # a different history (a replay can re-advance PAST the
            # synced version — version aliasing), so incremental
            # deltas are meaningless.  Full resync.
            since = None
        if since is not None and since == ver:
            return
        # Both forms ask for an effectively unbounded node count: the
        # ``since`` form can FALL BACK to the full walk server-side
        # (journal gap), and the server default (2048) would silently
        # truncate large pools — adopting a truncated walk as the
        # replica's complete table would hide exactly the deepest
        # (most valuable) chains from the index with no later repair
        # (occupancy is bounded by the replica's pool blocks, so the
        # payload stays sane).
        path = (
            "/debug/kv?n=1000000" if since is None
            else f"/debug/kv?since={since}&n=1000000"
        )
        got = self._get_replica_json(rep, path)
        if got is None or got[0] != 200:
            return
        doc = got[1]
        summ = doc.get("summary") or {}
        bb = int(summ.get("block_bytes") or 0)
        epoch = summ.get("epoch", dig.get("epoch"))
        applied = doc.get("version", ver)
        if "events" in doc:
            self.index.apply_events(
                rep.index, doc["events"], applied, bb, epoch=epoch,
            )
        else:
            if doc.get("truncated"):
                self._log(
                    "router_index_truncated_sync",
                    replica=rep.index,
                    truncated=doc.get("truncated"),
                )
            self.index.replace(
                rep.index, doc.get("nodes", []), applied, bb,
                epoch=epoch,
            )
        if applied != ver:
            # The digest moved between the /healthz scrape and the
            # /debug/kv fetch: the index is now FRESHER than the
            # stored health snapshot.  Refresh the snapshot's version
            # so the pick-time staleness check (synced != live) does
            # not miscount every hit as stale until the next poll.
            with self._lock:
                dig2 = (rep.last_health.get("kv") or {}).get("digest")
                if isinstance(dig2, dict):
                    dig2["version"] = applied

    # -- routing -------------------------------------------------------------

    def _affinity_key(self, payload: Dict[str, Any]) -> Optional[bytes]:
        """Session key: the prompt's leading tokens/characters (chat
        dialogs key on the first message — the system prompt — which is
        exactly the shared radix prefix)."""
        try:
            if isinstance(payload.get("prompt"), list):
                head = payload["prompt"][:_AFFINITY_PREFIX]
                return b"p:" + json.dumps(head).encode()
            if isinstance(payload.get("text"), str):
                return b"t:" + payload["text"][:_AFFINITY_PREFIX].encode()
            msgs = payload.get("messages")
            if isinstance(msgs, list) and msgs:
                first = msgs[0]
                if isinstance(first, dict):
                    return b"m:" + str(
                        first.get("content", "")
                    )[:_AFFINITY_PREFIX].encode()
        except (TypeError, ValueError, UnicodeEncodeError):
            return None
        return None

    def _routing_keys(
        self, path: str, payload: Dict[str, Any],
    ) -> Optional[List[str]]:
        """The request's chain-prefix keys (hex) for the cache-aware
        index lookup — computed OUTSIDE the routing lock (tokenizing
        is the expensive part).  Mirrors exactly what the replica's
        ``_submit`` will encode: /chat dialogs through the chat
        format, ``prompt`` token lists verbatim, ``text`` through the
        tokenizer (bos, no eos).  None = unroutable-by-cache (no
        tokenizer for text, malformed payload, policy not
        cache-aware): the pick falls back to load/role routing."""
        if self.policy != "cache-aware" or self.block_size is None:
            return None
        try:
            if path == "/chat":
                if self.chat_format is None:
                    return None
                msgs = payload.get("messages")
                if not isinstance(msgs, list) or not msgs:
                    return None
                tokens = self.chat_format.encode_dialog_prompt(msgs)
            elif isinstance(payload.get("prompt"), list):
                tokens = [int(t) for t in payload["prompt"]]
            elif (
                isinstance(payload.get("text"), str)
                and self.tokenizer is not None
            ):
                tokens = self.tokenizer.encode(
                    payload["text"], bos=True, eos=False
                )
            else:
                return None
        except (TypeError, ValueError, KeyError, AttributeError):
            return None  # the replica will 400 it; route by load
        return [k.hex() for k in chain_keys(tokens, self.block_size)]

    def _occupancy_locked(self, rep: _Replica) -> float:
        """Replica load as a slot fraction (caller holds ``_lock``):
        router-tracked in-flight requests over the replica's slot
        count from its last health scrape.  An unscraped replica
        reports its raw in-flight count — any load reads as past the
        watermark, so cache-aware routing stays conservative until
        the poller has numbers."""
        h = (rep.last_health.get("replica") or {})
        slots = int(h.get("n_slots") or 0)
        if slots <= 0:
            return float(rep.inflight)
        return rep.inflight / slots

    def _candidates_info_locked(
        self, candidates: List[_Replica],
    ) -> List[Dict[str, Any]]:
        """The decision-audit view of the candidate set (caller holds
        ``_lock``): per candidate, the load facts the pick minimizes
        over — what lets ``/debug/decisions`` answer "why replica Y"
        with the alternatives it beat."""
        return [
            {
                "replica": r.index,
                "inflight": r.inflight,
                "occupancy": round(self._occupancy_locked(r), 4),
                "routed_total": r.routed_total,
            }
            for r in candidates
        ]

    def _cache_pick_locked(
        self, chain: Optional[List[str]],
        candidates: List[_Replica],
        decision: Dict[str, Any],
    ) -> Tuple[_Replica, str, bool, Optional[Dict[str, Any]]]:
        """The cache-aware decision (caller holds ``_lock``): route to
        the replica holding the DEEPEST matching prefix fleet-wide,
        spilling to least-loaded past the occupancy watermark;
        returns ``(replica, how, stale, handoff_plan)`` where a
        non-None plan asks the scheduler to migrate the chain to
        where the request landed (depth x load disagreement past the
        configured threshold).  Cold prompts route least-loaded — or
        to the least-loaded PREFILL replica under role
        disaggregation.  ``decision`` (the audit-log record under
        construction) gains the hit depth, holder set, staleness and
        spill facts the choice was made from."""
        least = min(
            candidates, key=lambda r: (r.inflight, r.routed_total)
        )
        hit = (
            self.index.lookup(
                chain, {r.index for r in candidates}
            ) if chain else None
        )
        if hit is None:
            decision["hit_depth"] = 0
            if self.roles is not None:
                pre = [
                    r for r in candidates
                    if self.roles[r.index] == "prefill"
                ]
                if pre:
                    chosen = min(
                        pre,
                        key=lambda r: (r.inflight, r.routed_total),
                    )
                    return chosen, "prefill-role", False, None
            return least, "least-loaded", False, None
        depth, holders = hit
        decision["hit_depth"] = depth
        decision["holders"] = [
            {"replica": h[0], "tier": h[1]} for h in holders
        ]
        by_idx = {r.index: r for r in candidates}
        best_idx, _tier = min(
            holders,
            key=lambda h: (
                h[1] != "hbm",
                by_idx[h[0]].inflight,
                by_idx[h[0]].routed_total,
            ),
        )
        rep = by_idx[best_idx]
        # Digest freshness: the holder's LIVE digest version (last
        # health scrape) vs the version the index synced at.  A delta
        # means the chain may have moved/evicted since — routed
        # anyway (locality hint), counted, degrades to a cold
        # prefill, never to wrong tokens.
        synced = self.index.synced_version(rep.index)
        live = rep.kv_digest().get("version")
        stale = synced != live
        decision["synced_version"] = synced
        decision["live_version"] = live
        occ = self._occupancy_locked(rep)
        if rep is least or occ < self.spill_occupancy:
            self.cache_hit_depth_blocks_total += depth
            if stale:
                self.cache_stale_routes_total += 1
            return rep, "cache-aware", stale, None
        # Spill: the deepest-prefix holder is past the watermark.
        # Schedule the chain's migration to where the request lands
        # when depth x load-disagreement clears the threshold — the
        # request itself NEVER waits on the handoff (first token
        # rides a cold prefill on the spill target; the next turn
        # hits warm).
        plan = None
        score = depth * max(
            0.0, occ - self._occupancy_locked(least)
        )
        decision["spill_from"] = rep.index
        decision["spill_occupancy"] = round(occ, 4)
        decision["handoff_score"] = round(score, 4)
        if (
            depth >= self.handoff_min_depth
            and score >= self.handoff_threshold
        ):
            plan = {
                "src": rep.index, "dst": least.index,
                "keys_hex": list(chain[:depth]), "depth": depth,
            }
        return least, "spill", False, plan

    def _pick_locked(
        self, key: Optional[bytes], exclude: frozenset,
        chain: Optional[List[str]] = None,
    ) -> Tuple[Optional[_Replica], str, bool,
               Optional[Dict[str, Any]], Dict[str, Any]]:
        """Choose a replica (caller holds ``_lock``): the global-
        radix-index decision under the cache-aware policy, sticky key
        first under affinity, else least-loaded among healthy
        replicas not in ``exclude`` (prior failed attempts for this
        request).

        Returns ``(replica, how, stale, handoff_plan, decision)``.
        ``stale`` is True for an affinity/cache hit whose replica's
        chain digest has changed since the decision's information was
        current — the chain may have been evicted or demoted, so the
        route is a CACHE GAMBLE rather than a known hit.  Compared
        with ``!=`` (not ``>``): a crash-recovery rebuild resets the
        digest and empties the cache — exactly a staleness event.
        ``handoff_plan`` (cache-aware spill only) asks the scheduler
        to migrate the chain to the routed replica.  ``decision`` is
        the audit-log record of the choice — the candidate set with
        its load facts plus whatever hit/staleness/spill inputs the
        policy consulted (recorded by the caller OUTSIDE the lock)."""
        candidates = [
            r for r in self._replicas
            if r.healthy and not r.retiring and not r.retired
            and r.index not in exclude
        ]
        decision: Dict[str, Any] = {
            "candidates": self._candidates_info_locked(candidates),
        }
        if not candidates:
            return None, "none", False, None, decision
        if self.policy == "cache-aware":
            rep, how, stale, plan = self._cache_pick_locked(
                chain, candidates, decision
            )
            return rep, how, stale, plan, decision
        if self.policy == "affinity" and key is not None:
            ent = self._affinity.get(key)
            if ent is not None:
                for r in candidates:
                    if r.index == ent[0]:
                        self._affinity.move_to_end(key)  # LRU refresh
                        cur = r.kv_digest().get("loss_version")
                        stale = (
                            ent[1] is not None and cur is not None
                            and cur != ent[1]
                        )
                        if stale:
                            self.affinity_stale_routes_total += 1
                            # Re-pin at the observed version: one loss
                            # event counts once, not every turn.
                            ent[1] = cur
                        elif ent[1] is None and cur is not None:
                            # The session pinned before this replica's
                            # first digest scrape (None baseline) —
                            # BACKFILL at the first observed version,
                            # or the None would disable staleness
                            # detection for the session's whole life.
                            ent[1] = cur
                        decision["affinity_hit"] = True
                        return r, "affinity", stale, None, decision
        chosen = min(
            candidates, key=lambda r: (r.inflight, r.routed_total)
        )
        if self.policy == "affinity" and key is not None:
            while len(self._affinity) >= self.affinity_max_sessions:
                self._affinity.popitem(last=False)  # evict coldest
            self._affinity[key] = [
                chosen.index, chosen.kv_digest().get("loss_version"),
            ]
        return chosen, "least-loaded", False, None, decision

    # -- proxying ------------------------------------------------------------

    def _handle_post(self, handler: BaseHTTPRequestHandler) -> None:
        if handler.path not in ("/generate", "/chat"):
            self._reply_json(handler, 404, {"error": "not found"})
            return
        try:
            n = int(handler.headers.get("Content-Length") or 0)
        except ValueError:
            n = 0
        body = handler.rfile.read(n) if n > 0 else b"{}"
        try:
            payload = json.loads(body or b"{}")
            if not isinstance(payload, dict):
                payload = {}
        except ValueError:
            payload = {}
        key = self._affinity_key(payload)
        # Chain-prefix keys for the cache-aware index lookup —
        # tokenization happens HERE, outside the routing lock.
        chain = self._routing_keys(handler.path, payload)
        fwd_headers = {
            "Content-Type": "application/json",
            "Content-Length": str(len(body)),
        }
        for h in ("X-Request-Id",):
            if handler.headers.get(h):
                fwd_headers[h] = handler.headers[h]

        tried: set = set()
        first_attempt = True
        client_rid = handler.headers.get("X-Request-Id") or None
        while True:
            t_pick = self._now_ms()
            role_pending = False
            with self._lock:
                rep, how, stale, plan, decision = self._pick_locked(
                    key, frozenset(tried), chain
                )
                if rep is not None:
                    rep.inflight += 1
                    rep.routed_total += 1
                    if not first_attempt:
                        how = "reroute"
                    self.routed_by_policy[how] = (
                        self.routed_by_policy.get(how, 0) + 1
                    )
                    # A completed request on a prefill-role replica
                    # WILL schedule a disaggregation handoff after the
                    # relay; registering the intent here (cleared in
                    # this attempt's finally, after _maybe_role_handoff
                    # ran) closes the window where wait_handoffs()
                    # could report idle between the client seeing its
                    # reply and the job entering the queue.
                    role_pending = bool(
                        self.roles is not None and chain
                        and self.roles[rep.index] == "prefill"
                    )
                    if role_pending:
                        self._role_handoffs_pending += 1
            if rep is None:
                self.decisions.record(
                    "no_healthy_replica", request_id=client_rid,
                    path=handler.path, tried=sorted(tried),
                )
                self._reply_json(
                    handler, 503,
                    {"error": "no healthy replica"},
                    headers={"Retry-After": "5"},
                )
                return
            tried.add(rep.index)
            # Decision log: the route WITH the candidate set and the
            # policy inputs it was chosen from — recorded outside the
            # routing lock; joinable to the request timeline when the
            # client supplied an X-Request-Id (replica-minted ids
            # resolve through the routing record instead).
            self.decisions.record(
                "route", request_id=client_rid, replica=rep.index,
                policy=how, path=handler.path,
                stale_chain=stale or None,
                handoff_planned=(plan is not None) or None,
                **decision,
            )
            if stale:
                # Digest freshness said the pinned chain may be gone:
                # route anyway (locality hint, not a contract), but as
                # a counted, logged event — the cache-aware scheduler's
                # future miss signal, no longer silent.
                self._log(
                    "router_affinity_stale",
                    replica=rep.index, request_id=client_rid,
                )
            fwd_headers["X-Routed-By"] = (
                f"replica-{rep.index}/{how}"
            )
            # Route-decision span: closes immediately (the pick is a
            # lock-held min()); the forward span that follows carries
            # the relay wall time, so decision and transfer read as
            # two causally ordered slices on the router track.
            self._span(
                "route", t_pick, replica=rep.index, policy=how,
                path=handler.path, request_id=client_rid,
                stale_chain=stale or None,
            )
            if plan is not None:
                # Spill disagreement: migrate the chain to where the
                # request landed — asynchronously; the relay below
                # never waits on it.
                self._schedule_handoff(plan, client_rid)
            t_fwd = self._now_ms()
            try:
                if self.fault_injector is not None:
                    # Fires BEFORE any byte reaches the replica, so a
                    # drill's failure is always at the reroutable stage.
                    self.fault_injector.fire("router_replica")
                rid_seen = self._relay(
                    handler, rep, handler.path, body, fwd_headers
                )
                self._span(
                    "forward", t_fwd, replica=rep.index,
                    path=handler.path,
                    request_id=rid_seen or client_rid,
                )
                # Disaggregation: a completed request on a PREFILL
                # replica streams its freshly published chain to a
                # decode replica, re-pinning the session's routing
                # record there at handoff completion.
                self._maybe_role_handoff(
                    rep, chain, rid_seen or client_rid
                )
                return
            except _ClientDisconnect:
                # The CLIENT vanished mid-relay — the replica is fine
                # (it reaps the disconnect itself); nothing to reroute
                # and no health mark.
                self._span(
                    "forward", t_fwd, replica=rep.index,
                    path=handler.path, request_id=client_rid,
                    client_disconnect=True,
                )
                return
            except TimeoutError as e:
                # Proxy READ timeout from a slow-but-alive replica
                # (overload: streams defer headers until the first
                # token).  The replica has ADMITTED the request — a
                # re-submit would double the load exactly when
                # capacity is scarce, and an unhealthy mark would
                # serially quarantine loaded replicas (a retry-storm
                # amplifier).  504 the client; health stays with the
                # /healthz poller.
                self._log(
                    "router_replica_timeout", str(e), replica=rep.index,
                )
                self._span(
                    "forward", t_fwd, replica=rep.index,
                    path=handler.path, request_id=client_rid,
                    timeout=True,
                )
                if not getattr(e, "_relayed", False):
                    self._reply_json(
                        handler, 504,
                        {"error": (
                            f"replica {rep.index} did not respond "
                            f"within {self.proxy_timeout_s:.0f}s"
                        )},
                        headers={"Retry-After": "5"},
                    )
                return
            except (OSError, InjectedFault,
                    http.client.HTTPException) as e:
                relayed = getattr(e, "_relayed", False)
                with self._lock:
                    rep.healthy = False
                    rep.failures_total += 1
                    self.replica_failures_total += 1
                self._log(
                    "router_replica_failed", str(e),
                    replica=rep.index, rerouting=not relayed,
                )
                self._span(
                    "reroute", t_fwd, replica=rep.index,
                    path=handler.path, request_id=client_rid,
                    error=str(e), relayed=relayed,
                )
                self.decisions.record(
                    "reroute", request_id=client_rid,
                    failed_replica=rep.index, error=str(e),
                    relayed=relayed or None, path=handler.path,
                )
                if relayed:
                    # Bytes already reached the client: the router
                    # must NOT replay (a duplicate stream would
                    # double-deliver tokens); the client sees the
                    # truncated stream and retries with its own
                    # request id.
                    try:
                        handler.wfile.flush()
                    except OSError:
                        pass
                    return
                with self._lock:
                    self.reroutes_total += 1
                first_attempt = False
                continue  # re-route losslessly
            finally:
                with self._lock:
                    rep.inflight -= 1
                    if role_pending:
                        self._role_handoffs_pending -= 1

    def _relay(
        self, handler: BaseHTTPRequestHandler, rep: _Replica,
        path: str, body: bytes, headers: Dict[str, str],
    ) -> Optional[str]:
        """Forward one request and relay the reply (buffered when the
        replica sent Content-Length, line-by-line for close-delimited
        NDJSON streams).  Returns the reply's ``X-Request-Id`` (the
        end-to-end id — replica-minted when the client sent none),
        recorded into the routing record so ``/debug/requests/<id>``
        resolves without fan-out.  Failure attribution: REPLICA-side
        errors (connect/request/read) raise as-is, tagged ``_relayed``
        once any byte reached the client; CLIENT-side write errors
        raise :class:`_ClientDisconnect` — the replica must not be
        marked unhealthy because an impatient client hung up."""
        conn = http.client.HTTPConnection(
            rep.host, rep.port, timeout=self.proxy_timeout_s
        )
        relayed = False

        def to_client(fn, *a):
            nonlocal relayed
            try:
                out = fn(*a)
                relayed = True
                return out
            except OSError:
                raise _ClientDisconnect(relayed) from None

        try:
            conn.request("POST", path, body=body, headers=headers)
            resp = conn.getresponse()
            rid_seen = resp.getheader("X-Request-Id")
            self._note_route(rid_seen, rep.index)
            out_headers = [
                (k, v) for k, v in resp.getheaders()
                if k.lower() not in _SKIP_HEADERS
            ]
            out_headers.append(("X-Replica-Id", str(rep.index)))

            def send_head(extra):
                handler.send_response(resp.status)
                for k, v in out_headers + extra:
                    handler.send_header(k, v)
                handler.end_headers()

            if resp.length is not None:
                data = resp.read()  # replica-side: raises plain OSError
                to_client(
                    send_head, [("Content-Length", str(len(data)))]
                )
                to_client(handler.wfile.write, data)
                return rid_seen
            # Close-delimited NDJSON stream: relay line-by-line so the
            # client sees tokens as the replica emits them.
            to_client(send_head, [("Connection", "close")])
            while True:
                line = resp.readline()
                if not line:
                    break
                to_client(handler.wfile.write, line)
                to_client(handler.wfile.flush)
            return rid_seen
        except (OSError, http.client.HTTPException) as e:
            e._relayed = relayed
            raise
        finally:
            conn.close()

    # -- handoff scheduler ---------------------------------------------------

    def _maybe_role_handoff(
        self, rep: _Replica, chain: Optional[List[str]],
        request_id: Optional[str],
    ) -> None:
        """Prefill/decode disaggregation: after a request COMPLETES on
        a prefill-role replica, stream its published chain to the
        least-loaded decode replica (export -> import), so the
        session's next turn admits there as a plain prefix hit."""
        if self.roles is None or not chain:
            return
        if self.roles[rep.index] != "prefill":
            return
        with self._lock:
            decode = [
                r for r in self._replicas
                if r.healthy and self.roles[r.index] == "decode"
            ]
            if not decode:
                return
            dst = min(
                decode, key=lambda r: (r.inflight, r.routed_total)
            )
            dst_index = dst.index
        if dst_index == rep.index:
            return
        self._schedule_handoff(
            {"src": rep.index, "dst": dst_index,
             "keys_hex": list(chain), "depth": len(chain)},
            request_id,
        )

    def _schedule_handoff(
        self, plan: Dict[str, Any], request_id: Optional[str],
    ) -> None:
        """Admit a migration job into the handoff queue under the
        scheduler's bounds: at most ONE in-flight handoff per chain
        (keyed by its deepest prefix key), total estimated bytes in
        flight capped (a skipped job is counted, never queued — the
        chain stays where it is and the next disagreement re-tries),
        and only in-process replicas participate (the control path
        runs on their serving-loop threads)."""
        if not plan.get("keys_hex"):
            return
        # Chain identity = the ROOT key: plans for the same chain at
        # different matched depths (growing multi-turn prompts, spill
        # vs role triggers) must dedup against each other — a leaf
        # key would admit one job per depth and burn the source's
        # loop on empty re-exports after the first demote.
        head = plan["keys_hex"][0]
        skip_reason = None
        with self._lock:
            src = self._replicas[plan["src"]]
            dst = self._replicas[plan["dst"]]
            if src.server is None or dst.server is None:
                self.handoffs_skipped_total += 1
                skip_reason = "replica-not-in-process"
            elif head in self._handoff_chains:
                # One in-flight handoff per chain: the duplicate is
                # refused, and counted — a silently vanishing
                # migrate_chain() would read as accepted.
                self.handoffs_skipped_total += 1
                skip_reason = "chain-handoff-inflight"
            else:
                est = plan["depth"] * self.index.block_bytes(
                    plan["src"]
                )
                if (
                    self._handoff_bytes_inflight > 0
                    and self._handoff_bytes_inflight + est
                    > self.handoff_max_bytes_inflight
                ):
                    self.handoffs_skipped_total += 1
                    skip_reason = "bytes-inflight-cap"
                else:
                    self._handoff_chains.add(head)
                    self._handoff_bytes_inflight += est
                    self.handoffs_scheduled_total += 1
        if skip_reason is not None:
            self.decisions.record(
                "handoff_skipped", request_id=request_id,
                src=plan["src"], dst=plan["dst"],
                depth=plan["depth"], reason=skip_reason,
            )
            return
        job = dict(plan, head=head, est=est, request_id=request_id)
        self._log(
            "router_handoff_scheduled", src=plan["src"],
            dst=plan["dst"], depth=plan["depth"],
            request_id=request_id,
        )
        self.decisions.record(
            "handoff_scheduled", request_id=request_id,
            src=plan["src"], dst=plan["dst"], depth=plan["depth"],
            est_bytes=est,
        )
        self._handoff_q.put(job)

    def _handoff_loop(self) -> None:
        """The router-handoff worker: executes migration jobs one at
        a time through the replicas' control paths.  A failed or
        timed-out job counts as aborted and UNWINDS its scheduler
        accounting — the chain is re-eligible immediately and the
        worker never dies."""
        while not self._closed.is_set():
            try:
                job = self._handoff_q.get(timeout=0.1)
            except queue.Empty:
                continue
            try:
                self._run_handoff(job)
            except Exception as e:
                with self._lock:
                    self.handoffs_aborted_total += 1
                self._log(
                    "router_handoff_failed", str(e),
                    src=job["src"], dst=job["dst"],
                    request_id=job.get("request_id"),
                )
                self.decisions.record(
                    "handoff_aborted",
                    request_id=job.get("request_id"),
                    src=job["src"], dst=job["dst"], error=str(e),
                )
            finally:
                with self._lock:
                    self._handoff_chains.discard(job["head"])
                    self._handoff_bytes_inflight = max(
                        0, self._handoff_bytes_inflight - job["est"]
                    )

    def _execute_migration(
        self, src_idx: int, dst_idx: int, keys: Sequence[bytes],
        request_id: Optional[str] = None,
        timeout_s: Optional[float] = None,
        demote: Optional[bool] = None,
    ) -> Tuple[int, str]:
        """THE chain-move mechanics, shared by the handoff scheduler
        and the fleet controller's drain migration: export on the
        source's serving-loop thread, import on the destination's with
        the remaining wall budget (the import unwinds cleanly on
        timeout — serving.py owns that contract), demote the source's
        copy ONLY for the prefix the destination provably holds
        (residency probe), and on success fold the move into the
        global index + count/trace/re-pin the routing record
        (``note_handoff``).  Returns ``(blocks_landed, outcome)``;
        outcome is ``"completed"``, ``"nothing-resident"`` (source had
        nothing to move — benign) or
        ``"already-resident-or-no-capacity"`` (nothing landed, source
        copy intact — benign).  Export/import failures and timeouts
        RAISE: the caller owns abort accounting, and the source keeps
        its copy in every failure path — an aborted migration never
        strands or duplicates a session."""
        with self._lock:
            src = self._replicas[src_idx]
            dst = self._replicas[dst_idx]
        rid = request_id
        budget = (
            self.handoff_timeout_s if timeout_s is None
            else float(timeout_s)
        )
        t0 = self._now_ms()
        deadline = time.monotonic() + budget
        # Export WITHOUT demoting: the source gives up its copy only
        # AFTER the destination provably holds the chain (below) — an
        # abandoned/timed-out/failed handoff must never cost the
        # fleet its only HBM-resident copy.
        keys_out, slabs = src.server.call_on_loop(
            lambda b: b.export_prefix(
                keys=list(keys), request_id=rid,
                max_bytes=self.handoff_max_bytes,
            ),
            timeout_s=budget,
        )
        if not slabs:
            return 0, "nothing-resident"
        remaining = max(0.1, deadline - time.monotonic())
        n = dst.server.call_on_loop(
            lambda b: b.import_prefix(
                keys_out, slabs, request_id=rid,
                timeout_s=remaining,
            ),
            timeout_s=remaining + 1.0,
        )
        # The source gives up its copy only for the prefix the
        # destination PROVABLY holds HBM-resident now: an import can
        # return 0 both benignly (the spilled request's own prefill
        # won the race) and because the destination had no capacity,
        # and a capacity-truncated import lands a shorter prefix than
        # was exported — demoting past the landed depth would cost
        # the fleet its only copy of the tail.  One cheap host-side
        # residency probe resolves all cases exactly.  A drain passes
        # ``demote=False``: the source is being retired (its copies
        # die with it), and demoting mid-drain would hollow out the
        # shared prefixes of chains not yet exported.
        do_demote = self.demote_after_export if demote is None else demote
        if do_demote:
            try:
                resident = dst.server.call_on_loop(
                    lambda b: len(
                        b._match_prefix(list(keys_out)).blocks
                    ),
                    timeout_s=min(5.0, budget),
                )
                if resident > 0:
                    # Reuses the exported slabs (no second D2H
                    # fetch); best-effort — a busy source keeps its
                    # copy and the next disagreement re-tries.
                    src.server.call_on_loop(
                        lambda b: b.demote_exported(
                            keys_out[:resident], slabs[:resident],
                            request_id=rid,
                        ),
                        timeout_s=budget,
                    )
            except (TimeoutError, RuntimeError):
                pass
        if n <= 0:
            return 0, "already-resident-or-no-capacity"
        # note_handoff counts kv_handoffs_total, drops the linked
        # handoff span, and re-pins the routing record at dst.
        self.note_handoff(n, request_id=rid, src=src_idx, dst=dst_idx)
        self.index.note_handoff(
            src_idx, dst_idx, [k.hex() for k in keys_out[:n]],
        )
        self._span(
            "handoff_exec", t0, src=src_idx, dst=dst_idx,
            blocks=n, request_id=rid,
        )
        return n, "completed"

    def _run_handoff(self, job: Dict[str, Any]) -> None:
        """One scheduler job through :meth:`_execute_migration`, plus
        the scheduler's own ledger: empty/no-capacity outcomes count
        ``handoffs_empty_total`` (benign — the chain stayed put), a
        landed prefix counts completed blocks/bytes.  Failures raise
        into the worker loop (counted aborted, accounting unwound)."""
        rid = job.get("request_id")
        keys = [bytes.fromhex(k) for k in job["keys_hex"]]
        n, outcome = self._execute_migration(
            job["src"], job["dst"], keys, request_id=rid,
        )
        if outcome != "completed":
            with self._lock:
                self.handoffs_empty_total += 1
            self.decisions.record(
                "handoff_empty", request_id=rid, src=job["src"],
                dst=job["dst"], reason=outcome,
            )
            return
        bb = self.index.block_bytes(job["src"])
        with self._lock:
            self.handoffs_completed_total += 1
            self.handoff_blocks_total += n
            self.handoff_bytes_total += n * bb
        self.decisions.record(
            "handoff_completed", request_id=rid, src=job["src"],
            dst=job["dst"], blocks=n, bytes=n * bb,
        )

    def migrate_chain(
        self, keys_hex: Sequence[str], src: int, dst: int,
        request_id: Optional[str] = None,
    ) -> None:
        """Operator/bench entry point: schedule one chain migration
        src -> dst through the same bounded scheduler the spill path
        uses (dedup, bytes-in-flight cap, demote-after-export)."""
        self._schedule_handoff(
            {"src": int(src), "dst": int(dst),
             "keys_hex": list(keys_hex), "depth": len(keys_hex)},
            request_id,
        )

    def wait_handoffs(self, timeout_s: float = 10.0) -> bool:
        """Block until the handoff queue is drained and no job is in
        flight (tests / bench determinism); True when idle."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                idle = (
                    not self._handoff_chains
                    and self._handoff_q.empty()
                    and self._role_handoffs_pending == 0
                )
            if idle:
                return True
            time.sleep(0.01)
        return False

    # -- elastic fleet membership (FleetController's actuator surface) -------

    def attach_controller(self, controller: "FleetController") -> None:
        """Register the fleet controller (written once, before any
        scale action): /debug/fleet and /metrics render its state."""
        self.controller = controller

    def add_replica(self, replica: Any, role: Optional[str] = None) -> int:
        """Scale-up actuator: append one replica (a started in-process
        ``LLMServer`` or a ``"host:port"`` string) at the next index —
        never reusing a retired slot, so ``_replicas[i].index == i``
        stays structural.  Under role disaggregation the new replica
        must declare its role.  Returns the assigned index; the
        replica becomes routable at its first successful health
        scrape (``check_health_now`` in manual mode)."""
        if self.roles is not None:
            if role is None or role not in ROLES:
                raise ValueError(
                    "add_replica under role disaggregation needs "
                    f"role in {ROLES}, got {role!r}"
                )
        if isinstance(replica, str):
            h, p = _parse_address(replica)
            server = None
        else:
            h, p = _parse_address(replica.address)
            server = replica
        with self._lock:
            idx = len(self._replicas)
            rep = _Replica(index=idx, host=h, port=p, server=server)
            # Unscraped: not routable until the first health sweep
            # proves it answers (a half-started server must not eat
            # live traffic).
            rep.healthy = False
            self._replicas.append(rep)
            if self.roles is not None:
                self.roles = self.roles + (role,)
        self._log("router_replica_added", replica=idx,
                  address=f"{h}:{p}", role=role)
        return idx

    def swap_replica(self, index: int, replica: Any) -> None:
        """Rollout actuator: replace the INSTANCE in an existing slot
        (same index, new server — typically new weights/config).  The
        slot's sentinel state and global-index table are forgotten
        (the new instance starts from clean baselines and a full
        digest resync) and its retiring flag clears; like add_replica
        it becomes routable at the next health sweep.  The OLD
        server's shutdown stays with the caller — swap first, retire
        the old instance after, so the fleet never shrinks mid-rung."""
        if isinstance(replica, str):
            h, p = _parse_address(replica)
            server = None
        else:
            h, p = _parse_address(replica.address)
            server = replica
        with self._lock:
            rep = self._replicas[index]
            rep.host, rep.port, rep.server = h, p, server
            rep.healthy = False
            rep.retiring = False
            rep.retired = False
            rep.last_health = {}
            rep.last_health_t = 0.0
        self.sentinel.forget(index)
        self.index.drop_replica(index)
        self._log("router_replica_swapped", replica=index,
                  address=f"{h}:{p}")

    def set_retiring(self, index: int, retiring: bool = True) -> None:
        """Flip a replica's admission without touching its health: a
        retiring replica is excluded from every routing pick but stays
        scraped/canaried and its serving loop keeps running — exactly
        what drain migration needs (the source must still execute
        ``export_prefix`` control calls)."""
        with self._lock:
            self._replicas[index].retiring = bool(retiring)
        self._log("router_replica_retiring", replica=index,
                  retiring=bool(retiring))

    def retire_replica(self, index: int) -> None:
        """Take a replica out of the fleet permanently (scale-down
        completion): never picked, scraped or canaried again; its
        list slot survives (structural index invariant) but its
        sentinel state and index table are dropped so lookups stop
        seeing phantom holders.  Stopping the server stays with the
        caller (the controller stops instances it owns)."""
        with self._lock:
            rep = self._replicas[index]
            rep.retired = True
            rep.retiring = False
            rep.healthy = False
        self.sentinel.forget(index)
        self.index.drop_replica(index)
        self._log("router_replica_retired", replica=index)

    def repin_routes(self, src: int, dst: int) -> int:
        """Re-point every routing record and affinity pin from a
        drained replica to the survivor its sessions migrated to, so
        the very next turn of every session routes where its KV now
        lives (cache-aware routing would find it through the index
        anyway; affinity and /debug/requests need the explicit
        re-pin).  The affinity pin's digest version resets to None —
        backfilled at the destination's next scrape, same as a fresh
        pin.  Returns the number of records moved."""
        moved = 0
        with self._lock:
            for rid, idx in list(self._routes.items()):
                if idx == src:
                    self._routes[rid] = dst
                    moved += 1
            for key, ent in self._affinity.items():
                if ent[0] == src:
                    ent[0] = dst
                    ent[1] = None
                    moved += 1
        if moved:
            self._log("router_routes_repinned", src=src, dst=dst,
                      moved=moved)
        return moved

    # -- synthetic canary probes ---------------------------------------------

    def _canary_loop(self) -> None:
        """The canary prober thread (started when
        ``canary_interval_s > 0``): one probe per replica per
        interval.  ``<= 0`` is manual mode — deterministic
        drills/tests drive :meth:`run_canaries_now`."""
        while not self._closed.is_set():
            self.run_canaries_now()
            self._closed.wait(self.canary_interval_s)

    def run_canaries_now(self) -> List[Tuple[int, Dict[str, Any]]]:
        """One synchronous canary sweep over every NON-RETIRED replica
        — routable or not: an unhealthy replica's canary is exactly
        how its recovery (or continued sickness) is confirmed without
        risking real traffic (retired replicas have no server to
        probe).  Two phases: probe everyone FIRST, then resolve
        the token oracle against the whole sweep (majority rule — see
        ``_resolve_canary_oracle``) before any mismatch is judged, so
        a wrong-output replica that happens to be probed first cannot
        invert the fleet verdict.  Probes run CONCURRENTLY (one short
        thread per replica): a single hung replica costs its own
        probe timeout, never the whole fleet's sweep — otherwise one
        accept-but-never-answer replica would double every healthy
        replica's effective probe period.  Returns the oracle-resolved
        ``(replica_index, result)`` pairs — the fleet controller's
        rollout gate reads the restarted replica's entry directly."""
        with self._lock:
            reps = [r for r in self._replicas if not r.retired]
            if self._closed.is_set():
                return []
            seq0 = self._canary_seq
            self._canary_seq += len(reps)
        slots: List[Optional[Dict[str, Any]]] = [None] * len(reps)

        def probe(i: int, rep: _Replica) -> None:
            slots[i] = self._canary_probe(rep, seq0 + 1 + i)

        threads = [
            threading.Thread(
                target=probe, args=(i, rep), daemon=True,
                name=f"router-canary-probe-{rep.index}",
            )
            for i, rep in enumerate(reps)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=self.canary_timeout_s + 5.0)
        results: List[Tuple[_Replica, Dict[str, Any]]] = []
        for i, (rep, res) in enumerate(zip(reps, slots)):
            if res is None:
                # The probe thread outlived even the HTTP timeout (a
                # wedged resolver/socket): that IS a failed probe —
                # dropping it would hide exactly the hung replica.
                res = {
                    "ok": False,
                    "error": "canary probe did not complete "
                             "(probe thread hung past its timeout)",
                    "latency_ms": self.canary_timeout_s * 1000.0,
                    "request_id": f"canary-{rep.index}-{seq0 + 1 + i}",
                }
            results.append((rep, res))
        self._resolve_canary_oracle(results)
        for rep, res in results:
            self._ingest_canary(rep, res)
        return [(rep.index, res) for rep, res in results]

    def reset_canary_oracle(self) -> None:
        """Operator hook: forget the pinned oracle (the next sweep's
        majority re-establishes it).  Call on a KNOWN fleet-wide
        output change — a weight rollout, a tokenizer swap — so every
        replica does not read as mismatched against the old fleet's
        tokens."""
        with self._lock:
            self._canary_oracle = None
        self.decisions.record("canary_oracle_reset")
        self._log("router_canary_oracle_reset")

    def _resolve_canary_oracle(
        self, results: List[Tuple[_Replica, Dict[str, Any]]],
    ) -> None:
        """Resolve this sweep's token oracle and mark mismatches.

        The oracle is the plurality token sequence among the sweep's
        transport-successful probes (same weights + greedy decode ⇒
        replica-independent, so healthy fleets are unanimous).  An
        already-pinned oracle is RE-PINNED when a STRICT MAJORITY of
        this sweep's successful probes agree on a different sequence
        (counted ``canary_oracle_repins_total``): the pin was wrong —
        a corrupt replica happened to be probed first, or the whole
        fleet legitimately changed output (rollout) — and without the
        re-pin every HEALTHY replica would read as mismatched forever.
        A split with no majority (1-vs-1 on a 2-replica fleet) can
        never PIN or RE-PIN: with no pin yet the oracle stays unset
        (probe order must not crown a corrupt replica — the
        disagreement is recorded instead), and with a pin it is kept.
        Only after the oracle settles are individual probes marked
        ``mismatch`` (ok flips False); with no settled oracle nobody
        is mismatched — the sentinel cannot tell who is wrong, only
        that they disagree."""
        votes = [
            (rep.index, tuple(res.get("tokens") or ()))
            for rep, res in results
            if res.get("ok") and res.get("tokens")
        ]
        counts: Dict[Tuple[int, ...], int] = {}
        for _, t in votes:
            counts[t] = counts.get(t, 0) + 1
        repinned = None
        disagreement = False
        with self._lock:
            pinned = (
                tuple(self._canary_oracle)
                if self._canary_oracle is not None else None
            )
            if counts:
                best = max(counts, key=lambda t: counts[t])
                unanimous_or_majority = (
                    len(counts) == 1 or counts[best] > len(votes) / 2
                )
                if pinned is None:
                    if unanimous_or_majority:
                        self._canary_oracle = list(best)
                    else:
                        disagreement = True
                elif (
                    best != pinned
                    and counts[best] > len(votes) / 2
                    and counts.get(pinned, 0) < counts[best]
                ):
                    self._canary_oracle = list(best)
                    self.canary_oracle_repins_total += 1
                    repinned = list(best)
            oracle = (
                tuple(self._canary_oracle)
                if self._canary_oracle is not None else None
            )
        if repinned is not None:
            self.decisions.record(
                "canary_oracle_repin", oracle_tokens=repinned,
                votes=len(votes),
            )
            self._log(
                "router_canary_oracle_repin", votes=len(votes),
            )
        if disagreement:
            # No pin and no majority: crowning either side by probe
            # order would let a corrupt replica permanently invert
            # the verdict.  Record the split; the next sweep with a
            # majority (or an operator's eyes on this event) settles.
            self.decisions.record(
                "canary_oracle_disagreement", votes=len(votes),
                sequences=len(counts),
            )
            self._log(
                "router_canary_oracle_disagreement",
                votes=len(votes), sequences=len(counts),
            )
        if oracle is None:
            return
        for _, res in results:
            if res.get("ok") and tuple(res.get("tokens") or ()) != oracle:
                res["ok"] = False
                res["mismatch"] = True

    def _canary_payload(self) -> Dict[str, Any]:
        """The deterministic probe request: a tiny fixed token prompt,
        greedy (temperature 0), fixed seed, and the RESERVED canary
        priority class — the replica serves it normally but excludes
        it from SLO attainment, goodput, latency histograms and the
        brownout ladder's inputs (no self-triggered brownouts)."""
        return {
            "prompt": list(self.canary_prompt),
            "max_new_tokens": self.canary_max_new,
            "temperature": 0.0,
            "seed": 0,
            "priority": CANARY,
        }

    def _canary_probe(self, rep: _Replica, seq: int) -> Dict[str, Any]:
        """One TRANSPORT-level probe against one replica (direct POST
        — never through the routing path, so a probe can reach a
        replica the router has stopped routing to).  Returns the raw
        result (ok = HTTP 200 with a body; tokens; latency); token
        correctness is judged afterwards against the whole sweep by
        ``_resolve_canary_oracle``."""
        rid = f"canary-{rep.index}-{seq}"
        body = json.dumps(self._canary_payload()).encode()
        t0 = time.monotonic()
        try:
            conn = http.client.HTTPConnection(
                rep.host, rep.port, timeout=self.canary_timeout_s
            )
            try:
                conn.request(
                    "POST", "/generate", body=body,
                    headers={
                        "Content-Type": "application/json",
                        "X-Request-Id": rid,
                    },
                )
                resp = conn.getresponse()
                data = json.loads(resp.read() or b"{}")
            finally:
                conn.close()
        except (OSError, ValueError, http.client.HTTPException) as e:
            return {
                "ok": False, "error": repr(e),
                "latency_ms": (time.monotonic() - t0) * 1000.0,
                "request_id": rid,
            }
        lat = (time.monotonic() - t0) * 1000.0
        if resp.status != 200 or not isinstance(data, dict):
            return {
                "ok": False,
                "error": f"HTTP {resp.status}: "
                         f"{(data or {}).get('error')}",
                "latency_ms": lat, "request_id": rid,
                "status": resp.status,
            }
        tokens = [int(t) for t in (data.get("tokens") or [])]
        return {
            "ok": True, "latency_ms": lat, "tokens": tokens,
            "request_id": rid,
        }

    def _ingest_canary(self, rep: _Replica,
                       res: Dict[str, Any]) -> None:
        """Feed one oracle-resolved probe result everywhere it goes:
        the probe counters, the decision audit log, the structured log
        (failures only — a healthy fleet's probes are not log
        traffic), and the health sentinel (whose anomaly/verdict
        events land in the decision log via the ingest path)."""
        with self._lock:
            self.canary_probes_total += 1
            if res.get("mismatch"):
                self.canary_mismatches_total += 1
            elif not res["ok"]:
                self.canary_failures_total += 1
        self.decisions.record(
            "canary", request_id=res.get("request_id"),
            replica=rep.index, ok=res["ok"],
            latency_ms=round(res["latency_ms"], 3),
            mismatch=res.get("mismatch") or None,
            error=res.get("error"),
        )
        if not res["ok"]:
            self._log(
                "router_canary_failed", replica=rep.index,
                error=res.get("error"),
                mismatch=res.get("mismatch"),
            )
        events = self.sentinel.observe_canary(
            rep.index, ok=res["ok"],
            latency_ms=res.get("latency_ms"),
            mismatch=bool(res.get("mismatch")),
            error=res.get("error"),
        )
        self._ingest_sentinel_events(rep.index, events)

    # -- GET surface ---------------------------------------------------------

    def _handle_get(self, handler: BaseHTTPRequestHandler) -> None:
        parts = urlsplit(handler.path)
        route, query = parts.path, parse_qs(parts.query)
        if route == "/healthz":
            h = self.health()
            self._reply_json(handler, 200 if h["ok"] else 503, h)
        elif route == "/metrics":
            body = self.metrics_text().encode()
            handler.send_response(200)
            handler.send_header(
                "Content-Type", "text/plain; version=0.0.4"
            )
            handler.send_header("Content-Length", str(len(body)))
            handler.end_headers()
            handler.wfile.write(body)
        elif route == "/debug/trace":
            window_ms = None
            if "window_s" in query:
                try:
                    window_ms = float(query["window_s"][0]) * 1000.0
                except ValueError:
                    self._reply_json(
                        handler, 400, {"error": "bad window_s"}
                    )
                    return
            self._reply_json(
                handler, 200, self.fleet_trace_json(window_ms)
            )
        elif route == "/debug/kv/fleet":
            depth = None
            if "depth" in query:
                try:
                    depth = int(query["depth"][0])
                except ValueError:
                    self._reply_json(
                        handler, 400, {"error": "bad depth"}
                    )
                    return
            self._reply_json(handler, 200, self.fleet_kv_json(depth))
        elif route == "/debug/fleet":
            # The health-score/anomaly sentinel's fleet view — the
            # verdict surface the future autoscaler consumes.
            self._reply_json(handler, 200, self.fleet_health_json())
        elif route == "/debug/decisions":
            kind = (query.get("kind") or [None])[0]
            request_id = (query.get("request_id") or [None])[0]
            try:
                n = int((query.get("n") or [128])[0])
            except ValueError:
                n = 128
            self._reply_json(
                handler, 200,
                self.decisions.json(
                    n=n, kind=kind, request_id=request_id
                ),
            )
        elif route == "/debug/bundle":
            def qflag(name: str) -> bool:
                try:
                    return int((query.get(name) or [1])[0]) > 0
                except ValueError:
                    return True
            self._reply_json(
                handler, 200,
                self.bundle_json(
                    include_replicas=qflag("replicas"),
                    trace=qflag("trace"),
                ),
            )
        elif route == "/debug/requests":
            self._reply_json(
                handler, *self._fleet_requests_index(handler.path)
            )
        elif route.startswith("/debug/requests/"):
            rid = unquote(route[len("/debug/requests/"):])
            self._reply_json(
                handler, *self._fleet_request_lookup(rid, handler.path)
            )
        elif route.startswith("/debug/"):
            # Everything else (dispatch rings, profiler summaries...)
            # lives on whichever replica produced it: try each healthy
            # replica until one answers non-404.
            code, data = self._first_non_404(handler.path)
            self._reply_json(handler, code, data)
        else:
            self._reply_json(handler, 404, {"error": "not found"})

    def _get_replica_json(
        self, rep: _Replica, path: str, timeout: float = 2.0,
    ) -> Optional[Tuple[int, Dict[str, Any]]]:
        """One replica GET; None on connection/parse failure.  The
        default timeout matches the health probe's: the fleet /debug
        endpoints fetch replicas SEQUENTIALLY, so each hung-but-
        marked-healthy replica costs at most one probe interval, not
        a proxy-class stall per replica."""
        try:
            conn = http.client.HTTPConnection(
                rep.host, rep.port, timeout=timeout
            )
            try:
                conn.request("GET", path)
                resp = conn.getresponse()
                data = json.loads(resp.read() or b"{}")
            finally:
                conn.close()
        except (OSError, ValueError, http.client.HTTPException):
            return None
        if not isinstance(data, dict):
            return None
        return resp.status, data

    def _first_non_404(self, path: str) -> Tuple[int, Dict[str, Any]]:
        with self._lock:
            reps = [r for r in self._replicas if r.healthy]
        for rep in reps:
            got = self._get_replica_json(rep, path)
            if got is None:
                continue
            status, data = got
            if status != 404:
                data["replica"] = rep.index
                return status, data
        return 404, {"error": "not found on any replica"}

    def _fleet_requests_index(
        self, path: str,
    ) -> Tuple[int, Dict[str, Any]]:
        """GET /debug/requests aggregated across ALL healthy replicas
        (first-to-answer would show one replica's slice of the fleet
        and 404-hide the rest); every entry carries its replica id."""
        with self._lock:
            reps = [r for r in self._replicas if r.healthy]
        merged: List[Dict[str, Any]] = []
        replicas_answered: List[int] = []
        for rep in reps:
            got = self._get_replica_json(rep, path)
            if got is None or got[0] != 200:
                continue
            replicas_answered.append(rep.index)
            for entry in got[1].get("requests", []):
                if isinstance(entry, dict):
                    entry["replica"] = rep.index
                    merged.append(entry)
        return 200, {
            "requests": merged, "replicas": replicas_answered,
        }

    def _fleet_request_lookup(
        self, request_id: str, path: str,
    ) -> Tuple[int, Dict[str, Any]]:
        """GET /debug/requests/<id>: the ROUTING RECORD names the
        replica that served the id, so that replica answers first;
        healthy-replica fan-out only covers ids the bounded record has
        already evicted (or pre-router traffic)."""
        with self._lock:
            routed = self._routes.get(request_id)
            reps = list(self._replicas)
        ordered = (
            [r for r in reps if r.index == routed]
            + [r for r in reps if r.index != routed and r.healthy]
        )
        for rep in ordered:
            got = self._get_replica_json(rep, path)
            if got is None:
                continue
            status, data = got
            if status != 404:
                data["replica"] = rep.index
                data["routed_replica"] = routed
                # The decision join: every router decision carrying
                # this external id (route, reroute, handoff, canary)
                # rides the timeline reply, so "why did this request
                # land here" reads in one fetch.
                data["router_decisions"] = self.decisions.for_request(
                    request_id
                )
                return status, data
        return 404, {
            "error": f"request id {request_id!r} unknown fleet-wide",
            "routed_replica": routed,
            "router_decisions": self.decisions.for_request(request_id),
        }

    @staticmethod
    def _reply_json(
        handler: BaseHTTPRequestHandler, code: int,
        obj: Dict[str, Any], headers: Optional[Dict[str, str]] = None,
    ) -> None:
        body = json.dumps(obj).encode()
        handler.send_response(code)
        handler.send_header("Content-Type", "application/json")
        handler.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            handler.send_header(k, v)
        handler.end_headers()
        handler.wfile.write(body)

    # -- observability -------------------------------------------------------

    def fleet_trace_json(
        self, window_ms: Optional[float] = None,
    ) -> Dict[str, Any]:
        """The fleet-merged Perfetto document (module docstring): the
        router's span track plus every healthy replica's
        ``/debug/trace`` export, replica timestamps shifted into the
        router's frame through the ``t0_unix_s`` anchors and re-tagged
        to per-replica pids.  Snapshot under the lock, fetch and build
        outside it — replica HTTP round-trips must never hold the
        routing lock."""
        with self._lock:
            reps = [
                (r.index, r.host, r.port)
                for r in self._replicas if r.healthy
            ]
            spans = list(self._trace)
            now = self._now_ms()
        horizon = None if window_ms is None else now - window_ms
        ev: List[Dict[str, Any]] = [
            {"ph": "M", "pid": 0, "name": "process_name",
             "args": {"name": "router"}},
            {"ph": "M", "pid": 0, "tid": 1, "name": "thread_name",
             "args": {"name": "routing"}},
        ]
        for s in spans:
            if horizon is not None and s["t0_ms"] + s["dur_ms"] < horizon:
                continue
            ev.append({
                "name": s["name"], "cat": "router", "ph": "X",
                "pid": 0, "tid": 1,
                "ts": round(s["t0_ms"] * 1000.0, 1),
                "dur": max(1, round(s["dur_ms"] * 1000.0)),
                "args": dict(s["args"]),
            })
        suffix = (
            "" if window_ms is None
            else f"?window_s={window_ms / 1000.0:g}"
        )
        merged_replicas: List[int] = []
        for index, host, port in reps:
            got = self._get_replica_json(
                _Replica(index=index, host=host, port=port),
                "/debug/trace" + suffix,
            )
            if got is None or got[0] != 200:
                continue
            doc = got[1]
            merged_replicas.append(index)
            pid = 1 + index
            # Clock-offset normalization: replica ts are relative to
            # ITS Observability t0; the wall anchors captured at both
            # t0 instants give the shift into the router's frame.
            off_us = (
                float(doc.get("t0_unix_s", self.t0_unix))
                - self.t0_unix
            ) * 1e6
            ev.append({
                "ph": "M", "pid": pid, "name": "process_name",
                "args": {"name": f"replica-{index}"},
            })
            for e in doc.get("traceEvents", []):
                if not isinstance(e, dict):
                    continue
                e = dict(e)
                e["pid"] = pid
                if "ts" in e:
                    e["ts"] = round(e["ts"] + off_us, 1)
                ev.append(e)
        return {
            "traceEvents": ev, "displayTimeUnit": "ms",
            "t0_unix_s": round(self.t0_unix, 6),
            "replicas": merged_replicas,
        }

    def fleet_kv_json(
        self, depth: Optional[int] = None,
    ) -> Dict[str, Any]:
        """``GET /debug/kv/fleet``: the router-side fleet cache view.

        Scrapes every healthy replica's ``/debug/kv`` digest
        (sequential, probe-class 2 s timeouts — on demand, never from
        the poller) and aggregates:

          * **fleet prefix-hit ratio** — sum of hit tokens over sum of
            admitted prompt tokens across the fleet;
          * **per-replica occupancy/watermarks** — nodes, HBM/host
            residency, idle (evictable) depth, digest version/age;
          * **cross-replica duplicate chains** — chain-prefix keys
            HBM-resident on >= 2 replicas, with the redundant blocks
            and BYTES (copies beyond the first, priced at each extra
            copy's own block_bytes): the HBM a cache-aware
            disaggregation scheduler (ROADMAP item 2) would get back.

        The computed fleet aggregate is cached (``_fleet_kv``) for the
        ``llm_fleet_duplicate_kv_blocks`` /metrics gauges; truncated
        replica digests make the duplicate count a LOWER bound and are
        listed in ``truncated_replicas``."""
        with self._lock:
            reps = [
                (r.index, r.host, r.port)
                for r in self._replicas if r.healthy
            ]
        t0 = time.monotonic()
        suffix = f"?depth={depth}" if depth is not None else ""
        per: List[Dict[str, Any]] = []
        truncated: List[int] = []
        # chain key -> [(replica index, block_bytes), ...] HBM copies
        chains: Dict[str, List[Tuple[int, int]]] = {}
        hit_tokens = prompt_tokens = 0
        for index, host, port in reps:
            got = self._get_replica_json(
                _Replica(index=index, host=host, port=port),
                "/debug/kv" + suffix,
            )
            if got is None or got[0] != 200:
                continue
            doc = got[1]
            summ = doc.get("summary") or {}
            bb = int(summ.get("block_bytes") or 0)
            for node in doc.get("nodes", []):
                if (
                    isinstance(node, dict)
                    and node.get("tier") == "hbm"
                ):
                    chains.setdefault(str(node.get("key")), []).append(
                        (index, bb)
                    )
            if doc.get("truncated"):
                truncated.append(index)
            hit_tokens += int(summ.get("prefix_hit_tokens_total") or 0)
            prompt_tokens += int(summ.get("prompt_tokens_total") or 0)
            per.append({
                "replica": index,
                "summary": summ,
                "hit_ratio": round(
                    int(summ.get("prefix_hit_tokens_total") or 0)
                    / max(1, int(summ.get("prompt_tokens_total") or 0)),
                    6,
                ),
                "hbm_bytes": (
                    int(summ.get("hbm_blocks") or 0) * bb
                ),
            })
        dup_chains = dup_blocks = dup_bytes = 0
        for copies in chains.values():
            if len({i for i, _ in copies}) < 2:
                continue
            dup_chains += 1
            extra = sorted(copies)[1:]  # first copy is the keeper
            dup_blocks += len(extra)
            dup_bytes += sum(b for _, b in extra)
        scrape_ms = round((time.monotonic() - t0) * 1000.0, 3)
        fleet = {
            "prefix_hit_ratio": round(
                hit_tokens / max(1, prompt_tokens), 6
            ),
            "prefix_hit_tokens_total": hit_tokens,
            "prompt_tokens_total": prompt_tokens,
            "duplicate_chains": dup_chains,
            "duplicate_kv_blocks": dup_blocks,
            "duplicate_kv_bytes": dup_bytes,
            "replicas_scraped": [p["replica"] for p in per],
            "truncated_replicas": truncated,
            "scrape_ms": scrape_ms,
        }
        with self._lock:
            self._fleet_kv = dict(fleet, computed_unix_s=time.time())
        return {"fleet": fleet, "replicas": per}

    def fleet_health_json(self) -> Dict[str, Any]:
        """``GET /debug/fleet`` — the per-replica health-score /
        verdict view: the sentinel's scores, subscores, active
        anomalies and last canary result merged with the router's own
        routing facts (routable, inflight, scrape age), plus the
        fleet verdict (worst replica) and the edge-triggered anomaly
        counters.  THE surface ROADMAP item 3's autoscaler consults
        before it is allowed to kill or drain a replica."""
        now = time.monotonic()
        with self._lock:
            snaps = {
                r.index: {
                    "replica": r.index,
                    "healthy": r.healthy,
                    "retiring": r.retiring,
                    "retired": r.retired,
                    "inflight": r.inflight,
                    "routed_total": r.routed_total,
                    "failures_total": r.failures_total,
                    "health_age_s": (
                        round(now - r.last_health_t, 3)
                        if r.last_health_t > 0 else None
                    ),
                }
                for r in self._replicas
            }
            canary = {
                "probes_total": self.canary_probes_total,
                "failures_total": self.canary_failures_total,
                "mismatches_total": self.canary_mismatches_total,
                "oracle_repins_total": self.canary_oracle_repins_total,
                "interval_s": self.canary_interval_s,
                "prompt": list(self.canary_prompt),
                "max_new": self.canary_max_new,
                "oracle_tokens": (
                    list(self._canary_oracle)
                    if self._canary_oracle is not None else None
                ),
            }
        fleet = self.sentinel.fleet_json()
        replicas: List[Dict[str, Any]] = []
        for idx in sorted(snaps):
            ent = dict(snaps[idx])
            sent = fleet["replicas"].get(idx)
            if sent is None:
                sent = {
                    "score": 1.0, "verdict": "healthy",
                    "signals": {}, "anomalous": [],
                    "last_canary": None,
                }
            ent.update(sent)
            replicas.append(ent)
        ctrl = self.controller
        return {
            "verdict": fleet["verdict"],
            "verdict_index": fleet["verdict_index"],
            "replicas": replicas,
            "anomalies_total": fleet["anomalies_total"],
            "canary": canary,
            # Elastic-fleet controller state (None until one attaches):
            # ladder/dwell state, last signals, counters, rollout rung.
            "controller": (
                ctrl.state_json() if ctrl is not None else None
            ),
        }

    def bundle_json(self, include_replicas: bool = True,
                    trace: bool = True) -> Dict[str, Any]:
        """``GET /debug/bundle[?replicas=0&trace=0]`` — the router's
        black-box flight-recorder artifact: config + aggregate health
        + the fleet health-score view + the last-N control-plane
        decisions + the structured-log tail + the fleet-merged
        Perfetto trace, and (by default) every healthy replica's own
        ``/debug/bundle`` inline — ONE pull for the whole incident.
        Replica fetches use bounded timeouts so a hung replica costs
        seconds, not the artifact.  Replica bundles are fetched with
        ``?trace=0`` ALWAYS: the fleet-merged trace above already
        carries every replica's tracks (re-tagged, clock-shifted), so
        shipping each replica's own trace again would double the
        heaviest section — and with ``trace=0`` the slimming would
        otherwise not slim the dominant payload at all."""
        out: Dict[str, Any] = {
            "kind": "router_bundle",
            "generated_unix_s": round(time.time(), 3),
            "config": {
                "policy": self.policy,
                "roles": list(self.roles) if self.roles else None,
                "health_interval_s": self.health_interval_s,
                "proxy_timeout_s": self.proxy_timeout_s,
                "spill_occupancy": self.spill_occupancy,
                "handoff_threshold": self.handoff_threshold,
                "handoff_min_depth": self.handoff_min_depth,
                "handoff_max_bytes": self.handoff_max_bytes,
                "handoff_max_bytes_inflight": (
                    self.handoff_max_bytes_inflight
                ),
                "handoff_timeout_s": self.handoff_timeout_s,
                "canary_interval_s": self.canary_interval_s,
                "canary_max_new": self.canary_max_new,
                "canary_timeout_s": self.canary_timeout_s,
            },
            "health": self.health(),
            "fleet": self.fleet_health_json(),
            "decisions": self.decisions.json(n=256),
            "log_tail": self.logger.tail(),
        }
        if trace:
            out["trace"] = self.fleet_trace_json()
        if include_replicas:
            with self._lock:
                reps = [
                    (r.index, r.host, r.port)
                    for r in self._replicas if r.healthy
                ]
            bundles: List[Dict[str, Any]] = []
            for index, host, port in reps:
                got = self._get_replica_json(
                    _Replica(index=index, host=host, port=port),
                    "/debug/bundle?trace=0", timeout=5.0,
                )
                if got is not None and got[0] == 200:
                    doc = got[1]
                    doc["replica"] = index
                    bundles.append(doc)
            out["replicas"] = bundles
        return out

    def health(self) -> Dict[str, Any]:
        """Aggregate /healthz: ok while ANY replica is routable, with
        the per-replica snapshots under ``replicas``."""
        with self._lock:
            snaps = [r.snapshot() for r in self._replicas]
            affinity_sessions = len(self._affinity)
            handoffs = self.kv_handoffs_total
            stale_routes = self.affinity_stale_routes_total
            fleet_kv = (
                dict(self._fleet_kv)
                if self._fleet_kv is not None else None
            )
            scheduler = {
                "scheduled_total": self.handoffs_scheduled_total,
                "completed_total": self.handoffs_completed_total,
                "aborted_total": self.handoffs_aborted_total,
                "skipped_total": self.handoffs_skipped_total,
                "empty_total": self.handoffs_empty_total,
                "blocks_total": self.handoff_blocks_total,
                "bytes_total": self.handoff_bytes_total,
                "bytes_inflight": self._handoff_bytes_inflight,
                "chains_inflight": len(self._handoff_chains),
                "role_pending": self._role_handoffs_pending,
            }
            cache = {
                "stale_routes_total": self.cache_stale_routes_total,
                "hit_depth_blocks_total": (
                    self.cache_hit_depth_blocks_total
                ),
            }
            canary = {
                "probes_total": self.canary_probes_total,
                "failures_total": self.canary_failures_total,
                "mismatches_total": self.canary_mismatches_total,
                "oracle_repins_total": self.canary_oracle_repins_total,
                "interval_s": self.canary_interval_s,
            }
        cache.update(self.index.stats())
        sent = self.sentinel.fleet_json()
        return {
            "ok": any(s["healthy"] for s in snaps),
            "policy": self.policy,
            "roles": list(self.roles) if self.roles else None,
            "replicas": snaps,
            "affinity_sessions": affinity_sessions,
            "kv_handoffs_total": handoffs,
            "affinity_stale_routes_total": stale_routes,
            # Cache-aware routing state: the global radix index's
            # sync/size counters + routing outcomes.
            "cache_index": cache,
            # Handoff scheduler ledger (bounds + outcomes).
            "handoff": scheduler,
            # Last computed fleet cache aggregate (None until the
            # first GET /debug/kv/fleet).
            "fleet_kv": fleet_kv,
            # Control-plane observability: canary prober counters, the
            # sentinel's fleet verdict + per-signal anomaly counters,
            # and the decision audit log's size (GET /debug/decisions
            # for the events, GET /debug/fleet for the full view).
            "canary": canary,
            "fleet_health": {
                "verdict": sent["verdict"],
                "verdict_index": sent["verdict_index"],
                "anomalies_total": sent["anomalies_total"],
            },
            "decisions_total": self.decisions.total(),
        }

    def metrics_text(self) -> str:
        """Router Prometheus exposition: aggregate counters plus
        per-replica labeled gauges (occupancy / inflight / routed /
        health / mesh shape / sentinel score).  Every family's
        HELP/TYPE comes from the :data:`ROUTER_METRICS` registry
        (``fam``); the metrics-registry lint audits the two against
        each other both ways."""
        with self._lock:
            snaps = [r.snapshot() for r in self._replicas]
            by_policy = dict(self.routed_by_policy)
            reroutes = self.reroutes_total
            failures = self.replica_failures_total
            handoffs = self.kv_handoffs_total
            affinity_sessions = len(self._affinity)
            stale_routes = self.affinity_stale_routes_total
            fleet_kv = (
                dict(self._fleet_kv)
                if self._fleet_kv is not None else None
            )
            ho = {
                "scheduled": self.handoffs_scheduled_total,
                "completed": self.handoffs_completed_total,
                "aborted": self.handoffs_aborted_total,
                "skipped": self.handoffs_skipped_total,
                "bytes_inflight": self._handoff_bytes_inflight,
                "bytes_total": self.handoff_bytes_total,
            }
            cache_stale = self.cache_stale_routes_total
            cache_depth = self.cache_hit_depth_blocks_total
            canary = {
                "probes": self.canary_probes_total,
                "failures": self.canary_failures_total,
                "mismatches": self.canary_mismatches_total,
                "repins": self.canary_oracle_repins_total,
            }
        idx = self.index.stats()
        decision_counts = self.decisions.counts_snapshot()
        sent = self.sentinel.fleet_json()
        lines: List[str] = []

        def fam(name: str) -> None:
            kind, help_text = ROUTER_METRICS[name]
            lines.append(f"# HELP {name} {help_text}")
            lines.append(f"# TYPE {name} {kind}")

        fam("llm_router_replicas")
        # Retired slots survive in the table (index invariant) but are
        # no longer fleet members.
        lines.append(
            "llm_router_replicas "
            f"{sum(not s['retired'] for s in snaps)}"
        )
        fam("llm_router_replicas_healthy")
        lines.append(
            "llm_router_replicas_healthy "
            f"{sum(s['healthy'] for s in snaps)}"
        )
        fam("llm_router_routed_requests_total")
        for pol, n in sorted(by_policy.items()):
            lines.append(
                f'llm_router_routed_requests_total{{policy="{pol}"}} {n}'
            )
        fam("llm_router_reroutes_total")
        lines.append(f"llm_router_reroutes_total {reroutes}")
        fam("llm_router_replica_failures_total")
        lines.append(f"llm_router_replica_failures_total {failures}")
        fam("llm_router_kv_handoffs_total")
        lines.append(f"llm_router_kv_handoffs_total {handoffs}")
        fam("llm_router_affinity_sessions")
        lines.append(f"llm_router_affinity_sessions {affinity_sessions}")
        fam("llm_router_affinity_stale_routes_total")
        lines.append(
            f"llm_router_affinity_stale_routes_total {stale_routes}"
        )
        # Cache-aware routing: the global radix index + decision
        # outcome counters (policy="cache-aware" only; families are
        # always exposed for dashboard discovery).
        fam("llm_router_cache_index_nodes")
        lines.append(f"llm_router_cache_index_nodes {idx['nodes']}")
        fam("llm_router_cache_index_replicas_synced")
        lines.append(
            "llm_router_cache_index_replicas_synced "
            f"{idx['replicas_synced']}"
        )
        fam("llm_router_cache_index_syncs_total")
        lines.append(
            f"llm_router_cache_index_syncs_total {idx['syncs_total']}"
        )
        fam("llm_router_cache_index_resyncs_total")
        lines.append(
            "llm_router_cache_index_resyncs_total "
            f"{idx['resyncs_total']}"
        )
        fam("llm_router_cache_index_events_applied_total")
        lines.append(
            "llm_router_cache_index_events_applied_total "
            f"{idx['events_applied_total']}"
        )
        fam("llm_router_cache_stale_routes_total")
        lines.append(
            f"llm_router_cache_stale_routes_total {cache_stale}"
        )
        fam("llm_router_cache_hit_depth_blocks_total")
        lines.append(
            f"llm_router_cache_hit_depth_blocks_total {cache_depth}"
        )
        # Handoff scheduler ledger.
        fam("llm_router_handoffs_scheduled_total")
        lines.append(
            f"llm_router_handoffs_scheduled_total {ho['scheduled']}"
        )
        fam("llm_router_handoffs_completed_total")
        lines.append(
            f"llm_router_handoffs_completed_total {ho['completed']}"
        )
        fam("llm_router_handoffs_aborted_total")
        lines.append(
            f"llm_router_handoffs_aborted_total {ho['aborted']}"
        )
        fam("llm_router_handoffs_skipped_total")
        lines.append(
            f"llm_router_handoffs_skipped_total {ho['skipped']}"
        )
        fam("llm_router_handoff_bytes_inflight")
        lines.append(
            f"llm_router_handoff_bytes_inflight {ho['bytes_inflight']}"
        )
        fam("llm_router_handoff_bytes_total")
        lines.append(
            f"llm_router_handoff_bytes_total {ho['bytes_total']}"
        )
        # Control-plane observability: the decision audit log (one
        # labeled series per decision kind), the canary prober's
        # counters, the sentinel's edge-triggered anomaly counters
        # (one labeled series per signal) and the fleet verdict.
        fam("llm_router_decisions_total")
        for kname, n in sorted(decision_counts.items()):
            lines.append(
                f'llm_router_decisions_total{{kind="{kname}"}} {n}'
            )
        fam("llm_router_canary_probes_total")
        lines.append(
            f"llm_router_canary_probes_total {canary['probes']}"
        )
        fam("llm_router_canary_failures_total")
        lines.append(
            f"llm_router_canary_failures_total {canary['failures']}"
        )
        fam("llm_router_canary_mismatches_total")
        lines.append(
            f"llm_router_canary_mismatches_total {canary['mismatches']}"
        )
        fam("llm_router_canary_oracle_repins_total")
        lines.append(
            f"llm_router_canary_oracle_repins_total {canary['repins']}"
        )
        fam("llm_router_anomalies_total")
        for sig, n in sorted(sent["anomalies_total"].items()):
            lines.append(
                f'llm_router_anomalies_total{{signal="{sig}"}} {n}'
            )
        fam("llm_router_fleet_verdict")
        lines.append(
            f"llm_router_fleet_verdict {sent['verdict_index']}"
        )
        # Elastic-fleet controller (zeros / -1 until one attaches —
        # families always exposed for dashboard discovery).  Read via
        # the controller's own snapshot under ITS leaf lock, never
        # under the router lock.
        ctrl = self.controller
        cs = ctrl.metrics_snapshot() if ctrl is not None else None
        fam("llm_fleet_scale_events_total")
        for action in ("up", "down", "deferred", "aborted"):
            v = cs["scale_events"][action] if cs is not None else 0
            lines.append(
                f'llm_fleet_scale_events_total{{action="{action}"}} {v}'
            )
        fam("llm_sessions_migrated_total")
        lines.append(
            "llm_sessions_migrated_total "
            f"{cs['sessions_migrated'] if cs is not None else 0}"
        )
        fam("llm_rollout_rung")
        lines.append(
            "llm_rollout_rung "
            f"{cs['rollout_rung'] if cs is not None else -1}"
        )
        # Fleet cache aggregate (last GET /debug/kv/fleet computation;
        # headers always present for dashboard discovery, samples only
        # once a fleet view has been computed).
        fam("llm_fleet_duplicate_kv_blocks")
        fam("llm_fleet_duplicate_kv_bytes")
        fam("llm_fleet_prefix_hit_ratio")
        fam("llm_fleet_kv_age_s")
        if fleet_kv is not None:
            lines.append(
                "llm_fleet_duplicate_kv_blocks "
                f"{fleet_kv['duplicate_kv_blocks']}"
            )
            lines.append(
                "llm_fleet_duplicate_kv_bytes "
                f"{fleet_kv['duplicate_kv_bytes']}"
            )
            lines.append(
                "llm_fleet_prefix_hit_ratio "
                f"{fleet_kv['prefix_hit_ratio']}"
            )
            lines.append(
                "llm_fleet_kv_age_s "
                f"{round(time.time() - fleet_kv['computed_unix_s'], 3)}"
            )
        fam("llm_router_replica_healthy")
        fam("llm_router_replica_inflight")
        fam("llm_router_replica_routed_total")
        fam("llm_router_replica_active_slots")
        fam("llm_router_replica_mesh_devices")
        # Per-replica cache gauges (from the /healthz kv.digest
        # summary the poller already scrapes) + the staleness gauge
        # that qualifies EVERY per-replica labeled value here: a
        # replica that went unroutable keeps its last-scraped numbers,
        # so dashboards gate on the age instead of trusting them.
        fam("llm_replica_health_age_s")
        fam("llm_router_replica_kv_nodes")
        fam("llm_router_replica_kv_hbm_blocks")
        fam("llm_router_replica_kv_host_blocks")
        fam("llm_router_replica_kv_idle_blocks")
        fam("llm_router_replica_kv_digest_version")
        fam("llm_router_replica_kv_hit_ratio")
        # Per-replica sentinel gauges (health score / verdict / last
        # canary) — the labeled twins of the GET /debug/fleet view.
        fam("llm_router_replica_health_score")
        fam("llm_router_replica_verdict")
        fam("llm_router_replica_canary_latency_ms")
        fam("llm_router_replica_canary_ok")
        for s in snaps:
            lab = f'replica="{s["index"]}"'
            lines.append(
                f"llm_router_replica_healthy{{{lab}}} "
                f"{int(bool(s['healthy']))}"
            )
            lines.append(
                f"llm_router_replica_inflight{{{lab}}} {s['inflight']}"
            )
            lines.append(
                f"llm_router_replica_routed_total{{{lab}}} "
                f"{s['routed_total']}"
            )
            rep_info = s.get("replica") or {}
            lines.append(
                f"llm_router_replica_active_slots{{{lab}}} "
                f"{rep_info.get('active_slots', 0) or 0}"
            )
            mesh = rep_info.get("serve_mesh") or {}
            lines.append(
                f"llm_router_replica_mesh_devices{{{lab}}} "
                f"{mesh.get('devices', 1) or 1}"
            )
            age = s.get("health_age_s")
            lines.append(
                f"llm_replica_health_age_s{{{lab}}} "
                f"{age if age is not None else -1}"
            )
            kv = s.get("kv") or {}
            dig = kv.get("digest") or {}
            lines.append(
                f"llm_router_replica_kv_nodes{{{lab}}} "
                f"{dig.get('nodes', 0) or 0}"
            )
            lines.append(
                f"llm_router_replica_kv_hbm_blocks{{{lab}}} "
                f"{dig.get('hbm_blocks', 0) or 0}"
            )
            lines.append(
                f"llm_router_replica_kv_host_blocks{{{lab}}} "
                f"{dig.get('host_blocks', 0) or 0}"
            )
            lines.append(
                f"llm_router_replica_kv_idle_blocks{{{lab}}} "
                f"{dig.get('idle_blocks', 0) or 0}"
            )
            lines.append(
                f"llm_router_replica_kv_digest_version{{{lab}}} "
                f"{dig.get('version', 0) or 0}"
            )
            hit = int(kv.get("prefix_hit_tokens_total") or 0)
            prompt = int(kv.get("prompt_tokens_total") or 0)
            lines.append(
                f"llm_router_replica_kv_hit_ratio{{{lab}}} "
                f"{round(hit / max(1, prompt), 6)}"
            )
            st = sent["replicas"].get(s["index"]) or {}
            lines.append(
                f"llm_router_replica_health_score{{{lab}}} "
                f"{st.get('score', 1.0)}"
            )
            lines.append(
                f"llm_router_replica_verdict{{{lab}}} "
                f"{VERDICT_INDEX[st.get('verdict', 'healthy')]}"
            )
            lc = st.get("last_canary") or {}
            lat = lc.get("latency_ms")
            lines.append(
                f"llm_router_replica_canary_latency_ms{{{lab}}} "
                f"{lat if lat is not None else -1}"
            )
            ok = lc.get("ok") if lc else None
            lines.append(
                f"llm_router_replica_canary_ok{{{lab}}} "
                f"{int(ok) if ok is not None else -1}"
            )
        return "\n".join(lines) + "\n"

    def note_handoff(
        self, blocks: int, request_id: Optional[str] = None,
        src: Optional[int] = None, dst: Optional[int] = None,
    ) -> None:
        """Count a brokered prefix handoff and drop a ``handoff`` span
        on the router track carrying the external request id — the
        link that ties the source replica's ``prefix_export`` and the
        destination's ``prefix_import`` instants into one timeline in
        the merged trace.  When the destination is known the routing
        record re-pins the id there (route-follow: the session's next
        /debug lookup lands where its KV now lives)."""
        if blocks <= 0:
            return
        t = self._now_ms()
        with self._lock:
            self.kv_handoffs_total += 1
            self._trace.append({
                "name": "handoff", "t0_ms": round(t, 3),
                "dur_ms": 0.0,
                "args": {
                    k: v for k, v in (
                        ("request_id", request_id), ("src", src),
                        ("dst", dst), ("blocks", blocks),
                    ) if v is not None
                },
            })
        if dst is not None:
            self._note_route(request_id, dst)


class FleetController:
    """The elastic-fleet control loop: autoscaling, drain-by-migration
    and zero-downtime rollouts over one :class:`ReplicaRouter`.

    Three actuators, one invariant — **no session is dropped on any
    planned fleet event**:

    - :meth:`tick` (or the background loop when ``interval_s > 0``)
      scales the fleet against windowed interactive attainment and
      queue-wait pressure, with dwell/cooldown hysteresis exactly like
      the brownout ladder: pressure (attainment below
      ``attainment_floor`` or queue-wait p90 above
      ``queue_wait_high_ms``) sustained for ``dwell_s`` scales up;
      calm (no pressure, occupancy at or below ``occupancy_low``)
      sustained for ``dwell_s`` scales down; ``cooldown_s`` separates
      consecutive actions.  Every action lands in the decision log
      (``kind="scale"``) with the driving signals, and scale-down is
      gated on the health sentinel: a victim whose verdict is not
      ``"healthy"`` is never killed (the controller must not destroy
      the evidence of an anomaly it cannot explain) — the deferral is
      itself a recorded decision.

    - :meth:`drain_replica` is the drain primitive every removal goes
      through: stop admission (``retiring``), wait for in-flight
      streams and the serving loop to settle, enumerate every live
      session's chain (``resident_chain_keys``), and move each chain
      to a survivor through the same export→import→residency-proof
      path the handoff scheduler uses (``_execute_migration``, demote
      suppressed — the source's copies die with it).  Routing records
      re-pin to the receiving survivor so the next turn of every
      session lands where its KV now lives.  Any failure — injected
      fault at ``session_migrate``, export/import error, no surviving
      destination — resumes the source untouched and reports instead
      of dropping anyone.

    - :meth:`rollout` restarts the fleet replica-by-replica onto new
      weights: per rung, drain → swap the slot to the new instance →
      ``reset_canary_oracle()`` → full canary sweep, gated on the
      restarted replica's own probe matching the rollout oracle (the
      rung-0 probe pins it, or pass ``expect_tokens`` to pin it
      externally — mid-rollout the FLEET majority still runs old
      weights, so the fleet oracle would misjudge the new output).  A
      failed gate auto-rolls the rung back (``rollback_factory``) and
      aborts; after the last rung a final reset + sweep must be
      unanimously clean.

    Thread discipline: own leaf lock guarding only the controller's
    counters/hysteresis state — compute under it, act outside it.
    Controller methods take ``router._lock`` for snapshots and call
    router actuators (which take it internally), but NEVER while
    holding the controller lock, so the two locks never nest and no
    ordering constraint exists.  Fault sites: ``scale_event`` fires at
    the start of every scale-up/scale-down/rollout-rung (an injected
    fault aborts the whole action cleanly — fleet membership
    unchanged); ``session_migrate`` fires once per live session at the
    start of its drain migration (aborts that session's move only).
    """

    def __init__(
        self,
        router: ReplicaRouter,
        replica_factory: Optional[Any] = None,
        *,
        min_replicas: int = 1,
        max_replicas: int = 8,
        interval_s: float = 0.0,   # <= 0: manual (tests) — drive tick()
        attainment_floor: float = 0.9,
        queue_wait_high_ms: float = 500.0,
        occupancy_low: float = 0.25,
        dwell_s: float = 0.0,
        cooldown_s: float = 0.0,
        drain_timeout_s: float = 30.0,
        migrate_timeout_s: Optional[float] = None,
        fault_injector: Optional[FaultInjector] = None,
    ):
        self.router = router
        # ``replica_factory(index_hint)`` returns a started in-process
        # LLMServer (or a "host:port" string) for scale-up / rollouts.
        self.replica_factory = replica_factory
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.interval_s = float(interval_s)
        self.attainment_floor = float(attainment_floor)
        self.queue_wait_high_ms = float(queue_wait_high_ms)
        self.occupancy_low = float(occupancy_low)
        self.dwell_s = float(dwell_s)
        self.cooldown_s = float(cooldown_s)
        self.drain_timeout_s = float(drain_timeout_s)
        self.migrate_timeout_s = migrate_timeout_s
        self.fault_injector = (
            fault_injector if fault_injector is not None
            else router.fault_injector
        )
        self._lock = threading.Lock()
        self._scale_events: Dict[str, int] = {
            "up": 0, "down": 0, "deferred": 0, "aborted": 0,
        }
        self.sessions_migrated_total = 0
        self.sessions_migrate_failed_total = 0
        self.drains_total = 0
        self.drains_failed_total = 0
        self.rollouts_total = 0
        self.rollbacks_total = 0
        self.rollout_rung = -1
        self._pressure_since: Optional[float] = None
        self._calm_since: Optional[float] = None
        self._last_action_t = float("-inf")
        self._busy = False
        self._last_signals: Optional[Dict[str, Any]] = None
        # In-process servers the controller created (scale-up /
        # rollout swaps): the controller stops these on removal; all
        # other instances' lifecycles stay with their creator.
        self._owned: Dict[int, Any] = {}
        self._rollout_oracle: Optional[List[int]] = None
        self._closed = threading.Event()
        self._thread: Optional[threading.Thread] = None
        router.attach_controller(self)
        if self.interval_s > 0:
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="fleet-controller",
            )
            self._thread.start()

    # -- lifecycle -----------------------------------------------------------

    def _loop(self) -> None:
        while not self._closed.is_set():
            try:
                self.tick()
            except Exception as e:  # keep the loop alive; surface it
                self.router._log("fleet_tick_error", error=str(e))
            self._closed.wait(self.interval_s)

    def close(self, stop_owned: bool = False) -> None:
        """Stop the background loop; with ``stop_owned`` also stop
        every in-process server the controller created."""
        self._closed.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        if stop_owned:
            with self._lock:
                owned = list(self._owned.values())
            for srv in owned:
                self._stop_server(srv)

    # -- signals + decision --------------------------------------------------

    def signals(self) -> Dict[str, Any]:
        """One snapshot of the scaling inputs, from the last health
        scrapes (no network): worst interactive attainment and worst
        queue-wait p90 across healthy active replicas, worst slot
        occupancy, and fleet-wide in-flight."""
        r = self.router
        with r._lock:
            active = [
                x for x in r._replicas
                if not x.retired and not x.retiring
            ]
            healthy = [x for x in active if x.healthy]
            att: List[float] = []
            qw: List[float] = []
            occ: List[float] = []
            for x in healthy:
                ov = (x.last_health or {}).get("overload") or {}
                a = ov.get("interactive_attainment")
                if a is not None:
                    att.append(float(a))
                q = ov.get("queue_wait_ms_p90")
                if q is not None:
                    qw.append(float(q))
                occ.append(r._occupancy_locked(x))
            inflight = sum(x.inflight for x in active)
        return {
            "replicas_active": len(active),
            "replicas_healthy": len(healthy),
            "inflight": inflight,
            "attainment_min": round(min(att), 4) if att else None,
            "queue_wait_ms_p90_max": round(max(qw), 3) if qw else None,
            "occupancy_max": round(max(occ), 4) if occ else None,
        }

    def _decide_locked(
        self, now: float, sig: Dict[str, Any],
    ) -> Tuple[str, str]:
        """Hysteresis state machine (holds ``self._lock``): returns
        ``("up"|"down"|"hold", reason)``.  Pressure and calm must each
        be SUSTAINED for ``dwell_s`` (a single hot scrape scales
        nothing), and ``cooldown_s`` must have passed since the last
        action — the same shape as the brownout ladder, so the two
        controllers don't fight over transients."""
        if self._busy:
            return "hold", "action-in-progress"
        att = sig.get("attainment_min")
        qw = sig.get("queue_wait_ms_p90_max")
        occ = sig.get("occupancy_max")
        pressure = (
            (att is not None and att < self.attainment_floor)
            or (qw is not None and qw > self.queue_wait_high_ms)
        )
        calm = (
            not pressure
            and occ is not None and occ <= self.occupancy_low
        )
        if pressure:
            self._calm_since = None
            if self._pressure_since is None:
                self._pressure_since = now
        else:
            self._pressure_since = None
            if calm:
                if self._calm_since is None:
                    self._calm_since = now
            else:
                self._calm_since = None
        if not pressure and not calm:
            return "hold", "steady"
        if now - self._last_action_t < self.cooldown_s:
            return "hold", "cooldown"
        if pressure:
            if now - self._pressure_since < self.dwell_s:
                return "hold", "dwell"
            if sig["replicas_active"] >= self.max_replicas:
                return "hold", "at-max-replicas"
            if self.replica_factory is None:
                return "hold", "no-replica-factory"
            return "up", "pressure"
        if now - self._calm_since < self.dwell_s:
            return "hold", "dwell"
        if sig["replicas_active"] <= self.min_replicas:
            return "hold", "at-min-replicas"
        return "down", "calm"

    def tick(self) -> Dict[str, Any]:
        """One control-loop step: snapshot signals, run the hysteresis
        decision, act.  Gated deferrals (at-max/at-min/no-factory) are
        recorded decisions; dwell/cooldown/steady holds are silent
        (their state is visible in /debug/fleet's ``last_signals``)."""
        now = time.monotonic()
        sig = self.signals()
        with self._lock:
            action, reason = self._decide_locked(now, sig)
            self._last_signals = dict(sig, action=action, reason=reason)
        if action == "up":
            return self.scale_up(signals=sig)
        if action == "down":
            return self.scale_down(signals=sig)
        if reason in (
            "at-max-replicas", "at-min-replicas", "no-replica-factory",
        ):
            with self._lock:
                self._scale_events["deferred"] += 1
            self.router.decisions.record(
                "scale", action="deferred", reason=reason, signals=sig,
            )
        return {"ok": True, "action": "hold", "reason": reason,
                "signals": sig}

    # -- shared plumbing -----------------------------------------------------

    def _fire(self, site: str) -> Optional[str]:
        """Fire a controller fault site; returns the injected-fault
        message (action must abort) or None (proceed)."""
        fi = self.fault_injector
        if fi is None:
            return None
        try:
            fi.fire(site)
        except InjectedFault as e:
            return str(e) or f"injected fault at {site}"
        return None

    def _begin_action(self) -> bool:
        with self._lock:
            if self._busy:
                return False
            self._busy = True
            return True

    def _end_action(self, acted: bool) -> None:
        with self._lock:
            self._busy = False
            if acted:
                self._last_action_t = time.monotonic()

    @staticmethod
    def _stop_server(server: Any) -> None:
        if server is None or isinstance(server, str):
            return
        try:
            server.shutdown_for_restart(grace_s=2.0)
        except Exception:
            pass

    def _pick_destination(self, src: int) -> Optional[int]:
        """Least-loaded active healthy survivor (never the source)."""
        r = self.router
        with r._lock:
            cands = [
                x for x in r._replicas
                if x.index != src and x.healthy
                and not x.retiring and not x.retired
            ]
            if not cands:
                return None
            best = min(
                cands,
                key=lambda x: (r._occupancy_locked(x), x.inflight,
                               x.index),
            )
            return best.index

    def _pick_victim(
        self, explicit: Optional[int] = None,
    ) -> Tuple[Optional[int], Dict[int, str]]:
        """Scale-down victim, sentinel-gated: only a replica whose
        health-sentinel verdict is ``"healthy"`` may be killed —
        never destroy the evidence of an anomaly the sentinel cannot
        explain.  Among eligible victims, least in-flight wins."""
        r = self.router
        with r._lock:
            cands = [
                (x.index, x.inflight, x.routed_total)
                for x in r._replicas
                if not x.retired and not x.retiring and x.healthy
            ]
        verdicts = {i: r.sentinel.verdict(i) for i, _, _ in cands}
        if explicit is not None:
            v = verdicts.get(explicit) or r.sentinel.verdict(explicit)
            verdicts[explicit] = v
            return (explicit if v == "healthy" else None), verdicts
        ok = [c for c in cands if verdicts[c[0]] == "healthy"]
        if not ok:
            return None, verdicts
        return min(ok, key=lambda c: (c[1], c[2]))[0], verdicts

    # -- actuators -----------------------------------------------------------

    def scale_up(
        self, signals: Optional[Dict[str, Any]] = None,
        role: Optional[str] = None,
    ) -> Dict[str, Any]:
        """Add one replica through ``replica_factory``.  Fires
        ``scale_event`` first — an injected fault aborts with the
        fleet unchanged."""
        r = self.router
        sig = signals if signals is not None else self.signals()
        if not self._begin_action():
            return {"ok": False, "action": "up",
                    "reason": "action-in-progress"}
        acted = False
        try:
            err = self._fire("scale_event")
            if err is not None:
                with self._lock:
                    self._scale_events["aborted"] += 1
                r.decisions.record("scale", action="aborted", op="up",
                                   reason=err, signals=sig)
                return {"ok": False, "action": "up", "reason": err}
            if self.replica_factory is None:
                with self._lock:
                    self._scale_events["deferred"] += 1
                r.decisions.record(
                    "scale", action="deferred", op="up",
                    reason="no-replica-factory", signals=sig,
                )
                return {"ok": False, "action": "up",
                        "reason": "no-replica-factory"}
            with r._lock:
                hint = len(r._replicas)
            try:
                server = self.replica_factory(hint)
            except Exception as e:
                with self._lock:
                    self._scale_events["aborted"] += 1
                r.decisions.record(
                    "scale", action="aborted", op="up",
                    reason=f"replica-factory: {e}", signals=sig,
                )
                return {"ok": False, "action": "up",
                        "reason": f"replica-factory: {e}"}
            idx = r.add_replica(server, role=role)
            with self._lock:
                if not isinstance(server, str):
                    self._owned[idx] = server
                self._scale_events["up"] += 1
            if r.health_interval_s <= 0:
                r.check_health_now()
            r.decisions.record(
                "scale", action="up", replica=idx,
                sentinel=r.sentinel.verdict(idx), signals=sig,
            )
            acted = True
            return {"ok": True, "action": "up", "replica": idx,
                    "signals": sig}
        finally:
            self._end_action(acted)

    def scale_down(
        self, victim: Optional[int] = None,
        signals: Optional[Dict[str, Any]] = None,
    ) -> Dict[str, Any]:
        """Remove one replica: sentinel-gated victim pick, drain (live
        sessions migrate to survivors), then retire.  Fires
        ``scale_event`` first; any failure aborts with the fleet
        unchanged and every session still served."""
        r = self.router
        sig = signals if signals is not None else self.signals()
        if not self._begin_action():
            return {"ok": False, "action": "down",
                    "reason": "action-in-progress"}
        acted = False
        try:
            err = self._fire("scale_event")
            if err is not None:
                with self._lock:
                    self._scale_events["aborted"] += 1
                r.decisions.record("scale", action="aborted", op="down",
                                   reason=err, signals=sig)
                return {"ok": False, "action": "down", "reason": err}
            pick, verdicts = self._pick_victim(victim)
            if pick is None:
                with self._lock:
                    self._scale_events["deferred"] += 1
                r.decisions.record(
                    "scale", action="deferred", op="down",
                    reason="sentinel-cannot-explain",
                    sentinel=verdicts, signals=sig,
                )
                return {"ok": False, "action": "down",
                        "reason": "sentinel-cannot-explain",
                        "sentinel": verdicts}
            report = self.drain_replica(pick)
            if not report.get("ok"):
                # drain_replica already resumed admission: abort with
                # the fleet exactly as it was.
                with self._lock:
                    self._scale_events["aborted"] += 1
                r.decisions.record(
                    "scale", action="aborted", op="down", replica=pick,
                    reason=f"drain: {report.get('reason')}",
                    signals=sig,
                )
                return {"ok": False, "action": "down", "replica": pick,
                        "reason": f"drain: {report.get('reason')}",
                        "drain": report}
            with r._lock:
                server = r._replicas[pick].server
            r.retire_replica(pick)
            with self._lock:
                owned = self._owned.pop(pick, None)
                self._scale_events["down"] += 1
            if owned is not None and owned is server:
                self._stop_server(server)
            r.decisions.record(
                "scale", action="down", replica=pick,
                sentinel=verdicts.get(pick),
                migrated=report.get("migrated"),
                blocks=report.get("blocks"), signals=sig,
            )
            acted = True
            return {"ok": True, "action": "down", "replica": pick,
                    "drain": report, "signals": sig}
        finally:
            self._end_action(acted)

    def drain_replica(
        self, index: int, timeout_s: Optional[float] = None,
    ) -> Dict[str, Any]:
        """THE drain primitive (also the operator entry): stop
        admission, wait for in-flight streams + the serving loop to
        settle, migrate every live session's chain to a survivor, and
        re-pin routing records.  On success the replica is left
        ``retiring`` (the caller retires/swaps it, or resumes with
        ``set_retiring(index, False)`` to cancel).  On ANY failure the
        replica RESUMES admission untouched — no session is ever
        stranded halfway."""
        r = self.router
        budget = (
            self.drain_timeout_s if timeout_s is None
            else float(timeout_s)
        )
        with r._lock:
            rep = r._replicas[index]
            already_retired = rep.retired
            server = rep.server
        if already_retired:
            return {"ok": False, "replica": index,
                    "reason": "already-retired"}
        if server is None:
            return {"ok": False, "replica": index,
                    "reason": "not-in-process"}
        t_wall = time.monotonic()
        r.set_retiring(index, True)
        deadline = t_wall + budget

        def _fail(reason: str, **extra: Any) -> Dict[str, Any]:
            r.set_retiring(index, False)
            with self._lock:
                self.drains_total += 1
                self.drains_failed_total += 1
            rec = {"ok": False, "replica": index, "reason": reason}
            rec.update(extra)
            r.decisions.record("drain", **rec)
            return rec

        # 1. In-flight streams finish on the source (admission is
        #    already off, so the count only falls).
        while True:
            with r._lock:
                infl = r._replicas[index].inflight
            if infl == 0:
                break
            if time.monotonic() >= deadline:
                return _fail("inflight-timeout", inflight=infl)
            time.sleep(0.01)
        # 2. The serving loop settles (work admitted before retiring).
        if not server.wait_idle(
            timeout_s=max(0.1, deadline - time.monotonic()),
        ):
            return _fail("serving-loop-busy")
        # 3. Enumerate every live session's chain.
        try:
            chains = server.call_on_loop(
                lambda b: b.resident_chain_keys(),
                timeout_s=max(0.1, deadline - time.monotonic()),
            )
        except (TimeoutError, RuntimeError, OSError) as e:
            return _fail(f"enumerate: {e}")
        # Deepest-first is deterministic and moves whole sessions
        # before their prefix-sharing shorter siblings.
        chains = sorted(chains, key=lambda c: (-len(c), c))
        mig_budget = (
            r.handoff_timeout_s if self.migrate_timeout_s is None
            else float(self.migrate_timeout_s)
        )
        migrated = skipped = failed = blocks = 0
        dst_counts: Dict[int, int] = {}
        failures: List[Dict[str, Any]] = []
        for i, chain in enumerate(chains):
            rid = f"drain-{index}-{i}"
            err = self._fire("session_migrate")
            if err is not None:
                # This session's move aborts; its copy stays on the
                # source, which resumes below — nobody is dropped.
                failed += 1
                failures.append(
                    {"chain": chain[0].hex()[:16], "reason": err},
                )
                continue
            dst = self._pick_destination(index)
            if dst is None:
                return _fail(
                    "no-survivor", sessions=len(chains),
                    migrated=migrated, failed=failed,
                )
            try:
                n, outcome = r._execute_migration(
                    index, dst, chain, request_id=rid,
                    timeout_s=mig_budget, demote=False,
                )
            except (TimeoutError, RuntimeError, OSError,
                    InjectedFault) as e:
                failed += 1
                failures.append({
                    "chain": chain[0].hex()[:16], "dst": dst,
                    "reason": str(e) or type(e).__name__,
                })
                continue
            if outcome == "completed":
                migrated += 1
                blocks += n
                dst_counts[dst] = dst_counts.get(dst, 0) + 1
            else:
                skipped += 1  # nothing-resident / already at dst
        with self._lock:
            self.sessions_migrated_total += migrated
            self.sessions_migrate_failed_total += failed
        if failed:
            return _fail(
                "migration-failures", sessions=len(chains),
                migrated=migrated, failed=failed, skipped=skipped,
                failures=failures[:8],
            )
        # 4. Re-pin routing records + affinity to the survivor that
        #    received the most sessions (cache-aware routing finds
        #    per-chain placements through the index regardless).
        repin_dst = (
            max(dst_counts, key=lambda k: dst_counts[k])
            if dst_counts else None
        )
        repinned = (
            r.repin_routes(index, repin_dst)
            if repin_dst is not None else 0
        )
        with self._lock:
            self.drains_total += 1
        rec = {
            "ok": True, "replica": index, "sessions": len(chains),
            "migrated": migrated, "skipped": skipped, "blocks": blocks,
            "destinations": {str(k): v for k, v in dst_counts.items()},
            "repinned": repinned,
            "dur_ms": round((time.monotonic() - t_wall) * 1000.0, 3),
        }
        r.decisions.record("drain", **rec)
        return rec

    def rollout(
        self, factory: Any, rollback_factory: Optional[Any] = None,
        expect_tokens: Optional[Sequence[int]] = None,
    ) -> Dict[str, Any]:
        """Zero-downtime rollout: replica-by-replica drain → swap to
        ``factory(index)``'s instance → canary gate.  Per rung the
        canary oracle is reset and a full sweep runs; the gate is the
        restarted replica's own probe — transport-clean AND its tokens
        matching the rollout oracle (pinned from the rung-0 probe, or
        from ``expect_tokens`` when the operator knows the new
        weights' expected canary output).  A failed gate auto-rolls
        the rung back through ``rollback_factory`` (without one the
        rung's replica is retired) and aborts the rollout.  After the
        last rung a final reset + sweep must be unanimously clean.
        Sessions are migrated off each rung before its restart, so no
        session is dropped even by a failed rung."""
        r = self.router
        if not self._begin_action():
            return {"ok": False, "reason": "action-in-progress"}
        with self._lock:
            self.rollouts_total += 1
            self._rollout_oracle = (
                list(expect_tokens) if expect_tokens is not None
                else None
            )
        with r._lock:
            plan = [x.index for x in r._replicas if not x.retired]
        results: List[Dict[str, Any]] = []
        ok_all = True
        reason: Optional[str] = None
        try:
            for rung, idx in enumerate(plan):
                with self._lock:
                    self.rollout_rung = rung
                err = self._fire("scale_event")
                if err is not None:
                    with self._lock:
                        self._scale_events["aborted"] += 1
                    r.decisions.record(
                        "rollout_rung", rung=rung, replica=idx,
                        ok=False, reason=err,
                    )
                    ok_all, reason = False, err
                    break
                report = self.drain_replica(idx)
                if not report.get("ok"):
                    r.decisions.record(
                        "rollout_rung", rung=rung, replica=idx,
                        ok=False,
                        reason=f"drain: {report.get('reason')}",
                    )
                    ok_all = False
                    reason = f"drain: {report.get('reason')}"
                    break
                with r._lock:
                    old = r._replicas[idx].server
                try:
                    fresh = factory(idx)
                except Exception as e:
                    r.set_retiring(idx, False)
                    r.decisions.record(
                        "rollout_rung", rung=rung, replica=idx,
                        ok=False, reason=f"factory: {e}",
                    )
                    ok_all, reason = False, f"factory: {e}"
                    break
                r.swap_replica(idx, fresh)
                with self._lock:
                    if not isinstance(fresh, str):
                        self._owned[idx] = fresh
                    else:
                        self._owned.pop(idx, None)
                self._stop_server(old)
                if r.health_interval_s <= 0:
                    r.check_health_now()
                gate_ok, why = self._rung_gate(idx)
                if gate_ok:
                    r.decisions.record(
                        "rollout_rung", rung=rung, replica=idx,
                        ok=True, gate=why,
                        migrated=report.get("migrated"),
                    )
                    results.append(
                        {"rung": rung, "replica": idx, "ok": True},
                    )
                    continue
                rb = self._rollback_rung(idx, fresh, rollback_factory)
                with self._lock:
                    self.rollbacks_total += 1
                r.decisions.record(
                    "rollout_rung", rung=rung, replica=idx, ok=False,
                    reason=f"canary-gate: {why}", rollback=rb,
                )
                results.append({
                    "rung": rung, "replica": idx, "ok": False,
                    "reason": why, "rollback": rb,
                })
                ok_all, reason = False, f"canary-gate: {why}"
                break
            if ok_all:
                r.reset_canary_oracle()
                sweep = r.run_canaries_now()
                bad = [i for i, res in sweep if not res.get("ok")]
                if bad:
                    ok_all = False
                    reason = f"final-sweep-unclean: {bad}"
            r.decisions.record(
                "rollout", ok=ok_all, rungs_done=len(results),
                planned=len(plan), reason=reason,
            )
            return {"ok": ok_all, "rungs": results,
                    "planned": len(plan), "reason": reason}
        finally:
            with self._lock:
                self.rollout_rung = -1
                self._rollout_oracle = None
            self._end_action(True)

    def _rung_gate(self, idx: int) -> Tuple[bool, str]:
        """One rung's canary gate: reset the fleet oracle, sweep, and
        judge the restarted replica by its OWN probe against the
        rollout oracle — mid-rollout the fleet majority still runs old
        weights, so the sweep's plurality oracle cannot be trusted to
        judge the new output."""
        r = self.router
        r.reset_canary_oracle()
        sweep = dict(r.run_canaries_now())
        res = sweep.get(idx)
        if res is None:
            return False, "no-canary-result"
        tokens = res.get("tokens")
        if res.get("error") is not None or not tokens:
            return False, (
                f"probe-failed: {res.get('error') or 'no-tokens'}"
            )
        with self._lock:
            oracle = self._rollout_oracle
            if oracle is None:
                self._rollout_oracle = list(tokens)
                return True, "oracle-pinned"
        if list(tokens) == list(oracle):
            return True, "oracle-match"
        return False, "oracle-mismatch"

    def _rollback_rung(
        self, idx: int, bad_server: Any, rollback_factory: Optional[Any],
    ) -> str:
        """Undo one failed rung: swap the slot back to a
        ``rollback_factory(index)`` instance (old weights) — or,
        without one, retire the slot (its sessions already live on
        survivors).  The bad instance is stopped either way."""
        r = self.router
        if rollback_factory is None:
            r.retire_replica(idx)
            with self._lock:
                self._owned.pop(idx, None)
            self._stop_server(bad_server)
            return "retired"
        try:
            prev = rollback_factory(idx)
        except Exception as e:
            r.retire_replica(idx)
            with self._lock:
                self._owned.pop(idx, None)
            self._stop_server(bad_server)
            return f"retired (rollback factory failed: {e})"
        r.swap_replica(idx, prev)
        with self._lock:
            if not isinstance(prev, str):
                self._owned[idx] = prev
            else:
                self._owned.pop(idx, None)
        self._stop_server(bad_server)
        if r.health_interval_s <= 0:
            r.check_health_now()
        return "rolled-back"

    # -- introspection -------------------------------------------------------

    def state_json(self) -> Dict[str, Any]:
        """/debug/fleet's ``controller`` block."""
        with self._lock:
            return {
                "min_replicas": self.min_replicas,
                "max_replicas": self.max_replicas,
                "interval_s": self.interval_s,
                "attainment_floor": self.attainment_floor,
                "queue_wait_high_ms": self.queue_wait_high_ms,
                "occupancy_low": self.occupancy_low,
                "dwell_s": self.dwell_s,
                "cooldown_s": self.cooldown_s,
                "busy": self._busy,
                "rollout_rung": self.rollout_rung,
                "scale_events": dict(self._scale_events),
                "sessions_migrated_total": self.sessions_migrated_total,
                "sessions_migrate_failed_total":
                    self.sessions_migrate_failed_total,
                "drains_total": self.drains_total,
                "drains_failed_total": self.drains_failed_total,
                "rollouts_total": self.rollouts_total,
                "rollbacks_total": self.rollbacks_total,
                "last_signals": (
                    dict(self._last_signals)
                    if self._last_signals else None
                ),
            }

    def metrics_snapshot(self) -> Dict[str, Any]:
        """The /metrics families the router exposition renders."""
        with self._lock:
            return {
                "scale_events": dict(self._scale_events),
                "sessions_migrated": self.sessions_migrated_total,
                "rollout_rung": self.rollout_rung,
            }


def handoff_prefix(
    src_batcher, dst_batcher, tokens: Sequence[int],
    router: Optional[ReplicaRouter] = None,
    request_id: Optional[str] = None,
    src: Optional[int] = None,
    dst: Optional[int] = None,
) -> int:
    """Prefill/decode disaggregation handoff: move ``tokens``' cached
    prefix blocks from ``src_batcher`` (which prefilled them) into
    ``dst_batcher``'s pool + radix index, so the session's next
    admission on the destination replica is a plain prefix hit —
    ``export_prefix``'s D2H slab fetch feeding ``import_prefix``'s
    stage/adopt/publish, the exact path the host-DRAM tier restores
    through.  Both batcher calls MUST run on their owning serving-loop
    threads (the batchers are thread-confined).  ``request_id`` (the
    session's external id) threads through both batchers' trace
    annotations and the router's handoff span, so the fleet-merged
    trace shows the move as ONE linked timeline; ``src``/``dst`` are
    the replica indices when the caller knows them.  Returns the
    number of blocks landed on the destination."""
    keys, slabs = src_batcher.export_prefix(tokens, request_id=request_id)
    if not slabs:
        return 0
    n = dst_batcher.import_prefix(keys, slabs, request_id=request_id)
    if router is not None:
        router.note_handoff(n, request_id=request_id, src=src, dst=dst)
    return n
