"""Data-parallel replica routing: N serving replicas behind one door.

The scale-out serving subsystem's outermost layer (ROADMAP item 2; the
in-replica mesh sharding lives in ``parallel/serve_mesh.py``): a
:class:`ReplicaRouter` fronts N **independent** serving replicas — each
an ``LLMServer`` with its own ``ContinuousBatcher``, KV pool, radix
prefix index and (optionally) its own mesh slice — and routes each POST
to one of them:

  * **least-loaded** (default): the healthy replica with the fewest
    router-tracked in-flight requests (ties rotate by routed count), so
    a long-generation pileup on one replica never queues new arrivals
    behind it.
  * **affinity**: sticky sessions by prompt-prefix key — a revisited
    session routes to the replica already holding its radix chain, so
    multi-turn chats keep their prefix-cache hits (and host-tier slabs)
    local instead of re-prefilling cold on a random replica.  New
    sessions fall back to least-loaded; a dead replica's sessions
    re-pin wherever their next turn lands.

**Health / quarantine.**  A poller thread scrapes each replica's
``/healthz`` (the server's own ok/draining/degraded verdict — a replica
in drain or with a dead loop stops receiving new work while its
in-flight requests finish); a forward-time connection failure (or an
injected ``router_replica`` fault) marks the replica unhealthy
immediately.  Requests that have not yet streamed a byte RE-ROUTE to a
surviving replica losslessly; requests in flight on a genuinely crashed
replica are that replica's own crash-recovery problem (rebuild + replay
— the PR-1 machinery), not the router's: the router never duplicates a
request it may have half-delivered.

**Prefill/decode disaggregation (skeleton).**  :func:`handoff_prefix`
moves a session's cached prefix blocks between two batchers through the
existing host-tier primitives (``export_prefix`` D2H slab fetch on the
prefill side, ``import_prefix`` stage+adopt+publish on the decode
side), so an admission can prefill on one replica and decode on
another that receives its KV as a plain prefix hit.  The router counts
handoffs; scheduling WHEN to disaggregate (prefill-heavy vs
decode-heavy replica pools) is the open half — both batcher calls must
run on their owning serving-loop threads, so a live-traffic router
drives them through the replicas' control paths, not directly.

HTTP surface (the router speaks the same protocol as a single server,
so clients need no changes):

    POST /generate, /chat    routed + proxied (streaming NDJSON relays
                             line-by-line); the response carries
                             X-Replica-Id, and the replica's request
                             timeline records the routing decision
                             (X-Routed-By -> /debug/requests/<id>)
    GET  /healthz            aggregate: ok = any replica routable, plus
                             a ``replicas`` section (per-replica
                             health/occupancy/mesh snapshot)
    GET  /metrics            router gauges + per-replica labeled series
    GET  /debug/trace        FLEET-MERGED Perfetto trace (schema below)
    GET  /debug/requests     index aggregated across ALL healthy
                             replicas, each entry tagged ``replica``
    GET  /debug/requests/<id>  resolved through the ROUTING RECORD
                             first (the bounded request-id -> replica
                             map the relay fills from each reply's
                             X-Request-Id), then healthy-replica
                             fan-out — never first-to-answer guessing
    GET  /debug/*            (everything else) tried against each
                             healthy replica until one answers non-404

Fleet-merged tracing (``GET /debug/trace[?window_s=S]``): ONE
Chrome/Perfetto ``trace_event`` document containing

  * the router's own span track (pid 0, process_name ``router``):
    ``route`` (decision; args replica/policy/request_id), ``forward``
    (relay wall time; timeout/client-disconnect flagged), ``reroute``
    (a failed replica's lossless re-route) and ``handoff``
    (cross-replica prefix-KV moves, args request_id/blocks) spans,
    recorded in a bounded ring under ``_lock``;
  * every healthy replica's own ``/debug/trace`` export re-tagged to
    pid ``1+index`` (process_name ``replica-<index>``) with its
    timestamps shifted into the router's frame via the ``t0_unix_s``
    wall-clock anchor each Observability ring publishes (clock-offset
    normalization — replica monotonic clocks share no epoch);
  * handoff linkage: the router's ``handoff`` span and both replicas'
    ``prefix_export`` / ``prefix_import`` instants carry the same
    external request id, so a prefill-on-A / decode-on-B session
    reads as one timeline across three tracks.

Thread discipline: handler threads (forward) and the health poller
share the replica table, counters, routing record, and trace ring —
every access goes under ``_lock`` (registered in
analysis/lockcheck.py).  The router holds no jax state at all; it is
pure host-side HTTP."""

from __future__ import annotations

import http.client
import json
import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Sequence, Tuple
from urllib.parse import parse_qs, unquote, urlsplit

from .faults import FaultInjector, InjectedFault
from .obs import StructuredLogger

POLICIES = ("least-loaded", "affinity")


class _ClientDisconnect(Exception):
    """The CLIENT's socket died while relaying — the replica is fine.
    Distinct from replica-side OSErrors so a disconnecting client never
    marks a healthy replica unhealthy; ``relayed`` records whether any
    bytes reached the client before the drop."""

    def __init__(self, relayed: bool):
        super().__init__("client disconnected")
        self.relayed = relayed

# Hop-by-hop / recomputed headers never relayed from a replica reply.
_SKIP_HEADERS = frozenset({
    "connection", "transfer-encoding", "content-length", "server",
    "date",
})

# Prompt-prefix length (tokens or characters) the affinity key hashes:
# long enough to separate sessions with a shared system prompt short
# of one block, short enough that appending turns to a chat keeps the
# key (and therefore the replica holding the chain) stable.
_AFFINITY_PREFIX = 64


@dataclass
class _Replica:
    """Router-side view of one serving replica."""

    index: int
    host: str
    port: int
    server: Any = None            # in-process LLMServer (caller-owned)
    healthy: bool = True
    inflight: int = 0
    routed_total: int = 0
    failures_total: int = 0
    last_health: Dict[str, Any] = field(default_factory=dict)

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def snapshot(self) -> Dict[str, Any]:
        h = self.last_health
        return {
            "index": self.index,
            "address": self.address,
            "healthy": self.healthy,
            "inflight": self.inflight,
            "routed_total": self.routed_total,
            "failures_total": self.failures_total,
            "draining": h.get("draining"),
            "degraded": h.get("degraded"),
            "overload_state": (h.get("overload") or {}).get("state"),
            "replica": h.get("replica"),
        }


def _parse_address(addr: str) -> Tuple[str, int]:
    """Accepts ``host:port`` or ``http://host:port`` (LLMServer's own
    ``.address`` spelling)."""
    if addr.startswith("http://"):
        addr = addr[len("http://"):]
    addr = addr.rstrip("/")
    host, _, port = addr.rpartition(":")
    return host or "127.0.0.1", int(port)


class ReplicaRouter:
    """HTTP front-end routing requests across serving replicas
    (module docstring).  ``replicas`` mixes in-process ``LLMServer``
    instances (must already be started; their lifecycle stays with the
    caller) and ``"host:port"`` strings for out-of-process ones."""

    def __init__(
        self,
        replicas: Sequence[Any],
        host: str = "127.0.0.1",
        port: int = 0,
        policy: str = "least-loaded",
        health_interval_s: float = 0.5,  # <= 0: manual (tests) —
        #                                  check_health_now() only
        proxy_timeout_s: float = 300.0,
        affinity_max_sessions: int = 4096,
        fault_injector: Optional[FaultInjector] = None,
        logger: Optional[StructuredLogger] = None,
    ):
        if policy not in POLICIES:
            raise ValueError(
                f"unknown route policy {policy!r}; have {POLICIES}"
            )
        if not replicas:
            raise ValueError("ReplicaRouter needs at least one replica")
        self.policy = policy
        self.fault_injector = fault_injector
        self.logger = logger
        self.health_interval_s = float(health_interval_s)
        self.proxy_timeout_s = float(proxy_timeout_s)
        self.affinity_max_sessions = int(affinity_max_sessions)
        self._lock = threading.Lock()
        self._replicas: List[_Replica] = []
        for i, rep in enumerate(replicas):
            if isinstance(rep, str):
                h, p = _parse_address(rep)
                self._replicas.append(_Replica(index=i, host=h, port=p))
            else:  # in-process LLMServer
                h, p = _parse_address(rep.address)
                self._replicas.append(
                    _Replica(index=i, host=h, port=p, server=rep)
                )
        # Sticky-session map: affinity key -> replica index (bounded
        # LRU — hits refresh recency, so long-lived active sessions
        # are not the eviction victims; a dead replica's entries
        # re-pin on next use).
        self._affinity: "OrderedDict[bytes, int]" = OrderedDict()
        self.routed_by_policy: Dict[str, int] = {
            "least-loaded": 0, "affinity": 0, "reroute": 0,
        }
        self.reroutes_total = 0
        self.replica_failures_total = 0
        self.kv_handoffs_total = 0
        # Router-local trace ring (fleet-merged /debug/trace): bounded
        # span dicts, appended under _lock by handler threads.  The
        # monotonic/wall anchors are captured at the same instant —
        # the same clock-offset contract obs.Observability publishes.
        self._t0 = time.monotonic()
        self.t0_unix = time.time()
        self._trace: "deque[Dict[str, Any]]" = deque(maxlen=1024)
        # Routing record: external request id -> replica index
        # (bounded LRU, filled by the relay from each reply's
        # X-Request-Id header) — /debug/requests/<id> consults it
        # before any fan-out.
        self._routes: "OrderedDict[str, int]" = OrderedDict()
        self.route_record_max = 4096
        self._closed = threading.Event()
        router = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet test output
                pass

            def do_GET(self):
                router._handle_get(self)

            def do_POST(self):
                router._handle_post(self)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="router-http",
        )
        self._health_thread = threading.Thread(
            target=self._health_loop, daemon=True, name="router-health",
        )

    # -- lifecycle -----------------------------------------------------------

    @property
    def address(self) -> str:
        h, p = self._httpd.server_address[:2]
        return f"http://{h}:{p}"

    def start(self) -> "ReplicaRouter":
        self._http_thread.start()
        self._health_thread.start()
        return self

    def stop(self) -> None:
        """Stop the router (replica lifecycles stay with the caller)."""
        self._closed.set()
        self._httpd.shutdown()
        self._httpd.server_close()
        self._health_thread.join(timeout=5)

    def __enter__(self) -> "ReplicaRouter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _log(self, event: str, message: str = "", **fields) -> None:
        if self.logger is not None:
            self.logger.log(event, message, **fields)

    # -- router-local tracing / routing record -------------------------------

    def _now_ms(self) -> float:
        return (time.monotonic() - self._t0) * 1000.0

    def _span(self, name: str, t0_ms: float, **args) -> None:
        """Close a router span started at ``t0_ms`` (None-valued args
        drop, so absent request ids don't litter the trace)."""
        dur = max(0.0, self._now_ms() - t0_ms)
        rec = {
            "name": name, "t0_ms": round(t0_ms, 3),
            "dur_ms": round(dur, 3),
            "args": {k: v for k, v in args.items() if v is not None},
        }
        with self._lock:
            self._trace.append(rec)

    def _note_route(self, request_id: Optional[str],
                    index: int) -> None:
        """Record which replica served ``request_id`` (bounded LRU) —
        the /debug/requests/<id> resolution path."""
        if not request_id:
            return
        with self._lock:
            self._routes[request_id] = index
            self._routes.move_to_end(request_id)
            while len(self._routes) > self.route_record_max:
                self._routes.popitem(last=False)

    # -- health --------------------------------------------------------------

    def _probe(self, rep: _Replica) -> Tuple[bool, Dict[str, Any]]:
        """One /healthz scrape; (routable, payload).  A 503 body still
        parses (draining replicas report their state)."""
        conn = http.client.HTTPConnection(
            rep.host, rep.port, timeout=2.0
        )
        try:
            conn.request("GET", "/healthz")
            resp = conn.getresponse()
            payload = json.loads(resp.read() or b"{}")
            return bool(payload.get("ok")), payload
        finally:
            conn.close()

    def _health_loop(self) -> None:
        if self.health_interval_s <= 0:
            # Manual mode (deterministic drills/tests): health moves
            # only through check_health_now() and forward failures.
            return
        while not self._closed.is_set():
            with self._lock:
                reps = list(self._replicas)
            for rep in reps:
                try:
                    ok, payload = self._probe(rep)
                except (OSError, ValueError, http.client.HTTPException):
                    ok, payload = False, {}
                with self._lock:
                    was = rep.healthy
                    rep.healthy = ok
                    if payload:
                        rep.last_health = payload
                if was != ok:
                    self._log(
                        "router_replica_health",
                        replica=rep.index, healthy=ok,
                    )
            self._closed.wait(self.health_interval_s)

    def check_health_now(self) -> None:
        """Synchronous health sweep (tests / deterministic drills)."""
        with self._lock:
            reps = list(self._replicas)
        for rep in reps:
            try:
                ok, payload = self._probe(rep)
            except (OSError, ValueError, http.client.HTTPException):
                ok, payload = False, {}
            with self._lock:
                rep.healthy = ok
                if payload:
                    rep.last_health = payload

    # -- routing -------------------------------------------------------------

    def _affinity_key(self, payload: Dict[str, Any]) -> Optional[bytes]:
        """Session key: the prompt's leading tokens/characters (chat
        dialogs key on the first message — the system prompt — which is
        exactly the shared radix prefix)."""
        try:
            if isinstance(payload.get("prompt"), list):
                head = payload["prompt"][:_AFFINITY_PREFIX]
                return b"p:" + json.dumps(head).encode()
            if isinstance(payload.get("text"), str):
                return b"t:" + payload["text"][:_AFFINITY_PREFIX].encode()
            msgs = payload.get("messages")
            if isinstance(msgs, list) and msgs:
                first = msgs[0]
                if isinstance(first, dict):
                    return b"m:" + str(
                        first.get("content", "")
                    )[:_AFFINITY_PREFIX].encode()
        except (TypeError, ValueError, UnicodeEncodeError):
            return None
        return None

    def _pick_locked(
        self, key: Optional[bytes], exclude: frozenset
    ) -> Tuple[Optional[_Replica], str]:
        """Choose a replica (caller holds ``_lock``): sticky key first
        (affinity policy), else least-loaded among healthy replicas not
        in ``exclude`` (prior failed attempts for this request)."""
        candidates = [
            r for r in self._replicas
            if r.healthy and r.index not in exclude
        ]
        if not candidates:
            return None, "none"
        if self.policy == "affinity" and key is not None:
            idx = self._affinity.get(key)
            if idx is not None:
                for r in candidates:
                    if r.index == idx:
                        self._affinity.move_to_end(key)  # LRU refresh
                        return r, "affinity"
        chosen = min(
            candidates, key=lambda r: (r.inflight, r.routed_total)
        )
        if self.policy == "affinity" and key is not None:
            while len(self._affinity) >= self.affinity_max_sessions:
                self._affinity.popitem(last=False)  # evict coldest
            self._affinity[key] = chosen.index
        return chosen, "least-loaded"

    # -- proxying ------------------------------------------------------------

    def _handle_post(self, handler: BaseHTTPRequestHandler) -> None:
        if handler.path not in ("/generate", "/chat"):
            self._reply_json(handler, 404, {"error": "not found"})
            return
        try:
            n = int(handler.headers.get("Content-Length") or 0)
        except ValueError:
            n = 0
        body = handler.rfile.read(n) if n > 0 else b"{}"
        try:
            payload = json.loads(body or b"{}")
            if not isinstance(payload, dict):
                payload = {}
        except ValueError:
            payload = {}
        key = self._affinity_key(payload)
        fwd_headers = {
            "Content-Type": "application/json",
            "Content-Length": str(len(body)),
        }
        for h in ("X-Request-Id",):
            if handler.headers.get(h):
                fwd_headers[h] = handler.headers[h]

        tried: set = set()
        first_attempt = True
        client_rid = handler.headers.get("X-Request-Id") or None
        while True:
            t_pick = self._now_ms()
            with self._lock:
                rep, how = self._pick_locked(key, frozenset(tried))
                if rep is not None:
                    rep.inflight += 1
                    rep.routed_total += 1
                    if not first_attempt:
                        how = "reroute"
                    self.routed_by_policy[how] = (
                        self.routed_by_policy.get(how, 0) + 1
                    )
            if rep is None:
                self._reply_json(
                    handler, 503,
                    {"error": "no healthy replica"},
                    headers={"Retry-After": "5"},
                )
                return
            tried.add(rep.index)
            fwd_headers["X-Routed-By"] = (
                f"replica-{rep.index}/{how}"
            )
            # Route-decision span: closes immediately (the pick is a
            # lock-held min()); the forward span that follows carries
            # the relay wall time, so decision and transfer read as
            # two causally ordered slices on the router track.
            self._span(
                "route", t_pick, replica=rep.index, policy=how,
                path=handler.path, request_id=client_rid,
            )
            t_fwd = self._now_ms()
            try:
                if self.fault_injector is not None:
                    # Fires BEFORE any byte reaches the replica, so a
                    # drill's failure is always at the reroutable stage.
                    self.fault_injector.fire("router_replica")
                rid_seen = self._relay(
                    handler, rep, handler.path, body, fwd_headers
                )
                self._span(
                    "forward", t_fwd, replica=rep.index,
                    path=handler.path,
                    request_id=rid_seen or client_rid,
                )
                return
            except _ClientDisconnect:
                # The CLIENT vanished mid-relay — the replica is fine
                # (it reaps the disconnect itself); nothing to reroute
                # and no health mark.
                self._span(
                    "forward", t_fwd, replica=rep.index,
                    path=handler.path, request_id=client_rid,
                    client_disconnect=True,
                )
                return
            except TimeoutError as e:
                # Proxy READ timeout from a slow-but-alive replica
                # (overload: streams defer headers until the first
                # token).  The replica has ADMITTED the request — a
                # re-submit would double the load exactly when
                # capacity is scarce, and an unhealthy mark would
                # serially quarantine loaded replicas (a retry-storm
                # amplifier).  504 the client; health stays with the
                # /healthz poller.
                self._log(
                    "router_replica_timeout", str(e), replica=rep.index,
                )
                self._span(
                    "forward", t_fwd, replica=rep.index,
                    path=handler.path, request_id=client_rid,
                    timeout=True,
                )
                if not getattr(e, "_relayed", False):
                    self._reply_json(
                        handler, 504,
                        {"error": (
                            f"replica {rep.index} did not respond "
                            f"within {self.proxy_timeout_s:.0f}s"
                        )},
                        headers={"Retry-After": "5"},
                    )
                return
            except (OSError, InjectedFault,
                    http.client.HTTPException) as e:
                relayed = getattr(e, "_relayed", False)
                with self._lock:
                    rep.healthy = False
                    rep.failures_total += 1
                    self.replica_failures_total += 1
                self._log(
                    "router_replica_failed", str(e),
                    replica=rep.index, rerouting=not relayed,
                )
                self._span(
                    "reroute", t_fwd, replica=rep.index,
                    path=handler.path, request_id=client_rid,
                    error=str(e), relayed=relayed,
                )
                if relayed:
                    # Bytes already reached the client: the router
                    # must NOT replay (a duplicate stream would
                    # double-deliver tokens); the client sees the
                    # truncated stream and retries with its own
                    # request id.
                    try:
                        handler.wfile.flush()
                    except OSError:
                        pass
                    return
                with self._lock:
                    self.reroutes_total += 1
                first_attempt = False
                continue  # re-route losslessly
            finally:
                with self._lock:
                    rep.inflight -= 1

    def _relay(
        self, handler: BaseHTTPRequestHandler, rep: _Replica,
        path: str, body: bytes, headers: Dict[str, str],
    ) -> Optional[str]:
        """Forward one request and relay the reply (buffered when the
        replica sent Content-Length, line-by-line for close-delimited
        NDJSON streams).  Returns the reply's ``X-Request-Id`` (the
        end-to-end id — replica-minted when the client sent none),
        recorded into the routing record so ``/debug/requests/<id>``
        resolves without fan-out.  Failure attribution: REPLICA-side
        errors (connect/request/read) raise as-is, tagged ``_relayed``
        once any byte reached the client; CLIENT-side write errors
        raise :class:`_ClientDisconnect` — the replica must not be
        marked unhealthy because an impatient client hung up."""
        conn = http.client.HTTPConnection(
            rep.host, rep.port, timeout=self.proxy_timeout_s
        )
        relayed = False

        def to_client(fn, *a):
            nonlocal relayed
            try:
                out = fn(*a)
                relayed = True
                return out
            except OSError:
                raise _ClientDisconnect(relayed) from None

        try:
            conn.request("POST", path, body=body, headers=headers)
            resp = conn.getresponse()
            rid_seen = resp.getheader("X-Request-Id")
            self._note_route(rid_seen, rep.index)
            out_headers = [
                (k, v) for k, v in resp.getheaders()
                if k.lower() not in _SKIP_HEADERS
            ]
            out_headers.append(("X-Replica-Id", str(rep.index)))

            def send_head(extra):
                handler.send_response(resp.status)
                for k, v in out_headers + extra:
                    handler.send_header(k, v)
                handler.end_headers()

            if resp.length is not None:
                data = resp.read()  # replica-side: raises plain OSError
                to_client(
                    send_head, [("Content-Length", str(len(data)))]
                )
                to_client(handler.wfile.write, data)
                return rid_seen
            # Close-delimited NDJSON stream: relay line-by-line so the
            # client sees tokens as the replica emits them.
            to_client(send_head, [("Connection", "close")])
            while True:
                line = resp.readline()
                if not line:
                    break
                to_client(handler.wfile.write, line)
                to_client(handler.wfile.flush)
            return rid_seen
        except (OSError, http.client.HTTPException) as e:
            e._relayed = relayed
            raise
        finally:
            conn.close()

    # -- GET surface ---------------------------------------------------------

    def _handle_get(self, handler: BaseHTTPRequestHandler) -> None:
        parts = urlsplit(handler.path)
        route, query = parts.path, parse_qs(parts.query)
        if route == "/healthz":
            h = self.health()
            self._reply_json(handler, 200 if h["ok"] else 503, h)
        elif route == "/metrics":
            body = self.metrics_text().encode()
            handler.send_response(200)
            handler.send_header(
                "Content-Type", "text/plain; version=0.0.4"
            )
            handler.send_header("Content-Length", str(len(body)))
            handler.end_headers()
            handler.wfile.write(body)
        elif route == "/debug/trace":
            window_ms = None
            if "window_s" in query:
                try:
                    window_ms = float(query["window_s"][0]) * 1000.0
                except ValueError:
                    self._reply_json(
                        handler, 400, {"error": "bad window_s"}
                    )
                    return
            self._reply_json(
                handler, 200, self.fleet_trace_json(window_ms)
            )
        elif route == "/debug/requests":
            self._reply_json(
                handler, *self._fleet_requests_index(handler.path)
            )
        elif route.startswith("/debug/requests/"):
            rid = unquote(route[len("/debug/requests/"):])
            self._reply_json(
                handler, *self._fleet_request_lookup(rid, handler.path)
            )
        elif route.startswith("/debug/"):
            # Everything else (dispatch rings, profiler summaries...)
            # lives on whichever replica produced it: try each healthy
            # replica until one answers non-404.
            code, data = self._first_non_404(handler.path)
            self._reply_json(handler, code, data)
        else:
            self._reply_json(handler, 404, {"error": "not found"})

    def _get_replica_json(
        self, rep: _Replica, path: str, timeout: float = 2.0,
    ) -> Optional[Tuple[int, Dict[str, Any]]]:
        """One replica GET; None on connection/parse failure.  The
        default timeout matches the health probe's: the fleet /debug
        endpoints fetch replicas SEQUENTIALLY, so each hung-but-
        marked-healthy replica costs at most one probe interval, not
        a proxy-class stall per replica."""
        try:
            conn = http.client.HTTPConnection(
                rep.host, rep.port, timeout=timeout
            )
            try:
                conn.request("GET", path)
                resp = conn.getresponse()
                data = json.loads(resp.read() or b"{}")
            finally:
                conn.close()
        except (OSError, ValueError, http.client.HTTPException):
            return None
        if not isinstance(data, dict):
            return None
        return resp.status, data

    def _first_non_404(self, path: str) -> Tuple[int, Dict[str, Any]]:
        with self._lock:
            reps = [r for r in self._replicas if r.healthy]
        for rep in reps:
            got = self._get_replica_json(rep, path)
            if got is None:
                continue
            status, data = got
            if status != 404:
                data["replica"] = rep.index
                return status, data
        return 404, {"error": "not found on any replica"}

    def _fleet_requests_index(
        self, path: str,
    ) -> Tuple[int, Dict[str, Any]]:
        """GET /debug/requests aggregated across ALL healthy replicas
        (first-to-answer would show one replica's slice of the fleet
        and 404-hide the rest); every entry carries its replica id."""
        with self._lock:
            reps = [r for r in self._replicas if r.healthy]
        merged: List[Dict[str, Any]] = []
        replicas_answered: List[int] = []
        for rep in reps:
            got = self._get_replica_json(rep, path)
            if got is None or got[0] != 200:
                continue
            replicas_answered.append(rep.index)
            for entry in got[1].get("requests", []):
                if isinstance(entry, dict):
                    entry["replica"] = rep.index
                    merged.append(entry)
        return 200, {
            "requests": merged, "replicas": replicas_answered,
        }

    def _fleet_request_lookup(
        self, request_id: str, path: str,
    ) -> Tuple[int, Dict[str, Any]]:
        """GET /debug/requests/<id>: the ROUTING RECORD names the
        replica that served the id, so that replica answers first;
        healthy-replica fan-out only covers ids the bounded record has
        already evicted (or pre-router traffic)."""
        with self._lock:
            routed = self._routes.get(request_id)
            reps = list(self._replicas)
        ordered = (
            [r for r in reps if r.index == routed]
            + [r for r in reps if r.index != routed and r.healthy]
        )
        for rep in ordered:
            got = self._get_replica_json(rep, path)
            if got is None:
                continue
            status, data = got
            if status != 404:
                data["replica"] = rep.index
                data["routed_replica"] = routed
                return status, data
        return 404, {
            "error": f"request id {request_id!r} unknown fleet-wide",
            "routed_replica": routed,
        }

    @staticmethod
    def _reply_json(
        handler: BaseHTTPRequestHandler, code: int,
        obj: Dict[str, Any], headers: Optional[Dict[str, str]] = None,
    ) -> None:
        body = json.dumps(obj).encode()
        handler.send_response(code)
        handler.send_header("Content-Type", "application/json")
        handler.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            handler.send_header(k, v)
        handler.end_headers()
        handler.wfile.write(body)

    # -- observability -------------------------------------------------------

    def fleet_trace_json(
        self, window_ms: Optional[float] = None,
    ) -> Dict[str, Any]:
        """The fleet-merged Perfetto document (module docstring): the
        router's span track plus every healthy replica's
        ``/debug/trace`` export, replica timestamps shifted into the
        router's frame through the ``t0_unix_s`` anchors and re-tagged
        to per-replica pids.  Snapshot under the lock, fetch and build
        outside it — replica HTTP round-trips must never hold the
        routing lock."""
        with self._lock:
            reps = [
                (r.index, r.host, r.port)
                for r in self._replicas if r.healthy
            ]
            spans = list(self._trace)
            now = self._now_ms()
        horizon = None if window_ms is None else now - window_ms
        ev: List[Dict[str, Any]] = [
            {"ph": "M", "pid": 0, "name": "process_name",
             "args": {"name": "router"}},
            {"ph": "M", "pid": 0, "tid": 1, "name": "thread_name",
             "args": {"name": "routing"}},
        ]
        for s in spans:
            if horizon is not None and s["t0_ms"] + s["dur_ms"] < horizon:
                continue
            ev.append({
                "name": s["name"], "cat": "router", "ph": "X",
                "pid": 0, "tid": 1,
                "ts": round(s["t0_ms"] * 1000.0, 1),
                "dur": max(1, round(s["dur_ms"] * 1000.0)),
                "args": dict(s["args"]),
            })
        suffix = (
            "" if window_ms is None
            else f"?window_s={window_ms / 1000.0:g}"
        )
        merged_replicas: List[int] = []
        for index, host, port in reps:
            got = self._get_replica_json(
                _Replica(index=index, host=host, port=port),
                "/debug/trace" + suffix,
            )
            if got is None or got[0] != 200:
                continue
            doc = got[1]
            merged_replicas.append(index)
            pid = 1 + index
            # Clock-offset normalization: replica ts are relative to
            # ITS Observability t0; the wall anchors captured at both
            # t0 instants give the shift into the router's frame.
            off_us = (
                float(doc.get("t0_unix_s", self.t0_unix))
                - self.t0_unix
            ) * 1e6
            ev.append({
                "ph": "M", "pid": pid, "name": "process_name",
                "args": {"name": f"replica-{index}"},
            })
            for e in doc.get("traceEvents", []):
                if not isinstance(e, dict):
                    continue
                e = dict(e)
                e["pid"] = pid
                if "ts" in e:
                    e["ts"] = round(e["ts"] + off_us, 1)
                ev.append(e)
        return {
            "traceEvents": ev, "displayTimeUnit": "ms",
            "t0_unix_s": round(self.t0_unix, 6),
            "replicas": merged_replicas,
        }

    def health(self) -> Dict[str, Any]:
        """Aggregate /healthz: ok while ANY replica is routable, with
        the per-replica snapshots under ``replicas``."""
        with self._lock:
            snaps = [r.snapshot() for r in self._replicas]
            affinity_sessions = len(self._affinity)
            handoffs = self.kv_handoffs_total
        return {
            "ok": any(s["healthy"] for s in snaps),
            "policy": self.policy,
            "replicas": snaps,
            "affinity_sessions": affinity_sessions,
            "kv_handoffs_total": handoffs,
        }

    def metrics_text(self) -> str:
        """Router Prometheus exposition: aggregate counters plus
        per-replica labeled gauges (occupancy / inflight / routed /
        health / mesh shape)."""
        with self._lock:
            snaps = [r.snapshot() for r in self._replicas]
            by_policy = dict(self.routed_by_policy)
            reroutes = self.reroutes_total
            failures = self.replica_failures_total
            handoffs = self.kv_handoffs_total
            affinity_sessions = len(self._affinity)
        lines: List[str] = []

        def fam(name: str, kind: str, help_text: str) -> None:
            lines.append(f"# HELP llm_router_{name} {help_text}")
            lines.append(f"# TYPE llm_router_{name} {kind}")

        fam("replicas", "gauge", "Replicas behind this router")
        lines.append(f"llm_router_replicas {len(snaps)}")
        fam("replicas_healthy", "gauge", "Replicas currently routable")
        lines.append(
            "llm_router_replicas_healthy "
            f"{sum(s['healthy'] for s in snaps)}"
        )
        fam("routed_requests_total", "counter",
            "Requests routed, by decision policy")
        for pol, n in sorted(by_policy.items()):
            lines.append(
                f'llm_router_routed_requests_total{{policy="{pol}"}} {n}'
            )
        fam("reroutes_total", "counter",
            "Requests re-routed off a failed replica")
        lines.append(f"llm_router_reroutes_total {reroutes}")
        fam("replica_failures_total", "counter",
            "Forward-time replica failures observed")
        lines.append(f"llm_router_replica_failures_total {failures}")
        fam("kv_handoffs_total", "counter",
            "Cross-replica prefix-KV handoffs brokered")
        lines.append(f"llm_router_kv_handoffs_total {handoffs}")
        fam("affinity_sessions", "gauge",
            "Sticky sessions currently pinned")
        lines.append(f"llm_router_affinity_sessions {affinity_sessions}")
        fam("replica_healthy", "gauge", "Replica routable (per replica)")
        fam("replica_inflight", "gauge",
            "Router-tracked in-flight requests (per replica)")
        fam("replica_routed_total", "counter",
            "Requests routed to this replica")
        fam("replica_active_slots", "gauge",
            "Replica batcher slots holding a live request (last "
            "health scrape)")
        fam("replica_mesh_devices", "gauge",
            "Devices in the replica's serving mesh (last health "
            "scrape)")
        for s in snaps:
            lab = f'replica="{s["index"]}"'
            lines.append(
                f"llm_router_replica_healthy{{{lab}}} "
                f"{int(bool(s['healthy']))}"
            )
            lines.append(
                f"llm_router_replica_inflight{{{lab}}} {s['inflight']}"
            )
            lines.append(
                f"llm_router_replica_routed_total{{{lab}}} "
                f"{s['routed_total']}"
            )
            rep_info = s.get("replica") or {}
            lines.append(
                f"llm_router_replica_active_slots{{{lab}}} "
                f"{rep_info.get('active_slots', 0) or 0}"
            )
            mesh = rep_info.get("serve_mesh") or {}
            lines.append(
                f"llm_router_replica_mesh_devices{{{lab}}} "
                f"{mesh.get('devices', 1) or 1}"
            )
        return "\n".join(lines) + "\n"

    def note_handoff(
        self, blocks: int, request_id: Optional[str] = None,
        src: Optional[int] = None, dst: Optional[int] = None,
    ) -> None:
        """Count a brokered prefix handoff and drop a ``handoff`` span
        on the router track carrying the external request id — the
        link that ties the source replica's ``prefix_export`` and the
        destination's ``prefix_import`` instants into one timeline in
        the merged trace.  When the destination is known the routing
        record re-pins the id there (route-follow: the session's next
        /debug lookup lands where its KV now lives)."""
        if blocks <= 0:
            return
        t = self._now_ms()
        with self._lock:
            self.kv_handoffs_total += 1
            self._trace.append({
                "name": "handoff", "t0_ms": round(t, 3),
                "dur_ms": 0.0,
                "args": {
                    k: v for k, v in (
                        ("request_id", request_id), ("src", src),
                        ("dst", dst), ("blocks", blocks),
                    ) if v is not None
                },
            })
        if dst is not None:
            self._note_route(request_id, dst)


def handoff_prefix(
    src_batcher, dst_batcher, tokens: Sequence[int],
    router: Optional[ReplicaRouter] = None,
    request_id: Optional[str] = None,
    src: Optional[int] = None,
    dst: Optional[int] = None,
) -> int:
    """Prefill/decode disaggregation handoff: move ``tokens``' cached
    prefix blocks from ``src_batcher`` (which prefilled them) into
    ``dst_batcher``'s pool + radix index, so the session's next
    admission on the destination replica is a plain prefix hit —
    ``export_prefix``'s D2H slab fetch feeding ``import_prefix``'s
    stage/adopt/publish, the exact path the host-DRAM tier restores
    through.  Both batcher calls MUST run on their owning serving-loop
    threads (the batchers are thread-confined).  ``request_id`` (the
    session's external id) threads through both batchers' trace
    annotations and the router's handoff span, so the fleet-merged
    trace shows the move as ONE linked timeline; ``src``/``dst`` are
    the replica indices when the caller knows them.  Returns the
    number of blocks landed on the destination."""
    keys, slabs = src_batcher.export_prefix(tokens, request_id=request_id)
    if not slabs:
        return 0
    n = dst_batcher.import_prefix(keys, slabs, request_id=request_id)
    if router is not None:
        router.note_handoff(n, request_id=request_id, src=src, dst=dst)
    return n
