"""Data-parallel replica routing: N serving replicas behind one door.

The scale-out serving subsystem's outermost layer (ROADMAP item 2; the
in-replica mesh sharding lives in ``parallel/serve_mesh.py``): a
:class:`ReplicaRouter` fronts N **independent** serving replicas — each
an ``LLMServer`` with its own ``ContinuousBatcher``, KV pool, radix
prefix index and (optionally) its own mesh slice — and routes each POST
to one of them:

  * **least-loaded** (default): the healthy replica with the fewest
    router-tracked in-flight requests (ties rotate by routed count), so
    a long-generation pileup on one replica never queues new arrivals
    behind it.
  * **affinity**: sticky sessions by prompt-prefix key — a revisited
    session routes to the replica already holding its radix chain, so
    multi-turn chats keep their prefix-cache hits (and host-tier slabs)
    local instead of re-prefilling cold on a random replica.  New
    sessions fall back to least-loaded; a dead replica's sessions
    re-pin wherever their next turn lands.

**Health / quarantine.**  A poller thread scrapes each replica's
``/healthz`` (the server's own ok/draining/degraded verdict — a replica
in drain or with a dead loop stops receiving new work while its
in-flight requests finish); a forward-time connection failure (or an
injected ``router_replica`` fault) marks the replica unhealthy
immediately.  Requests that have not yet streamed a byte RE-ROUTE to a
surviving replica losslessly; requests in flight on a genuinely crashed
replica are that replica's own crash-recovery problem (rebuild + replay
— the PR-1 machinery), not the router's: the router never duplicates a
request it may have half-delivered.

**Prefill/decode disaggregation (skeleton).**  :func:`handoff_prefix`
moves a session's cached prefix blocks between two batchers through the
existing host-tier primitives (``export_prefix`` D2H slab fetch on the
prefill side, ``import_prefix`` stage+adopt+publish on the decode
side), so an admission can prefill on one replica and decode on
another that receives its KV as a plain prefix hit.  The router counts
handoffs; scheduling WHEN to disaggregate (prefill-heavy vs
decode-heavy replica pools) is the open half — both batcher calls must
run on their owning serving-loop threads, so a live-traffic router
drives them through the replicas' control paths, not directly.

HTTP surface (the router speaks the same protocol as a single server,
so clients need no changes):

    POST /generate, /chat    routed + proxied (streaming NDJSON relays
                             line-by-line); the response carries
                             X-Replica-Id, and the replica's request
                             timeline records the routing decision
                             (X-Routed-By -> /debug/requests/<id>)
    GET  /healthz            aggregate: ok = any replica routable, plus
                             a ``replicas`` section (per-replica
                             health/occupancy/mesh snapshot)
    GET  /metrics            router gauges + per-replica labeled series
    GET  /debug/kv/fleet     FLEET CACHE VIEW (schema below)
    GET  /debug/trace        FLEET-MERGED Perfetto trace (schema below)
    GET  /debug/requests     index aggregated across ALL healthy
                             replicas, each entry tagged ``replica``
    GET  /debug/requests/<id>  resolved through the ROUTING RECORD
                             first (the bounded request-id -> replica
                             map the relay fills from each reply's
                             X-Request-Id), then healthy-replica
                             fan-out — never first-to-answer guessing
    GET  /debug/*            (everything else) tried against each
                             healthy replica until one answers non-404

Fleet-merged tracing (``GET /debug/trace[?window_s=S]``): ONE
Chrome/Perfetto ``trace_event`` document containing

  * the router's own span track (pid 0, process_name ``router``):
    ``route`` (decision; args replica/policy/request_id), ``forward``
    (relay wall time; timeout/client-disconnect flagged), ``reroute``
    (a failed replica's lossless re-route) and ``handoff``
    (cross-replica prefix-KV moves, args request_id/blocks) spans,
    recorded in a bounded ring under ``_lock``;
  * every healthy replica's own ``/debug/trace`` export re-tagged to
    pid ``1+index`` (process_name ``replica-<index>``) with its
    timestamps shifted into the router's frame via the ``t0_unix_s``
    wall-clock anchor each Observability ring publishes (clock-offset
    normalization — replica monotonic clocks share no epoch);
  * handoff linkage: the router's ``handoff`` span and both replicas'
    ``prefix_export`` / ``prefix_import`` instants carry the same
    external request id, so a prefill-on-A / decode-on-B session
    reads as one timeline across three tracks.

**Fleet cache view** (``GET /debug/kv/fleet[?depth=D]``, r13): the
router-side aggregation of every healthy replica's chain digest
(``GET /debug/kv``, scraped on demand with probe-class timeouts —
never from the poller; the poller's ``/healthz`` scrape already
carries each replica's O(1) digest summary under ``kv.digest``)::

    {"fleet": {
       "prefix_hit_ratio": float,        # sum(hit tokens)/sum(prompt)
       "prefix_hit_tokens_total": int, "prompt_tokens_total": int,
       "duplicate_chains": int,          # chain keys HBM-resident on
                                         # >= 2 replicas
       "duplicate_kv_blocks": int,       # copies beyond the first
       "duplicate_kv_bytes": int,        # ... priced per replica's
                                         # block_bytes — the HBM a
                                         # cache-aware scheduler
                                         # (ROADMAP item 2) reclaims
       "replicas_scraped": [int, ...],
       "truncated_replicas": [int, ...], # digests cut at the node cap
                                         # (duplicates = LOWER bound)
       "scrape_ms": float},
     "replicas": [{"replica": int, "summary": {<replica /debug/kv
                   summary>}, "hit_ratio": float,
                   "hbm_bytes": int}, ...]}

The computed aggregate is cached for ``/metrics``:
``llm_fleet_duplicate_kv_blocks`` / ``llm_fleet_duplicate_kv_bytes`` /
``llm_fleet_prefix_hit_ratio`` / ``llm_fleet_kv_age_s`` (samples
appear after the first fleet-view computation).  Per-replica labeled
cache gauges ride every scrape of the health poller:
``llm_router_replica_kv_{nodes,hbm_blocks,host_blocks,idle_blocks,
digest_version,hit_ratio}`` — qualified by
``llm_replica_health_age_s`` (seconds since that replica's labeled
values were last refreshed; -1 = never scraped; an unroutable
replica's gauges persist STALE, so dashboards gate on the age).
Digest freshness also feeds the affinity policy: an affinity hit onto
a replica whose digest ``loss_version`` changed since the session
pinned (evictions/demotions — or a rebuild, which resets versions)
still routes there, but as a counted, logged stale event
(``llm_router_affinity_stale_routes_total``; the pin refreshes to the
observed version so one loss event counts once) instead of a silent
cache miss.

Thread discipline: handler threads (forward) and the health poller
share the replica table, counters, routing record, trace ring, and
the cached fleet cache view — every access goes under ``_lock``
(registered in analysis/lockcheck.py).  The router holds no jax state
at all; it is pure host-side HTTP."""

from __future__ import annotations

import http.client
import json
import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Sequence, Tuple
from urllib.parse import parse_qs, unquote, urlsplit

from .faults import FaultInjector, InjectedFault
from .obs import StructuredLogger

POLICIES = ("least-loaded", "affinity")


class _ClientDisconnect(Exception):
    """The CLIENT's socket died while relaying — the replica is fine.
    Distinct from replica-side OSErrors so a disconnecting client never
    marks a healthy replica unhealthy; ``relayed`` records whether any
    bytes reached the client before the drop."""

    def __init__(self, relayed: bool):
        super().__init__("client disconnected")
        self.relayed = relayed

# Hop-by-hop / recomputed headers never relayed from a replica reply.
_SKIP_HEADERS = frozenset({
    "connection", "transfer-encoding", "content-length", "server",
    "date",
})

# Prompt-prefix length (tokens or characters) the affinity key hashes:
# long enough to separate sessions with a shared system prompt short
# of one block, short enough that appending turns to a chat keeps the
# key (and therefore the replica holding the chain) stable.
_AFFINITY_PREFIX = 64


@dataclass
class _Replica:
    """Router-side view of one serving replica."""

    index: int
    host: str
    port: int
    server: Any = None            # in-process LLMServer (caller-owned)
    healthy: bool = True
    inflight: int = 0
    routed_total: int = 0
    failures_total: int = 0
    last_health: Dict[str, Any] = field(default_factory=dict)
    # Monotonic instant of the last SUCCESSFUL health scrape (0.0 =
    # never scraped).  A replica that goes unroutable keeps its last
    # scraped values in ``last_health`` — the per-replica labeled
    # /metrics gauges would silently serve stale numbers, so the
    # exposition emits ``llm_replica_health_age_s`` alongside them and
    # dashboards gate on it.
    last_health_t: float = 0.0

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def kv_digest(self) -> Dict[str, Any]:
        """The chain-digest summary of the last health scrape (empty
        dict before the first scrape / from pre-digest replicas)."""
        return (self.last_health.get("kv") or {}).get("digest") or {}

    def snapshot(self) -> Dict[str, Any]:
        h = self.last_health
        return {
            "index": self.index,
            "address": self.address,
            "healthy": self.healthy,
            "inflight": self.inflight,
            "routed_total": self.routed_total,
            "failures_total": self.failures_total,
            "draining": h.get("draining"),
            "degraded": h.get("degraded"),
            "overload_state": (h.get("overload") or {}).get("state"),
            "replica": h.get("replica"),
            "health_age_s": (
                round(time.monotonic() - self.last_health_t, 3)
                if self.last_health_t > 0 else None
            ),
            "kv": h.get("kv"),
        }


def _parse_address(addr: str) -> Tuple[str, int]:
    """Accepts ``host:port`` or ``http://host:port`` (LLMServer's own
    ``.address`` spelling)."""
    if addr.startswith("http://"):
        addr = addr[len("http://"):]
    addr = addr.rstrip("/")
    host, _, port = addr.rpartition(":")
    return host or "127.0.0.1", int(port)


class ReplicaRouter:
    """HTTP front-end routing requests across serving replicas
    (module docstring).  ``replicas`` mixes in-process ``LLMServer``
    instances (must already be started; their lifecycle stays with the
    caller) and ``"host:port"`` strings for out-of-process ones."""

    def __init__(
        self,
        replicas: Sequence[Any],
        host: str = "127.0.0.1",
        port: int = 0,
        policy: str = "least-loaded",
        health_interval_s: float = 0.5,  # <= 0: manual (tests) —
        #                                  check_health_now() only
        proxy_timeout_s: float = 300.0,
        affinity_max_sessions: int = 4096,
        fault_injector: Optional[FaultInjector] = None,
        logger: Optional[StructuredLogger] = None,
    ):
        if policy not in POLICIES:
            raise ValueError(
                f"unknown route policy {policy!r}; have {POLICIES}"
            )
        if not replicas:
            raise ValueError("ReplicaRouter needs at least one replica")
        self.policy = policy
        self.fault_injector = fault_injector
        self.logger = logger
        self.health_interval_s = float(health_interval_s)
        self.proxy_timeout_s = float(proxy_timeout_s)
        self.affinity_max_sessions = int(affinity_max_sessions)
        self._lock = threading.Lock()
        self._replicas: List[_Replica] = []
        for i, rep in enumerate(replicas):
            if isinstance(rep, str):
                h, p = _parse_address(rep)
                self._replicas.append(_Replica(index=i, host=h, port=p))
            else:  # in-process LLMServer
                h, p = _parse_address(rep.address)
                self._replicas.append(
                    _Replica(index=i, host=h, port=p, server=rep)
                )
        # Sticky-session map: affinity key -> [replica index, the
        # replica's chain-digest loss_version at pin time] (bounded
        # LRU — hits refresh recency, so long-lived active sessions
        # are not the eviction victims; a dead replica's entries
        # re-pin on next use).  The loss_version is the digest-
        # freshness check: a later hit whose replica has since evicted
        # or demoted chains (loss_version changed) is routed anyway —
        # affinity is a locality HINT, not a correctness contract —
        # but as a COUNTED, logged stale-route event instead of a
        # silent cache miss (affinity_stale_routes_total; the entry
        # re-pins at the observed version so one loss event counts
        # once, not on every subsequent turn).
        self._affinity: "OrderedDict[bytes, List[Any]]" = OrderedDict()
        self.routed_by_policy: Dict[str, int] = {
            "least-loaded": 0, "affinity": 0, "reroute": 0,
        }
        self.reroutes_total = 0
        self.replica_failures_total = 0
        self.kv_handoffs_total = 0
        self.affinity_stale_routes_total = 0
        # Last computed fleet cache view (fleet_kv_json fills it; the
        # /metrics fleet gauges read it) — None until the first
        # GET /debug/kv/fleet.
        self._fleet_kv: Optional[Dict[str, Any]] = None
        # Router-local trace ring (fleet-merged /debug/trace): bounded
        # span dicts, appended under _lock by handler threads.  The
        # monotonic/wall anchors are captured at the same instant —
        # the same clock-offset contract obs.Observability publishes.
        self._t0 = time.monotonic()
        self.t0_unix = time.time()
        self._trace: "deque[Dict[str, Any]]" = deque(maxlen=1024)
        # Routing record: external request id -> replica index
        # (bounded LRU, filled by the relay from each reply's
        # X-Request-Id header) — /debug/requests/<id> consults it
        # before any fan-out.
        self._routes: "OrderedDict[str, int]" = OrderedDict()
        self.route_record_max = 4096
        self._closed = threading.Event()
        router = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet test output
                pass

            def do_GET(self):
                router._handle_get(self)

            def do_POST(self):
                router._handle_post(self)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="router-http",
        )
        self._health_thread = threading.Thread(
            target=self._health_loop, daemon=True, name="router-health",
        )

    # -- lifecycle -----------------------------------------------------------

    @property
    def address(self) -> str:
        h, p = self._httpd.server_address[:2]
        return f"http://{h}:{p}"

    def start(self) -> "ReplicaRouter":
        self._http_thread.start()
        self._health_thread.start()
        return self

    def stop(self) -> None:
        """Stop the router (replica lifecycles stay with the caller)."""
        self._closed.set()
        self._httpd.shutdown()
        self._httpd.server_close()
        self._health_thread.join(timeout=5)

    def __enter__(self) -> "ReplicaRouter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _log(self, event: str, message: str = "", **fields) -> None:
        if self.logger is not None:
            self.logger.log(event, message, **fields)

    # -- router-local tracing / routing record -------------------------------

    def _now_ms(self) -> float:
        return (time.monotonic() - self._t0) * 1000.0

    def _span(self, name: str, t0_ms: float, **args) -> None:
        """Close a router span started at ``t0_ms`` (None-valued args
        drop, so absent request ids don't litter the trace)."""
        dur = max(0.0, self._now_ms() - t0_ms)
        rec = {
            "name": name, "t0_ms": round(t0_ms, 3),
            "dur_ms": round(dur, 3),
            "args": {k: v for k, v in args.items() if v is not None},
        }
        with self._lock:
            self._trace.append(rec)

    def _note_route(self, request_id: Optional[str],
                    index: int) -> None:
        """Record which replica served ``request_id`` (bounded LRU) —
        the /debug/requests/<id> resolution path."""
        if not request_id:
            return
        with self._lock:
            self._routes[request_id] = index
            self._routes.move_to_end(request_id)
            while len(self._routes) > self.route_record_max:
                self._routes.popitem(last=False)

    # -- health --------------------------------------------------------------

    def _probe(self, rep: _Replica) -> Tuple[bool, Dict[str, Any]]:
        """One /healthz scrape; (routable, payload).  A 503 body still
        parses (draining replicas report their state)."""
        conn = http.client.HTTPConnection(
            rep.host, rep.port, timeout=2.0
        )
        try:
            conn.request("GET", "/healthz")
            resp = conn.getresponse()
            payload = json.loads(resp.read() or b"{}")
            return bool(payload.get("ok")), payload
        finally:
            conn.close()

    def _health_loop(self) -> None:
        if self.health_interval_s <= 0:
            # Manual mode (deterministic drills/tests): health moves
            # only through check_health_now() and forward failures.
            return
        while not self._closed.is_set():
            with self._lock:
                reps = list(self._replicas)
            for rep in reps:
                try:
                    ok, payload = self._probe(rep)
                except (OSError, ValueError, http.client.HTTPException):
                    ok, payload = False, {}
                with self._lock:
                    was = rep.healthy
                    rep.healthy = ok
                    if payload:
                        rep.last_health = payload
                        rep.last_health_t = time.monotonic()
                if was != ok:
                    self._log(
                        "router_replica_health",
                        replica=rep.index, healthy=ok,
                    )
            self._closed.wait(self.health_interval_s)

    def check_health_now(self) -> None:
        """Synchronous health sweep (tests / deterministic drills)."""
        with self._lock:
            reps = list(self._replicas)
        for rep in reps:
            try:
                ok, payload = self._probe(rep)
            except (OSError, ValueError, http.client.HTTPException):
                ok, payload = False, {}
            with self._lock:
                rep.healthy = ok
                if payload:
                    rep.last_health = payload
                    rep.last_health_t = time.monotonic()

    # -- routing -------------------------------------------------------------

    def _affinity_key(self, payload: Dict[str, Any]) -> Optional[bytes]:
        """Session key: the prompt's leading tokens/characters (chat
        dialogs key on the first message — the system prompt — which is
        exactly the shared radix prefix)."""
        try:
            if isinstance(payload.get("prompt"), list):
                head = payload["prompt"][:_AFFINITY_PREFIX]
                return b"p:" + json.dumps(head).encode()
            if isinstance(payload.get("text"), str):
                return b"t:" + payload["text"][:_AFFINITY_PREFIX].encode()
            msgs = payload.get("messages")
            if isinstance(msgs, list) and msgs:
                first = msgs[0]
                if isinstance(first, dict):
                    return b"m:" + str(
                        first.get("content", "")
                    )[:_AFFINITY_PREFIX].encode()
        except (TypeError, ValueError, UnicodeEncodeError):
            return None
        return None

    def _pick_locked(
        self, key: Optional[bytes], exclude: frozenset
    ) -> Tuple[Optional[_Replica], str, bool]:
        """Choose a replica (caller holds ``_lock``): sticky key first
        (affinity policy), else least-loaded among healthy replicas not
        in ``exclude`` (prior failed attempts for this request).

        Returns ``(replica, how, stale)``.  ``stale`` is True for an
        affinity hit whose replica's chain-digest ``loss_version`` has
        changed since the session pinned — the pinned chain may have
        been evicted or demoted, so the route is a CACHE GAMBLE rather
        than a known hit.  Compared with ``!=`` (not ``>``): a
        crash-recovery rebuild resets the digest to version 0 and
        empties the cache — exactly a staleness event."""
        candidates = [
            r for r in self._replicas
            if r.healthy and r.index not in exclude
        ]
        if not candidates:
            return None, "none", False
        if self.policy == "affinity" and key is not None:
            ent = self._affinity.get(key)
            if ent is not None:
                for r in candidates:
                    if r.index == ent[0]:
                        self._affinity.move_to_end(key)  # LRU refresh
                        cur = r.kv_digest().get("loss_version")
                        stale = (
                            ent[1] is not None and cur is not None
                            and cur != ent[1]
                        )
                        if stale:
                            self.affinity_stale_routes_total += 1
                            # Re-pin at the observed version: one loss
                            # event counts once, not every turn.
                            ent[1] = cur
                        elif ent[1] is None and cur is not None:
                            # The session pinned before this replica's
                            # first digest scrape (None baseline) —
                            # BACKFILL at the first observed version,
                            # or the None would disable staleness
                            # detection for the session's whole life.
                            ent[1] = cur
                        return r, "affinity", stale
        chosen = min(
            candidates, key=lambda r: (r.inflight, r.routed_total)
        )
        if self.policy == "affinity" and key is not None:
            while len(self._affinity) >= self.affinity_max_sessions:
                self._affinity.popitem(last=False)  # evict coldest
            self._affinity[key] = [
                chosen.index, chosen.kv_digest().get("loss_version"),
            ]
        return chosen, "least-loaded", False

    # -- proxying ------------------------------------------------------------

    def _handle_post(self, handler: BaseHTTPRequestHandler) -> None:
        if handler.path not in ("/generate", "/chat"):
            self._reply_json(handler, 404, {"error": "not found"})
            return
        try:
            n = int(handler.headers.get("Content-Length") or 0)
        except ValueError:
            n = 0
        body = handler.rfile.read(n) if n > 0 else b"{}"
        try:
            payload = json.loads(body or b"{}")
            if not isinstance(payload, dict):
                payload = {}
        except ValueError:
            payload = {}
        key = self._affinity_key(payload)
        fwd_headers = {
            "Content-Type": "application/json",
            "Content-Length": str(len(body)),
        }
        for h in ("X-Request-Id",):
            if handler.headers.get(h):
                fwd_headers[h] = handler.headers[h]

        tried: set = set()
        first_attempt = True
        client_rid = handler.headers.get("X-Request-Id") or None
        while True:
            t_pick = self._now_ms()
            with self._lock:
                rep, how, stale = self._pick_locked(
                    key, frozenset(tried)
                )
                if rep is not None:
                    rep.inflight += 1
                    rep.routed_total += 1
                    if not first_attempt:
                        how = "reroute"
                    self.routed_by_policy[how] = (
                        self.routed_by_policy.get(how, 0) + 1
                    )
            if rep is None:
                self._reply_json(
                    handler, 503,
                    {"error": "no healthy replica"},
                    headers={"Retry-After": "5"},
                )
                return
            tried.add(rep.index)
            if stale:
                # Digest freshness said the pinned chain may be gone:
                # route anyway (locality hint, not a contract), but as
                # a counted, logged event — the cache-aware scheduler's
                # future miss signal, no longer silent.
                self._log(
                    "router_affinity_stale",
                    replica=rep.index, request_id=client_rid,
                )
            fwd_headers["X-Routed-By"] = (
                f"replica-{rep.index}/{how}"
            )
            # Route-decision span: closes immediately (the pick is a
            # lock-held min()); the forward span that follows carries
            # the relay wall time, so decision and transfer read as
            # two causally ordered slices on the router track.
            self._span(
                "route", t_pick, replica=rep.index, policy=how,
                path=handler.path, request_id=client_rid,
                stale_chain=stale or None,
            )
            t_fwd = self._now_ms()
            try:
                if self.fault_injector is not None:
                    # Fires BEFORE any byte reaches the replica, so a
                    # drill's failure is always at the reroutable stage.
                    self.fault_injector.fire("router_replica")
                rid_seen = self._relay(
                    handler, rep, handler.path, body, fwd_headers
                )
                self._span(
                    "forward", t_fwd, replica=rep.index,
                    path=handler.path,
                    request_id=rid_seen or client_rid,
                )
                return
            except _ClientDisconnect:
                # The CLIENT vanished mid-relay — the replica is fine
                # (it reaps the disconnect itself); nothing to reroute
                # and no health mark.
                self._span(
                    "forward", t_fwd, replica=rep.index,
                    path=handler.path, request_id=client_rid,
                    client_disconnect=True,
                )
                return
            except TimeoutError as e:
                # Proxy READ timeout from a slow-but-alive replica
                # (overload: streams defer headers until the first
                # token).  The replica has ADMITTED the request — a
                # re-submit would double the load exactly when
                # capacity is scarce, and an unhealthy mark would
                # serially quarantine loaded replicas (a retry-storm
                # amplifier).  504 the client; health stays with the
                # /healthz poller.
                self._log(
                    "router_replica_timeout", str(e), replica=rep.index,
                )
                self._span(
                    "forward", t_fwd, replica=rep.index,
                    path=handler.path, request_id=client_rid,
                    timeout=True,
                )
                if not getattr(e, "_relayed", False):
                    self._reply_json(
                        handler, 504,
                        {"error": (
                            f"replica {rep.index} did not respond "
                            f"within {self.proxy_timeout_s:.0f}s"
                        )},
                        headers={"Retry-After": "5"},
                    )
                return
            except (OSError, InjectedFault,
                    http.client.HTTPException) as e:
                relayed = getattr(e, "_relayed", False)
                with self._lock:
                    rep.healthy = False
                    rep.failures_total += 1
                    self.replica_failures_total += 1
                self._log(
                    "router_replica_failed", str(e),
                    replica=rep.index, rerouting=not relayed,
                )
                self._span(
                    "reroute", t_fwd, replica=rep.index,
                    path=handler.path, request_id=client_rid,
                    error=str(e), relayed=relayed,
                )
                if relayed:
                    # Bytes already reached the client: the router
                    # must NOT replay (a duplicate stream would
                    # double-deliver tokens); the client sees the
                    # truncated stream and retries with its own
                    # request id.
                    try:
                        handler.wfile.flush()
                    except OSError:
                        pass
                    return
                with self._lock:
                    self.reroutes_total += 1
                first_attempt = False
                continue  # re-route losslessly
            finally:
                with self._lock:
                    rep.inflight -= 1

    def _relay(
        self, handler: BaseHTTPRequestHandler, rep: _Replica,
        path: str, body: bytes, headers: Dict[str, str],
    ) -> Optional[str]:
        """Forward one request and relay the reply (buffered when the
        replica sent Content-Length, line-by-line for close-delimited
        NDJSON streams).  Returns the reply's ``X-Request-Id`` (the
        end-to-end id — replica-minted when the client sent none),
        recorded into the routing record so ``/debug/requests/<id>``
        resolves without fan-out.  Failure attribution: REPLICA-side
        errors (connect/request/read) raise as-is, tagged ``_relayed``
        once any byte reached the client; CLIENT-side write errors
        raise :class:`_ClientDisconnect` — the replica must not be
        marked unhealthy because an impatient client hung up."""
        conn = http.client.HTTPConnection(
            rep.host, rep.port, timeout=self.proxy_timeout_s
        )
        relayed = False

        def to_client(fn, *a):
            nonlocal relayed
            try:
                out = fn(*a)
                relayed = True
                return out
            except OSError:
                raise _ClientDisconnect(relayed) from None

        try:
            conn.request("POST", path, body=body, headers=headers)
            resp = conn.getresponse()
            rid_seen = resp.getheader("X-Request-Id")
            self._note_route(rid_seen, rep.index)
            out_headers = [
                (k, v) for k, v in resp.getheaders()
                if k.lower() not in _SKIP_HEADERS
            ]
            out_headers.append(("X-Replica-Id", str(rep.index)))

            def send_head(extra):
                handler.send_response(resp.status)
                for k, v in out_headers + extra:
                    handler.send_header(k, v)
                handler.end_headers()

            if resp.length is not None:
                data = resp.read()  # replica-side: raises plain OSError
                to_client(
                    send_head, [("Content-Length", str(len(data)))]
                )
                to_client(handler.wfile.write, data)
                return rid_seen
            # Close-delimited NDJSON stream: relay line-by-line so the
            # client sees tokens as the replica emits them.
            to_client(send_head, [("Connection", "close")])
            while True:
                line = resp.readline()
                if not line:
                    break
                to_client(handler.wfile.write, line)
                to_client(handler.wfile.flush)
            return rid_seen
        except (OSError, http.client.HTTPException) as e:
            e._relayed = relayed
            raise
        finally:
            conn.close()

    # -- GET surface ---------------------------------------------------------

    def _handle_get(self, handler: BaseHTTPRequestHandler) -> None:
        parts = urlsplit(handler.path)
        route, query = parts.path, parse_qs(parts.query)
        if route == "/healthz":
            h = self.health()
            self._reply_json(handler, 200 if h["ok"] else 503, h)
        elif route == "/metrics":
            body = self.metrics_text().encode()
            handler.send_response(200)
            handler.send_header(
                "Content-Type", "text/plain; version=0.0.4"
            )
            handler.send_header("Content-Length", str(len(body)))
            handler.end_headers()
            handler.wfile.write(body)
        elif route == "/debug/trace":
            window_ms = None
            if "window_s" in query:
                try:
                    window_ms = float(query["window_s"][0]) * 1000.0
                except ValueError:
                    self._reply_json(
                        handler, 400, {"error": "bad window_s"}
                    )
                    return
            self._reply_json(
                handler, 200, self.fleet_trace_json(window_ms)
            )
        elif route == "/debug/kv/fleet":
            depth = None
            if "depth" in query:
                try:
                    depth = int(query["depth"][0])
                except ValueError:
                    self._reply_json(
                        handler, 400, {"error": "bad depth"}
                    )
                    return
            self._reply_json(handler, 200, self.fleet_kv_json(depth))
        elif route == "/debug/requests":
            self._reply_json(
                handler, *self._fleet_requests_index(handler.path)
            )
        elif route.startswith("/debug/requests/"):
            rid = unquote(route[len("/debug/requests/"):])
            self._reply_json(
                handler, *self._fleet_request_lookup(rid, handler.path)
            )
        elif route.startswith("/debug/"):
            # Everything else (dispatch rings, profiler summaries...)
            # lives on whichever replica produced it: try each healthy
            # replica until one answers non-404.
            code, data = self._first_non_404(handler.path)
            self._reply_json(handler, code, data)
        else:
            self._reply_json(handler, 404, {"error": "not found"})

    def _get_replica_json(
        self, rep: _Replica, path: str, timeout: float = 2.0,
    ) -> Optional[Tuple[int, Dict[str, Any]]]:
        """One replica GET; None on connection/parse failure.  The
        default timeout matches the health probe's: the fleet /debug
        endpoints fetch replicas SEQUENTIALLY, so each hung-but-
        marked-healthy replica costs at most one probe interval, not
        a proxy-class stall per replica."""
        try:
            conn = http.client.HTTPConnection(
                rep.host, rep.port, timeout=timeout
            )
            try:
                conn.request("GET", path)
                resp = conn.getresponse()
                data = json.loads(resp.read() or b"{}")
            finally:
                conn.close()
        except (OSError, ValueError, http.client.HTTPException):
            return None
        if not isinstance(data, dict):
            return None
        return resp.status, data

    def _first_non_404(self, path: str) -> Tuple[int, Dict[str, Any]]:
        with self._lock:
            reps = [r for r in self._replicas if r.healthy]
        for rep in reps:
            got = self._get_replica_json(rep, path)
            if got is None:
                continue
            status, data = got
            if status != 404:
                data["replica"] = rep.index
                return status, data
        return 404, {"error": "not found on any replica"}

    def _fleet_requests_index(
        self, path: str,
    ) -> Tuple[int, Dict[str, Any]]:
        """GET /debug/requests aggregated across ALL healthy replicas
        (first-to-answer would show one replica's slice of the fleet
        and 404-hide the rest); every entry carries its replica id."""
        with self._lock:
            reps = [r for r in self._replicas if r.healthy]
        merged: List[Dict[str, Any]] = []
        replicas_answered: List[int] = []
        for rep in reps:
            got = self._get_replica_json(rep, path)
            if got is None or got[0] != 200:
                continue
            replicas_answered.append(rep.index)
            for entry in got[1].get("requests", []):
                if isinstance(entry, dict):
                    entry["replica"] = rep.index
                    merged.append(entry)
        return 200, {
            "requests": merged, "replicas": replicas_answered,
        }

    def _fleet_request_lookup(
        self, request_id: str, path: str,
    ) -> Tuple[int, Dict[str, Any]]:
        """GET /debug/requests/<id>: the ROUTING RECORD names the
        replica that served the id, so that replica answers first;
        healthy-replica fan-out only covers ids the bounded record has
        already evicted (or pre-router traffic)."""
        with self._lock:
            routed = self._routes.get(request_id)
            reps = list(self._replicas)
        ordered = (
            [r for r in reps if r.index == routed]
            + [r for r in reps if r.index != routed and r.healthy]
        )
        for rep in ordered:
            got = self._get_replica_json(rep, path)
            if got is None:
                continue
            status, data = got
            if status != 404:
                data["replica"] = rep.index
                data["routed_replica"] = routed
                return status, data
        return 404, {
            "error": f"request id {request_id!r} unknown fleet-wide",
            "routed_replica": routed,
        }

    @staticmethod
    def _reply_json(
        handler: BaseHTTPRequestHandler, code: int,
        obj: Dict[str, Any], headers: Optional[Dict[str, str]] = None,
    ) -> None:
        body = json.dumps(obj).encode()
        handler.send_response(code)
        handler.send_header("Content-Type", "application/json")
        handler.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            handler.send_header(k, v)
        handler.end_headers()
        handler.wfile.write(body)

    # -- observability -------------------------------------------------------

    def fleet_trace_json(
        self, window_ms: Optional[float] = None,
    ) -> Dict[str, Any]:
        """The fleet-merged Perfetto document (module docstring): the
        router's span track plus every healthy replica's
        ``/debug/trace`` export, replica timestamps shifted into the
        router's frame through the ``t0_unix_s`` anchors and re-tagged
        to per-replica pids.  Snapshot under the lock, fetch and build
        outside it — replica HTTP round-trips must never hold the
        routing lock."""
        with self._lock:
            reps = [
                (r.index, r.host, r.port)
                for r in self._replicas if r.healthy
            ]
            spans = list(self._trace)
            now = self._now_ms()
        horizon = None if window_ms is None else now - window_ms
        ev: List[Dict[str, Any]] = [
            {"ph": "M", "pid": 0, "name": "process_name",
             "args": {"name": "router"}},
            {"ph": "M", "pid": 0, "tid": 1, "name": "thread_name",
             "args": {"name": "routing"}},
        ]
        for s in spans:
            if horizon is not None and s["t0_ms"] + s["dur_ms"] < horizon:
                continue
            ev.append({
                "name": s["name"], "cat": "router", "ph": "X",
                "pid": 0, "tid": 1,
                "ts": round(s["t0_ms"] * 1000.0, 1),
                "dur": max(1, round(s["dur_ms"] * 1000.0)),
                "args": dict(s["args"]),
            })
        suffix = (
            "" if window_ms is None
            else f"?window_s={window_ms / 1000.0:g}"
        )
        merged_replicas: List[int] = []
        for index, host, port in reps:
            got = self._get_replica_json(
                _Replica(index=index, host=host, port=port),
                "/debug/trace" + suffix,
            )
            if got is None or got[0] != 200:
                continue
            doc = got[1]
            merged_replicas.append(index)
            pid = 1 + index
            # Clock-offset normalization: replica ts are relative to
            # ITS Observability t0; the wall anchors captured at both
            # t0 instants give the shift into the router's frame.
            off_us = (
                float(doc.get("t0_unix_s", self.t0_unix))
                - self.t0_unix
            ) * 1e6
            ev.append({
                "ph": "M", "pid": pid, "name": "process_name",
                "args": {"name": f"replica-{index}"},
            })
            for e in doc.get("traceEvents", []):
                if not isinstance(e, dict):
                    continue
                e = dict(e)
                e["pid"] = pid
                if "ts" in e:
                    e["ts"] = round(e["ts"] + off_us, 1)
                ev.append(e)
        return {
            "traceEvents": ev, "displayTimeUnit": "ms",
            "t0_unix_s": round(self.t0_unix, 6),
            "replicas": merged_replicas,
        }

    def fleet_kv_json(
        self, depth: Optional[int] = None,
    ) -> Dict[str, Any]:
        """``GET /debug/kv/fleet``: the router-side fleet cache view.

        Scrapes every healthy replica's ``/debug/kv`` digest
        (sequential, probe-class 2 s timeouts — on demand, never from
        the poller) and aggregates:

          * **fleet prefix-hit ratio** — sum of hit tokens over sum of
            admitted prompt tokens across the fleet;
          * **per-replica occupancy/watermarks** — nodes, HBM/host
            residency, idle (evictable) depth, digest version/age;
          * **cross-replica duplicate chains** — chain-prefix keys
            HBM-resident on >= 2 replicas, with the redundant blocks
            and BYTES (copies beyond the first, priced at each extra
            copy's own block_bytes): the HBM a cache-aware
            disaggregation scheduler (ROADMAP item 2) would get back.

        The computed fleet aggregate is cached (``_fleet_kv``) for the
        ``llm_fleet_duplicate_kv_blocks`` /metrics gauges; truncated
        replica digests make the duplicate count a LOWER bound and are
        listed in ``truncated_replicas``."""
        with self._lock:
            reps = [
                (r.index, r.host, r.port)
                for r in self._replicas if r.healthy
            ]
        t0 = time.monotonic()
        suffix = f"?depth={depth}" if depth is not None else ""
        per: List[Dict[str, Any]] = []
        truncated: List[int] = []
        # chain key -> [(replica index, block_bytes), ...] HBM copies
        chains: Dict[str, List[Tuple[int, int]]] = {}
        hit_tokens = prompt_tokens = 0
        for index, host, port in reps:
            got = self._get_replica_json(
                _Replica(index=index, host=host, port=port),
                "/debug/kv" + suffix,
            )
            if got is None or got[0] != 200:
                continue
            doc = got[1]
            summ = doc.get("summary") or {}
            bb = int(summ.get("block_bytes") or 0)
            for node in doc.get("nodes", []):
                if (
                    isinstance(node, dict)
                    and node.get("tier") == "hbm"
                ):
                    chains.setdefault(str(node.get("key")), []).append(
                        (index, bb)
                    )
            if doc.get("truncated"):
                truncated.append(index)
            hit_tokens += int(summ.get("prefix_hit_tokens_total") or 0)
            prompt_tokens += int(summ.get("prompt_tokens_total") or 0)
            per.append({
                "replica": index,
                "summary": summ,
                "hit_ratio": round(
                    int(summ.get("prefix_hit_tokens_total") or 0)
                    / max(1, int(summ.get("prompt_tokens_total") or 0)),
                    6,
                ),
                "hbm_bytes": (
                    int(summ.get("hbm_blocks") or 0) * bb
                ),
            })
        dup_chains = dup_blocks = dup_bytes = 0
        for copies in chains.values():
            if len({i for i, _ in copies}) < 2:
                continue
            dup_chains += 1
            extra = sorted(copies)[1:]  # first copy is the keeper
            dup_blocks += len(extra)
            dup_bytes += sum(b for _, b in extra)
        scrape_ms = round((time.monotonic() - t0) * 1000.0, 3)
        fleet = {
            "prefix_hit_ratio": round(
                hit_tokens / max(1, prompt_tokens), 6
            ),
            "prefix_hit_tokens_total": hit_tokens,
            "prompt_tokens_total": prompt_tokens,
            "duplicate_chains": dup_chains,
            "duplicate_kv_blocks": dup_blocks,
            "duplicate_kv_bytes": dup_bytes,
            "replicas_scraped": [p["replica"] for p in per],
            "truncated_replicas": truncated,
            "scrape_ms": scrape_ms,
        }
        with self._lock:
            self._fleet_kv = dict(fleet, computed_unix_s=time.time())
        return {"fleet": fleet, "replicas": per}

    def health(self) -> Dict[str, Any]:
        """Aggregate /healthz: ok while ANY replica is routable, with
        the per-replica snapshots under ``replicas``."""
        with self._lock:
            snaps = [r.snapshot() for r in self._replicas]
            affinity_sessions = len(self._affinity)
            handoffs = self.kv_handoffs_total
            stale_routes = self.affinity_stale_routes_total
            fleet_kv = (
                dict(self._fleet_kv)
                if self._fleet_kv is not None else None
            )
        return {
            "ok": any(s["healthy"] for s in snaps),
            "policy": self.policy,
            "replicas": snaps,
            "affinity_sessions": affinity_sessions,
            "kv_handoffs_total": handoffs,
            "affinity_stale_routes_total": stale_routes,
            # Last computed fleet cache aggregate (None until the
            # first GET /debug/kv/fleet).
            "fleet_kv": fleet_kv,
        }

    def metrics_text(self) -> str:
        """Router Prometheus exposition: aggregate counters plus
        per-replica labeled gauges (occupancy / inflight / routed /
        health / mesh shape)."""
        with self._lock:
            snaps = [r.snapshot() for r in self._replicas]
            by_policy = dict(self.routed_by_policy)
            reroutes = self.reroutes_total
            failures = self.replica_failures_total
            handoffs = self.kv_handoffs_total
            affinity_sessions = len(self._affinity)
            stale_routes = self.affinity_stale_routes_total
            fleet_kv = (
                dict(self._fleet_kv)
                if self._fleet_kv is not None else None
            )
        lines: List[str] = []

        def fam(name: str, kind: str, help_text: str) -> None:
            lines.append(f"# HELP llm_router_{name} {help_text}")
            lines.append(f"# TYPE llm_router_{name} {kind}")

        fam("replicas", "gauge", "Replicas behind this router")
        lines.append(f"llm_router_replicas {len(snaps)}")
        fam("replicas_healthy", "gauge", "Replicas currently routable")
        lines.append(
            "llm_router_replicas_healthy "
            f"{sum(s['healthy'] for s in snaps)}"
        )
        fam("routed_requests_total", "counter",
            "Requests routed, by decision policy")
        for pol, n in sorted(by_policy.items()):
            lines.append(
                f'llm_router_routed_requests_total{{policy="{pol}"}} {n}'
            )
        fam("reroutes_total", "counter",
            "Requests re-routed off a failed replica")
        lines.append(f"llm_router_reroutes_total {reroutes}")
        fam("replica_failures_total", "counter",
            "Forward-time replica failures observed")
        lines.append(f"llm_router_replica_failures_total {failures}")
        fam("kv_handoffs_total", "counter",
            "Cross-replica prefix-KV handoffs brokered")
        lines.append(f"llm_router_kv_handoffs_total {handoffs}")
        fam("affinity_sessions", "gauge",
            "Sticky sessions currently pinned")
        lines.append(f"llm_router_affinity_sessions {affinity_sessions}")
        fam("affinity_stale_routes_total", "counter",
            "Affinity routes taken onto a replica whose chain digest "
            "changed since the session pinned (possible cache miss — "
            "counted, no longer silent)")
        lines.append(
            f"llm_router_affinity_stale_routes_total {stale_routes}"
        )
        # Fleet cache aggregate (last GET /debug/kv/fleet computation;
        # headers always present for dashboard discovery, samples only
        # once a fleet view has been computed).
        lines.append(
            "# HELP llm_fleet_duplicate_kv_blocks HBM blocks holding "
            "chain prefixes duplicated on >= 2 replicas (copies beyond "
            "the first; last fleet-view computation)"
        )
        lines.append("# TYPE llm_fleet_duplicate_kv_blocks gauge")
        lines.append(
            "# HELP llm_fleet_duplicate_kv_bytes HBM bytes behind the "
            "duplicate chain blocks — the disaggregation scheduler's "
            "reclaimable redundancy"
        )
        lines.append("# TYPE llm_fleet_duplicate_kv_bytes gauge")
        lines.append(
            "# HELP llm_fleet_prefix_hit_ratio Fleet-wide fraction of "
            "admitted prompt tokens served from cached prefix blocks "
            "(last fleet-view computation)"
        )
        lines.append("# TYPE llm_fleet_prefix_hit_ratio gauge")
        lines.append(
            "# HELP llm_fleet_kv_age_s Seconds since the fleet cache "
            "view was last computed"
        )
        lines.append("# TYPE llm_fleet_kv_age_s gauge")
        if fleet_kv is not None:
            lines.append(
                "llm_fleet_duplicate_kv_blocks "
                f"{fleet_kv['duplicate_kv_blocks']}"
            )
            lines.append(
                "llm_fleet_duplicate_kv_bytes "
                f"{fleet_kv['duplicate_kv_bytes']}"
            )
            lines.append(
                "llm_fleet_prefix_hit_ratio "
                f"{fleet_kv['prefix_hit_ratio']}"
            )
            lines.append(
                "llm_fleet_kv_age_s "
                f"{round(time.time() - fleet_kv['computed_unix_s'], 3)}"
            )
        fam("replica_healthy", "gauge", "Replica routable (per replica)")
        fam("replica_inflight", "gauge",
            "Router-tracked in-flight requests (per replica)")
        fam("replica_routed_total", "counter",
            "Requests routed to this replica")
        fam("replica_active_slots", "gauge",
            "Replica batcher slots holding a live request (last "
            "health scrape)")
        fam("replica_mesh_devices", "gauge",
            "Devices in the replica's serving mesh (last health "
            "scrape)")
        # Per-replica cache gauges (from the /healthz kv.digest
        # summary the poller already scrapes) + the staleness gauge
        # that qualifies EVERY per-replica labeled value here: a
        # replica that went unroutable keeps its last-scraped numbers,
        # so dashboards gate on the age instead of trusting them.
        lines.append(
            "# HELP llm_replica_health_age_s Seconds since this "
            "replica's labeled gauges were last refreshed from a "
            "successful /healthz scrape (-1 = never scraped; stale "
            "values persist for unroutable replicas — gate on this)"
        )
        lines.append("# TYPE llm_replica_health_age_s gauge")
        fam("replica_kv_nodes", "gauge",
            "Chain-digest nodes (keyed blocks) on this replica (last "
            "health scrape)")
        fam("replica_kv_hbm_blocks", "gauge",
            "HBM-resident chain blocks on this replica (last health "
            "scrape)")
        fam("replica_kv_host_blocks", "gauge",
            "Host-tier-resident chain blocks on this replica (last "
            "health scrape)")
        fam("replica_kv_idle_blocks", "gauge",
            "Idle (refcount-0, evictable) chain blocks on this "
            "replica (last health scrape)")
        fam("replica_kv_digest_version", "gauge",
            "Chain-digest content version on this replica (last "
            "health scrape)")
        fam("replica_kv_hit_ratio", "gauge",
            "Replica fraction of admitted prompt tokens served from "
            "cached prefix blocks (last health scrape)")
        for s in snaps:
            lab = f'replica="{s["index"]}"'
            lines.append(
                f"llm_router_replica_healthy{{{lab}}} "
                f"{int(bool(s['healthy']))}"
            )
            lines.append(
                f"llm_router_replica_inflight{{{lab}}} {s['inflight']}"
            )
            lines.append(
                f"llm_router_replica_routed_total{{{lab}}} "
                f"{s['routed_total']}"
            )
            rep_info = s.get("replica") or {}
            lines.append(
                f"llm_router_replica_active_slots{{{lab}}} "
                f"{rep_info.get('active_slots', 0) or 0}"
            )
            mesh = rep_info.get("serve_mesh") or {}
            lines.append(
                f"llm_router_replica_mesh_devices{{{lab}}} "
                f"{mesh.get('devices', 1) or 1}"
            )
            age = s.get("health_age_s")
            lines.append(
                f"llm_replica_health_age_s{{{lab}}} "
                f"{age if age is not None else -1}"
            )
            kv = s.get("kv") or {}
            dig = kv.get("digest") or {}
            lines.append(
                f"llm_router_replica_kv_nodes{{{lab}}} "
                f"{dig.get('nodes', 0) or 0}"
            )
            lines.append(
                f"llm_router_replica_kv_hbm_blocks{{{lab}}} "
                f"{dig.get('hbm_blocks', 0) or 0}"
            )
            lines.append(
                f"llm_router_replica_kv_host_blocks{{{lab}}} "
                f"{dig.get('host_blocks', 0) or 0}"
            )
            lines.append(
                f"llm_router_replica_kv_idle_blocks{{{lab}}} "
                f"{dig.get('idle_blocks', 0) or 0}"
            )
            lines.append(
                f"llm_router_replica_kv_digest_version{{{lab}}} "
                f"{dig.get('version', 0) or 0}"
            )
            hit = int(kv.get("prefix_hit_tokens_total") or 0)
            prompt = int(kv.get("prompt_tokens_total") or 0)
            lines.append(
                f"llm_router_replica_kv_hit_ratio{{{lab}}} "
                f"{round(hit / max(1, prompt), 6)}"
            )
        return "\n".join(lines) + "\n"

    def note_handoff(
        self, blocks: int, request_id: Optional[str] = None,
        src: Optional[int] = None, dst: Optional[int] = None,
    ) -> None:
        """Count a brokered prefix handoff and drop a ``handoff`` span
        on the router track carrying the external request id — the
        link that ties the source replica's ``prefix_export`` and the
        destination's ``prefix_import`` instants into one timeline in
        the merged trace.  When the destination is known the routing
        record re-pins the id there (route-follow: the session's next
        /debug lookup lands where its KV now lives)."""
        if blocks <= 0:
            return
        t = self._now_ms()
        with self._lock:
            self.kv_handoffs_total += 1
            self._trace.append({
                "name": "handoff", "t0_ms": round(t, 3),
                "dur_ms": 0.0,
                "args": {
                    k: v for k, v in (
                        ("request_id", request_id), ("src", src),
                        ("dst", dst), ("blocks", blocks),
                    ) if v is not None
                },
            })
        if dst is not None:
            self._note_route(request_id, dst)


def handoff_prefix(
    src_batcher, dst_batcher, tokens: Sequence[int],
    router: Optional[ReplicaRouter] = None,
    request_id: Optional[str] = None,
    src: Optional[int] = None,
    dst: Optional[int] = None,
) -> int:
    """Prefill/decode disaggregation handoff: move ``tokens``' cached
    prefix blocks from ``src_batcher`` (which prefilled them) into
    ``dst_batcher``'s pool + radix index, so the session's next
    admission on the destination replica is a plain prefix hit —
    ``export_prefix``'s D2H slab fetch feeding ``import_prefix``'s
    stage/adopt/publish, the exact path the host-DRAM tier restores
    through.  Both batcher calls MUST run on their owning serving-loop
    threads (the batchers are thread-confined).  ``request_id`` (the
    session's external id) threads through both batchers' trace
    annotations and the router's handoff span, so the fleet-merged
    trace shows the move as ONE linked timeline; ``src``/``dst`` are
    the replica indices when the caller knows them.  Returns the
    number of blocks landed on the destination."""
    keys, slabs = src_batcher.export_prefix(tokens, request_id=request_id)
    if not slabs:
        return 0
    n = dst_batcher.import_prefix(keys, slabs, request_id=request_id)
    if router is not None:
        router.note_handoff(n, request_id=request_id, src=src, dst=dst)
    return n
