"""Pipeline parallelism — GPipe microbatch schedule over the ``stage`` axis.

The reference has no pipeline parallelism (SURVEY.md §2.13b: its layer stack
is a plain Python loop, ``/root/reference/jax_llama/model.py:579-592``); this
module adds it the TPU way: no per-stage processes or send/recv threads, one
SPMD program in which the ``stage`` mesh axis holds ``L / n_stages`` layers
per device group and activations rotate stage→stage+1 with ``lax.ppermute``
over ICI/DCN point-to-point links.

Schedule: classic GPipe.  The batch splits into M microbatches; the pipeline
runs ``M + S - 1`` ticks; at tick ``t`` stage ``s`` runs microbatch
``t - s`` (when in range).  Bubble fraction is ``(S-1)/(M+S-1)`` — callers
pick M per memory/efficiency trade-off (default M = S).

Composition: the shard_map is *manual only over* ``stage``
(``axis_names={"stage"}``); data/fsdp/tensor stay auto, so the blocks'
internal sharding constraints (tensor-parallel activations, batch sharding)
keep working inside each stage — GSPMD still inserts the TP collectives
per-stage.  Ring (seq>1) attention nests a second shard_map and is not
composable with the pipeline; callers must keep seq == 1 when stage > 1.

Because each microbatch's positions ride the ring alongside its
activations, masking stays correct for left-padded rows without any global
coordination.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .mesh import shard_map_compat

StageFn = Callable[
    [Any, jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray], jnp.ndarray
]


def pipeline_blocks(
    stage_fn: StageFn,
    layer_params: Any,
    x: jnp.ndarray,
    positions: jnp.ndarray,
    slot_pos: jnp.ndarray,
    *,
    mesh,
    n_microbatches: int,
    axis_name: str = "stage",
) -> jnp.ndarray:
    """Run the stacked layer params as a pipeline over the ``stage`` axis.

    Args:
      stage_fn: ``(stage_layers, x, positions, slot_pos, mb_index) -> x``
        applying one stage's layers to one microbatch (``stage_layers``
        leaves keep a leading ``L/S`` axis for the caller's own scan).
        ``mb_index`` is the int32 index of the microbatch this stage is
        processing this tick (clamped during fill/drain bubble ticks,
        whose outputs are discarded) — dropout callers fold it into their
        per-layer keys so every (layer, microbatch) draws independently.
      layer_params: pytree of stacked layer params, leading axis L.
      x: [B, T, D] embeddings.
      positions: [B, T] int32 query positions (clamped >= 0).
      slot_pos: [B, T] int32 kv slot positions (-1 padding).
      mesh: the active Mesh (must contain ``stage``).
      n_microbatches: M; must divide B.
    Returns:
      [B, T, D] block-stack output.

    Call under ``jax.jit`` (as every engine/train entry point does): in
    eager mode the shard_map's auto-axes/out_specs interaction trips a
    strictness check even though the jitted program is valid.
    """
    S = mesh.shape[axis_name]
    M = n_microbatches
    B, T, D = x.shape
    L = jax.tree.leaves(layer_params)[0].shape[0]
    if L % S:
        raise ValueError(f"n_layers={L} not divisible by stage={S}")
    if B % M:
        raise ValueError(f"batch={B} not divisible by microbatches={M}")
    mb = B // M

    staged = jax.tree.map(
        lambda a: a.reshape((S, L // S) + a.shape[1:]), layer_params
    )
    x_mb = x.reshape(M, mb, T, D)
    pos_mb = positions.reshape(M, mb, T)
    spos_mb = slot_pos.reshape(M, mb, T)

    perm = [(i, (i + 1) % S) for i in range(S)]

    def per_stage(staged, x_mb, pos_mb, spos_mb):
        # Local views: staged leaves [1, L/S, ...]; the rest replicated.
        stage = lax.axis_index(axis_name)
        layers = jax.tree.map(lambda a: a[0], staged)
        state = jnp.zeros((mb, T, D), x_mb.dtype)
        state_pos = jnp.zeros((mb, T), pos_mb.dtype)
        state_spos = jnp.full((mb, T), -1, spos_mb.dtype)
        outs = jnp.zeros((1, M, mb, T, D), x_mb.dtype)

        for t in range(M + S - 1):
            # Stage 0 injects microbatch t (clamped during drain ticks —
            # drained garbage can never reach the last stage in time).
            inject = min(t, M - 1)
            is_first = stage == 0
            xx = jnp.where(is_first, x_mb[inject], state)
            pos = jnp.where(is_first, pos_mb[inject], state_pos)
            spos = jnp.where(is_first, spos_mb[inject], state_spos)

            # Microbatch index at this stage this tick (GPipe: stage s runs
            # microbatch t - s); clamped on bubble ticks, whose compute is
            # discarded.
            mb_index = jnp.clip(t - stage, 0, M - 1).astype(jnp.int32)
            y = stage_fn(layers, xx, pos, spos, mb_index)

            # The last stage finished microbatch t - (S-1) this tick; every
            # stage writes uniformly (SPMD), only the last stage's buffer is
            # read back outside.
            m = t - (S - 1)
            if 0 <= m < M:
                outs = outs.at[0, m].set(y)
            if t < M + S - 2:
                state, state_pos, state_spos = (
                    lax.ppermute(v, axis_name, perm)
                    for v in (y, pos, spos)
                )
        return outs

    out = shard_map_compat(
        per_stage,
        mesh=mesh,
        in_specs=(P(axis_name), P(), P(), P()),
        out_specs=P(axis_name),
        axis_names={axis_name},
        # The rotating carries flip between stage-invariant (initial zeros)
        # and stage-varying (post-ppermute); the varying-manual-axes checker
        # rejects the mix although the program is correct (same situation as
        # ring attention).
        check_vma=False,
    )(staged, x_mb, pos_mb, spos_mb)
    return out[-1].reshape(B, T, D)
