"""Device mesh construction and activation-sharding helpers.

The reference builds a ``(1, n_devices)`` mesh with axes ``('dp','mp')``
(``/root/reference/jax_example.py:12-13``) and gates its sharding-constraint
helper on a deprecated global-mesh API (``/root/reference/jax_llama/
partition.py:83-98``).  Here the mesh is an explicit context with four axes:

    data    — data parallel (batch), rides DCN between slices
    stage   — pipeline parallel (GPipe microbatches, parallel.pipeline);
              stage→stage+1 ppermute traffic is point-to-point, so outer
              ICI / DCN links suffice
    fsdp    — ZeRO-style param sharding (batch-combined with `data` for
              activations), inner ICI
    seq     — sequence/context parallel (ring attention), ICI
    tensor  — Megatron-style tensor parallel, innermost ICI

Axis sizes of 1 are free, so a single config covers 1-chip dev runs through
multi-host pods.  ``constrain`` translates *logical* axis names to mesh axes
and no-ops when no mesh is active, so model code stays mesh-agnostic.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXES = ("data", "stage", "fsdp", "seq", "tensor")

# Logical-name -> mesh-axis translation for activation constraints.  The
# batch dimension is sharded over both data-parallel axes (pure-DP inference
# and FSDP training both land batch there).
LOGICAL_RULES = {
    "data": ("data", "fsdp"),
    "fsdp": "fsdp",
    "seq": "seq",
    "tensor": "tensor",
    None: None,
}

_local = threading.local()


def shard_map_compat(f, *, mesh, in_specs, out_specs, check_vma=None,
                     axis_names=None):
    """``jax.shard_map`` across the API rename: newer jax exposes it
    top-level with ``check_vma``/``axis_names``; older jax has
    ``jax.experimental.shard_map.shard_map`` with ``check_rep`` and the
    complementary ``auto`` axis set.  Same manual-sharding semantics —
    this wrapper only translates the spelling, so the parallel code is
    written once against the current API and still runs on the older
    runtime this image bakes in."""
    if hasattr(jax, "shard_map"):
        kwargs = {}
        if check_vma is not None:
            kwargs["check_vma"] = check_vma
        if axis_names is not None:
            kwargs["axis_names"] = axis_names
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    kwargs = {}
    if check_vma is not None:
        kwargs["check_rep"] = check_vma
    if axis_names is not None:
        kwargs["auto"] = frozenset(mesh.axis_names) - set(axis_names)
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
    )


def make_mesh(
    data: int = 1,
    stage: int = 1,
    fsdp: int = 1,
    seq: int = 1,
    tensor: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a 5-axis mesh.  Total axis product must equal device count.

    Axis order places `tensor` innermost so TP collectives ride the
    highest-bandwidth ICI links, `data` outermost so DP gradients/batches
    cross DCN, `stage` next-outermost (pipeline hops are point-to-point)
    (cf. the scaling-book mesh recipe).
    """
    devices = list(devices if devices is not None else jax.devices())
    want = data * stage * fsdp * seq * tensor
    if want != len(devices):
        raise ValueError(
            f"mesh {data}x{stage}x{fsdp}x{seq}x{tensor}={want} "
            f"!= {len(devices)} devices"
        )
    arr = np.asarray(devices).reshape(data, stage, fsdp, seq, tensor)
    return Mesh(arr, AXES)


def auto_mesh(tensor: Optional[int] = None) -> Mesh:
    """All local devices on the tensor axis (single-host TP), unless told
    otherwise."""
    n = len(jax.devices())
    tensor = tensor or n
    return make_mesh(data=n // tensor, tensor=tensor)


@contextlib.contextmanager
def use_mesh(mesh: Optional[Mesh]):
    """Activate a mesh for `constrain`/`shard_params` in this thread."""
    prev = getattr(_local, "mesh", None)
    _local.mesh = mesh
    try:
        yield mesh
    finally:
        _local.mesh = prev


def current_mesh() -> Optional[Mesh]:
    return getattr(_local, "mesh", None)


def logical_to_spec(*logical) -> P:
    """Translate logical axis names to a PartitionSpec."""
    return P(*(LOGICAL_RULES.get(name, name) for name in logical))


def constrain(x: jax.Array, *logical) -> jax.Array:
    """Apply a sharding constraint in logical-axis terms.

    No-ops when no mesh is active (single-device dev loop, parity tests) —
    the reference's equivalent no-op gate is partition.py:88-93, built on a
    deprecated API.
    """
    mesh = current_mesh()
    if mesh is None:
        return x
    spec = logical_to_spec(*logical)
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
