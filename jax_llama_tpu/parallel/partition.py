"""Parameter partition specs: tensor-parallel and FSDP sharding rules.

Capability parity with the reference rule tables (``/root/reference/
jax_llama/partition.py:43-78``): Megatron-style column-parallel shards on
the fused qkv/gate_up projections and lm_head (the reference shards the
same weights, stored separately), row-parallel on o/down, vocab-sharded
embedding, replicated norms; the ``fsdp`` variant additionally shards the
non-TP axis over the fsdp mesh axis (the reference defines the same table
over ``dp`` but never uses it — jax_example.py:25 hardcodes fsdp=False;
here it is a first-class option).

Because the param tree is structured (not a flat dict of dotted names),
specs are written as a mirror-shaped pytree — no regex window-matching
(reference partition.py:16-41) needed, and completeness is checked
structurally rather than via runtime assert on a miss.

Mesh axes are the canonical five from ``parallel.mesh``: data / stage /
fsdp / seq / tensor.  KV-head sharding requires ``tensor`` to divide
``n_kv_heads`` (GQA models: 8 for llama3); pipeline sharding requires
``stage`` to divide ``n_layers`` — checked in `validate_tp`.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config import LLaMAConfig
from ..ops.quant import QuantizedTensor


def param_partition_specs(
    config: LLaMAConfig, *, fsdp: bool = False, pp: bool = False
) -> Dict[str, Any]:
    """PartitionSpec pytree mirroring the `init_params` tree.

    Layer params carry a leading stacked-L axis: with ``pp=True`` it is
    sharded over the ``stage`` mesh axis (each pipeline stage stores only
    its own L/S layers); otherwise it is unsharded (lax.scan iterates it).
    With ``fsdp=True`` the non-tensor-parallel dimension of every
    projection is sharded over the ``fsdp`` axis (ZeRO-3-style).
    """
    f = "fsdp" if fsdp else None
    s = "stage" if pp else None
    specs: Dict[str, Any] = {
        # Vocab-sharded over BOTH model axes, hidden dim unsharded: a
        # vocab-sharded table lowers the token gather to masked-gather +
        # all-reduce, while sharding D (e.g. over fsdp) was observed to
        # trigger SPMD's involuntary-full-rematerialization fallback when
        # resharding the gather output to batch-sharded activations.
        "embed": {"embedding": P(("tensor", f) if f else "tensor", None)},
        "layers": {
            "attn_norm": P(s, None),
            # Fused [L, KVH, G+2, D, hd]: column-parallel over KV heads
            # (each shard holds its heads' q slots AND k/v slots — the
            # same per-shard contents as the separate q/k/v layout).
            "qkv": P(s, "tensor", None, f, None),
            "o": P(s, "tensor", None, f),            # row-parallel
            "mlp_norm": P(s, None),
            # Fused [L, 2, D, F]: column-parallel over F.
            "gate_up": P(s, None, f, "tensor"),
            "down": P(s, "tensor", f),               # row-parallel
        },
        "final_norm": P(None),
    }
    if not config.tie_word_embeddings:
        specs["lm_head"] = P(f, "tensor")            # column-parallel
    return specs


def validate_tp(config: LLaMAConfig, mesh: Mesh, *, fsdp: bool = False) -> None:
    """Check mesh axes divide the dims they shard — a clear error here
    beats the opaque one device_put raises mid-tree.

    (The KV cache built inside the jitted decode needs no spec tree of its
    own: its sharding propagates from the constrained k/v projections that
    write it.)
    """
    st = mesh.shape.get("stage", 1)
    if config.n_layers % st:
        raise ValueError(
            f"stage={st} must divide n_layers={config.n_layers} "
            "(pipeline stages hold equal layer counts)"
        )
    tp = mesh.shape["tensor"]
    if config.kv_heads % tp:
        raise ValueError(
            f"tensor={tp} must divide n_kv_heads={config.kv_heads} "
            "(GQA KV cache is head-sharded)"
        )
    if config.n_heads % tp:
        raise ValueError(f"tensor={tp} must divide n_heads={config.n_heads}")
    if config.ffn_dim % tp:
        raise ValueError(f"tensor={tp} must divide ffn_dim={config.ffn_dim}")
    if config.vocab_size % tp:
        raise ValueError(f"tensor={tp} must divide vocab={config.vocab_size}")
    if fsdp:
        fs = mesh.shape["fsdp"]
        if config.dim % fs:
            raise ValueError(f"fsdp={fs} must divide dim={config.dim}")
        if config.ffn_dim % fs:
            raise ValueError(f"fsdp={fs} must divide ffn_dim={config.ffn_dim}")
        if config.vocab_size % (tp * fs):
            raise ValueError(
                f"tensor*fsdp={tp * fs} must divide vocab="
                f"{config.vocab_size} (vocab-sharded embedding)"
            )


def shard_params(
    params: Any,
    mesh: Mesh,
    config: LLaMAConfig,
    *,
    fsdp: bool = False,
) -> Any:
    """Place a (host or device) param pytree onto the mesh.

    The reference does the equivalent with per-leaf ``jax.device_put(leaf,
    NamedSharding(mesh, spec))`` (jax_example.py:26); same mechanism here,
    driven by the structured spec tree.
    """
    validate_tp(config, mesh, fsdp=fsdp)
    specs = param_partition_specs(
        config, fsdp=fsdp, pp=mesh.shape.get("stage", 1) > 1
    )

    def put(x, sharding):
        return jax.device_put(x, sharding)

    return _map_with_shardings(put, params, specs, mesh)


def shard_abstract(
    shapes: Any,
    mesh: Mesh,
    config: LLaMAConfig,
    *,
    fsdp: bool = False,
) -> Any:
    """Attach NamedShardings to an abstract (eval_shape) param tree — the
    form Orbax needs to restore each shard straight to its owning host."""
    validate_tp(config, mesh, fsdp=fsdp)
    specs = param_partition_specs(
        config, fsdp=fsdp, pp=mesh.shape.get("stage", 1) > 1
    )

    def abstract(x, sharding):
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sharding)

    return _map_with_shardings(abstract, shapes, specs, mesh)


def _scale_spec(spec: P, q_ndim: int, scale_shape) -> P:
    """Spec for a QuantizedTensor's per-channel scale: the weight's spec,
    minus axes on contracted dims (size 1 in the scale — must not shard)."""
    full = tuple(spec) + (None,) * (q_ndim - len(tuple(spec)))
    return P(*(
        ax if dim != 1 else None for ax, dim in zip(full, scale_shape)
    ))


def _map_with_shardings(fn, tree: Any, specs: Any, mesh: Mesh) -> Any:
    """Apply ``fn(leaf, NamedSharding)`` over a (possibly quantized) param
    tree zipped with its PartitionSpec tree."""

    def apply(x, s):
        if isinstance(x, QuantizedTensor):
            # The int8 payload takes the weight's spec; the scale keeps the
            # spec only on dims it actually has.
            q = x.q
            return QuantizedTensor(
                q=fn(q, NamedSharding(mesh, s)),
                scale=fn(
                    x.scale,
                    NamedSharding(mesh, _scale_spec(s, q.ndim, x.scale.shape)),
                ),
            )
        return fn(x, NamedSharding(mesh, s))

    return jax.tree.map(
        apply, tree, specs,
        is_leaf=lambda x: isinstance(x, QuantizedTensor),
    )
