"""Multi-host initialization: the TPU-native communication backend.

The reference has no distributed backend of its own — XLA:GPU inserts NCCL
collectives from sharding specs, and only its *torch test oracle* ever
calls ``init_process_group`` (SURVEY.md §2.13b).  The TPU-native
equivalent is the GSPMD model over ICI (intra-slice) and DCN (inter-slice):
``jax.distributed.initialize()`` brings up the coordination service, every
host then sees the global device set, and a ``Mesh`` spanning
``jax.devices()`` makes XLA emit collectives that ride ICI for inner mesh
axes (tensor/seq) and DCN for outer ones (data) — no hand-written
communication anywhere.

Typical multi-host entry (same code on every host, e.g. under
``gcloud compute tpus tpu-vm ssh --worker=all``):

    from jax_llama_tpu.parallel import distributed, make_mesh
    distributed.initialize()          # no-op on single host / single proc
    mesh = make_mesh(data=jax.process_count(), tensor=jax.local_device_count())
"""

from __future__ import annotations

import os
from typing import Optional

import jax

_initialized = False


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Bring up ``jax.distributed`` when running multi-process.

    On Cloud TPU all three arguments are auto-detected from the metadata
    server, so a bare ``initialize()`` works on every host of a pod slice.
    Single-process runs (one chip, CPU meshes, unit tests) skip
    initialization entirely — calling this is always safe.

    Explicit args (or ``JAX_COORDINATOR_ADDRESS`` / ``JAX_NUM_PROCESSES``
    / ``JAX_PROCESS_ID`` env vars) cover non-TPU-metadata environments.
    """
    global _initialized
    if _initialized:
        return
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    if num_processes is None and "JAX_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and "JAX_PROCESS_ID" in os.environ:
        process_id = int(os.environ["JAX_PROCESS_ID"])

    explicit = coordinator_address is not None
    # Pod detection must NOT touch the jax backend (e.g. via
    # jax.default_backend()): jax.distributed.initialize() raises if any
    # XLA backend is already initialized.  The TPU runtime env is enough:
    # TPU_WORKER_HOSTNAMES lists every worker of a slice, so >1 entry
    # means multi-host (a single-host TPU VM lists only itself and needs
    # no coordination service).
    workers = [
        h for h in os.environ.get("TPU_WORKER_HOSTNAMES", "").split(",")
        if h.strip()
    ]
    on_tpu_pod = not explicit and len(workers) > 1
    if not explicit and not on_tpu_pod:
        return  # single-process: nothing to initialize

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True


def is_initialized() -> bool:
    return _initialized


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()
