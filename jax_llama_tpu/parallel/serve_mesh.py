"""Scale-out serving: mesh-sharded placement for the serving stack.

The model side of this repo has been mesh-capable since the seed
(``partition.py`` weight specs, GSPMD ``constrain`` calls through
``models/llama.py``, the paged-attention op's own fully-manual
``shard_map`` over the tensor/data axes) — but the *serving* stack the
batcher owns stayed single-chip: ``init_pool`` built the KV block pool
on the default device, the per-slot device twins (``d_*``) were plain
``jnp.asarray`` uploads, and the first sharded dispatch paid a GSPMD
reshard of every one of them (worse: donation aliasing only holds when
a donated input's sharding matches its carried output's, so an
unplaced pool silently COPIES on its first mesh dispatch instead of
being reused).

This module is the missing placement layer (ROADMAP item 2 — "the
millions-of-users scaling step"):

  * **Serving-mesh geometry** (:class:`ServeMeshSpec` /
    :func:`parse_serve_mesh` / :func:`build_serve_mesh`): a serving
    mesh is ``data x tensor`` (seq/stage axes stay 1 — ring/pipeline
    constructs do not apply to cached decode; ``fsdp`` may ride along
    as a second row axis).  ``run.py --serve-mesh dp,tp`` parses here.
  * **Canonical shardings** (:func:`pool_pspec` / :func:`row_pspec` /
    :func:`shard_pool` / :func:`place_rows`): the KV block pool shards
    its KV-head axis over ``tensor`` (each shard holds its heads'
    blocks — the same per-shard contents the paged kernel's manual
    sharding expects, so the kernel's ``shard_map`` never reshards);
    ``pos`` planes replicate (every row indexes them); per-slot state
    rows shard over the batch axes (``data``/``fsdp``).  The batcher
    places its pool, draft-pool and ``d_*`` twins through these at
    construction, and the chunk programs re-CONSTRAIN their outputs to
    the same specs (:func:`constrain_pool` / :func:`constrain_rows`) —
    input placement + output constraint is what makes donated-leaf
    aliasing STABLE under sharding (proven per-program by the
    PR-8 lowering auditor's mesh pass, ``analysis/lowering.py``).
  * **Sharded swap staging** (:func:`staging_shardings`): host-tier
    slabs restore through ``kvcache.stage_restore`` staging buffers
    placed with the pool's own specs, so ``adopt_into_pool``'s
    donated-pool scatter is shard-local (no cross-shard reshard on
    the adoption dispatch).  The radix prefix index itself stays
    host-global — one tree indexes the sharded pool, because block
    ids are global and every shard holds the same block GEOMETRY
    (only the KV-head slice differs).

Data parallelism ACROSS meshes — N independent batcher replicas, each
owning a mesh (slice), fronted by least-loaded/affinity routing and
the prefill/decode disaggregation handoff — lives one layer up in
``jax_llama_tpu/router.py``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import current_mesh, make_mesh

# Per-slot state rows shard over the batch axes — the same pair the
# model's `constrain` shards activation batch over and the paged
# kernel's shard_map shards rows over, so state never reshards between
# the program body and the op.
ROW_AXES = ("data", "fsdp")


@dataclasses.dataclass(frozen=True)
class ServeMeshSpec:
    """Serving-mesh geometry: ``data`` replicas-worth of row sharding
    INSIDE one batcher x ``tensor``-way model/KV sharding.  (Replica
    data-parallelism across batchers is the router's axis, not this
    one's.)"""

    data: int = 1
    tensor: int = 1

    @property
    def n_devices(self) -> int:
        return self.data * self.tensor

    def __post_init__(self):
        if self.data < 1 or self.tensor < 1:
            raise ValueError(
                f"serve mesh axes must be >= 1 (got data={self.data}, "
                f"tensor={self.tensor})"
            )


def parse_serve_mesh(text: str) -> ServeMeshSpec:
    """Parse run.py's ``--serve-mesh dp,tp`` (also accepts a bare
    ``tp``, sugar for ``1,tp``)."""
    parts = [p.strip() for p in str(text).split(",") if p.strip()]
    try:
        nums = [int(p) for p in parts]
    except ValueError:
        nums = []
    if len(nums) == 1:
        return ServeMeshSpec(data=1, tensor=nums[0])
    if len(nums) == 2:
        return ServeMeshSpec(data=nums[0], tensor=nums[1])
    raise ValueError(
        f"bad --serve-mesh {text!r}: expected 'dp,tp' (two positive "
        "ints, e.g. '2,4') or a bare 'tp'"
    )


def build_serve_mesh(
    spec: ServeMeshSpec,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Materialize the serving mesh on ``spec.n_devices`` devices
    (default: the first n of ``jax.devices()``)."""
    if devices is None:
        devices = jax.devices()[: spec.n_devices]
    if len(devices) != spec.n_devices:
        raise ValueError(
            f"serve mesh {spec.data}x{spec.tensor} needs "
            f"{spec.n_devices} devices, got {len(devices)}"
        )
    return make_mesh(data=spec.data, tensor=spec.tensor, devices=devices)


def is_serving_mesh(mesh: Optional[Mesh]) -> bool:
    """A mesh the serving placement layer covers: no seq/stage axes
    (ring/pipeline constructs do not apply to cached paged decode)."""
    return (
        mesh is not None
        and mesh.shape.get("seq", 1) == 1
        and mesh.shape.get("stage", 1) == 1
    )


def row_shards(mesh: Mesh) -> int:
    return int(np.prod([mesh.shape.get(a, 1) for a in ROW_AXES]))


def placement_ok(
    config, mesh: Optional[Mesh], n_slots: int, draft_config=None
) -> bool:
    """Whether the canonical sharded placement applies: a serving mesh
    whose tensor axis divides the KV heads (pool shards head-wise) and
    whose row axes divide ``n_slots``.  Meshes outside this envelope
    keep the legacy unplaced behavior (GSPMD still reshards them
    correctly through the gathered fallback — just without the
    placement guarantees)."""
    if not is_serving_mesh(mesh):
        return False
    tp = mesh.shape.get("tensor", 1)
    if config.kv_heads % tp or config.n_heads % tp:
        return False
    if draft_config is not None and draft_config.kv_heads % tp:
        return False
    return n_slots % row_shards(mesh) == 0


def validate_serve_mesh(
    config, mesh: Mesh, n_slots: int, draft_config=None
) -> None:
    """Hard-error version of :func:`placement_ok` for explicit
    ``--serve-mesh`` requests — a clear refusal at startup beats a
    silently unplaced mesh."""
    if not is_serving_mesh(mesh):
        raise ValueError(
            "serving mesh must not carry seq/stage axes "
            f"(got {dict(mesh.shape)})"
        )
    tp = mesh.shape.get("tensor", 1)
    if config.kv_heads % tp:
        raise ValueError(
            f"serve-mesh tensor={tp} must divide n_kv_heads="
            f"{config.kv_heads} (the KV pool shards head-wise)"
        )
    if config.n_heads % tp:
        raise ValueError(
            f"serve-mesh tensor={tp} must divide n_heads={config.n_heads}"
        )
    if draft_config is not None and draft_config.kv_heads % tp:
        raise ValueError(
            f"serve-mesh tensor={tp} must divide the draft model's "
            f"n_kv_heads={draft_config.kv_heads}"
        )
    rows = row_shards(mesh)
    if n_slots % rows:
        raise ValueError(
            f"serve-mesh row shards (data*fsdp={rows}) must divide "
            f"n_slots={n_slots}"
        )


def mesh_shape(mesh: Optional[Mesh]) -> Dict[str, int]:
    """The mesh's non-trivial axis sizes — the /metrics ``serve_mesh_*``
    gauges and /healthz ``replicas`` section read this."""
    if mesh is None:
        return {"data": 1, "tensor": 1, "devices": 1}
    return {
        "data": int(mesh.shape.get("data", 1))
        * int(mesh.shape.get("fsdp", 1)),
        "tensor": int(mesh.shape.get("tensor", 1)),
        "devices": int(np.prod(list(mesh.shape.values()))),
    }


# ---------------------------------------------------------------------------
# Canonical partition specs
# ---------------------------------------------------------------------------

def pool_pspec(name: str, ndim: int) -> P:
    """Spec for one BlockPool leaf (or its staged-restore twin, which
    shares the layout): k/v ``[L, KVH, NB, BLK, hd]`` and scales
    ``[L, KVH, NB, BLK]`` shard the KV-head axis over ``tensor``;
    ``pos`` planes ``[NB, BLK]`` replicate (every row's table indexes
    them; 2 ints per cache slot — replication is noise next to the KV
    bytes)."""
    if name.endswith("pos"):
        return P()
    return P(*((None, "tensor") + (None,) * (ndim - 2)))


def row_pspec(ndim: int) -> P:
    """Spec for one per-slot state leaf ``[B, ...]``: rows shard over
    the batch axes, trailing dims replicate."""
    return P(*((ROW_AXES,) + (None,) * (ndim - 1)))


def shard_pool(pool, mesh: Mesh):
    """Place a BlockPool's leaves with the canonical specs (ctor-time;
    the chunk programs' output constraints keep them there, so the
    donated pool aliases shard-local from the first dispatch on)."""
    def put(name):
        arr = getattr(pool, name)
        if arr is None:
            return None
        return jax.device_put(
            arr, NamedSharding(mesh, pool_pspec(name, arr.ndim))
        )

    return dataclasses.replace(
        pool,
        k=put("k"), v=put("v"), pos=put("pos"),
        k_scale=put("k_scale"), v_scale=put("v_scale"),
    )


def place_rows(mesh: Optional[Mesh], x) -> jax.Array:
    """Upload/replace one per-slot array with rows sharded over the
    mesh's batch axes; plain ``jnp.asarray`` semantics when no mesh."""
    import jax.numpy as jnp

    if mesh is None:
        return jnp.asarray(x)
    x = np.asarray(x) if not isinstance(x, jax.Array) else x
    return jax.device_put(x, NamedSharding(mesh, row_pspec(x.ndim)))


def staging_shardings(
    mesh: Optional[Mesh], slab_names: Sequence[str]
) -> Optional[Dict[str, Any]]:
    """Shardings for ``kvcache.stage_restore`` staging buffers: each
    staged field takes the pool leaf's own spec (the stacked block axis
    sits where NB does), so the adoption scatter lands shard-local —
    each tensor shard restores ITS head slice of the slab, no
    cross-shard reshard on the adopt dispatch.  ``ids`` replicates.
    None (no mesh) keeps default placement."""
    if mesh is None:
        return None
    out: Dict[str, Any] = {"ids": NamedSharding(mesh, P())}
    for name in slab_names:
        # Staged k/v: [L, KVH, nb, BLK(, hd)]; staged pos: [nb, BLK].
        ndim = 2 if name.endswith("pos") else (
            4 if name.endswith("_scale") else 5
        )
        out[name] = NamedSharding(mesh, pool_pspec(name, ndim))
    return out


# ---------------------------------------------------------------------------
# In-program output constraints (trace-time; no-op without a mesh)
# ---------------------------------------------------------------------------

def constraints_apply(kv_heads: int, n_rows: int) -> bool:
    """Trace-time guard for the output constraints: the ACTIVE mesh is
    a serving mesh the canonical placement covers (tensor divides the
    pool's KV heads, row axes divide the slot count).  Meshes outside
    the envelope — seq/stage axes, non-dividing tensor — keep the
    legacy propagation behavior; constraining there would be a
    lowering error, not a slow path."""
    mesh = current_mesh()
    if not is_serving_mesh(mesh):
        return False
    tp = mesh.shape.get("tensor", 1)
    return kv_heads % tp == 0 and n_rows % row_shards(mesh) == 0


def _constrain(x, spec: P):
    mesh = current_mesh()
    if mesh is None or x is None:
        return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def constrain_view(view):
    """Pin a gathered per-row cache view (``_gather_cache`` output:
    k/v ``[L, B, W, KVH, hd]``, scales ``[L, B, W, KVH]``, pos
    ``[B, W]``) to KV-heads-over-``tensor`` — the same head slice the
    pool itself shards — with rows over the batch axes when they
    divide.  Without this pin GSPMD is free to satisfy the gather by
    REPLICATING the source pool first: a full-pool all-gather inside
    every scan iteration (the silent reshard the comms-budget pass
    exists to catch), instead of the shard-local block gather the
    placement implies.  No-op when no serving mesh is active or the
    head axis does not divide."""
    mesh = current_mesh()
    if not is_serving_mesh(mesh):
        return view
    tp = mesh.shape.get("tensor", 1)
    kvh = int(view.k.shape[3])
    if tp == 1 or kvh % tp:
        return view
    rows = (
        ROW_AXES if int(view.k.shape[1]) % row_shards(mesh) == 0
        else None
    )
    spec_kv = P(None, rows, None, "tensor", None)
    spec_scale = P(None, rows, None, "tensor")
    return dataclasses.replace(
        view,
        k=_constrain(view.k, spec_kv),
        v=_constrain(view.v, spec_kv),
        pos=_constrain(view.pos, P(rows, None)),
        k_scale=(
            None if view.k_scale is None
            else _constrain(view.k_scale, spec_scale)
        ),
        v_scale=(
            None if view.v_scale is None
            else _constrain(view.v_scale, spec_scale)
        ),
    )


def constrain_pool(pool):
    """Pin a program's output pool to the canonical pool specs — called
    inside the jitted programs under ``use_mesh``, so the donated input
    pool (placed the same way at ctor) aliases instead of resharding.
    No-op when no mesh is active (the single-chip trace is unchanged)."""
    if current_mesh() is None:
        return pool
    return dataclasses.replace(
        pool,
        k=_constrain(pool.k, pool_pspec("k", pool.k.ndim)),
        v=_constrain(pool.v, pool_pspec("v", pool.v.ndim)),
        pos=_constrain(pool.pos, pool_pspec("pos", pool.pos.ndim)),
        k_scale=_constrain(
            pool.k_scale,
            None if pool.k_scale is None
            else pool_pspec("k_scale", pool.k_scale.ndim),
        ),
        v_scale=_constrain(
            pool.v_scale,
            None if pool.v_scale is None
            else pool_pspec("v_scale", pool.v_scale.ndim),
        ),
    )


def constrain_rows(*arrays) -> Tuple:
    """Pin per-slot state outputs (``[B, ...]`` leaves) to the
    canonical row sharding; identity without an active mesh."""
    if current_mesh() is None:
        return arrays
    return tuple(
        None if a is None else _constrain(a, row_pspec(a.ndim))
        for a in arrays
    )
