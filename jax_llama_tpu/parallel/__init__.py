from .mesh import (
    AXES,
    auto_mesh,
    constrain,
    current_mesh,
    logical_to_spec,
    make_mesh,
    use_mesh,
)
from .partition import (
    param_partition_specs,
    shard_params,
    validate_tp,
)
from .ring import ring_attention, ring_sdpa

__all__ = [
    "ring_attention",
    "ring_sdpa",
    "AXES",
    "auto_mesh",
    "constrain",
    "current_mesh",
    "logical_to_spec",
    "make_mesh",
    "use_mesh",
    "param_partition_specs",
    "shard_params",
    "validate_tp",
]
