from .mesh import (
    AXES,
    auto_mesh,
    constrain,
    current_mesh,
    logical_to_spec,
    make_mesh,
    use_mesh,
)
from .partition import (
    param_partition_specs,
    shard_abstract,
    shard_params,
    validate_tp,
)
from .pipeline import pipeline_blocks
from .ring import ring_attention, ring_sdpa
from . import distributed

__all__ = [
    "distributed",
    "pipeline_blocks",
    "shard_abstract",
    "ring_attention",
    "ring_sdpa",
    "AXES",
    "auto_mesh",
    "constrain",
    "current_mesh",
    "logical_to_spec",
    "make_mesh",
    "use_mesh",
    "param_partition_specs",
    "shard_params",
    "validate_tp",
]
