from .mesh import (
    AXES,
    auto_mesh,
    constrain,
    current_mesh,
    logical_to_spec,
    make_mesh,
    use_mesh,
)

__all__ = [
    "AXES",
    "auto_mesh",
    "constrain",
    "current_mesh",
    "logical_to_spec",
    "make_mesh",
    "use_mesh",
]
