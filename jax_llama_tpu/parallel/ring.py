"""Ring attention — sequence/context parallelism over the ``seq`` mesh axis.

The reference has no sequence parallelism at all (SURVEY.md §2.13b: full-
sequence attention with a materialized S×S mask, ``/root/reference/
jax_llama/model.py:154``) — its context length is capped by one device's
memory.  Here the sequence axis is sharded over the ``seq`` mesh axis and
attention runs as a ring: each device holds one KV shard, computes blockwise
attention of its local queries against the shard it currently holds while
accumulating online-softmax state (running max ``m``, denominator ``l``,
fp32 accumulator), then rotates the KV shard to its ring neighbor with
``lax.ppermute``.  After ``n`` steps every query has seen every key, no
device ever held more than ``S/n`` keys, and the rotation rides ICI
point-to-point links, overlapping with the local compute under XLA's
latency-hiding scheduler.

Within a rotation the shard is folded CHUNKWISE (``lax.scan`` over
fixed-size kv chunks with the same online-softmax update): peak per-device
attention memory is O(B·H·T_local·chunk), not O(T_local·S/n) — the
[B, H, T, S/n] probability tensor the first implementation materialized
per rotation is gone, which is what makes 32k+ contexts per shard real.
Each chunk update is ``jax.checkpoint``ed, so the backward pass recomputes
chunk probabilities instead of saving them (same recompute-not-store deal
as the Pallas flash backward).

Masking is positional (same contract as ``ops.attention.attention_bias`` /
the flash kernel): slot attendable iff ``kv_pos <= q_pos`` and
``kv_pos >= 0``.  Because masks derive from absolute positions carried with
the shards, causality is layout-independent — no zig-zag reordering games
are needed for correctness (contiguous sharding does leave the usual causal
load imbalance; acceptable at this stage).

Decode (``ring_decode``) does NOT rotate: the KV cache stays sharded over
``seq`` (each device owns S/n slots permanently) and the tiny [B, T]
queries are replicated; every device computes its shard's partial
online-softmax statistics and ONE pmax + two psums over ``seq`` combine
them exactly.  The step's own new tokens merge at the softmax level
afterwards (the ``sdpa_cached`` append-free contract), so the cache rides
the layer scan immutably and generation context is bounded by the MESH's
combined HBM, not one chip's.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..ops.attention import attention_bias, repeat_kv, sdpa
from ..ops.flash_attention import MASK_VALUE, _mix32, _normalize_seed
from .mesh import current_mesh, shard_map_compat

BATCH_AXES = ("data", "fsdp")

# kv-chunk length of the inner accumulation scan: MXU-friendly (multiple
# of 128 lanes) and small enough that [B, H, T_local, RING_CHUNK] fp32
# stays a rounding error next to the activations.
RING_CHUNK = 512


def dropout_base(seed, B, H, b_off, h_off):
    """Per-(global batch, global head) hash bases [2, B, H] uint32 — the
    same keying scheme as the flash kernels' ``_dropout_keep``: the
    64-bit seed's low word keys the row-side base plane and its high
    word the column-side plane, so a repeated mask plane needs BOTH
    32-bit bases to collide — a 64-bit birthday event, not the old
    single-word ~65k-step horizon.  Global indices are supplied by the
    caller so every device of a data/fsdp/tensor-sharded mesh draws an
    independent plane.  ``seed``: [2] uint32 (scalar / [1] legacy inputs
    widen with a zero high word, validated by ``_normalize_seed``)."""
    s = _normalize_seed(seed)
    gb = (
        jnp.asarray(b_off, jnp.uint32)
        + jnp.arange(B, dtype=jnp.uint32)[:, None]
    )
    gh = (
        jnp.asarray(h_off, jnp.uint32)
        + jnp.arange(H, dtype=jnp.uint32)[None, :]
    )
    plane = _mix32(
        gb * jnp.uint32(0x9E3779B9)
        + gh * jnp.uint32(0x85EBCA6B)
        + jnp.uint32(1)
    )
    return jnp.stack([
        _mix32(s[0] ^ plane),
        # Same lane constant as _dropout_keep: keeps the two bases
        # independent when the seed words coincide.
        _mix32(s[1] ^ plane ^ jnp.uint32(0x85EBCA6B)),
    ])


def dropout_keep(base, q_pos, kv_pos, rate):
    """Deterministic keep mask [B, H, T, C] for attention-probability
    dropout under ring attention.

    Keyed on ABSOLUTE (query position, kv position) — the coordinates
    that ride the shards — so the mask is a pure function of the global
    (row, column) pair and survives chunking, ring rotation, and any
    seq-mesh layout by construction (the property the flash kernels get
    from global tile indices).  Row and column enter the element hash
    jointly (xor of two independently mixed words), same rationale — and
    the same two-base seed split — as ``_dropout_keep``.
    base: [2, B, H] (``dropout_base``); q_pos: [B, T]; kv_pos: [B, C].
    """
    rows = q_pos.astype(jnp.uint32)[:, None, :, None]
    cols = kv_pos.astype(jnp.uint32)[:, None, None, :]
    bits = _mix32(
        _mix32(base[0][:, :, None, None] ^ rows)
        ^ _mix32(
            base[1][:, :, None, None] ^ (cols * jnp.uint32(0x9E3779B9))
        )
    )
    threshold = jnp.uint32(min(int(rate * 4294967296.0), 4294967295))
    return bits >= threshold


def _fold_chunk(qt, q_pos, kc, vc, pc, m, l, acc, *, scale,
                dropout_rate=0.0, drop_base=None):
    """Fold one kv chunk into the running online-softmax state.

    qt: [B, H, T, d]; kc, vc: [B, C, KVH, d]; pc: [B, C];
    m, l: [B, H, T] f32; acc: [B, H, T, d] f32.

    With ``dropout_rate`` > 0 the acc-side probabilities are
    inverted-dropout masked (``dropout_keep``) while ``l`` keeps the full
    sum — exactly dropout applied to the post-softmax weights w = p / l,
    the flash kernels' (and sdpa's) semantics, chunkwise.
    """
    group = qt.shape[1] // kc.shape[2]
    kr = repeat_kv(kc, group)  # [B, C, H, d]
    vr = repeat_kv(vc, group)
    s = jnp.einsum(
        "bhtd,bshd->bhts", qt, kr, preferred_element_type=jnp.float32
    ) * scale
    allowed = (pc[:, None, None, :] <= q_pos[:, None, :, None]) & (
        pc >= 0
    )[:, None, None, :]
    s = jnp.where(allowed, s, MASK_VALUE)

    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    alpha = jnp.exp(m - m_new)  # [B, H, T]
    p = jnp.exp(s - m_new[..., None])  # [B, H, T, C] f32
    l = alpha * l + jnp.sum(p, axis=-1)
    if dropout_rate > 0.0:
        keep = dropout_keep(drop_base, q_pos, pc, dropout_rate)
        p_acc = jnp.where(keep, p, 0.0) * (1.0 / (1.0 - dropout_rate))
    else:
        p_acc = p
    acc = alpha[..., None] * acc + jnp.einsum(
        "bhts,bshd->bhtd", p_acc.astype(vr.dtype), vr,
        preferred_element_type=jnp.float32,
    )
    return m_new, l, acc


def _accumulate(qt, q_pos, k, v, kv_pos, m, l, acc, *, scale,
                chunk: int = RING_CHUNK, dropout_rate=0.0, drop_base=None):
    """Fold one KV shard into the running state, chunk by chunk.

    Memory: O(B·H·T·chunk) per step of the scan (the dense predecessor
    held the full [B, H, T, S_shard] probability tensor).  Each chunk is
    rematerialized in the backward pass (jax.checkpoint), so residuals
    are O(S_shard·d), not O(T·S_shard).

    NB the FIRST chunk folded for a live query must contain an attendable
    slot before any fully-masked chunk can be skipped-by-zero: the ring
    starts with the query's own shard and positions ascend within it, so
    chunk 0 always contains the query's own slot — after which
    exp(MASK - finite m) underflows to exactly 0 for masked chunks.
    (Padding queries accumulate garbage that is masked downstream, same
    as the dense version.)
    """
    B, S = k.shape[0], k.shape[1]
    C = min(chunk, S)
    pad = (-S) % C
    if pad:
        widths = [(0, 0)] * k.ndim
        widths[1] = (0, pad)
        k = jnp.pad(k, widths)
        v = jnp.pad(v, widths)
        kv_pos = jnp.pad(kv_pos, ((0, 0), (0, pad)), constant_values=-1)
    nc = k.shape[1] // C

    def to_chunks(a):  # [B, nc*C, ...] -> [nc, B, C, ...]
        return jnp.moveaxis(
            a.reshape((a.shape[0], nc, C) + a.shape[2:]), 1, 0
        )

    @jax.checkpoint
    def body(carry, xs):
        m, l, acc = carry
        kc, vc, pc = xs
        # The dropout mask is a pure function of (base, positions), so the
        # checkpointed backward rebuilds it bit-identically for free.
        m, l, acc = _fold_chunk(
            qt, q_pos, kc, vc, pc, m, l, acc, scale=scale,
            dropout_rate=dropout_rate, drop_base=drop_base,
        )
        return (m, l, acc), None

    (m, l, acc), _ = lax.scan(
        body, (m, l, acc), (to_chunks(k), to_chunks(v), to_chunks(kv_pos))
    )
    return m, l, acc


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    q_pos: jnp.ndarray,
    kv_pos: jnp.ndarray,
    *,
    axis_name: str = "seq",
    axis_size: int,
    dropout_rate: float = 0.0,
    dropout_seed=None,
    b_off=0,
    h_off=0,
) -> jnp.ndarray:
    """Per-device body (call under shard_map): local q attends to all KV
    shards as they rotate around the ring.

    q: [B, T_local, H, d]; k, v: [B, S_local, KVH, d];
    q_pos: [B, T_local]; kv_pos: [B, S_local].  Returns [B, T_local, H, d].

    ``dropout_rate`` > 0 (training): attention-probability dropout via a
    position-keyed counter hash (``dropout_keep``) — the mask depends only
    on (seed, global batch/head, absolute row/column position), so it is
    identical for every chunking and every ring layout; ``b_off``/``h_off``
    are this device's global batch/head offsets (0 off-mesh).
    """
    B, T, H, d = q.shape
    scale = 1.0 / (d ** 0.5)
    qt = jnp.swapaxes(q, 1, 2)  # [B, H, T, d]
    m = jnp.full((B, H, T), MASK_VALUE, dtype=jnp.float32)
    l = jnp.zeros((B, H, T), dtype=jnp.float32)
    acc = jnp.zeros((B, H, T, d), dtype=jnp.float32)
    drop_base = (
        dropout_base(dropout_seed, B, H, b_off, h_off)
        if dropout_rate > 0.0 else None
    )

    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]

    def body(_, carry):
        k, v, kv_pos, m, l, acc = carry
        m, l, acc = _accumulate(
            qt, q_pos, k, v, kv_pos, m, l, acc, scale=scale,
            dropout_rate=dropout_rate, drop_base=drop_base,
        )
        k, v, kv_pos = (
            lax.ppermute(x, axis_name, perm) for x in (k, v, kv_pos)
        )
        return k, v, kv_pos, m, l, acc

    # n-1 rotations; the last shard is folded in without a trailing permute.
    # (axis_size 1: no rotation, no collective — the body is also valid
    # outside shard_map, which the 32k memory test exploits.)
    if axis_size > 1:
        k, v, kv_pos, m, l, acc = lax.fori_loop(
            0, axis_size - 1, body, (k, v, kv_pos, m, l, acc)
        )
    m, l, acc = _accumulate(
        qt, q_pos, k, v, kv_pos, m, l, acc, scale=scale,
        dropout_rate=dropout_rate, drop_base=drop_base,
    )

    out = acc / l[..., None]
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)  # [B, T, H, d]


def ring_sdpa(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    q_pos: jnp.ndarray,
    kv_pos: jnp.ndarray,
    *,
    axis_name: str = "seq",
    dropout_rng=None,
    dropout_rate: float = 0.0,
) -> jnp.ndarray:
    """Mesh-aware entry point: shard_map over the active mesh's ``seq`` axis
    (batch over data/fsdp, heads over tensor stay local per device).  Falls
    back to dense sdpa when no mesh is active or seq == 1.

    ``dropout_rng`` + ``dropout_rate`` > 0 enable attention-probability
    dropout (training).  On a seq > 1 mesh the mask is the position-keyed
    counter hash (``dropout_keep``) — sharding-layout-invariant; the
    seq == 1 fallback uses ``sdpa``'s jax.random mask (different draw,
    same distribution — masks are not required to match across meshes,
    only within one program's fwd/bwd, which both schemes guarantee).
    """
    mesh = current_mesh()
    n = mesh.shape.get(axis_name, 1) if mesh is not None else 1
    if n == 1:
        bias = attention_bias(q_pos, kv_pos, kv_pos >= 0)
        return sdpa(
            q, k, v, bias,
            dropout_rng=dropout_rng if dropout_rate > 0.0 else None,
            dropout_rate=dropout_rate,
        )

    with_drop = dropout_rng is not None and dropout_rate > 0.0
    B, _, H, _ = q.shape
    b_local = B
    for a in BATCH_AXES:
        b_local //= mesh.shape.get(a, 1)
    h_local = H // mesh.shape.get("tensor", 1)

    def body(q, k, v, q_pos, kv_pos, seed):
        if with_drop:
            # Global batch/head offsets of this device's shard — mesh
            # axes are all manual under shard_map, so axis_index is
            # available whether or not the axis is sharded here (0 when
            # the axis is absent from a custom mesh entirely).
            def _idx(a):
                return (
                    lax.axis_index(a) if a in mesh.axis_names
                    else jnp.zeros((), jnp.int32)
                )

            bi = _idx(BATCH_AXES[0]) * mesh.shape.get(
                BATCH_AXES[1], 1
            ) + _idx(BATCH_AXES[1])
            b_off = bi * b_local
            h_off = _idx("tensor") * h_local
        else:
            b_off = h_off = 0
        return ring_attention(
            q, k, v, q_pos, kv_pos, axis_name=axis_name, axis_size=n,
            dropout_rate=dropout_rate if with_drop else 0.0,
            dropout_seed=seed, b_off=b_off, h_off=h_off,
        )

    seed = (
        jax.random.bits(dropout_rng, (2,), "uint32")
        if with_drop else jnp.zeros((2,), jnp.uint32)
    )
    spec4 = P(BATCH_AXES, axis_name, "tensor", None)
    spec2 = P(BATCH_AXES, axis_name)
    # check_vma=False: the fori_loop carry starts from freshly-created
    # (device-invariant) accumulators and becomes device-varying after the
    # first ppermute, which the varying-manual-axes checker rejects even
    # though the program is correct.
    fn = shard_map_compat(
        body,
        mesh=mesh,
        in_specs=(spec4, spec4, spec4, spec2, spec2, P(None)),
        out_specs=spec4,
        check_vma=False,
    )
    return fn(q, k, v, q_pos, kv_pos, seed)


# ---------------------------------------------------------------------------
# Seq-sharded cached decode
# ---------------------------------------------------------------------------

def _scale_rows(sc: jnp.ndarray, group: int) -> jnp.ndarray:
    """Per-slot dequant scales [B, S, KVH] -> [B, H, 1, S] for folding
    into scores/probabilities (constant along d, so they commute with the
    attention contractions — the sdpa_cached trick, ring-sharded)."""
    scr = repeat_kv(sc[..., None], group)[..., 0]  # [B, S, H]
    return jnp.transpose(scr, (0, 2, 1))[:, :, None, :]


def _ring_decode_body(
    q, kc, vc, sp, kn, vn, qp, npos, *args, axis_name: str, scale: float,
    softmax_dtype, quantized: bool = False,
):
    """Per-device body: partial softmax over the LOCAL cache shard, exact
    combine over ``seq``, then the step's own new tokens merge at the
    softmax level (replicated arithmetic, no collective).

    q: [B, T, H, d]; kc, vc: [B, S_local, KVH, d] (int8 when quantized);
    sp: [B, S_local]; kn, vn: [B, T, KVH, d]; qp, npos: [B, T]; with
    ``quantized``, *args carries (k_scale, v_scale) [B, S_local, KVH] fp32
    local shards — folded at the scores/probability level, so the int8
    payload is never dequantized in memory (the new tokens merge at full
    precision, matching sdpa_cached's same-step treatment).
    """
    B, T, H, d = q.shape
    group = H // kc.shape[2]
    qt = jnp.swapaxes(q, 1, 2)  # [B, H, T, d]

    if quantized:
        k_scale, v_scale = args
        kc = kc.astype(q.dtype)
        vc = vc.astype(q.dtype)
    kr = repeat_kv(kc, group)
    vr = repeat_kv(vc, group)
    s = jnp.einsum(
        "bhtd,bshd->bhts", qt, kr, preferred_element_type=softmax_dtype
    ) * scale
    if quantized:
        s = s * _scale_rows(k_scale, group)
    allowed = (sp[:, None, None, :] <= qp[:, None, :, None]) & (
        sp >= 0
    )[:, None, None, :]
    s = jnp.where(allowed, s, MASK_VALUE)
    m_i = jnp.max(s, axis=-1)                      # [B, H, T]
    p = jnp.exp(s - m_i[..., None])
    p = jnp.where(allowed, p, 0.0)                 # all-masked shard: l_i = 0
    l_i = jnp.sum(p, axis=-1)
    if quantized:
        # v_scale folds into the (tiny) probabilities, AFTER l_i: the
        # denominator must sum the unscaled p.
        pv = (p * _scale_rows(v_scale, group)).astype(vr.dtype)
    else:
        pv = p.astype(vr.dtype)
    o_i = jnp.einsum(
        "bhts,bshd->bhtd", pv, vr,
        preferred_element_type=softmax_dtype,
    )

    if axis_name is None:
        # Single-shard (no mesh / seq == 1): the local stats are global.
        m, l, o = m_i, l_i, o_i
    else:
        # Exact combine across the seq shards: one pmax + two psums of
        # [B, H, T(, d)] — decode-sized, so the collectives are tiny.
        m = lax.pmax(m_i, axis_name)
        w = jnp.exp(m_i - m)
        l = lax.psum(l_i * w, axis_name)
        o = lax.psum(o_i * w[..., None], axis_name)

    # New-token merge (same two-source softmax split as sdpa_cached):
    # token t attends new slot j iff npos[j] <= qp[t] (and j valid).
    s_new = jnp.einsum(
        "bhtd,bjhd->bhtj", qt, repeat_kv(kn, group),
        preferred_element_type=softmax_dtype,
    ) * scale
    allowed_new = (
        npos[:, None, None, :] <= qp[:, None, :, None]
    ) & (npos >= 0)[:, None, None, :]
    s_new = jnp.where(allowed_new, s_new, MASK_VALUE)
    m_tot = jnp.maximum(m, jnp.max(s_new, axis=-1))
    p_new = jnp.exp(s_new - m_tot[..., None])
    p_new = jnp.where(allowed_new, p_new, 0.0)
    w_old = jnp.exp(m - m_tot)
    denom = l * w_old + jnp.sum(p_new, axis=-1)
    denom = jnp.where(denom == 0.0, 1.0, denom)
    out = (
        o * (w_old / denom)[..., None]
        + jnp.einsum(
            "bhtj,bjhd->bhtd", p_new.astype(vn.dtype), repeat_kv(vn, group),
            preferred_element_type=softmax_dtype,
        ) / denom[..., None]
    )
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


def ring_decode(
    q: jnp.ndarray,        # [B, T, H, d] — this step's queries
    k_cache: jnp.ndarray,  # [B, S, KVH, d] — seq-sharded KV cache (layer)
    v_cache: jnp.ndarray,
    slot_pos: jnp.ndarray,  # [B, S] int32 (-1 = invalid slot)
    k_new: jnp.ndarray,    # [B, T, KVH, d] — this step's projections
    v_new: jnp.ndarray,
    q_pos: jnp.ndarray,    # [B, T] query positions (clamped >= 0)
    new_pos: jnp.ndarray,  # [B, T] new-slot positions (-1 = padding)
    *,
    softmax_dtype=jnp.float32,
    axis_name: str = "seq",
    k_scale: Optional[jnp.ndarray] = None,  # [B, S, KVH] fp32 (int8 cache)
    v_scale: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Cached decode over a KV cache sharded along S over the ``seq`` mesh
    axis: generation context is bounded by the mesh's combined HBM.

    The cache never moves: each device reduces its own shard and the
    partial softmax statistics combine with one pmax + two psums of
    decode-sized tensors.  The cache stays immutable through the layer
    scan; the caller lands the new K/V afterwards (the ``sdpa_cached``
    append-free contract — so this is the drop-in seq>1 counterpart of
    the xla decode path).  S must be divisible by the seq axis size.

    int8 caches pass ``k_scale``/``v_scale`` per-slot dequant planes; the
    scales shard along S with the payload and fold at the scores /
    probability level per shard (``k_new``/``v_new`` stay full-precision —
    same-step tokens merge unquantized, like sdpa_cached).
    """
    mesh = current_mesh()
    n = mesh.shape.get(axis_name, 1) if mesh is not None else 1
    scale = 1.0 / (q.shape[-1] ** 0.5)
    quantized = k_scale is not None
    scale_ops = (k_scale, v_scale) if quantized else ()
    if n == 1:
        return _ring_decode_body(
            q, k_cache, v_cache, slot_pos, k_new, v_new, q_pos, new_pos,
            *scale_ops,
            axis_name=None, scale=scale, softmax_dtype=softmax_dtype,
            quantized=quantized,
        )

    head4 = P(BATCH_AXES, None, "tensor", None)
    cache4 = P(BATCH_AXES, axis_name, "tensor", None)
    scale3 = P(BATCH_AXES, axis_name, "tensor")
    fn = shard_map_compat(
        functools.partial(
            _ring_decode_body, axis_name=axis_name, scale=scale,
            softmax_dtype=softmax_dtype, quantized=quantized,
        ),
        mesh=mesh,
        in_specs=(
            head4, cache4, cache4, P(BATCH_AXES, axis_name), head4, head4,
            P(BATCH_AXES, None), P(BATCH_AXES, None),
        ) + ((scale3, scale3) if quantized else ()),
        out_specs=head4,
        check_vma=False,
    )
    return fn(
        q, k_cache, v_cache, slot_pos, k_new, v_new, q_pos, new_pos,
        *scale_ops,
    )
