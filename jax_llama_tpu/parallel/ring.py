"""Ring attention — sequence/context parallelism over the ``seq`` mesh axis.

The reference has no sequence parallelism at all (SURVEY.md §2.13b: full-
sequence attention with a materialized S×S mask, ``/root/reference/
jax_llama/model.py:154``) — its context length is capped by one device's
memory.  Here the sequence axis is sharded over the ``seq`` mesh axis and
attention runs as a ring: each device holds one KV shard, computes blockwise
attention of its local queries against the shard it currently holds while
accumulating online-softmax state (running max ``m``, denominator ``l``,
fp32 accumulator), then rotates the KV shard to its ring neighbor with
``lax.ppermute``.  After ``n`` steps every query has seen every key, no
device ever held more than ``S/n`` keys, and the rotation rides ICI
point-to-point links, overlapping with the local compute under XLA's
latency-hiding scheduler.

Masking is positional (same contract as ``ops.attention.attention_bias`` /
the flash kernel): slot attendable iff ``kv_pos <= q_pos`` and
``kv_pos >= 0``.  Because masks derive from absolute positions carried with
the shards, causality is layout-independent — no zig-zag reordering games
are needed for correctness (contiguous sharding does leave the usual causal
load imbalance; acceptable at this stage).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..ops.attention import attention_bias, repeat_kv, sdpa
from ..ops.flash_attention import MASK_VALUE
from .mesh import current_mesh

BATCH_AXES = ("data", "fsdp")


def _accumulate(qt, q_pos, k, v, kv_pos, m, l, acc, *, scale):
    """Fold one KV shard into the running online-softmax state.

    qt: [B, H, T, d]; k, v: [B, S, KVH, d]; m, l: [B, H, T] f32;
    acc: [B, H, T, d] f32.
    """
    group = qt.shape[1] // k.shape[2]
    kr = repeat_kv(k, group)  # [B, S, H, d]
    vr = repeat_kv(v, group)
    s = jnp.einsum(
        "bhtd,bshd->bhts", qt, kr, preferred_element_type=jnp.float32
    ) * scale
    allowed = (kv_pos[:, None, None, :] <= q_pos[:, None, :, None]) & (
        kv_pos >= 0
    )[:, None, None, :]
    s = jnp.where(allowed, s, MASK_VALUE)

    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    alpha = jnp.exp(m - m_new)  # [B, H, T]
    p = jnp.exp(s - m_new[..., None])  # [B, H, T, S] f32
    l = alpha * l + jnp.sum(p, axis=-1)
    acc = alpha[..., None] * acc + jnp.einsum(
        "bhts,bshd->bhtd", p.astype(vr.dtype), vr,
        preferred_element_type=jnp.float32,
    )
    return m_new, l, acc


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    q_pos: jnp.ndarray,
    kv_pos: jnp.ndarray,
    *,
    axis_name: str = "seq",
    axis_size: int,
) -> jnp.ndarray:
    """Per-device body (call under shard_map): local q attends to all KV
    shards as they rotate around the ring.

    q: [B, T_local, H, d]; k, v: [B, S_local, KVH, d];
    q_pos: [B, T_local]; kv_pos: [B, S_local].  Returns [B, T_local, H, d].
    """
    B, T, H, d = q.shape
    scale = 1.0 / (d ** 0.5)
    qt = jnp.swapaxes(q, 1, 2)  # [B, H, T, d]
    m = jnp.full((B, H, T), MASK_VALUE, dtype=jnp.float32)
    l = jnp.zeros((B, H, T), dtype=jnp.float32)
    acc = jnp.zeros((B, H, T, d), dtype=jnp.float32)

    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]

    def body(_, carry):
        k, v, kv_pos, m, l, acc = carry
        m, l, acc = _accumulate(
            qt, q_pos, k, v, kv_pos, m, l, acc, scale=scale
        )
        k, v, kv_pos = (
            lax.ppermute(x, axis_name, perm) for x in (k, v, kv_pos)
        )
        return k, v, kv_pos, m, l, acc

    # n-1 rotations; the last shard is folded in without a trailing permute.
    k, v, kv_pos, m, l, acc = lax.fori_loop(
        0, axis_size - 1, body, (k, v, kv_pos, m, l, acc)
    )
    m, l, acc = _accumulate(qt, q_pos, k, v, kv_pos, m, l, acc, scale=scale)

    out = acc / l[..., None]
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)  # [B, T, H, d]


def ring_sdpa(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    q_pos: jnp.ndarray,
    kv_pos: jnp.ndarray,
    *,
    axis_name: str = "seq",
) -> jnp.ndarray:
    """Mesh-aware entry point: shard_map over the active mesh's ``seq`` axis
    (batch over data/fsdp, heads over tensor stay local per device).  Falls
    back to dense sdpa when no mesh is active or seq == 1.
    """
    mesh = current_mesh()
    n = mesh.shape.get(axis_name, 1) if mesh is not None else 1
    if n == 1:
        bias = attention_bias(q_pos, kv_pos, kv_pos >= 0)
        return sdpa(q, k, v, bias)

    spec4 = P(BATCH_AXES, axis_name, "tensor", None)
    spec2 = P(BATCH_AXES, axis_name)
    # check_vma=False: the fori_loop carry starts from freshly-created
    # (device-invariant) accumulators and becomes device-varying after the
    # first ppermute, which the varying-manual-axes checker rejects even
    # though the program is correct.
    fn = jax.shard_map(
        functools.partial(ring_attention, axis_name=axis_name, axis_size=n),
        mesh=mesh,
        in_specs=(spec4, spec4, spec4, spec2, spec2),
        out_specs=spec4,
        check_vma=False,
    )
    return fn(q, k, v, q_pos, kv_pos)
