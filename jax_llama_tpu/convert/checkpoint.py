"""Orbax checkpoint save/restore with sharding-aware loading.

The reference has **no** checkpoint-save path at all — it re-runs the torch
conversion into host RAM on every process start (SURVEY.md §5
"Checkpoint/resume": load-only, convert.sh broken).  Here conversion is a
one-time offline step; serving restores directly from an Orbax checkpoint,
and when a mesh is given each host reads only the shards it owns
(``ocp.StandardCheckpointer`` + sharded abstract tree), so a 70B restore
never materializes the full model on one host.

Layout on disk:
    <dir>/params/...     Orbax tree of arrays
    <dir>/config.json    LLaMAConfig fields
    <dir>/manifest.json  per-file sha256 + size, verified on restore

Saves are ATOMIC: the checkpoint is assembled in a temp sibling
directory and renamed into place, so a crash mid-save never leaves a
half-written tree at the target path (a pre-existing checkpoint is
swapped aside and removed only after the new tree has landed).  The
manifest is written over the finished tree at save time; restore
verifies every listed file's size and sha256 first, so a truncated or
bit-flipped shard fails loudly before serving starts instead of
surfacing as silent garbage logits.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import os
import shutil
import tempfile
from pathlib import Path
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import orbax.checkpoint as ocp
from jax.sharding import Mesh, NamedSharding

from ..config import LLaMAConfig
from ..models.llama import init_params
from ..ops.quant import is_quantized, quantize_params

MANIFEST_NAME = "manifest.json"


def _sha256_file(path: Path) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _write_manifest(root: Path) -> None:
    """Record every file under ``root`` (sha256 + byte size), manifest
    excluded, keyed by POSIX-relative path."""
    files: Dict[str, Dict[str, Any]] = {}
    for p in sorted(root.rglob("*")):
        if p.is_file() and p.name != MANIFEST_NAME:
            files[p.relative_to(root).as_posix()] = {
                "sha256": _sha256_file(p),
                "bytes": p.stat().st_size,
            }
    with open(root / MANIFEST_NAME, "w") as f:
        json.dump({"version": 1, "files": files}, f, indent=2)


def verify_manifest(path: str) -> bool:
    """Verify every manifest-listed file's existence, size, and sha256.

    Returns False (nothing to verify) for pre-manifest checkpoints;
    raises ValueError naming every bad shard otherwise.  Size is checked
    before hashing so plain truncation is reported as truncation, not as
    a hash mismatch.
    """
    root = Path(path).absolute()
    mf = root / MANIFEST_NAME
    if not mf.exists():
        return False
    with open(mf) as f:
        manifest = json.load(f)
    errors = []
    for rel, want in manifest.get("files", {}).items():
        p = root / rel
        if not p.is_file():
            errors.append(f"{rel}: missing")
            continue
        size = p.stat().st_size
        if size != want["bytes"]:
            errors.append(
                f"{rel}: truncated/resized ({size} bytes, "
                f"recorded {want['bytes']})"
            )
            continue
        if _sha256_file(p) != want["sha256"]:
            errors.append(f"{rel}: sha256 mismatch (corrupted shard)")
    if errors:
        raise ValueError(
            f"checkpoint {root} failed integrity verification — "
            "refusing to restore corrupt weights: " + "; ".join(errors)
        )
    return True


def _promote(tmp: Path, path: Path) -> None:
    """Rename the finished tree into place — atomic when ``path`` does
    not exist; otherwise the old checkpoint is swapped aside first and
    removed only after the new tree has landed, so no crash point
    leaves ``path`` holding a partial tree (worst case: ``path``
    briefly absent with the old tree intact in a ``.trash`` sibling)."""
    if path.exists():
        trash = path.parent / f".{path.name}.trash-{os.getpid()}"
        if trash.exists():
            shutil.rmtree(trash)
        os.rename(path, trash)
        os.rename(tmp, path)
        shutil.rmtree(trash)
    else:
        os.rename(tmp, path)


def _atomic_save(path: Path, write: Callable[[Path], None]) -> None:
    """Assemble a checkpoint via ``write(tmp_dir)`` then promote it
    into ``path`` (see ``_promote``).

    Multi-process programs (jax.process_count() > 1, shared storage —
    the only topology Orbax multi-host saves support) must all hand
    Orbax the SAME directory, so the temp dir name is deterministic
    there; process 0 clears any stale one, every process syncs before
    writing and after Orbax finishes, and only process 0 hashes the
    manifest and performs the rename.  Single-process saves use a
    random temp dir (no collision with a concurrent saver) and clean it
    up on failure."""
    multi = jax.process_count() > 1
    path.parent.mkdir(parents=True, exist_ok=True)
    if multi:
        from jax.experimental import multihost_utils

        tmp = path.parent / f".{path.name}.tmp-save"
        if jax.process_index() == 0 and tmp.exists():
            shutil.rmtree(tmp)
        multihost_utils.sync_global_devices(f"ckpt-clear:{path.name}")
        tmp.mkdir(exist_ok=True)
    else:
        tmp = Path(tempfile.mkdtemp(
            prefix=f".{path.name}.tmp-", dir=path.parent
        ))
        # mkdtemp creates 0700 (private), and _promote's rename would
        # keep that — restore umask-default perms so a checkpoint saved
        # by one user stays restorable by another on shared storage
        # (matching the old path.mkdir behavior).
        um = os.umask(0)
        os.umask(um)
        os.chmod(tmp, 0o777 & ~um)
    try:
        write(tmp)
        if multi:
            multihost_utils.sync_global_devices(
                f"ckpt-written:{path.name}"
            )
        if jax.process_index() == 0:
            _write_manifest(tmp)
            _promote(tmp, path)
        if multi:
            multihost_utils.sync_global_devices(
                f"ckpt-promoted:{path.name}"
            )
    except BaseException:
        if not multi:
            shutil.rmtree(tmp, ignore_errors=True)
        raise


def save_checkpoint(path: str, params: Any, config: LLaMAConfig) -> None:
    """Write params + config to `path` — atomically, with an integrity
    manifest (module docstring).

    Quantized trees (``quantize_params`` output) round-trip: a marker in
    config.json tells ``load_checkpoint`` to build the matching abstract
    tree on restore.
    """
    final = Path(path).absolute()
    meta = dict(dataclasses.asdict(config), _quantized=is_quantized(params))

    def write(tmp: Path) -> None:
        with open(tmp / "config.json", "w") as f:
            json.dump(meta, f, indent=2)
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(tmp / "params", params, force=True)
        ckptr.wait_until_finished()

    _atomic_save(final, write)


def load_config(path: str) -> Tuple[LLaMAConfig, bool]:
    config, quantized, is_train = _load_meta(path)
    return config, quantized


def _load_meta(path: str) -> Tuple[LLaMAConfig, bool, bool]:
    with open(Path(path) / "config.json") as f:
        meta = json.load(f)
    quantized = meta.pop("_quantized", False)
    is_train = meta.pop("_train_state", False)
    return LLaMAConfig(**meta), quantized, is_train


def load_checkpoint(
    path: str,
    mesh: Optional[Mesh] = None,
    *,
    fsdp: bool = False,
    verify: bool = True,
) -> Tuple[Any, LLaMAConfig]:
    """Restore (params, config).

    With ``mesh``: arrays are restored directly into their NamedSharding —
    per-host partial reads, no full-model host copy (this replaces the
    reference's convert-into-RAM-then-device_put startup, jax_example.py:
    21-26).  Without: plain host restore.

    ``verify`` (default True) checks the integrity manifest first — a
    truncated/corrupted shard raises before serving starts.  It re-reads
    every checkpoint byte to hash it; pass ``verify=False`` when restore
    I/O dominates startup and the storage layer already guarantees
    integrity.  Pre-manifest checkpoints skip the check silently.
    """
    path = Path(path).absolute()
    if verify:
        verify_manifest(path)
    config, quantized, is_train = _load_meta(path)
    if is_train:
        raise ValueError(
            f"{path} is a training checkpoint (params + optimizer state); "
            "restore it with load_train_state, or save serving weights "
            "with save_checkpoint(state.params, ...)"
        )

    def build():
        params = init_params(jax.random.PRNGKey(0), config)
        return quantize_params(params) if quantized else params

    shapes = jax.eval_shape(build)
    if mesh is not None:
        from ..parallel.partition import shard_abstract

        abstract = shard_abstract(shapes, mesh, config, fsdp=fsdp)
    else:
        abstract = shapes
    ckptr = ocp.StandardCheckpointer()
    layout = _saved_layout(ckptr, path / "params", config)
    if layout != "current":
        params = _restore_old_layout(
            ckptr, path, config, quantized, mesh, fsdp, layout
        )
    else:
        # Current layout (or metadata unavailable): restore directly,
        # letting any real failure (truncated files, version mismatch,
        # OOM) propagate as itself — a restore error must never be
        # mis-diagnosed as "old layout".
        params = ckptr.restore(path / "params", abstract)
    return params, config


def _saved_layout(ckptr, item_path: Path, config: LLaMAConfig) -> str:
    """Which param layout the checkpoint was saved in, decided from its
    own tree metadata (cheap — no array reads): "separate" (rounds 1-2
    q/k/v/gate/up), "d_first" (the r3 fused layout with the contracted D
    axis leading), or "current".  Unreadable metadata counts as current.
    """
    try:
        md = ckptr.metadata(item_path)
        # Orbax version skew: .metadata() has returned an object with
        # .item_metadata.tree, an object with .tree, and (current image)
        # the raw tree dict itself.  Accept all three shapes.
        tree = getattr(md, "item_metadata", md)
        tree = getattr(tree, "tree", tree)
        layers = tree.get("layers", {})
        if "q" in layers and "qkv" not in layers:
            return "separate"
        qkv_md = layers["qkv"]
        if isinstance(qkv_md, dict):  # QuantizedTensor: {q, scale} subtree
            qkv_md = qkv_md["q"]
        qkv_shape = tuple(qkv_md.shape)
    except Exception as e:
        # Fall back to "current", but say so: if the checkpoint really is
        # a legacy layout whose metadata read transiently failed, the
        # restore below will die with an Orbax shape mismatch — this line
        # is what points the reader at the metadata problem instead of at
        # a "corrupt checkpoint".
        logging.getLogger(__name__).warning(
            "checkpoint layout detection skipped (metadata read failed: "
            "%s: %s); assuming current layout — if restore now fails "
            "with a shape mismatch, the checkpoint may be a legacy "
            "layout whose metadata could not be read",
            type(e).__name__,
            e,
        )
        return "current"
    if len(qkv_shape) == 5 and qkv_shape[1] == config.dim:
        return "d_first"
    return "current"


def _to_d_first(lp: dict) -> dict:
    from ..models.llama import permute_d_axis

    return permute_d_axis(lp, to_d_first=True)


def _from_d_first(lp: dict) -> dict:
    from ..models.llama import permute_d_axis

    return permute_d_axis(lp, to_d_first=False)


def _old_layout_shapes(config: LLaMAConfig, layout: str, quantized: bool) -> Any:
    """Abstract param tree in a historical layout: "separate" (rounds 1-2
    q/k/v + gate/up) or "d_first" (r3 fused, D leading)."""
    from ..models.llama import split_qkv
    from ..ops.quant import quantize_params

    def build():
        params = init_params(jax.random.PRNGKey(0), config)
        if quantized:
            params = quantize_params(params)
        lp = dict(params["layers"])
        if layout == "d_first":
            lp = _to_d_first(lp)
        else:
            q, k, v = split_qkv(lp.pop("qkv"))
            gate_up = lp.pop("gate_up")
            lp.update(
                q=q, k=k, v=v, gate=gate_up[:, 0], up=gate_up[:, 1]
            )
        out = dict(params)
        out["layers"] = lp
        return out

    return jax.eval_shape(build)


def _restore_old_layout(ckptr, path, config, quantized, mesh, fsdp, layout):
    """Fallback for checkpoints saved in a historical layout: restore the
    old tree on host, migrate, then shard onto the mesh if one was given.

    The d_first→current migration is a pure axis permutation, exact for
    full-precision AND int8 trees (payload and scale permute together).
    Quantized SEPARATE-layout checkpoints (rounds 1-2) are refused:
    fusing them needs a quantized fuse_qkv (feature permutation + slot
    concat on payload and scales) that is not implemented — re-quantize
    from the full-precision source instead."""
    from ..models.llama import fuse_params

    if quantized and layout != "d_first":
        raise ValueError(
            f"{path} is an int8-quantized checkpoint in the old separate "
            "q/k/v layout; migrating it is not implemented — re-quantize "
            "from the full-precision checkpoint with quantize_params and "
            "save again"
        )
    old = ckptr.restore(
        path / "params", _old_layout_shapes(config, layout, quantized)
    )
    if layout == "d_first":
        params = dict(old)
        params["layers"] = _from_d_first(old["layers"])
    else:
        params = fuse_params(old)
    if mesh is not None:
        from ..parallel.partition import shard_params

        params = shard_params(params, mesh, config, fsdp=fsdp)
    return params


# ---------------------------------------------------------------------------
# Training checkpoint / resume
# ---------------------------------------------------------------------------

def save_train_state(path: str, state: Any, config: LLaMAConfig) -> None:
    """Write a full TrainState (params + optimizer state + step) + config.

    The reference cannot resume anything (SURVEY.md §5: checkpointing is
    load-only and its convert CLI is broken); this is the training half of
    the checkpoint story: crash-safe resume with optimizer moments intact.
    Atomic + manifest-verified like ``save_checkpoint`` — a periodic
    save that crashes mid-write must never destroy the previous good
    resume point.
    """
    final = Path(path).absolute()
    meta = dict(dataclasses.asdict(config), _train_state=True)

    def write(tmp: Path) -> None:
        with open(tmp / "config.json", "w") as f:
            json.dump(meta, f, indent=2)
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(tmp / "state", state, force=True)
        ckptr.wait_until_finished()

    _atomic_save(final, write)


def _suffix_sharding_tree(abstract: Any, abstract_params: Any, mesh: Mesh) -> Any:
    """Assign shardings to an arbitrary state tree by param-path suffix.

    Optimizer moments (Adam mu/nu) are param-shaped subtrees nested inside
    optax's state tuples; their leaf paths END with the corresponding param
    path (e.g. ``(..., 'mu', 'layers', 'q')``).  Each state leaf whose path
    suffix + shape matches a param leaf inherits that param's sharding;
    everything else (counts, scalars) is replicated.
    """
    from jax.sharding import PartitionSpec as P

    param_leaves = [
        (tuple(_key_str(k) for k in kp), leaf.sharding, leaf.shape)
        for kp, leaf in jax.tree_util.tree_leaves_with_path(abstract_params)
    ]
    replicated = NamedSharding(mesh, P())

    def assign(kp, leaf):
        path = tuple(_key_str(k) for k in kp)
        for ppath, sharding, shape in param_leaves:
            if len(path) >= len(ppath) and path[-len(ppath):] == ppath \
                    and leaf.shape == shape:
                return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype,
                                            sharding=sharding)
        return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype,
                                    sharding=replicated)

    return jax.tree_util.tree_map_with_path(assign, abstract)


def _key_str(k) -> str:
    if hasattr(k, "key"):
        return str(k.key)
    if hasattr(k, "name"):
        return str(k.name)
    if hasattr(k, "idx"):
        return str(k.idx)
    return str(k)


def load_train_state(
    path: str,
    optimizer: Any,
    mesh: Optional[Mesh] = None,
    *,
    fsdp: bool = False,
    verify: bool = True,
) -> Tuple[Any, LLaMAConfig]:
    """Restore (TrainState, config) for training resume.

    With ``mesh``: params and the param-shaped optimizer moments restore
    straight into their NamedShardings (per-host partial reads); scalar
    state (step, Adam count) is replicated.  ``verify`` as in
    ``load_checkpoint``.
    """
    from ..train import init_train_state

    path = Path(path).absolute()
    if verify:
        verify_manifest(path)
    config, _, is_train = _load_meta(path)
    if not is_train:
        raise ValueError(
            f"{path} is a serving checkpoint (params only); restore it "
            "with load_checkpoint"
        )

    shapes = jax.eval_shape(
        lambda: init_train_state(
            init_params(jax.random.PRNGKey(0), config), optimizer
        )
    )
    if mesh is not None:
        from ..parallel.partition import shard_abstract

        param_shapes = jax.eval_shape(
            lambda: init_params(jax.random.PRNGKey(0), config)
        )
        abstract_params = shard_abstract(param_shapes, mesh, config, fsdp=fsdp)
        abstract = _suffix_sharding_tree(shapes, abstract_params, mesh)
    else:
        abstract = shapes
    ckptr = ocp.StandardCheckpointer()
    state = ckptr.restore(path / "state", abstract)
    return state, config
