"""Orbax checkpoint save/restore with sharding-aware loading.

The reference has **no** checkpoint-save path at all — it re-runs the torch
conversion into host RAM on every process start (SURVEY.md §5
"Checkpoint/resume": load-only, convert.sh broken).  Here conversion is a
one-time offline step; serving restores directly from an Orbax checkpoint,
and when a mesh is given each host reads only the shards it owns
(``ocp.StandardCheckpointer`` + sharded abstract tree), so a 70B restore
never materializes the full model on one host.

Layout on disk:
    <dir>/params/...   Orbax tree of arrays
    <dir>/config.json  LLaMAConfig fields
"""

from __future__ import annotations

import dataclasses
import json
from pathlib import Path
from typing import Any, Optional, Tuple

import jax
import orbax.checkpoint as ocp
from jax.sharding import Mesh, NamedSharding

from ..config import LLaMAConfig
from ..models.llama import init_params
from ..ops.quant import is_quantized, quantize_params
from ..parallel.partition import param_partition_specs


def save_checkpoint(path: str, params: Any, config: LLaMAConfig) -> None:
    """Write params + config to `path` (created if needed).

    Quantized trees (``quantize_params`` output) round-trip: a marker in
    config.json tells ``load_checkpoint`` to build the matching abstract
    tree on restore.
    """
    path = Path(path).absolute()
    path.mkdir(parents=True, exist_ok=True)
    meta = dict(dataclasses.asdict(config), _quantized=is_quantized(params))
    with open(path / "config.json", "w") as f:
        json.dump(meta, f, indent=2)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path / "params", params, force=True)
    ckptr.wait_until_finished()


def load_config(path: str) -> Tuple[LLaMAConfig, bool]:
    with open(Path(path) / "config.json") as f:
        meta = json.load(f)
    quantized = meta.pop("_quantized", False)
    return LLaMAConfig(**meta), quantized


def load_checkpoint(
    path: str,
    mesh: Optional[Mesh] = None,
    *,
    fsdp: bool = False,
) -> Tuple[Any, LLaMAConfig]:
    """Restore (params, config).

    With ``mesh``: arrays are restored directly into their NamedSharding —
    per-host partial reads, no full-model host copy (this replaces the
    reference's convert-into-RAM-then-device_put startup, jax_example.py:
    21-26).  Without: plain host restore.
    """
    path = Path(path).absolute()
    config, quantized = load_config(path)

    def build():
        params = init_params(jax.random.PRNGKey(0), config)
        return quantize_params(params) if quantized else params

    shapes = jax.eval_shape(build)
    if mesh is not None:
        from ..parallel.partition import shard_abstract

        abstract = shard_abstract(shapes, mesh, config, fsdp=fsdp)
    else:
        abstract = shapes
    ckptr = ocp.StandardCheckpointer()
    params = ckptr.restore(path / "params", abstract)
    return params, config
