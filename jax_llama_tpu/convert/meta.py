"""Meta checkpoint converter: torch ``consolidated.NN.pth`` → param pytree.

Capability parity with the reference converter (``/root/reference/jax_llama/
convert_weights.py:52-92``), same tensor mapping contract:

  Meta tensor (torch [out, in])        shard axis  →  this framework
  ----------------------------------   ----------     ------------------------
  tok_embeddings.weight  [V, D]        1 (D)          embed.embedding [V, D]
  layers.N.attention.wq  [H*hd, D]     0              layers.qkv[:, :, :G]
  layers.N.attention.wk  [KVH*hd, D]   0              layers.qkv[:, :, G]
  layers.N.attention.wv  [KVH*hd, D]   0              layers.qkv[:, :, G+1]
                                       (qkv is the fused
                                        [L, KVH, G+2, D, hd] decode layout,
                                        G = H // KVH, D second-from-last —
                                        the scan-slice layout contract; see
                                        models.llama.fuse_qkv)
  layers.N.attention.wo  [D, H*hd]     1              layers.o  [L, H, hd, D]
  layers.N.feed_forward.w1 [F, D]      0              layers.gate_up[:, 0]
  layers.N.feed_forward.w3 [F, D]      0              layers.gate_up[:, 1]
  layers.N.feed_forward.w2 [D, F]      1              layers.down [L, F, D]
  layers.N.attention_norm / ffn_norm   replicated     layers.attn_norm/mlp_norm
  norm.weight                          replicated     final_norm
  output.weight          [V, D]        0              lm_head [D, V]
                                                      (absent → tied embeddings)

Column-parallel weights (wq/wk/wv/w1/w3/output) concatenate along torch
axis 0; row-parallel (wo/w2) and the embedding along axis 1; linear kernels
transpose from torch [out, in] to [in, out].  Meta's head ORDER is kept
(query head h = kvh*G + g, no head permutation — unlike HF-format
checkpoints), but the q/k head_dim FEATURES are permuted from Meta's
interleaved RoPE pairing to the runtime half-split order
(``models.llama.rope_permute``; ``split_qkv`` inverts it exactly).

TPU-first differences from the reference:
  * Shards are opened with ``mmap=True`` and tensors are consumed
    (popped) one at a time, so peak host RAM is ~one full tensor set, not
    the reference's two full fp32 copies (SURVEY.md §3.1 hot spot).
  * Output dtype is configurable (bf16 by default for TPU serving); the
    converted tree is the scan-stacked layout, ready for `shard_params` or
    Orbax serialization.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ..config import LLaMAConfig
from ..models.llama import rope_permute


def _load_shards(ckpt_dir: str):
    """Load all ``*.pth`` shard state-dicts, ordered by shard index
    (``consolidated.00.pth`` …), mmap'd where torch supports it."""
    import torch

    paths = sorted(Path(ckpt_dir).glob("*.pth"))
    if not paths:
        raise FileNotFoundError(f"no .pth checkpoint shards in {ckpt_dir}")

    def shard_index(p: Path) -> int:
        # 'consolidated.00.pth' -> 0; single unnumbered file -> 0.
        parts = p.name.split(".")
        for part in parts[1:-1]:
            if part.isdigit():
                return int(part)
        return 0

    shards = []
    for p in sorted(paths, key=shard_index):
        try:
            sd = torch.load(p, map_location="cpu", mmap=True, weights_only=True)
        except (RuntimeError, TypeError, ValueError):
            sd = torch.load(p, map_location="cpu", weights_only=True)
        shards.append(sd)
    return shards


def _take(shards, key: str, concat_axis: Optional[int]) -> np.ndarray:
    """Pop `key` from every shard, concat (or take shard 0), as fp32 numpy."""
    import torch

    tensors = [sd.pop(key) for sd in shards]
    if concat_axis is None:
        arrs = [tensors[0].to(torch.float32).numpy()]
        out = arrs[0]
    else:
        out = np.concatenate(
            [t.to(torch.float32).numpy() for t in tensors], axis=concat_axis
        )
    return out


def config_from_params_json(
    ckpt_dir: str, vocab_size: int, max_seq_len: int = 2048, **overrides
) -> LLaMAConfig:
    """Build a LLaMAConfig from Meta's ``params.json`` (parity: reference
    ``config_from_params``, convert_weights.py:35-50 — the SwiGLU sizing
    rule lives in LLaMAConfig.ffn_dim here)."""
    with open(Path(ckpt_dir) / "params.json") as f:
        p = json.load(f)
    kw = dict(
        vocab_size=vocab_size,
        dim=p["dim"],
        n_layers=p["n_layers"],
        n_heads=p["n_heads"],
        n_kv_heads=p.get("n_kv_heads"),
        multiple_of=p.get("multiple_of", 256),
        ffn_dim_multiplier=p.get("ffn_dim_multiplier"),
        rms_norm_eps=p.get("norm_eps", 1e-5),
        rope_theta=p.get("rope_theta", 10000.0),
        use_scaled_rope=bool(p.get("use_scaled_rope", False)),
        max_seq_len=max_seq_len,
    )
    consumed = {
        "dim", "n_layers", "n_heads", "n_kv_heads", "multiple_of",
        "ffn_dim_multiplier", "norm_eps", "rope_theta", "use_scaled_rope",
        "vocab_size", "max_seq_len", "max_batch_size",
    }
    unknown = set(p) - consumed
    if unknown:
        raise ValueError(
            f"params.json has architecture keys this converter does not "
            f"understand: {sorted(unknown)} — refusing to convert a model "
            "that would be silently wrong"
        )
    kw.update(overrides)
    return LLaMAConfig(**kw)


def convert_meta_checkpoint(
    ckpt_dir: str,
    tokenizer: Any = None,
    *,
    vocab_size: Optional[int] = None,
    max_seq_len: int = 2048,
    dtype: str = "bfloat16",
) -> Tuple[Dict[str, Any], LLaMAConfig]:
    """Convert a Meta checkpoint directory into (params, config).

    Args:
      ckpt_dir: directory with ``consolidated.*.pth`` + ``params.json``.
      tokenizer: anything with ``__len__`` — supplies vocab_size (the
        reference takes the tokenizer for the same reason,
        convert_weights.py:90); or pass ``vocab_size`` directly.
      max_seq_len: context length to configure.
      dtype: storage dtype of the converted params ("float32" to match the
        reference's fp32 conversion; bf16 default halves host RAM and load
        time on TPU).
    """
    if vocab_size is None:
        if tokenizer is None:
            raise ValueError("pass a tokenizer or an explicit vocab_size")
        vocab_size = len(tokenizer)
    # Compute dtype follows the storage dtype the user asked for, except
    # fp16 params still compute in bf16 (fp16 ranges overflow on TPU).
    compute = "bfloat16" if dtype in ("bfloat16", "float16") else dtype
    config = config_from_params_json(
        ckpt_dir, vocab_size, max_seq_len, dtype=compute, param_dtype=dtype
    )
    config.validate()
    D, H, KVH, hd = config.dim, config.n_heads, config.kv_heads, config.head_dim
    od = np.dtype(dtype)

    shards = _load_shards(ckpt_dir)

    def col(key: str) -> np.ndarray:  # [out, D] shards -> [D, out]
        return _take(shards, key, 0).T

    def row(key: str) -> np.ndarray:  # [D, out] shards -> [out, D]
        return _take(shards, key, 1).T

    G = H // KVH
    layer_acc: Dict[str, list] = {
        k: [] for k in ("attn_norm", "qkv", "o", "mlp_norm",
                        "gate_up", "down")
    }
    for i in range(config.n_layers):
        pre = f"layers.{i}."
        layer_acc["attn_norm"].append(
            _take(shards, pre + "attention_norm.weight", None).astype(od)
        )
        # Fused decode layout [KVH, G+2, D, hd]: per KV head, slots
        # [q_0..q_{G-1}, k, v] (models.llama.fuse_qkv's contract; query
        # head h = kvh*G + g is Meta's own head order, so no HEAD
        # permutation happens — but the q/k head_dim FEATURES are permuted
        # to the runtime half-split RoPE order, see ops.rope /
        # models.llama.rope_permute; D second-from-last is the scan-slice
        # layout contract, models.llama module docstring).
        q_i = np.moveaxis(
            rope_permute(
                col(pre + "attention.wq.weight").reshape(D, H, hd)
            ).reshape(D, KVH, G, hd), 0, 2,
        )  # [KVH, G, D, hd]
        k_i = rope_permute(
            col(pre + "attention.wk.weight").reshape(D, KVH, hd)
        ).transpose(1, 0, 2)[:, None]  # [KVH, 1, D, hd]
        v_i = col(
            pre + "attention.wv.weight"
        ).reshape(D, KVH, hd).transpose(1, 0, 2)[:, None]
        layer_acc["qkv"].append(
            np.concatenate([q_i, k_i, v_i], axis=1).astype(od)
        )
        layer_acc["o"].append(
            row(pre + "attention.wo.weight").reshape(H, hd, D).astype(od)
        )
        layer_acc["mlp_norm"].append(
            _take(shards, pre + "ffn_norm.weight", None).astype(od)
        )
        layer_acc["gate_up"].append(
            np.stack(
                [col(pre + "feed_forward.w1.weight"),
                 col(pre + "feed_forward.w3.weight")], axis=0
            ).astype(od)  # [2, D, F]
        )
        layer_acc["down"].append(row(pre + "feed_forward.w2.weight").astype(od))

    # Embedding shard layout differs by family: Llama-2 splits the model dim
    # (ParallelEmbedding, concat axis 1); Llama-3 splits the vocab dim
    # (VocabParallelEmbedding, concat axis 0).  Detect from the shard shape
    # against the known vocab size.  (The reference hardcodes axis 1,
    # convert_weights.py:68 — wrong for multi-shard Llama-3 checkpoints.)
    emb_shard_rows = shards[0]["tok_embeddings.weight"].shape[0]
    emb_axis = 1 if emb_shard_rows == vocab_size else 0
    params: Dict[str, Any] = {
        "embed": {
            "embedding": _take(
                shards, "tok_embeddings.weight", emb_axis
            ).astype(od)
        },
        "layers": {k: np.stack(v) for k, v in layer_acc.items()},
        "final_norm": _take(shards, "norm.weight", None).astype(od),
    }
    tied = "output.weight" not in shards[0]
    if tied:
        config = config.replace(tie_word_embeddings=True)
    else:
        params["lm_head"] = col("output.weight").astype(od)

    assert params["embed"]["embedding"].shape[0] == vocab_size, (
        params["embed"]["embedding"].shape, vocab_size
    )
    return params, config
