from .checkpoint import load_checkpoint, load_config, save_checkpoint
from .meta import config_from_params_json, convert_meta_checkpoint

__all__ = [
    "convert_meta_checkpoint",
    "config_from_params_json",
    "save_checkpoint",
    "load_checkpoint",
    "load_config",
]
