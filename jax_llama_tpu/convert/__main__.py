"""One-time offline conversion CLI: Meta torch checkpoint → Orbax.

Fixes the reference's broken ``convert.sh`` workflow (its converter has no
CLI and nothing ever serializes the converted weights — SURVEY.md §2.17):

    python -m jax_llama_tpu.convert \
        --ckpt-dir /path/to/Meta-Llama-3-8B \
        --tokenizer /path/to/tokenizer.model \
        --out-dir /path/to/llama3-8b-orbax \
        [--max-seq-len 8192] [--dtype bfloat16]
"""

from __future__ import annotations

import argparse


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--ckpt-dir", required=True,
                    help="directory with consolidated.*.pth + params.json")
    ap.add_argument("--tokenizer", required=True,
                    help="tokenizer.model path (tiktoken ranks for llama3, "
                         "sentencepiece for llama2)")
    ap.add_argument("--llama2", action="store_true",
                    help="use the sentencepiece (llama2) tokenizer")
    ap.add_argument("--out-dir", required=True)
    ap.add_argument("--max-seq-len", type=int, default=2048)
    ap.add_argument("--dtype", default="bfloat16",
                    choices=["bfloat16", "float32", "float16"])
    args = ap.parse_args()

    from . import convert_meta_checkpoint, save_checkpoint

    if args.llama2:
        from ..tokenizers import LLaMA2Tokenizer as Tok
    else:
        from ..tokenizers import LLaMA3Tokenizer as Tok
    tokenizer = Tok(args.tokenizer)

    params, config = convert_meta_checkpoint(
        args.ckpt_dir, tokenizer,
        max_seq_len=args.max_seq_len, dtype=args.dtype,
    )
    save_checkpoint(args.out_dir, params, config)
    print(f"wrote {args.out_dir}: {config}")


if __name__ == "__main__":
    main()
