"""Autoregressive decode engine: jit-compiled prefill + lax.while_loop.

This replaces the one piece the reference does NOT own — its decode loop
lives in HF transformers' ``FlaxGenerationMixin`` (reference
``generation.py:28`` delegates to ``model.generate``; SURVEY.md §1).  Here
the whole pipeline — prefill, per-step sampling, stop-token handling, cache
update — is a single jitted function built on ``lax.while_loop``, so the
loop never leaves the device and XLA sees static shapes throughout.

Shape discipline (the reference's recipe, kept):
  * Prompts arrive **left-padded** to a common length P, so every row's last
    prompt token sits in column P-1 and one gather serves the whole batch
    (reference generation.py:55-57 left-pads with eos for the same reason).
  * The token buffer is preallocated to P + max_new_tokens; the KV cache to
    the same, so `cache.index + T <= max_len` holds by construction (the
    while cond caps decode steps at max_new_tokens) — important because
    `dynamic_update_slice` would clamp out-of-range writes silently.
  * Stop tokens are a static tuple (llama3 has two: end_of_text and eot_id,
    reference llama3_tokenizer.py:91-94).  A stop token is written to the
    buffer (so callers can see it), then the row emits pad_id forever.
  * The while_loop exits early once every row has stopped.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .config import LLaMAConfig
from .models.llama import forward, init_cache
from .ops.sampling import sample
from .parallel.mesh import use_mesh


@dataclasses.dataclass(frozen=True)
class GenerationConfig:
    """Static sampling/stopping policy (hashable — becomes part of the jit
    cache key).  Surface parity with the reference's HF GenerationConfig use
    (generation.py:28-41): num_beams=1, do_sample == (temperature != 0)."""

    max_new_tokens: int = 256
    temperature: float = 0.8
    top_p: Optional[float] = 0.95
    top_k: Optional[int] = None
    stop_tokens: Tuple[int, ...] = ()
    pad_id: int = 0
    # Prefill the prompt in fixed-size chunks instead of one T=P forward:
    # bounds activation memory to O(B·chunk·ffn) — at 8B scale a 32k-token
    # batch-8 prompt otherwise peaks at ~3.7GB per layer in MLP
    # intermediates alone.  None = single-shot prefill.
    prefill_chunk: Optional[int] = None


@functools.partial(jax.jit, static_argnames=("config", "mesh"))
def score(
    params,
    tokens: jnp.ndarray,
    attn_mask: Optional[jnp.ndarray] = None,
    *,
    config: LLaMAConfig,
    mesh=None,
) -> jnp.ndarray:
    """Per-token log-probabilities of a given sequence (evals/perplexity).

    Args:
      tokens: [B, T] int32; position t is scored against target tokens[t+1].
      attn_mask: optional [B, T] bool, False on (left) padding.
    Returns:
      [B, T-1] fp32: logp[b, t] = log p(tokens[b, t+1] | tokens[b, :t+1]);
      positions whose query or target is padding score 0.
    """
    from .parallel.mesh import current_mesh

    if mesh is None and current_mesh() is not None:
        raise ValueError(
            "score: pass mesh= explicitly (it is part of the jit cache key)"
        )
    with use_mesh(mesh):
        B, T = tokens.shape
        if attn_mask is None:
            attn_mask = jnp.ones((B, T), bool)
        positions = prompt_positions(attn_mask)
        logits, _ = forward(
            params, tokens, positions, config, attn_mask=attn_mask
        )
        logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), axis=-1)
        out = jnp.take_along_axis(
            logp, tokens[:, 1:, None].astype(jnp.int32), axis=-1
        )[..., 0]
        valid = attn_mask[:, :-1] & attn_mask[:, 1:]
        return jnp.where(valid, out, 0.0)


def next_pow2(n: int) -> int:
    """Bucket serving lengths to powers of two so varied prompt lengths
    trigger O(log max_len) compilations, not one per distinct length."""
    return 1 << max(n - 1, 1).bit_length()


def pow2_bucket(n: int) -> int:
    """Smallest power of two >= max(n, 1) — the jit-cache-key bucketing
    discipline shared by serving's admission row counts / dirty-row
    syncs and kvcache's swap-in batches (``next_pow2`` above is the
    LENGTH variant with a floor of 2; this is the exact count bucket:
    pow2_bucket(4) == 4, pow2_bucket(5) == 8, pow2_bucket(0) == 1)."""
    return 1 << max(n - 1, 0).bit_length()


def prompt_positions(prompt_mask: jnp.ndarray) -> jnp.ndarray:
    """Left-padded prompt mask [B, P] (bool) -> absolute positions [B, P],
    -1 on padding (parity: reference model.py:756-761 computes
    cumsum(mask)-1; our -1 sentinel replaces its masked-out negatives)."""
    pos = jnp.cumsum(prompt_mask.astype(jnp.int32), axis=-1) - 1
    return jnp.where(prompt_mask, pos, -1)


def window_positions(
    base: jnp.ndarray, offset: jnp.ndarray, width: int, length: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Positions/mask for one chunk-windowed prefill slice — the fused
    prefill-decode scheduler's window walk (``serving._fused_chunk``):
    tokens ``[offset, offset + width)`` of a ``length``-token suffix
    whose row KV begins at absolute position ``base`` (nonzero for
    prefix-cache hits, which start their chunk walk at fill0).  Returns
    ([1, width] int32 absolute positions with the -1 padding sentinel,
    [1, width] bool mask) — the ``prompt_positions`` contract for a
    window cut out of a longer right-padded prompt, without
    materializing the whole prompt's position row."""
    j = jnp.arange(width, dtype=jnp.int32)[None, :]
    real = (offset + j) < length
    pos = jnp.where(real, base + offset + j, -1).astype(jnp.int32)
    return pos, real


def finite_rows(logits: jnp.ndarray) -> jnp.ndarray:
    """Per-row non-finite guard: [..., V] logits -> [...] bool, True only
    where EVERY logit is finite.  A NaN/Inf here means the forward itself
    produced garbage (bad weights, a silently-corrupting kernel, an HBM
    bit flip) — sampling from it streams nonsense tokens, and the
    logprob of any sample is undefined.  The serving step programs fold
    this flag into a -1 token sentinel so the batcher can fail just the
    poisoned request with a clean error instead of emitting from a
    corrupt distribution; serving's fused chunk programs
    (``_paged_decode_chunk``, and ``_spec_rounds_chunk`` via the
    speculative verify's -1 *acceptance* sentinel) additionally fold the
    sentinel row out of their on-device active masks mid-chunk, so a
    poisoned request stops attending and writing without a host
    round-trip (raw logits from a healthy model are always finite; -inf
    only ever appears post-warp, which this guard runs before)."""
    return jnp.all(jnp.isfinite(logits.astype(jnp.float32)), axis=-1)


def _is_stop(tokens: jnp.ndarray, stop_tokens: Tuple[int, ...]) -> jnp.ndarray:
    if not stop_tokens:
        return jnp.zeros(tokens.shape, dtype=bool)
    stops = jnp.asarray(stop_tokens, dtype=tokens.dtype)
    return jnp.any(tokens[..., None] == stops, axis=-1)


@functools.partial(
    jax.jit, static_argnames=("config", "gen_config", "mesh")
)
def generate(
    params,
    prompt_tokens: jnp.ndarray,
    prompt_mask: jnp.ndarray,
    rng: jax.Array,
    *,
    config: LLaMAConfig,
    gen_config: GenerationConfig,
    mesh=None,
) -> jnp.ndarray:
    """Generate up to ``max_new_tokens`` per row.

    Args:
      params: model params pytree.
      prompt_tokens: [B, P] int32, left-padded.
      prompt_mask: [B, P] bool, False on padding.
      rng: PRNG key (unused when temperature == 0).
      mesh: optional jax.sharding.Mesh for activation sharding constraints.
        Passed explicitly (it is part of the jit cache key) — reading a
        thread-local mesh during tracing would silently bake whatever mesh
        happened to be active at first call into the compiled executable.
    Returns:
      [B, P + max_new_tokens] int32: the prompt (padding preserved) followed
      by generated tokens; pad_id after a row's stop token.
    """
    from .parallel.mesh import current_mesh

    if mesh is None and current_mesh() is not None:
        raise ValueError(
            "generate: pass mesh= explicitly (it is part of the jit cache "
            "key); an ambient use_mesh(...) context is not seen by the "
            "compiled executable on later calls"
        )
    with use_mesh(mesh):
        return _generate_impl(
            params, prompt_tokens, prompt_mask, rng, config, gen_config
        )


def _generate_impl(params, prompt_tokens, prompt_mask, rng, config, gc):
    B, P = prompt_tokens.shape
    total = P + gc.max_new_tokens
    positions = prompt_positions(prompt_mask)
    prompt_lens = jnp.sum(prompt_mask.astype(jnp.int32), axis=-1)  # [B]

    cache = init_cache(config, B, max_len=total)
    chunk = gc.prefill_chunk
    if chunk is not None and chunk < P:
        # Static chunk count: P is a trace-time constant, so the Python
        # loop unrolls into ceil(P/chunk) sequential forwards; each writes
        # its KV and attends the cache so far.  Only the final chunk's
        # logits matter (the last prompt token sits in column P-1) —
        # non-final chunks skip the lm_head entirely: their discarded
        # [B, chunk, V] fp32 logits would otherwise dwarf the activation
        # memory chunking exists to bound.
        for start in range(0, P, chunk):
            end = min(start + chunk, P)
            logits, cache = forward(
                params,
                prompt_tokens[:, start:end],
                positions[:, start:end],
                config,
                cache=cache,
                attn_mask=prompt_mask[:, start:end],
                compute_logits=end >= P,
            )
    else:
        logits, cache = forward(
            params, prompt_tokens, positions, config, cache=cache,
            attn_mask=prompt_mask,
        )
    rng, sub = jax.random.split(rng)
    next_tok = sample(
        sub, logits[:, -1], gc.temperature, gc.top_p, gc.top_k
    )  # [B]

    buf = jnp.full((B, total), gc.pad_id, dtype=jnp.int32)
    buf = lax.dynamic_update_slice(buf, prompt_tokens.astype(jnp.int32), (0, 0))

    State = Tuple  # (step, buf, cache, rng, next_tok, done)
    init_state = (
        jnp.zeros((), jnp.int32), buf, cache, rng, next_tok,
        jnp.zeros((B,), dtype=bool),
    )

    def cond(state: State):
        step, _, _, _, _, done = state
        return jnp.logical_and(step < gc.max_new_tokens, ~jnp.all(done))

    def body(state: State):
        step, buf, cache, rng, next_tok, done = state
        tok = jnp.where(done, gc.pad_id, next_tok).astype(jnp.int32)
        buf = lax.dynamic_update_slice(buf, tok[:, None], (0, P + step))
        done = jnp.logical_or(done, _is_stop(next_tok, gc.stop_tokens))
        rng, sub = jax.random.split(rng)

        # The forward runs unconditionally — including on the final
        # iteration, whose sampled token is discarded.  Guarding it with a
        # lax.cond (skip-on-last / skip-when-done) was measured to cost far
        # more than the one wasted forward: the conditional's branch-merge
        # forced XLA to re-layout the whole KV cache twice per step (~7% of
        # step time), to save 1/max_new_tokens of the forwards.
        pos = (prompt_lens + step)[:, None]  # [B, 1]
        logits, cache = forward(
            params, tok[:, None], pos, config, cache=cache,
            attn_mask=jnp.ones((B, 1), dtype=bool),
        )
        nxt = sample(sub, logits[:, -1], gc.temperature, gc.top_p, gc.top_k)
        return (step + 1, buf, cache, rng, nxt, done)

    _, buf, _, _, _, _ = lax.while_loop(cond, body, init_state)
    return buf
