"""LLaMA-3 tokenizer: tiktoken BPE + chat-format dialog encoding.

Capability parity with the reference (``/root/reference/jax_llama/
llama3_tokenizer.py:38-232``).  The token-id layout below is a fixed public
constant of the Llama-3 model family — the split regex, the 256-slot special
token block (begin/end_of_text at 0/1, header ids at 6/7, eot at 9, the rest
reserved), and the chat framing must match bit-for-bit or checkpoints are
unusable.  Implementation differences from the reference:

  * The BPE ranks file is read by a self-contained parser (base64 token +
    rank per line) instead of ``tiktoken.load.load_tiktoken_bpe``, removing
    the implicit blobfile dependency.
  * ``Tokenizer.from_ranks`` allows constructing from an in-memory rank
    table (tests use a 256-byte identity table; no proprietary vocab file
    is shipped).
  * Oversized-input handling (tiktoken panics beyond ~400k chars, and
    degrades on >25k-char same-class runs: github.com/openai/tiktoken/
    issues/195) is a standalone generator, property-tested.
"""

from __future__ import annotations

import base64
from typing import Dict, Iterator, List, Sequence

try:
    import tiktoken

    _HAVE_TIKTOKEN = True
except ImportError:  # pragma: no cover - environment dependent
    tiktoken = None
    _HAVE_TIKTOKEN = False

# Fixed public constants of the Llama-3 tokenizer.
SPLIT_REGEX = (
    r"(?i:'s|'t|'re|'ve|'m|'ll|'d)"
    r"|[^\r\n\p{L}\p{N}]?\p{L}+"
    r"|\p{N}{1,3}"
    r"| ?[^\s\p{L}\p{N}]+[\r\n]*"
    r"|\s*[\r\n]+"
    r"|\s+(?!\S)"
    r"|\s+"
)
NUM_RESERVED_SPECIAL_TOKENS = 256

# tiktoken's rust core panics past ~400k chars, and runs of >25k same-class
# (all-space / all-non-space) chars blow up the split regex.
MAX_ENCODE_CHARS = 400_000
MAX_SAME_CLASS_RUN = 25_000


def special_token_names() -> List[str]:
    """The 256 special tokens in id order (offset from the base vocab)."""
    named = {
        0: "<|begin_of_text|>",
        1: "<|end_of_text|>",
        6: "<|start_header_id|>",
        7: "<|end_header_id|>",
        9: "<|eot_id|>",
    }
    names = []
    reserved = 0
    for i in range(NUM_RESERVED_SPECIAL_TOKENS):
        if i in named:
            names.append(named[i])
        else:
            names.append(f"<|reserved_special_token_{reserved}|>")
            reserved += 1
    return names


def read_bpe_ranks(path: str) -> Dict[bytes, int]:
    """Parse a tiktoken ranks file: one 'base64(token) rank' pair per line."""
    ranks: Dict[bytes, int] = {}
    with open(path, "rb") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            token_b64, rank = line.split()
            ranks[base64.b64decode(token_b64)] = int(rank)
    return ranks


def split_oversized(s: str, max_run: int = MAX_SAME_CLASS_RUN) -> Iterator[str]:
    """Yield substrings whose same-class (space / non-space) runs never
    exceed ``max_run`` characters.  Concatenation of the pieces == s."""
    if not s:
        return
    start = 0
    run_len = 0
    run_is_space = s[0].isspace()
    for i, ch in enumerate(s):
        is_space = ch.isspace()
        if is_space != run_is_space:
            run_is_space = is_space
            run_len = 1
        else:
            run_len += 1
            if run_len > max_run:
                yield s[start:i]
                start = i
                run_len = 1
    yield s[start:]


class Tokenizer:
    """LLaMA-3 BPE tokenizer (surface parity: encode/decode/bos_id/eos_id/
    pad_id/stop_tokens/__len__)."""

    def __init__(self, model_path: str):
        self._init_from_ranks(read_bpe_ranks(model_path), name=model_path)

    @classmethod
    def from_ranks(cls, ranks: Dict[bytes, int], name: str = "custom") -> "Tokenizer":
        self = cls.__new__(cls)
        self._init_from_ranks(ranks, name=name)
        return self

    def _init_from_ranks(self, ranks: Dict[bytes, int], name: str) -> None:
        if not _HAVE_TIKTOKEN:
            raise ImportError(
                "tiktoken is required for the LLaMA-3 tokenizer but is not "
                "installed; `pip install tiktoken` or use ByteTokenizer"
            )
        n_base = len(ranks)
        self.special_tokens: Dict[str, int] = {
            tok: n_base + i for i, tok in enumerate(special_token_names())
        }
        self._enc = tiktoken.Encoding(
            name=name,
            pat_str=SPLIT_REGEX,
            mergeable_ranks=ranks,
            special_tokens=self.special_tokens,
        )
        self.n_words: int = self._enc.n_vocab
        self.bos_id: int = self.special_tokens["<|begin_of_text|>"]
        self.eos_id: int = self.special_tokens["<|end_of_text|>"]
        self.eot_id: int = self.special_tokens["<|eot_id|>"]
        self.pad_id: int = -1
        self.stop_tokens = {self.eos_id, self.eot_id}

    def __len__(self) -> int:
        return self.n_words

    def encode(
        self,
        s: str,
        bos: bool = False,
        eos: bool = False,
        allowed_special=frozenset(),
        disallowed_special=(),
    ) -> List[int]:
        """Encode text.  Special-token text in the input is encoded as plain
        text unless listed in ``allowed_special`` (pass "all" to enable all —
        same contract as the reference, llama3_tokenizer.py:99-128)."""
        ids: List[int] = []
        for i in range(0, len(s), MAX_ENCODE_CHARS):
            for piece in split_oversized(s[i : i + MAX_ENCODE_CHARS]):
                ids.extend(
                    self._enc.encode(
                        piece,
                        allowed_special=allowed_special,
                        disallowed_special=disallowed_special,
                    )
                )
        if bos:
            ids.insert(0, self.bos_id)
        if eos:
            ids.append(self.eos_id)
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        return self._enc.decode(list(ids))


class ChatFormat:
    """Dialog → token framing (parity: reference llama3_tokenizer.py:205-232).

    Frame:  <|begin_of_text|> then per message
            <|start_header_id|>{role}<|end_header_id|>\\n\\n{content}<|eot_id|>
            and finally an open assistant header for the model to complete.
    """

    def __init__(self, tokenizer: Tokenizer):
        self.tokenizer = tokenizer

    def encode_header(self, message: dict) -> List[int]:
        t = self.tokenizer
        return (
            [t.special_tokens["<|start_header_id|>"]]
            + t.encode(message["role"])
            + [t.special_tokens["<|end_header_id|>"]]
            + t.encode("\n\n")
        )

    def encode_message(self, message: dict) -> List[int]:
        return (
            self.encode_header(message)
            + self.tokenizer.encode(message["content"].strip())
            + [self.tokenizer.eot_id]
        )

    def encode_dialog_prompt(self, dialog: Sequence[dict]) -> List[int]:
        ids = [self.tokenizer.bos_id]
        for message in dialog:
            ids.extend(self.encode_message(message))
        ids.extend(self.encode_header({"role": "assistant", "content": ""}))
        return ids
