"""LLaMA-1/2 tokenizer: SentencePiece wrapper.

Capability parity with the reference (``/root/reference/jax_llama/
llama2_tokenizer.py:14-71``).  The ``sentencepiece`` package is not part of
this image's baked dependency set, so the import is gated: constructing the
tokenizer without it raises a clear error instead of breaking package import
(the reference lists sentencepiece in requirements.txt but its repo is
importable only when installed).
"""

from __future__ import annotations

from typing import List, Sequence

try:
    from sentencepiece import SentencePieceProcessor  # type: ignore

    _HAVE_SENTENCEPIECE = True
except ImportError:  # pragma: no cover - environment dependent
    SentencePieceProcessor = None
    _HAVE_SENTENCEPIECE = False


class Tokenizer:
    """SentencePiece tokenizer (surface parity: encode/decode/bos_id/eos_id/
    pad_id/n_words/__len__)."""

    def __init__(self, model_path: str):
        if not _HAVE_SENTENCEPIECE:
            raise ImportError(
                "sentencepiece is required for the LLaMA-2 tokenizer but is "
                "not installed; `pip install sentencepiece` or use the "
                "LLaMA-3 (tiktoken) tokenizer"
            )
        self.sp = SentencePieceProcessor(model_file=model_path)
        self.n_words: int = self.sp.vocab_size()
        self.bos_id: int = self.sp.bos_id()
        self.eos_id: int = self.sp.eos_id()
        self.pad_id: int = self.sp.pad_id()
        assert self.sp.vocab_size() == self.sp.get_piece_size()

    @property
    def stop_tokens(self) -> List[int]:
        return [self.eos_id]

    def __len__(self) -> int:
        return self.n_words

    def encode(self, s: str, bos: bool = False, eos: bool = False) -> List[int]:
        ids = self.sp.encode(s)
        if bos:
            ids = [self.bos_id] + ids
        if eos:
            ids = ids + [self.eos_id]
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        return self.sp.decode(list(ids))
