"""Byte-level fallback tokenizer.

Not part of the reference surface — exists so the decode engine, generation
API, tests, and benchmarks can run end-to-end without Meta's proprietary
tokenizer files (no sentencepiece model / tiktoken BPE ranks are shippable
in this repo).  Vocab: 256 raw bytes + BOS(256) + EOS(257) + PAD(258).
"""

from __future__ import annotations

from typing import List, Sequence


class ByteTokenizer:
    def __init__(self):
        self.n_words = 259
        self.bos_id = 256
        self.eos_id = 257
        self.pad_id = 258

    @property
    def stop_tokens(self) -> List[int]:
        return [self.eos_id]

    def __len__(self) -> int:
        return self.n_words

    def encode(self, s: str, bos: bool = False, eos: bool = False) -> List[int]:
        ids = list(s.encode("utf-8"))
        if bos:
            ids = [self.bos_id] + ids
        if eos:
            ids = ids + [self.eos_id]
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        data = bytes(i for i in ids if 0 <= i < 256)
        return data.decode("utf-8", errors="replace")
