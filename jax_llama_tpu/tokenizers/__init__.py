from .bytes import ByteTokenizer
from .llama2 import Tokenizer as LLaMA2Tokenizer
from .llama3 import ChatFormat, Tokenizer as LLaMA3Tokenizer

__all__ = ["ByteTokenizer", "LLaMA2Tokenizer", "LLaMA3Tokenizer", "ChatFormat"]
