from .bytes import ByteTokenizer

__all__ = ["ByteTokenizer"]
