"""Meta checkpoint downloader with checksum verification.

Capability parity with the reference's ``download.sh`` (presigned-URL wget
loop + ``md5sum -c`` verification, ``/root/reference/download.sh:15-33``),
rebuilt as a Python CLI so it is portable, resumable (skips files that
already verify), and unit-testable:

    python -m jax_llama_tpu.download \
        --presigned-url 'https://...*...' \
        --model-sizes 7B,13B \
        --target-dir /data/llama

The presigned URL contains a ``*`` placeholder that is substituted with
each file's relative path (same contract as the email Meta sends).  After
downloading, run the converter:

    python -m jax_llama_tpu.convert --ckpt-dir /data/llama/7B ...
"""

from __future__ import annotations

import argparse
import errno
import hashlib
import random
import shutil
import time
from pathlib import Path
from typing import Callable, Dict, List, Optional, Tuple

# consolidated.*.pth shard count per model size (reference download.sh:9-13
# covers LLaMA-1; LLaMA-2/3 use the same layout with these counts).
N_SHARDS: Dict[str, int] = {
    "7B": 1, "13B": 2, "30B": 4, "33B": 4, "65B": 8,
    "70B": 8, "8B": 1, "8B-Instruct": 1, "70B-Instruct": 8,
}


def md5_file(path: Path, chunk: int = 1 << 20) -> str:
    h = hashlib.md5()
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                break
            h.update(b)
    return h.hexdigest()


def parse_checklist(text: str) -> List[Tuple[str, str]]:
    """Parse ``md5sum``-format checklist lines into (hexdigest, filename)."""
    out = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        digest, _, name = line.partition(" ")
        out.append((digest.strip(), name.strip().lstrip("*")))
    return out


def verify_checklist(directory: Path, checklist_name: str = "checklist.chk") -> bool:
    """Equivalent of ``(cd dir && md5sum -c checklist.chk)``."""
    checklist = directory / checklist_name
    if not checklist.exists():
        return False
    ok = True
    for digest, name in parse_checklist(checklist.read_text()):
        target = directory / name
        if not target.exists() or md5_file(target) != digest:
            print(f"  FAILED {target}")
            ok = False
    return ok


# Transient-failure policy for _fetch: a hard socket timeout (a stalled
# CDN connection must not hang a 130 GB download forever) plus bounded
# exponential backoff with jitter on transient errors — URLError
# (connection reset / DNS / timeout) and HTTP 5xx.  4xx (e.g. an expired
# presigned URL) fails immediately: retrying cannot fix it.
FETCH_TIMEOUT_S = 60.0
FETCH_RETRIES = 4          # total attempts = 1 + FETCH_RETRIES
FETCH_BACKOFF_BASE_S = 1.0
# Local-filesystem errnos retrying a download can never fix (the OSError
# branch below otherwise also wraps the .part write/rename): surface
# them immediately instead of re-pulling a multi-GB shard with backoff.
_NONRETRYABLE_ERRNO = frozenset({
    errno.ENOSPC, errno.EACCES, errno.EROFS, errno.EDQUOT, errno.EISDIR,
})


def _fetch(
    url: str,
    dest: Path,
    *,
    timeout: float = FETCH_TIMEOUT_S,
    retries: int = FETCH_RETRIES,
    opener: Optional[Callable] = None,
    sleep: Callable[[float], None] = time.sleep,
    jitter: Optional[Callable[[], float]] = None,
) -> None:
    """Download ``url`` to ``dest`` atomically (.part then rename), with
    a socket timeout and bounded retry.  ``opener``/``sleep``/``jitter``
    are injectable for unit tests (default: ``urllib.request.urlopen``,
    ``time.sleep``, ``random.random``)."""
    import urllib.error
    import urllib.request

    opener = opener or urllib.request.urlopen
    jitter = jitter or random.random
    dest.parent.mkdir(parents=True, exist_ok=True)
    tmp = dest.with_suffix(dest.suffix + ".part")
    print(f"  {dest.name} <- {url.split('?')[0]}")
    for attempt in range(retries + 1):
        try:
            with opener(url, timeout=timeout) as r, open(tmp, "wb") as f:
                shutil.copyfileobj(r, f)
            tmp.rename(dest)
            return
        except urllib.error.HTTPError as e:
            # HTTPError subclasses URLError — catch it first.  Only
            # server-side (5xx) failures are transient.
            if e.code < 500 or attempt == retries:
                raise
            err = e
        except (urllib.error.URLError, TimeoutError, OSError) as e:
            # URLError/TimeoutError carry no filesystem errno, so this
            # only fires for genuine disk-side failures.
            if getattr(e, "errno", None) in _NONRETRYABLE_ERRNO:
                raise
            if attempt == retries:
                raise
            err = e
        # Full-jitter exponential backoff: base * 2^attempt * U(0.5, 1.5),
        # capped implicitly by the bounded retry count.
        delay = FETCH_BACKOFF_BASE_S * (2 ** attempt) * (0.5 + jitter())
        print(f"  retrying in {delay:.1f}s ({err})")
        sleep(delay)


def download(presigned_url: str, model_sizes: List[str], target: Path) -> None:
    sub = lambda rel: presigned_url.replace("*", rel)

    if verify_checklist(target, "tokenizer_checklist.chk"):
        print("Tokenizer already downloaded and verified, skipping")
    else:
        print("Downloading tokenizer")
        for name in ("tokenizer.model", "tokenizer_checklist.chk"):
            _fetch(sub(name), target / name)
        if not verify_checklist(target, "tokenizer_checklist.chk"):
            raise SystemExit("tokenizer checksum verification failed")

    for size in model_sizes:
        if size not in N_SHARDS:
            raise SystemExit(f"unknown model size {size!r}; have {sorted(N_SHARDS)}")
        d = target / size
        if verify_checklist(d):
            print(f"{size}: already downloaded and verified, skipping")
            continue
        print(f"Downloading {size}")
        # Checklist first, so per-shard resume can verify against it: an
        # interrupted 8-shard (~130GB) download then re-fetches only the
        # shards that are missing or fail their checksum.
        for name in ("checklist.chk", "params.json"):
            if not (d / name).exists():
                _fetch(sub(f"{size}/{name}"), d / name)
        digests = {
            name: digest
            for digest, name in parse_checklist((d / "checklist.chk").read_text())
        }
        for s in range(N_SHARDS[size]):
            name = f"consolidated.{s:02d}.pth"
            dest = d / name
            if dest.exists() and digests.get(name) == md5_file(dest):
                print(f"  {name}: verified, skipping")
                continue
            _fetch(sub(f"{size}/{name}"), dest)
        print("Checking checksums")
        if not verify_checklist(d):
            raise SystemExit(f"{size}: checksum verification failed")


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--presigned-url", required=True,
                    help="URL with a '*' placeholder (from Meta's email)")
    ap.add_argument("--model-sizes", default="7B",
                    help="comma-separated, e.g. 7B,13B,70B")
    ap.add_argument("--target-dir", required=True)
    args = ap.parse_args()
    download(
        args.presigned_url,
        [s.strip() for s in args.model_sizes.split(",") if s.strip()],
        Path(args.target_dir),
    )


if __name__ == "__main__":
    main()
