# Test / drill entry points.  All CPU targets force JAX_PLATFORMS=cpu
# (tests/conftest.py pins it anyway; the env var keeps jax's platform
# probe from touching an attached accelerator during collection).

PYTEST := env JAX_PLATFORMS=cpu python -m pytest

.PHONY: tier1 faults chaos tpu

# The gating suite: everything not marked slow, under the 870 s budget.
tier1:
	$(PYTEST) tests/ -q -m 'not slow' --continue-on-collection-errors

# Just the fault-injection / crash-recovery / degradation tests.
faults:
	$(PYTEST) tests/ -q -m faults

# Chaos smoke drill: the full fault matrix — every injection site
# (step / insert / suffix_insert / alloc and the kernel sites
# flash_kernel / paged_kernel / spec_decode, driven through
# `run.py --inject-faults`), kernel quarantine + XLA-fallback identity,
# non-finite-guard, and drain-on-signal.  Includes the slow drills that
# tier-1 excludes for time.
chaos:
	$(PYTEST) tests/ -q -m 'chaos or faults'

# On-chip kernel regressions (run on a TPU host; self-skip elsewhere).
tpu:
	python -m pytest tests/ -q -m tpu
