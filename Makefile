# Test / drill entry points.  All CPU targets force JAX_PLATFORMS=cpu
# (tests/conftest.py pins it anyway; the env var keeps jax's platform
# probe from touching an attached accelerator during collection).

PYTEST := env JAX_PLATFORMS=cpu python -m pytest

.PHONY: tier1 tier1-budget faults chaos tpu perf-smoke kvcache obs overload lint lint-invariants mesh-serve fleet elastic bench-compare check kernels

# The gating suite: everything not marked slow, under the 870 s budget.
tier1:
	$(PYTEST) tests/ -q -m 'not slow' --continue-on-collection-errors

# Tier-1 time budget report: the same gating run, ending with the 20
# slowest tests (pytest --durations; includes setup/teardown phases).
# The suite sits near its 870 s ceiling — run this before and after
# adding tier-1 tests, keep each new test to a few seconds, and push
# matrices behind @pytest.mark.slow (rebalance with in-test
# justification when a cell must move).
tier1-budget:
	$(PYTEST) tests/ -q -m 'not slow' --continue-on-collection-errors --durations=20

# Just the fault-injection / crash-recovery / degradation tests.
faults:
	$(PYTEST) tests/ -q -m faults

# Chaos smoke drill: the full fault matrix — every injection site
# (step / insert / suffix_insert / alloc and the kernel sites
# flash_kernel / paged_kernel / spec_decode, driven through
# `run.py --inject-faults`), kernel quarantine + XLA-fallback identity,
# non-finite-guard, and drain-on-signal.  Includes the slow drills that
# tier-1 excludes for time.
chaos:
	$(PYTEST) tests/ -q -m 'chaos or faults'

# Tier-1-safe perf guardrails (CPU, no accelerator needed): chunked
# decode's, chunked speculative serving's AND fused prefill-decode
# scheduling's host-boundary discipline — instrumented counter tests
# asserting <= 1 device->host sync and 0 steady-state host->device
# state uploads per fused dispatch (K decode iterations, R draft+verify
# rounds, or a prefill-carrying chunk), that decode rows keep emitting
# while a long prompt is mid-prefill (zero full-prefill stalls) with K
# un-collapsed — plus the K>1 vs K=1, spec_rounds>1 vs 1, and fused vs
# classic-admission token-identity matrices.  The KV-capacity subsystem
# owes the same discipline: ZERO decode-chunk stalls while a host-tier
# swap-in is in flight (every mid-swap dispatch keeps emitting at an
# un-collapsed K) and a radix/restored admission pays <= 1 state
# upload — the same budget as a fused admission.  Observability owes
# the strictest version: tracing is ALWAYS ON, so the same counters
# prove it adds zero device dispatches and zero extra host syncs per
# chunk (every dispatch span in the obs ring maps 1:1 onto a counted
# dispatch; the 1-fetch/0-upload steady state is unchanged).  These
# also run inside tier1; this target is the fast pre-push slice.
perf-smoke:
	$(PYTEST) tests/test_perf_smoke.py tests/test_serving_chunked.py tests/test_serving_spec.py tests/test_serving_fused.py tests/test_kvcache.py -q -m 'not slow'

# Just the KV-capacity subsystem (radix prefix index + host-DRAM tier).
kvcache:
	$(PYTEST) tests/ -q -m kvcache

# Observability layer (obs.py): request span timelines, dispatch
# spans, latency histograms, SLO accounting, Perfetto trace export,
# the /metrics registry exposition, and the /debug endpoints — the
# obs-marked suite plus the whole HTTP server suite (request-id
# plumbing and exposition live there), plus the control-plane layer
# (decision audit log, flight recorder, canary probes, health
# sentinel — tests/test_controlplane.py incl. the fleet drill).
obs:
	$(PYTEST) tests/test_obs.py tests/test_server.py tests/test_controlplane.py -q -m 'not slow'

# Overload control (overload.py): priority-class admission, the
# cost-based deadline refusal, the brownout ladder's transitions and
# hysteresis recovery, and the open-loop flood + ladder drills —
# including the slow-marked acceptance drill (Poisson mixed-class
# flood at >= 2x the sustainable rate: interactive attainment held,
# batch shed with clean 503 + Retry-After, zero hung clients, ladder
# stepped back to normal afterwards) that tier-1 excludes for time.
overload:
	$(PYTEST) tests/test_overload.py -q

# Scale-out serving (parallel/serve_mesh.py + router.py): the full
# mesh_serving suite including the slow matrices (tensor-only mesh,
# sharded speculative chunk, host-tier restore under sharded
# placement), the router fault drills, and the multichip_serving
# dryrun round (sharded-chunk parity + mesh lowering contracts +
# routed-replica token identity on the forced 8-host-device mesh —
# what MULTICHIP_r06.json records; add `--record MULTICHIP_rNN.json`
# to roll a new round).
mesh-serve:
	$(PYTEST) tests/test_serve_mesh.py tests/test_router.py -q
	$(PYTEST) tests/test_faults.py -q -k router
	$(PYTEST) tests/test_run_cli.py -q -k serve_mesh
	env JAX_PLATFORMS=cpu python bench.py --multichip-serving

# Globally cache-aware routing (router.py RouterRadixIndex + handoff
# scheduler + prefill/decode disaggregation): the full cache-routing
# suite (index/journal units, export/import bounds + demote-after-
# export, the routed deep-hit / spill-migration / stale-digest /
# mid-handoff-fault acceptance drills), the slow-marked CLI
# disaggregation smoke (--route cache-aware --replica-roles), and the
# fleet-TTFT A/B round (cache-aware vs least-loaded hit ratio +
# dedup-by-migration — what MULTICHIP_r08.json records; add
# `--record MULTICHIP_rNN.json` to roll a new round).
fleet:
	$(PYTEST) tests/test_cache_routing.py -q
	$(PYTEST) tests/test_run_cli.py -q -k 'cache_aware or replica'
	env JAX_PLATFORMS=cpu python bench.py --multichip-serving

# Elastic fleet (FleetController): autoscaler hysteresis, drain-by-
# migration (zero dropped sessions, token-identical), zero-downtime
# rollouts with the per-rung canary gate, and the scale_event /
# session_migrate chaos drills.
elastic:
	$(PYTEST) tests/test_elastic.py -q
	$(PYTEST) tests/test_faults.py -q -k 'migrate or scale_event'

# Invariant auditor (jax_llama_tpu/analysis): host-boundary lint,
# lowering-contract audit (donated args actually alias, host-fetch
# surface within budget, no full-pool-copy equations — all ten
# registered jitted programs lowered at a tiny geometry), the
# lock-discipline / thread-confinement check, the retrace auditor
# (bounded jit-cache-key domains statically + the admission-sweep
# cache drill), the comms-budget contracts (collective counts/bytes
# in the COMPILED sharded lowerings; full-pool collectives are hard
# findings), the schedule explorer (every racy-read/unguarded pragma
# backed by a passing interleaving model) and the metrics-registry
# lint — plus `ruff check` (pyflakes-class rules, [tool.ruff] in
# pyproject.toml) when ruff is installed in the environment.  Exit
# non-zero on any finding; the static layers also gate tier-1 via
# tests/test_analysis.py.
lint-invariants:
	env JAX_PLATFORMS=cpu python -m jax_llama_tpu.analysis
	@if command -v ruff >/dev/null 2>&1; then \
		ruff check .; \
	else \
		echo "ruff not installed; skipping ruff check (pip install ruff)"; \
	fi

# THE single pre-PR gate: the full invariant audit (above, ruff
# included behind its command gate), the fast analysis tests, and the
# perf-smoke host-boundary drills.  Green `make check` = the static
# contracts, the thread-safety models, the jit-cache/comms budgets
# and the 1-fetch/0-upload discipline all hold — run it before every
# push; tier1 remains the full gating suite.
check: lint-invariants
	$(PYTEST) tests/test_analysis.py -q -m 'not slow'
	$(MAKE) perf-smoke

# Machine-check the bench trajectory: diff headline keys between two
# BENCH_*/MULTICHIP_* records and exit non-zero past tolerance
# (bench.py --compare; override OLD/NEW/TOL, e.g.
# `make bench-compare OLD=BENCH_r05.json NEW=BENCH_r07.json`).
# Heterogeneous rounds that share no headline keys warn instead of
# failing — the gate bites on same-shaped rounds (the next TPU round
# vs r05's chip numbers).
OLD ?= BENCH_r05.json
NEW ?= BENCH_r06.json
TOL ?= 5
bench-compare:
	env JAX_PLATFORMS=cpu python bench.py --compare $(OLD) $(NEW) --tolerance $(TOL)

# The full lint gate (alias kept separate so CI can grow style/type
# layers here without slowing the invariant auditor).
lint: lint-invariants

# On-chip kernel regressions (run on a TPU host; self-skip elsewhere).
tpu:
	python -m pytest tests/ -q -m tpu

# Kernel-selection layer (ops/kernels.py): the CPU-runnable parity
# suite (splash-mha prefill + stock paged-attention decode in Pallas
# interpret mode, op-level AND through the serving paths), the
# serving A/B drills (kernel vs fallback token behavior) and the
# quarantine drills proving splash->flash and stock-paged->paged
# fallbacks keep serving token-identically.  Runs the file UNFILTERED
# so the slow-marked serving matrices (r17 budget rebalance) are
# included; TPU cells self-skip off-TPU and run under `make tpu`.
# The throughput side of the A/B — prefill_kernel_sweep (flash vs
# splash TFLOPs at 8k/16k/32k) and decode_kernel_ab (custom vs stock
# vs gathered tok/s) — lands in the BENCH_* record via
# `python bench.py` on a TPU host.
kernels:
	$(PYTEST) tests/test_kernels.py -q -m 'not tpu'
