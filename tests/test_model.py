"""Full-model parity vs the torch oracle at tiny config (tier-1 analogue of
the reference's ``test_Transformer``, jax_test.py:316).  Also covers the
scan-vs-unrolled stack equivalence and weight tying."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from jax_llama_tpu import config as cfg_lib
from jax_llama_tpu.models import forward, init_params, param_count
import torch_oracle as oracle

CFG = cfg_lib.tiny()


def _np_params(params):
    return jax.tree.map(np.asarray, params)


def test_forward_matches_torch_oracle():
    params = init_params(jax.random.PRNGKey(0), CFG)
    for trial in range(4):
        rng = np.random.RandomState(trial)
        tokens = rng.randint(0, CFG.vocab_size, size=(2, 12))
        positions = np.tile(np.arange(12), (2, 1))
        got, _ = forward(
            params, jnp.asarray(tokens), jnp.asarray(positions), CFG
        )
        want = oracle.oracle_forward(_np_params(params), tokens, positions, CFG)
        np.testing.assert_allclose(np.asarray(got), want, atol=2e-4, rtol=1e-4)


def test_forward_left_padding_matches_oracle():
    params = init_params(jax.random.PRNGKey(1), CFG)
    rng = np.random.RandomState(7)
    tokens = rng.randint(0, CFG.vocab_size, size=(2, 10))
    # Left-pad: first 3 (row 0) / 5 (row 1) tokens are padding.
    positions = np.stack([
        np.concatenate([-np.ones(3, int), np.arange(7)]),
        np.concatenate([-np.ones(5, int), np.arange(5)]),
    ])
    got, _ = forward(params, jnp.asarray(tokens), jnp.asarray(positions), CFG)
    want = oracle.oracle_forward(_np_params(params), tokens, positions, CFG)
    # Compare only non-pad rows — pad-row outputs are don't-care.
    mask = positions >= 0
    np.testing.assert_allclose(
        np.asarray(got)[mask], want[mask], atol=2e-4, rtol=1e-4
    )
    assert not np.isnan(np.asarray(got)).any(), "pad rows must not go NaN"


def test_scan_and_unrolled_stacks_agree():
    params = init_params(jax.random.PRNGKey(2), CFG)
    tokens = jnp.asarray(np.random.randint(0, CFG.vocab_size, size=(1, 8)))
    positions = jnp.arange(8)[None, :]
    a, _ = forward(params, tokens, positions, CFG.replace(scan_layers=True))
    b, _ = forward(params, tokens, positions, CFG.replace(scan_layers=False))
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-5)


def test_tied_embeddings():
    cfg = CFG.replace(tie_word_embeddings=True)
    params = init_params(jax.random.PRNGKey(3), cfg)
    assert "lm_head" not in params
    tokens = jnp.asarray([[1, 2, 3]])
    positions = jnp.arange(3)[None, :]
    logits, _ = forward(params, tokens, positions, cfg)
    want = oracle.oracle_forward(_np_params(params), np.asarray(tokens), np.asarray(positions), cfg)
    np.testing.assert_allclose(np.asarray(logits), want, atol=2e-4, rtol=1e-4)


def test_remat_matches_baseline():
    params = init_params(jax.random.PRNGKey(4), CFG)
    tokens = jnp.asarray([[5, 6, 7, 8]])
    positions = jnp.arange(4)[None, :]
    a, _ = forward(params, tokens, positions, CFG)
    b, _ = forward(params, tokens, positions, CFG.replace(remat=True))
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_param_count_tiny():
    params = init_params(jax.random.PRNGKey(0), CFG)
    D, F, V, L = CFG.dim, CFG.ffn_dim, CFG.vocab_size, CFG.n_layers
    H, KVH, hd = CFG.n_heads, CFG.kv_heads, CFG.head_dim
    expect = (
        V * D                                   # embed
        + L * (2 * D)                           # norms
        + L * (D * H * hd + 2 * D * KVH * hd + H * hd * D)  # attn
        + L * (2 * D * F + F * D)               # mlp
        + D                                     # final norm
        + D * V                                 # lm head
    )
    assert param_count(params) == expect


def test_gqa_group_validation():
    with pytest.raises(AssertionError):
        cfg_lib.tiny(n_heads=4, n_kv_heads=3).validate()
