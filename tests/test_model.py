"""Full-model parity vs the torch oracle at tiny config (tier-1 analogue of
the reference's ``test_Transformer``, jax_test.py:316).  Also covers the
scan-vs-unrolled stack equivalence and weight tying."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from jax_llama_tpu import config as cfg_lib
from jax_llama_tpu.models import forward, init_params, param_count
import torch_oracle as oracle

CFG = cfg_lib.tiny()


def _np_params(params):
    return jax.tree.map(np.asarray, params)


def test_forward_matches_torch_oracle():
    params = init_params(jax.random.PRNGKey(0), CFG)
    for trial in range(4):
        rng = np.random.RandomState(trial)
        tokens = rng.randint(0, CFG.vocab_size, size=(2, 12))
        positions = np.tile(np.arange(12), (2, 1))
        got, _ = forward(
            params, jnp.asarray(tokens), jnp.asarray(positions), CFG
        )
        want = oracle.oracle_forward(_np_params(params), tokens, positions, CFG)
        np.testing.assert_allclose(np.asarray(got), want, atol=2e-4, rtol=1e-4)


def test_forward_left_padding_matches_oracle():
    params = init_params(jax.random.PRNGKey(1), CFG)
    rng = np.random.RandomState(7)
    tokens = rng.randint(0, CFG.vocab_size, size=(2, 10))
    # Left-pad: first 3 (row 0) / 5 (row 1) tokens are padding.
    positions = np.stack([
        np.concatenate([-np.ones(3, int), np.arange(7)]),
        np.concatenate([-np.ones(5, int), np.arange(5)]),
    ])
    got, _ = forward(params, jnp.asarray(tokens), jnp.asarray(positions), CFG)
    want = oracle.oracle_forward(_np_params(params), tokens, positions, CFG)
    # Compare only non-pad rows — pad-row outputs are don't-care.
    mask = positions >= 0
    np.testing.assert_allclose(
        np.asarray(got)[mask], want[mask], atol=2e-4, rtol=1e-4
    )
    assert not np.isnan(np.asarray(got)).any(), "pad rows must not go NaN"


def test_scan_and_unrolled_stacks_agree():
    params = init_params(jax.random.PRNGKey(2), CFG)
    tokens = jnp.asarray(np.random.randint(0, CFG.vocab_size, size=(1, 8)))
    positions = jnp.arange(8)[None, :]
    a, _ = forward(params, tokens, positions, CFG.replace(scan_layers=True))
    b, _ = forward(params, tokens, positions, CFG.replace(scan_layers=False))
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-5)


def test_tied_embeddings():
    cfg = CFG.replace(tie_word_embeddings=True)
    params = init_params(jax.random.PRNGKey(3), cfg)
    assert "lm_head" not in params
    tokens = jnp.asarray([[1, 2, 3]])
    positions = jnp.arange(3)[None, :]
    logits, _ = forward(params, tokens, positions, cfg)
    want = oracle.oracle_forward(_np_params(params), np.asarray(tokens), np.asarray(positions), cfg)
    np.testing.assert_allclose(np.asarray(logits), want, atol=2e-4, rtol=1e-4)


def test_remat_matches_baseline():
    params = init_params(jax.random.PRNGKey(4), CFG)
    tokens = jnp.asarray([[5, 6, 7, 8]])
    positions = jnp.arange(4)[None, :]
    a, _ = forward(params, tokens, positions, CFG)
    b, _ = forward(params, tokens, positions, CFG.replace(remat=True))
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_param_count_tiny():
    params = init_params(jax.random.PRNGKey(0), CFG)
    D, F, V, L = CFG.dim, CFG.ffn_dim, CFG.vocab_size, CFG.n_layers
    H, KVH, hd = CFG.n_heads, CFG.kv_heads, CFG.head_dim
    expect = (
        V * D                                   # embed
        + L * (2 * D)                           # norms
        + L * (D * H * hd + 2 * D * KVH * hd + H * hd * D)  # attn
        + L * (2 * D * F + F * D)               # mlp
        + D                                     # final norm
        + D * V                                 # lm head
    )
    assert param_count(params) == expect


def test_gqa_group_validation():
    with pytest.raises(AssertionError):
        cfg_lib.tiny(n_heads=4, n_kv_heads=3).validate()


def test_aux_outputs_surface():
    """forward(..., output_hidden_states/output_attentions) — the
    eval/interp surface: hidden-state stack semantics (per-block inputs +
    post-final-norm), attention rows summing to 1 over attendable slots,
    logits unchanged, cached-decode aux consistent with the cache-free
    forward at the same positions, and the documented refusals."""
    from jax_llama_tpu.models import init_cache
    from jax_llama_tpu.models.llama import PagedKVCache  # noqa: F401

    params = init_params(jax.random.PRNGKey(5), CFG)
    T = 10
    tokens = jnp.asarray(np.random.RandomState(9).randint(
        0, CFG.vocab_size, size=(2, T)
    ))
    positions = jnp.tile(jnp.arange(T)[None, :], (2, 1))

    logits, _, aux = forward(
        params, tokens, positions, CFG,
        output_hidden_states=True, output_attentions=True,
    )
    L, H, D = CFG.n_layers, CFG.n_heads, CFG.dim
    assert aux.hidden_states.shape == (L + 1, 2, T, D)
    assert aux.attentions.shape == (L, 2, H, T, T)
    np.testing.assert_array_equal(
        np.asarray(aux.last_hidden_state), np.asarray(aux.hidden_states[-1])
    )
    # Rows are distributions over the causal prefix.
    sums = np.asarray(aux.attentions.astype(jnp.float32)).sum(-1)
    np.testing.assert_allclose(sums, 1.0, atol=1e-3)
    causal = np.triu(np.ones((T, T), bool), k=1)
    assert np.all(np.asarray(aux.attentions)[..., causal] == 0.0)
    # Flags are pure observation: logits identical to the plain forward
    # (both run the unrolled xla stack here).
    plain, _ = forward(
        params, tokens, positions,
        CFG.replace(scan_layers=False, attn_impl="xla"),
    )
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(plain), atol=1e-5, rtol=1e-5
    )

    # Cached decode: step t's aux equals the cache-free forward's values
    # at column t (same math, append-free path).
    cache = init_cache(CFG, 2, max_len=T)
    step_h = []
    for t in range(4):
        _, cache, aux_t = forward(
            params, tokens[:, t:t + 1], positions[:, t:t + 1], CFG,
            cache=cache, output_hidden_states=True, output_attentions=True,
        )
        assert aux_t.attentions.shape == (L, 2, H, 1, T + 1)
        step_h.append(np.asarray(aux_t.hidden_states[:, :, 0]))
    full = np.asarray(aux.hidden_states)
    np.testing.assert_allclose(
        np.stack(step_h, axis=2), full[:, :, :4], atol=2e-4, rtol=1e-4
    )

    # Refusals: ring attention never materializes weights; paged caches
    # are a serving path.
    with pytest.raises(NotImplementedError, match="ring"):
        forward(
            params, tokens, positions, CFG.replace(attn_impl="ring"),
            output_attentions=True,
        )
