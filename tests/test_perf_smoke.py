"""Host-boundary discipline of chunked decode AND chunked speculative
serving (make perf-smoke; tier-1-safe, CPU).

The whole point of decode_chunk / spec_rounds > 1 is amortizing
host<->device traffic: steady-state decode must pay AT MOST ONE
device->host sync (the packed token block) and ZERO host->device state
uploads per chunk dispatch — whether the chunk carries K plain decode
iterations or R speculative draft+verify rounds.  These tests assert
that contract through the batcher's instrumented counters
(``host_syncs_total`` / ``state_uploads_total`` count every np.asarray
fetch and every ``_scatter_rows`` state-sync dispatch the serving loop
performs; the ``spec_*`` twins attribute the speculative path's share),
plus the adaptive-K/R policy around admissions."""

import jax
import numpy as np
import pytest

from jax_llama_tpu import get_config, init_params
from jax_llama_tpu.serving import ContinuousBatcher

CFG = dict(
    vocab_size=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
    multiple_of=32, max_seq_len=128, dtype="float32", param_dtype="float32",
)


@pytest.fixture(scope="module")
def model():
    config = get_config("tiny", **CFG)
    params = init_params(jax.random.PRNGKey(0), config)
    return params, config


def test_flight_recorder_zero_overhead(model):
    """ACCEPTANCE PIN (ISSUE 15): the control-plane recorder is
    host-side bookkeeping only — steady-state chunk dispatches keep
    the exact 1-fetch / 0-upload contract while decisions are being
    recorded and EVERY flight-recorder surface (the decision log's
    json, the metric-snapshot ring, the config snapshot) is scraped
    mid-decode, exactly as /debug/decisions and /debug/bundle handler
    threads would."""
    params, config = model
    cb = ContinuousBatcher(
        params, config, n_slots=2, max_len=128, decode_chunk=4,
        block_size=16,
    )
    cb.submit(list(np.random.RandomState(7).randint(1, 128, 40)),
              max_new_tokens=40)
    cb.step(); cb.step()  # admission + chunk ramp
    s0, u0, d0 = (
        cb.host_syncs_total, cb.state_uploads_total,
        cb.decode_dispatches_total,
    )
    for i in range(4):
        cb.step()
        # Record + scrape the recorder surfaces mid-decode.
        cb.obs.decisions.record(
            "route", request_id=f"r{i}", replica=0,
            policy="least-loaded",
        )
        cb.obs.record_metrics_snapshot(
            {"emitted_tokens_total": int(cb.emitted_total)}
        )
        doc = cb.obs.decisions.json(n=8)
        assert doc["events_total"] == i + 1
        assert len(cb.obs.metric_snapshots_json()) == i + 1
        assert cb.describe()["decode_chunk"] == 4
    dispatches = cb.decode_dispatches_total - d0
    assert dispatches == 4
    # Bit-identical steady-state contract with the recorder live:
    # 1 fetch per chunk, 0 uploads, no extra dispatches from any of
    # the recording or scraping above.
    assert cb.host_syncs_total - s0 == dispatches
    assert cb.state_uploads_total == u0


def test_steady_state_host_sync_discipline(model):
    """Steady-state chunk dispatches: exactly 1 device->host sync each,
    0 host->device state uploads (state is device-resident; only
    admission/free/cancel may upload, and only the rows they touched)."""
    params, config = model
    cb = ContinuousBatcher(
        params, config, n_slots=2, max_len=128, decode_chunk=4,
    )
    cb.submit(list(np.random.RandomState(0).randint(1, 128, 9)),
              max_new_tokens=40)
    cb.step()   # admission (K=1) + the one state sync it owes
    cb.step()   # chunk-size ramp
    assert cb.state_uploads_total == 1  # the admission's row sync
    s0, u0, d0 = (
        cb.host_syncs_total, cb.state_uploads_total,
        cb.decode_dispatches_total,
    )
    for _ in range(4):
        cb.step()
    dispatches = cb.decode_dispatches_total - d0
    assert dispatches == 4
    # <= 1 sync per dispatch (exactly 1: the packed token block)...
    assert cb.host_syncs_total - s0 == dispatches
    # ...and ZERO steady-state state uploads.
    assert cb.state_uploads_total == u0
    # The steady-state chunks ran fused (K > 1).
    assert cb.decode_chunk_last == 4


def test_chunk_size_adapts_around_admissions(model):
    """K drops to 1 right after an admission (TTFT), stays clamped at
    <= _QUEUED_CHUNK_CAP while the queue holds capacity-blocked
    requests (bounded slot turnaround WITHOUT reverting to per-token
    dispatches under saturation), then ramps to the configured chunk,
    clamped pow2 by the remaining budget."""
    params, config = model
    cb = ContinuousBatcher(
        params, config, n_slots=1, max_len=128, decode_chunk=8,
    )
    cb.submit([4, 5, 6], max_new_tokens=20)
    cb.submit([7, 8, 9], max_new_tokens=20)  # queued behind slot 0
    cb.step()
    assert cb.decode_chunk_last == 1   # admission step
    cb.step()
    # Queue capacity-blocked: clamped small but still > 1 (saturation
    # must keep amortizing dispatches).
    assert cb.decode_chunk_last == cb._QUEUED_CHUNK_CAP
    # Drain request 0; once the queue empties and request 1 is steady,
    # chunks ramp to 8.
    seen = set()
    guard = 0
    while cb.pending():
        guard += 1
        assert guard < 200
        cb.step()
        seen.add(cb.decode_chunk_last)
    assert 8 in seen
    # Tail-of-budget clamping keeps K a power of two <= remaining.
    assert seen <= {1, 2, 4, 8}


def test_logprobs_mode_single_packed_fetch(model):
    """logprobs ride the packed block (bitcast int32): logprobs mode
    must not add a second per-chunk fetch."""
    params, config = model
    cb = ContinuousBatcher(
        params, config, n_slots=1, max_len=128, decode_chunk=4,
        logprobs=True,
    )
    cb.submit([5, 17, 99], max_new_tokens=24)
    cb.step(); cb.step()
    s0, d0 = cb.host_syncs_total, cb.decode_dispatches_total
    events = []
    for _ in range(3):
        events += cb.step()
    assert cb.host_syncs_total - s0 == cb.decode_dispatches_total - d0
    # And the logprobs delivered through the packed path are real.
    assert all(len(ev) == 4 and np.isfinite(ev[3]) for ev in events)


def test_metrics_surface(model):
    """The chunked-decode observability counters are in stats() (and
    therefore in the HTTP /metrics exposition)."""
    params, config = model
    cb = ContinuousBatcher(
        params, config, n_slots=1, max_len=64, decode_chunk=4,
    )
    cb.submit([4, 5, 6], max_new_tokens=6)
    cb.run_to_completion()
    stats = cb.stats()
    for key in (
        "decode_chunk_size", "decode_dispatches_total",
        "host_syncs_total", "state_uploads_total",
        "host_syncs_per_token",
    ):
        assert key in stats, key
    assert stats["decode_dispatches_total"] > 0
    assert 0 < stats["host_syncs_per_token"] <= 1.5


def test_kv_digest_zero_overhead(model):
    """ACCEPTANCE PIN (PR 13): chain-digest maintenance is host-side
    bookkeeping only — steady-state chunk dispatches keep the exact
    1-fetch / 0-upload contract with the digest live, the digest does
    not mutate during steady decode (content edits happen only at
    admission/free boundaries), and READING every digest surface
    (/debug/kv walk, summary, the stats() gauges) performs zero device
    dispatches and zero host syncs."""
    params, config = model
    cb = ContinuousBatcher(
        params, config, n_slots=2, max_len=128, decode_chunk=4,
        block_size=16,
    )
    cb.submit(list(np.random.RandomState(1).randint(1, 128, 40)),
              max_new_tokens=40)
    cb.step(); cb.step()  # admission + ramp
    v0 = cb.kv_digest.summary()["version"]
    assert v0 >= 2  # the admission published its chain
    s0, u0, d0 = (
        cb.host_syncs_total, cb.state_uploads_total,
        cb.decode_dispatches_total,
    )
    for _ in range(4):
        cb.step()
        # Scrape every digest surface mid-decode, as /metrics and
        # /debug/kv handler threads would.
        walk = cb.kv_debug_json()
        assert walk["summary"]["version"] == v0  # steady: no edits
        assert cb.stats()["kv_digest_version"] == v0
    dispatches = cb.decode_dispatches_total - d0
    assert dispatches == 4
    # The steady-state contract is bit-identical with the digest (and
    # its readers) live: 1 fetch per chunk, 0 uploads, no extra
    # dispatches from any of the reads above.
    assert cb.host_syncs_total - s0 == dispatches
    assert cb.state_uploads_total == u0


# ---------------------------------------------------------------------------
# Fused prefill-decode scheduling owes the same discipline
# ---------------------------------------------------------------------------

def test_fused_admission_host_sync_discipline(model):
    """A fused admission's whole prefill pays ONE state upload (its
    admission-time dirty-row sync; the suffix/walk buffers upload once
    and are not state syncs) and every chunk dispatch — prefill riding
    or not — pays exactly 1 device->host fetch: no per-prefill-chunk
    host sync, the satellite contract of stall-free admission."""
    params, config = model
    cb = ContinuousBatcher(
        params, config, n_slots=2, max_len=128, decode_chunk=4,
        block_size=16, prefill_budget=16,
    )
    cb.submit(list(np.random.RandomState(0).randint(1, 128, 9)),
              max_new_tokens=60)
    cb.step()   # cold pool: classic admission (nobody to stall)
    cb.step()   # chunk ramp
    assert cb.fused_admissions_total == 0
    s0, u0, d0 = (
        cb.host_syncs_total, cb.state_uploads_total,
        cb.decode_dispatches_total,
    )
    # 60-token prompt at a 16-token budget: 4 prefill-carrying chunks.
    cb.submit(list(np.random.RandomState(1).randint(1, 128, 60)),
              max_new_tokens=8)
    steps = 0
    while cb._pf is not None or cb.prefill_chunks_total == 0:
        cb.step()
        steps += 1
        assert steps < 10
    assert cb.fused_admissions_total == 1
    assert cb.prefill_chunks_total == 4
    dispatches = cb.decode_dispatches_total - d0
    # Exactly 1 fetch per chunk dispatch (the packed token block) —
    # fused admission added NO insert barrier and NO per-chunk sync...
    assert cb.host_syncs_total - s0 == dispatches
    # ...and exactly ONE state upload for the whole admission.
    assert cb.state_uploads_total - u0 == 1
    while cb.pending():
        cb.step()


def test_fused_prefill_does_not_collapse_chunk_size(model):
    """_pick_chunk no longer resets K to 1 when an admission rides the
    fused path (the first token comes out of the dispatch chain itself,
    so there is no TTFT reason to shrink the chunk), and decode rows
    keep emitting through every mid-prefill dispatch — zero
    full-prefill stalls."""
    params, config = model
    cb = ContinuousBatcher(
        params, config, n_slots=2, max_len=128, decode_chunk=4,
        block_size=16, prefill_budget=16,
    )
    r0 = cb.submit(list(np.random.RandomState(0).randint(1, 128, 9)),
                   max_new_tokens=60)
    cb.step(); cb.step(); cb.step()
    assert cb.decode_chunk_last == 4  # steady before the admission
    cb.submit(list(np.random.RandomState(1).randint(1, 128, 60)),
              max_new_tokens=8)
    steps = 0
    while cb._pf is not None or cb.prefill_chunks_total == 0:
        evs = cb.step()
        steps += 1
        assert steps < 10
        # The fused dispatch kept a fused-K scan AND the resident row
        # kept emitting (the classic path would have reset to K=1 and,
        # worse, stalled the row for the whole-prompt insert).
        assert cb.decode_chunk_last == 4
        assert any(ev[0] == r0 for ev in evs)
    while cb.pending():
        cb.step()


def test_fused_metrics_surface(model):
    """The fused-scheduling observability gauges are in stats() (and
    therefore in the HTTP /metrics exposition)."""
    params, config = model
    cb = ContinuousBatcher(
        params, config, n_slots=2, max_len=128, decode_chunk=4,
        block_size=16, prefill_budget=16,
    )
    cb.submit([4, 5, 6], max_new_tokens=20)
    cb.step(); cb.step()
    cb.submit(list(np.random.RandomState(1).randint(1, 128, 40)),
              max_new_tokens=4)
    cb.run_to_completion()
    stats = cb.stats()
    for key in (
        "prefill_budget", "prefill_tokens_inflight",
        "prefill_chunks_total", "fused_admissions_total",
        "decode_stall_ms_total",
    ):
        assert key in stats, key
    assert stats["prefill_budget"] == 16
    assert stats["fused_admissions_total"] == 1
    assert stats["prefill_chunks_total"] >= 2
    assert stats["prefill_tokens_inflight"] == 0  # drained


# ---------------------------------------------------------------------------
# The speculative path (spec_rounds > 1) owes the same discipline
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def spec_models(model):
    params, config = model
    draft_config = get_config(
        "tiny", **{**CFG, "dim": 32, "n_layers": 1, "n_heads": 2,
                   "n_kv_heads": 1}
    )
    draft_params = init_params(jax.random.PRNGKey(1), draft_config)
    return params, config, draft_params, draft_config


def test_spec_steady_state_host_sync_discipline(spec_models):
    """Steady-state fused-spec dispatches: exactly 1 device->host fetch
    (the packed [B, R, W] block) and ZERO host->device state uploads
    per R-round dispatch — the classic loop paid 2-3 fetches + a
    5-array mirror upload PER ROUND."""
    params, config, draft_params, draft_config = spec_models
    cb = ContinuousBatcher(
        params, config, n_slots=2, max_len=128,
        draft_params=draft_params, draft_config=draft_config,
        n_draft=2, spec_rounds=4,
    )
    cb.submit(list(np.random.RandomState(0).randint(1, 128, 9)),
              max_new_tokens=90)
    cb.step()   # admission (R=1) + the one state sync it owes
    cb.step()   # round-count ramp
    assert cb.state_uploads_total == 1  # the admission's row sync
    s0, u0, d0 = (
        cb.host_syncs_total, cb.state_uploads_total,
        cb.spec_dispatches_total,
    )
    for _ in range(4):
        cb.step()
    dispatches = cb.spec_dispatches_total - d0
    assert dispatches == 4
    # Exactly 1 sync per dispatch (the packed token/acc/logprob block)...
    assert cb.host_syncs_total - s0 == dispatches
    # ...and ZERO steady-state state uploads.
    assert cb.state_uploads_total == u0
    # The steady-state chunks ran fused (R > 1).
    assert cb.spec_rounds_last == 4
    while cb.pending():
        cb.step()


# slow (r17 budget rebalance, ~15 s): R follows the SAME ``_pick_chunk``
# policy the plain chunked path follows (the docstring's own claim) —
# tier-1 pins the policy via test_chunk_size_adapts_around_admissions
# and the spec path's host-sync discipline + gauges via
# test_spec_steady_state_host_sync_discipline / test_spec_metrics_surface;
# the spec-R adaptivity drill rides slow (unfiltered suite runs it).
@pytest.mark.slow
def test_spec_rounds_adapt_around_admissions(spec_models):
    """R drops to 1 right after an admission (TTFT), stays clamped at
    <= _QUEUED_CHUNK_CAP while the queue holds capacity-blocked
    requests, then ramps to the configured spec_rounds — the same
    _pick_chunk policy the plain chunked path follows."""
    params, config, draft_params, draft_config = spec_models
    cb = ContinuousBatcher(
        params, config, n_slots=1, max_len=128,
        draft_params=draft_params, draft_config=draft_config,
        n_draft=2, spec_rounds=8,
    )
    cb.submit([4, 5, 6], max_new_tokens=40)
    cb.submit([7, 8, 9], max_new_tokens=40)  # queued behind slot 0
    cb.step()
    assert cb.spec_rounds_last == 1   # admission step
    cb.step()
    # Queue capacity-blocked: clamped small but still > 1.
    assert cb.spec_rounds_last == cb._QUEUED_CHUNK_CAP
    seen = set()
    guard = 0
    while cb.pending():
        guard += 1
        assert guard < 200
        cb.step()
        seen.add(cb.spec_rounds_last)
    assert 8 in seen
    assert seen <= {1, 2, 4, 8}


def test_spec_metrics_surface(spec_models):
    """The speculative observability gauges are in stats() (and
    therefore in the HTTP /metrics exposition)."""
    params, config, draft_params, draft_config = spec_models
    cb = ContinuousBatcher(
        params, config, n_slots=1, max_len=64,
        draft_params=draft_params, draft_config=draft_config,
        n_draft=2, spec_rounds=4,
    )
    cb.submit([4, 5, 6], max_new_tokens=8)
    cb.run_to_completion()
    stats = cb.stats()
    for key in (
        "spec_rounds_per_dispatch", "spec_dispatches_total",
        "spec_host_syncs_per_token", "spec_window_acceptance_rate",
    ):
        assert key in stats, key
    assert stats["spec_dispatches_total"] > 0
    # Fused rounds amortize: well under the classic loop's >= 2
    # fetches per round (>= 2 per token at acceptance 0).
    assert 0 < stats["spec_host_syncs_per_token"] <= 1.5
    assert 0.0 <= stats["spec_window_acceptance_rate"] <= 1.0


# ---------------------------------------------------------------------------
# Observability overhead (obs.py): tracing is ALWAYS ON, so its cost
# contract — zero device dispatches, zero extra host syncs — is proven
# by the same instrumented counters the chunk discipline uses.
# ---------------------------------------------------------------------------


@pytest.mark.obs
def test_tracing_adds_zero_device_dispatches_and_host_syncs(model):
    """Dispatch-span recording is pure host bookkeeping at boundaries
    the loop already crosses: steady-state chunks still pay EXACTLY one
    device->host sync and zero state uploads each, every counted
    dispatch owns exactly one span in the obs ring (1:1 — a span that
    cost its own dispatch would break the equality from the other
    side), and recording never fetches (fetch_ms is measured around the
    loop's OWN packed np.asarray, not a second one)."""
    params, config = model
    cb = ContinuousBatcher(
        params, config, n_slots=2, max_len=128, decode_chunk=4,
    )
    cb.submit(list(np.random.RandomState(0).randint(1, 128, 9)),
              max_new_tokens=40)
    cb.step()   # admission + its one owed state sync
    cb.step()   # chunk-size ramp
    s0, u0, d0 = (
        cb.host_syncs_total, cb.state_uploads_total,
        cb.decode_dispatches_total,
    )
    seq0 = cb.obs._seq
    for _ in range(4):
        cb.step()
    dispatches = cb.decode_dispatches_total - d0
    assert dispatches == 4
    # The 1-fetch/0-upload steady state is bit-identical with tracing
    # on (it cannot be turned off — this IS the with-tracing number,
    # and the pre-obs suites above pin the same constants).
    assert cb.host_syncs_total - s0 == dispatches
    assert cb.state_uploads_total == u0
    # Exactly one dispatch span per counted dispatch, no extras.
    assert cb.obs._seq - seq0 == dispatches
    spans = list(cb.obs.dispatches)[-dispatches:]
    assert all(sp["kind"] == "decode" and sp["k"] == 4 for sp in spans)
    # The span's fetch wraps the loop's own sync: bounded by wall.
    assert all(0.0 <= sp["fetch_ms"] <= sp["wall_ms"] for sp in spans)


@pytest.mark.obs
def test_attribution_adds_zero_device_dispatches_and_host_syncs(model):
    """The device-time attribution layer (static cost models + compile
    attribution) rides the existing one-fetch-per-chunk boundary: with
    ``cost_models=True`` steady-state chunks STILL pay exactly one
    device->host sync and zero state uploads each, every dispatch span
    carries its program name and roofline estimate, and the cost
    analysis ran at TRACE time only — the cache holds one entry per
    (program, key), not one per dispatch."""
    from jax_llama_tpu.serving import _COST_MODELS

    params, config = model
    cb = ContinuousBatcher(
        params, config, n_slots=2, max_len=128, decode_chunk=4,
        cost_models=True,
    )
    cb.submit(list(np.random.RandomState(3).randint(1, 128, 9)),
              max_new_tokens=40)
    cb.step()   # admission + its one owed state sync
    cb.step()   # chunk-size ramp (K=1,2 cost models land here)
    cb.step()
    s0, u0, d0 = (
        cb.host_syncs_total, cb.state_uploads_total,
        cb.decode_dispatches_total,
    )
    keys0 = sum(
        e["keys"] for e in _COST_MODELS.snapshot().values()
    )
    for _ in range(4):
        cb.step()
    dispatches = cb.decode_dispatches_total - d0
    assert dispatches == 4
    # The 1-fetch/0-upload steady state is bit-identical with the
    # attribution layer on.
    assert cb.host_syncs_total - s0 == dispatches
    assert cb.state_uploads_total == u0
    # Steady-state dispatches hit the cost cache — zero new lowerings.
    assert sum(
        e["keys"] for e in _COST_MODELS.snapshot().values()
    ) == keys0
    spans = list(cb.obs.dispatches)[-dispatches:]
    assert all(
        sp["program"] == "_paged_decode_chunk" and "flops" in sp
        and sp["device_est_ms"] > 0
        for sp in spans
    )
    # The utilization window saw them: per-kind gauges are live.
    fams = {f for f, lab, _ in cb.obs.utilization_metrics()
            if lab.get("kind") == "decode"}
    assert {"mxu_utilization", "hbm_utilization",
            "host_overhead_ratio"} <= fams


@pytest.mark.obs
def test_tracing_overhead_fused_admission_budget_unchanged(model):
    """A fused admission's host-boundary budget (<= 1 state upload for
    the whole prefill, 1 fetch per chunk dispatch) is unchanged by the
    span bookkeeping riding those dispatches, and the admission's
    prefill-carrying dispatches each recorded a span linked to the
    admitted request."""
    params, config = model
    cb = ContinuousBatcher(
        params, config, n_slots=2, max_len=128, decode_chunk=4,
        prefill_budget=32,
    )
    rid0 = cb.submit(
        list(np.random.RandomState(1).randint(1, 128, 9)),
        max_new_tokens=48,
    )
    for _ in range(6):
        cb.step()
    s0, u0, d0 = (
        cb.host_syncs_total, cb.state_uploads_total,
        cb.decode_dispatches_total,
    )
    seq0 = cb.obs._seq
    rid = cb.submit(
        list(np.random.RandomState(2).randint(1, 128, 40)),
        max_new_tokens=4,
    )
    while any(
        s is not None and s.request_id == rid0
        for s in cb.slots.values()
    ) and cb.pending():
        cb.step()
    dispatches = cb.decode_dispatches_total - d0
    # One fetch per dispatch, and the fused admission's single upload.
    assert cb.host_syncs_total - s0 == dispatches
    assert cb.state_uploads_total - u0 <= 1
    assert cb.obs._seq - seq0 == dispatches
    fused = [
        sp for sp in cb.obs.dispatches
        if sp["seq"] >= seq0 and sp["prefill_tokens"] > 0
    ]
    assert fused, "expected prefill-carrying dispatch spans"
    assert all(rid in sp["rids"] for sp in fused)
