"""Speculative decoding inside the continuous batcher: output must be
token-identical to the plain greedy batcher — the draft model only changes
speed (acceptance), never content."""

import jax
import numpy as np
import pytest

from jax_llama_tpu import get_config, init_params
from jax_llama_tpu.serving import ContinuousBatcher

CFG = dict(
    vocab_size=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
    multiple_of=32, max_seq_len=128, dtype="float32", param_dtype="float32",
)


@pytest.fixture(scope="module")
def models():
    config = get_config("tiny", **CFG)
    params = init_params(jax.random.PRNGKey(0), config)
    draft_config = get_config(
        "tiny", **{**CFG, "dim": 32, "n_layers": 1, "n_heads": 2,
                   "n_kv_heads": 1}
    )
    draft_params = init_params(jax.random.PRNGKey(1), draft_config)
    return params, config, draft_params, draft_config


def _plain(params, config, prompts, max_new, stop=()):
    cb = ContinuousBatcher(params, config, n_slots=2, max_len=64,
                           stop_tokens=stop)
    rids = [cb.submit(p, max_new_tokens=max_new) for p in prompts]
    return rids, cb.run_to_completion()


@pytest.mark.slow  # interpret-mode Pallas / long decode on CPU; out of the tier-1 budget (plain `pytest tests/` still runs it)
def test_spec_batcher_matches_plain_greedy(models):
    params, config, draft_params, draft_config = models
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, 128, size=rng.randint(3, 12)).tolist()
               for _ in range(5)]
    prids, pres = _plain(params, config, prompts, 12)

    cb = ContinuousBatcher(
        params, config, n_slots=2, max_len=64,
        draft_params=draft_params, draft_config=draft_config, n_draft=3,
    )
    rids = [cb.submit(p, max_new_tokens=12) for p in prompts]
    results = cb.run_to_completion()
    for rid, prid in zip(rids, prids):
        assert results[rid] == pres[prid]
    assert cb.drafts_proposed > 0
    assert 0.0 <= cb.acceptance_rate() <= 1.0


@pytest.mark.slow  # interpret-mode Pallas / long decode on CPU; out of the tier-1 budget (plain `pytest tests/` still runs it)
def test_spec_batcher_self_draft_accepts_everything(models):
    """With the target as its own draft, greedy proposals always match —
    acceptance must be 100% and each request finishes in ~max_new/(G+1)
    rounds instead of max_new steps."""
    params, config, _, _ = models
    prompt = [5, 17, 99, 3, 42]
    _, pres = _plain(params, config, [prompt], 12)

    cb = ContinuousBatcher(
        params, config, n_slots=1, max_len=64,
        draft_params=params, draft_config=config, n_draft=3,
    )
    rid = cb.submit(prompt, max_new_tokens=12)
    results = cb.run_to_completion()
    assert results[rid] == pres[0]
    assert cb.acceptance_rate() == 1.0
    # 1 emission step + ceil(11 / 4) spec rounds, not 12 steps.
    assert cb.steps_total <= 4


def test_spec_batcher_stop_tokens(models):
    params, config, draft_params, draft_config = models
    prompt = [5, 17, 99, 3, 42]
    _, pres = _plain(params, config, [prompt], 16)
    stop = pres[0][4]  # 5th emitted token becomes the stop
    _, pres_stop = _plain(params, config, [prompt], 16, stop=(stop,))

    cb = ContinuousBatcher(
        params, config, n_slots=1, max_len=64, stop_tokens=(stop,),
        draft_params=draft_params, draft_config=draft_config, n_draft=4,
    )
    rid = cb.submit(prompt, max_new_tokens=16)
    results = cb.run_to_completion()
    assert results[rid] == pres_stop[0]
    assert not cb.pending()
    assert sorted(cb.free_blocks) == list(range(cb.n_blocks))


@pytest.mark.slow  # interpret-mode Pallas / long decode on CPU; out of the tier-1 budget (plain `pytest tests/` still runs it)
def test_spec_batcher_sampled_matches_standalone(models):
    """Sampled speculative serving: a sampled slot must emit BIT-identical
    tokens to a standalone seeded ``generate_speculative`` of the same
    request (same key-split topology, same warp math), while a greedy slot
    sharing the batch stays token-identical to the plain greedy batcher."""
    import jax.numpy as jnp

    from jax_llama_tpu.engine import GenerationConfig
    from jax_llama_tpu.spec_decode import generate_speculative

    params, config, draft_params, draft_config = models
    rng = np.random.RandomState(5)
    sampled_prompt = rng.randint(1, 128, size=7).tolist()
    greedy_prompt = rng.randint(1, 128, size=5).tolist()

    cb = ContinuousBatcher(
        params, config, n_slots=2, max_len=64,
        draft_params=draft_params, draft_config=draft_config, n_draft=3,
    )
    r0 = cb.submit(
        sampled_prompt, max_new_tokens=10, temperature=0.9, top_p=0.8,
        seed=123,
    )
    r1 = cb.submit(greedy_prompt, max_new_tokens=10)
    results = cb.run_to_completion()

    # Greedy slot: unchanged vs the plain (non-spec) greedy batcher.
    _, pres = _plain(params, config, [greedy_prompt], 10)
    assert results[r1] == list(pres.values())[0]

    # Sampled slot: bit-identical to the standalone engine with its seed.
    gc = GenerationConfig(
        max_new_tokens=10, temperature=0.9, top_p=0.8, top_k=None,
        stop_tokens=(), pad_id=0,
    )
    P = len(sampled_prompt)
    buf, _ = generate_speculative(
        params, draft_params,
        jnp.asarray([sampled_prompt], jnp.int32),
        jnp.ones((1, P), bool),
        jax.random.PRNGKey(123),
        target_config=config, draft_config=draft_config, gen_config=gc,
        n_draft=3, mesh=None,
    )
    want = np.asarray(buf)[0, P:P + 10].tolist()
    assert results[r0] == want


@pytest.mark.slow  # interpret-mode Pallas / long decode on CPU; out of the tier-1 budget (plain `pytest tests/` still runs it)
def test_spec_batcher_logprobs_match_engine_score(models):
    """logprobs=True composes with speculative decoding: every emitted
    token's logprob equals ``engine.score``'s teacher-forced
    log p(token | prefix) at the same position — for greedy AND sampled
    slots, whether the token was emitted from an accepted draft prefix,
    a rejection replacement/bonus, or the carried tau.  Tokens themselves
    stay identical to the logprobs=False batcher (the logprob read is
    pure observation)."""
    import jax.numpy as jnp

    from jax_llama_tpu.engine import score

    params, config, draft_params, draft_config = models
    rng = np.random.RandomState(11)
    prompts = [rng.randint(1, 128, size=n).tolist() for n in (6, 9)]

    def run(logprobs):
        cb = ContinuousBatcher(
            params, config, n_slots=2, max_len=64, logprobs=logprobs,
            draft_params=draft_params, draft_config=draft_config,
            n_draft=3,
        )
        r0 = cb.submit(prompts[0], max_new_tokens=8)  # greedy
        r1 = cb.submit(
            prompts[1], max_new_tokens=8, temperature=0.7, top_p=0.9,
            seed=7,
        )
        got, lps = {}, {}
        while cb.pending():
            for rid, tok, done, *rest in cb.step():
                got.setdefault(rid, []).append(tok)
                if rest:
                    lps.setdefault(rid, []).append(rest[0])
        return r0, r1, got, lps

    r0, r1, got, lps = run(True)
    p0, p1, got_plain, _ = run(False)
    assert got[r0] == got_plain[p0] and got[r1] == got_plain[p1]

    for rid, prompt in ((r0, prompts[0]), (r1, prompts[1])):
        toks = got[rid]
        assert len(lps[rid]) == len(toks)
        full = jnp.asarray([prompt + toks], jnp.int32)
        sc = np.asarray(score(params, full, config=config))[0]
        want = [float(sc[len(prompt) + i - 1]) for i in range(len(toks))]
        np.testing.assert_allclose(lps[rid], want, atol=1e-4, rtol=1e-4)


@pytest.mark.slow  # interpret-mode Pallas / long decode on CPU; out of the tier-1 budget (plain `pytest tests/` still runs it)
def test_spec_batcher_sampled_only_batch(models):
    """Two sampled slots with different seeds/policies, no greedy rows:
    each must reproduce its standalone seeded run."""
    import jax.numpy as jnp

    from jax_llama_tpu.engine import GenerationConfig
    from jax_llama_tpu.spec_decode import generate_speculative

    params, config, draft_params, draft_config = models
    rng = np.random.RandomState(11)
    prompts = [rng.randint(1, 128, size=6).tolist(),
               rng.randint(1, 128, size=9).tolist()]
    policies = [dict(temperature=0.7, top_p=1.0, seed=7),
                dict(temperature=1.3, top_p=0.9, top_k=20, seed=8)]

    cb = ContinuousBatcher(
        params, config, n_slots=2, max_len=64,
        draft_params=draft_params, draft_config=draft_config, n_draft=2,
    )
    rids = [
        cb.submit(p, max_new_tokens=8, **pol)
        for p, pol in zip(prompts, policies)
    ]
    results = cb.run_to_completion()

    for p, pol, rid in zip(prompts, policies, rids):
        gc = GenerationConfig(
            max_new_tokens=8, temperature=pol["temperature"],
            top_p=pol["top_p"], top_k=pol.get("top_k"),
            stop_tokens=(), pad_id=0,
        )
        P = len(p)
        buf, _ = generate_speculative(
            params, draft_params, jnp.asarray([p], jnp.int32),
            jnp.ones((1, P), bool), jax.random.PRNGKey(pol["seed"]),
            target_config=config, draft_config=draft_config,
            gen_config=gc, n_draft=2, mesh=None,
        )
        want = np.asarray(buf)[0, P:P + 8].tolist()
        assert results[rid] == want, f"slot {rid}"


def test_spec_batcher_staggered_admission(models):
    """Requests entering mid-flight under overcommit must still match the
    plain batcher exactly."""
    params, config, draft_params, draft_config = models
    rng = np.random.RandomState(3)
    prompts = [rng.randint(1, 128, size=rng.randint(3, 10)).tolist()
               for _ in range(4)]
    prids, pres = _plain(params, config, prompts, 10)

    cb = ContinuousBatcher(
        params, config, n_slots=2, max_len=64, block_size=16, n_blocks=5,
        draft_params=draft_params, draft_config=draft_config, n_draft=2,
    )
    rids = {}
    results = {}
    rids[cb.submit(prompts[0], max_new_tokens=10)] = 0
    submitted = 1
    guard = 0
    while cb.pending():
        guard += 1
        assert guard < 300
        for rid, tok, done in cb.step():
            results.setdefault(rid, []).append(tok)
        if submitted < len(prompts):
            rids[cb.submit(prompts[submitted], max_new_tokens=10)] = submitted
            submitted += 1
    for rid, pi in rids.items():
        assert results[rid] == pres[prids[pi]], f"prompt {pi}"
