"""Speculative decoding inside the continuous batcher: output must be
token-identical to the plain greedy batcher — the draft model only changes
speed (acceptance), never content."""

import jax
import numpy as np
import pytest

from jax_llama_tpu import get_config, init_params
from jax_llama_tpu.serving import ContinuousBatcher

CFG = dict(
    vocab_size=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
    multiple_of=32, max_seq_len=128, dtype="float32", param_dtype="float32",
)


@pytest.fixture(scope="module")
def models():
    config = get_config("tiny", **CFG)
    params = init_params(jax.random.PRNGKey(0), config)
    draft_config = get_config(
        "tiny", **{**CFG, "dim": 32, "n_layers": 1, "n_heads": 2,
                   "n_kv_heads": 1}
    )
    draft_params = init_params(jax.random.PRNGKey(1), draft_config)
    return params, config, draft_params, draft_config


def _plain(params, config, prompts, max_new, stop=()):
    cb = ContinuousBatcher(params, config, n_slots=2, max_len=64,
                           stop_tokens=stop)
    rids = [cb.submit(p, max_new_tokens=max_new) for p in prompts]
    return rids, cb.run_to_completion()


def test_spec_batcher_matches_plain_greedy(models):
    params, config, draft_params, draft_config = models
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, 128, size=rng.randint(3, 12)).tolist()
               for _ in range(5)]
    prids, pres = _plain(params, config, prompts, 12)

    cb = ContinuousBatcher(
        params, config, n_slots=2, max_len=64,
        draft_params=draft_params, draft_config=draft_config, n_draft=3,
    )
    rids = [cb.submit(p, max_new_tokens=12) for p in prompts]
    results = cb.run_to_completion()
    for rid, prid in zip(rids, prids):
        assert results[rid] == pres[prid]
    assert cb.drafts_proposed > 0
    assert 0.0 <= cb.acceptance_rate() <= 1.0


def test_spec_batcher_self_draft_accepts_everything(models):
    """With the target as its own draft, greedy proposals always match —
    acceptance must be 100% and each request finishes in ~max_new/(G+1)
    rounds instead of max_new steps."""
    params, config, _, _ = models
    prompt = [5, 17, 99, 3, 42]
    _, pres = _plain(params, config, [prompt], 12)

    cb = ContinuousBatcher(
        params, config, n_slots=1, max_len=64,
        draft_params=params, draft_config=config, n_draft=3,
    )
    rid = cb.submit(prompt, max_new_tokens=12)
    results = cb.run_to_completion()
    assert results[rid] == pres[0]
    assert cb.acceptance_rate() == 1.0
    # 1 emission step + ceil(11 / 4) spec rounds, not 12 steps.
    assert cb.steps_total <= 4


def test_spec_batcher_stop_tokens(models):
    params, config, draft_params, draft_config = models
    prompt = [5, 17, 99, 3, 42]
    _, pres = _plain(params, config, [prompt], 16)
    stop = pres[0][4]  # 5th emitted token becomes the stop
    _, pres_stop = _plain(params, config, [prompt], 16, stop=(stop,))

    cb = ContinuousBatcher(
        params, config, n_slots=1, max_len=64, stop_tokens=(stop,),
        draft_params=draft_params, draft_config=draft_config, n_draft=4,
    )
    rid = cb.submit(prompt, max_new_tokens=16)
    results = cb.run_to_completion()
    assert results[rid] == pres_stop[0]
    assert not cb.pending()
    assert sorted(cb.free_blocks) == list(range(cb.n_blocks))


def test_spec_batcher_rejects_sampling(models):
    params, config, draft_params, draft_config = models
    cb = ContinuousBatcher(
        params, config, n_slots=1, max_len=64,
        draft_params=draft_params, draft_config=draft_config,
    )
    with pytest.raises(ValueError, match="greedy-only"):
        cb.submit([1, 2, 3], max_new_tokens=4, temperature=0.8)
    with pytest.raises(ValueError, match="greedy-only"):
        ContinuousBatcher(
            params, config, n_slots=1, max_len=64, temperature=0.7,
            draft_params=draft_params, draft_config=draft_config,
        )


def test_spec_batcher_staggered_admission(models):
    """Requests entering mid-flight under overcommit must still match the
    plain batcher exactly."""
    params, config, draft_params, draft_config = models
    rng = np.random.RandomState(3)
    prompts = [rng.randint(1, 128, size=rng.randint(3, 10)).tolist()
               for _ in range(4)]
    prids, pres = _plain(params, config, prompts, 10)

    cb = ContinuousBatcher(
        params, config, n_slots=2, max_len=64, block_size=16, n_blocks=5,
        draft_params=draft_params, draft_config=draft_config, n_draft=2,
    )
    rids = {}
    results = {}
    rids[cb.submit(prompts[0], max_new_tokens=10)] = 0
    submitted = 1
    guard = 0
    while cb.pending():
        guard += 1
        assert guard < 300
        for rid, tok, done in cb.step():
            results.setdefault(rid, []).append(tok)
        if submitted < len(prompts):
            rids[cb.submit(prompts[submitted], max_new_tokens=10)] = submitted
            submitted += 1
    for rid, pi in rids.items():
        assert results[rid] == pres[prids[pi]], f"prompt {pi}"
