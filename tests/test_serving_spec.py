"""Speculative decoding inside the continuous batcher: output must be
token-identical to the plain greedy batcher — the draft model only changes
speed (acceptance), never content.

Fused R-round chunking (``spec_rounds`` > 1, ``_spec_rounds_chunk``)
must additionally be token-identical to the classic per-round loop —
including the ACCEPTANCE PATTERN (drafts proposed/accepted) and
per-token logprobs — across greedy/seeded-sampled policies, stop tokens
and max_new landing mid-chunk, non-finite logits mid-chunk, and the
int8-KV pool; and the crash-recovery / non-finite-guard / quarantine
semantics proven for the per-round loop must hold with round fusion
(fault sites fire once per R-round chunk dispatch, replay works from
delivered tokens, quarantine falls back to plain CHUNKED decode with
the decode_chunk / spec_rounds configuration preserved)."""

import dataclasses
import json
import threading
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from jax_llama_tpu import get_config, init_params
from jax_llama_tpu.faults import FaultInjector
from jax_llama_tpu.server import LLMServer
from jax_llama_tpu.serving import ContinuousBatcher

CFG = dict(
    vocab_size=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
    multiple_of=32, max_seq_len=128, dtype="float32", param_dtype="float32",
)


@pytest.fixture(scope="module")
def models():
    config = get_config("tiny", **CFG)
    params = init_params(jax.random.PRNGKey(0), config)
    draft_config = get_config(
        "tiny", **{**CFG, "dim": 32, "n_layers": 1, "n_heads": 2,
                   "n_kv_heads": 1}
    )
    draft_params = init_params(jax.random.PRNGKey(1), draft_config)
    return params, config, draft_params, draft_config


def _plain(params, config, prompts, max_new, stop=()):
    cb = ContinuousBatcher(params, config, n_slots=2, max_len=64,
                           stop_tokens=stop)
    rids = [cb.submit(p, max_new_tokens=max_new) for p in prompts]
    return rids, cb.run_to_completion()


@pytest.mark.slow  # interpret-mode Pallas / long decode on CPU; out of the tier-1 budget (plain `pytest tests/` still runs it)
def test_spec_batcher_matches_plain_greedy(models):
    params, config, draft_params, draft_config = models
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, 128, size=rng.randint(3, 12)).tolist()
               for _ in range(5)]
    prids, pres = _plain(params, config, prompts, 12)

    cb = ContinuousBatcher(
        params, config, n_slots=2, max_len=64,
        draft_params=draft_params, draft_config=draft_config, n_draft=3,
    )
    rids = [cb.submit(p, max_new_tokens=12) for p in prompts]
    results = cb.run_to_completion()
    for rid, prid in zip(rids, prids):
        assert results[rid] == pres[prid]
    assert cb.drafts_proposed > 0
    assert 0.0 <= cb.acceptance_rate() <= 1.0


@pytest.mark.slow  # interpret-mode Pallas / long decode on CPU; out of the tier-1 budget (plain `pytest tests/` still runs it)
def test_spec_batcher_self_draft_accepts_everything(models):
    """With the target as its own draft, greedy proposals always match —
    acceptance must be 100% and each request finishes in ~max_new/(G+1)
    rounds instead of max_new steps."""
    params, config, _, _ = models
    prompt = [5, 17, 99, 3, 42]
    _, pres = _plain(params, config, [prompt], 12)

    cb = ContinuousBatcher(
        params, config, n_slots=1, max_len=64,
        draft_params=params, draft_config=config, n_draft=3,
    )
    rid = cb.submit(prompt, max_new_tokens=12)
    results = cb.run_to_completion()
    assert results[rid] == pres[0]
    assert cb.acceptance_rate() == 1.0
    # 1 emission step + ceil(11 / 4) spec rounds, not 12 steps.
    assert cb.steps_total <= 4


def test_spec_batcher_stop_tokens(models):
    params, config, draft_params, draft_config = models
    prompt = [5, 17, 99, 3, 42]
    _, pres = _plain(params, config, [prompt], 16)
    stop = pres[0][4]  # 5th emitted token becomes the stop
    _, pres_stop = _plain(params, config, [prompt], 16, stop=(stop,))

    cb = ContinuousBatcher(
        params, config, n_slots=1, max_len=64, stop_tokens=(stop,),
        draft_params=draft_params, draft_config=draft_config, n_draft=4,
    )
    rid = cb.submit(prompt, max_new_tokens=16)
    results = cb.run_to_completion()
    assert results[rid] == pres_stop[0]
    assert not cb.pending()
    assert sorted(cb.free_blocks) == list(range(cb.n_blocks))


@pytest.mark.slow  # interpret-mode Pallas / long decode on CPU; out of the tier-1 budget (plain `pytest tests/` still runs it)
def test_spec_batcher_sampled_matches_standalone(models):
    """Sampled speculative serving: a sampled slot must emit BIT-identical
    tokens to a standalone seeded ``generate_speculative`` of the same
    request (same key-split topology, same warp math), while a greedy slot
    sharing the batch stays token-identical to the plain greedy batcher."""
    import jax.numpy as jnp

    from jax_llama_tpu.engine import GenerationConfig
    from jax_llama_tpu.spec_decode import generate_speculative

    params, config, draft_params, draft_config = models
    rng = np.random.RandomState(5)
    sampled_prompt = rng.randint(1, 128, size=7).tolist()
    greedy_prompt = rng.randint(1, 128, size=5).tolist()

    cb = ContinuousBatcher(
        params, config, n_slots=2, max_len=64,
        draft_params=draft_params, draft_config=draft_config, n_draft=3,
    )
    r0 = cb.submit(
        sampled_prompt, max_new_tokens=10, temperature=0.9, top_p=0.8,
        seed=123,
    )
    r1 = cb.submit(greedy_prompt, max_new_tokens=10)
    results = cb.run_to_completion()

    # Greedy slot: unchanged vs the plain (non-spec) greedy batcher.
    _, pres = _plain(params, config, [greedy_prompt], 10)
    assert results[r1] == list(pres.values())[0]

    # Sampled slot: bit-identical to the standalone engine with its seed.
    gc = GenerationConfig(
        max_new_tokens=10, temperature=0.9, top_p=0.8, top_k=None,
        stop_tokens=(), pad_id=0,
    )
    P = len(sampled_prompt)
    buf, _ = generate_speculative(
        params, draft_params,
        jnp.asarray([sampled_prompt], jnp.int32),
        jnp.ones((1, P), bool),
        jax.random.PRNGKey(123),
        target_config=config, draft_config=draft_config, gen_config=gc,
        n_draft=3, mesh=None,
    )
    want = np.asarray(buf)[0, P:P + 10].tolist()
    assert results[r0] == want


@pytest.mark.slow  # interpret-mode Pallas / long decode on CPU; out of the tier-1 budget (plain `pytest tests/` still runs it)
def test_spec_batcher_logprobs_match_engine_score(models):
    """logprobs=True composes with speculative decoding: every emitted
    token's logprob equals ``engine.score``'s teacher-forced
    log p(token | prefix) at the same position — for greedy AND sampled
    slots, whether the token was emitted from an accepted draft prefix,
    a rejection replacement/bonus, or the carried tau.  Tokens themselves
    stay identical to the logprobs=False batcher (the logprob read is
    pure observation)."""
    import jax.numpy as jnp

    from jax_llama_tpu.engine import score

    params, config, draft_params, draft_config = models
    rng = np.random.RandomState(11)
    prompts = [rng.randint(1, 128, size=n).tolist() for n in (6, 9)]

    def run(logprobs):
        cb = ContinuousBatcher(
            params, config, n_slots=2, max_len=64, logprobs=logprobs,
            draft_params=draft_params, draft_config=draft_config,
            n_draft=3,
        )
        r0 = cb.submit(prompts[0], max_new_tokens=8)  # greedy
        r1 = cb.submit(
            prompts[1], max_new_tokens=8, temperature=0.7, top_p=0.9,
            seed=7,
        )
        got, lps = {}, {}
        while cb.pending():
            for rid, tok, done, *rest in cb.step():
                got.setdefault(rid, []).append(tok)
                if rest:
                    lps.setdefault(rid, []).append(rest[0])
        return r0, r1, got, lps

    r0, r1, got, lps = run(True)
    p0, p1, got_plain, _ = run(False)
    assert got[r0] == got_plain[p0] and got[r1] == got_plain[p1]

    for rid, prompt in ((r0, prompts[0]), (r1, prompts[1])):
        toks = got[rid]
        assert len(lps[rid]) == len(toks)
        full = jnp.asarray([prompt + toks], jnp.int32)
        sc = np.asarray(score(params, full, config=config))[0]
        want = [float(sc[len(prompt) + i - 1]) for i in range(len(toks))]
        np.testing.assert_allclose(lps[rid], want, atol=1e-4, rtol=1e-4)


@pytest.mark.slow  # interpret-mode Pallas / long decode on CPU; out of the tier-1 budget (plain `pytest tests/` still runs it)
def test_spec_batcher_sampled_only_batch(models):
    """Two sampled slots with different seeds/policies, no greedy rows:
    each must reproduce its standalone seeded run."""
    import jax.numpy as jnp

    from jax_llama_tpu.engine import GenerationConfig
    from jax_llama_tpu.spec_decode import generate_speculative

    params, config, draft_params, draft_config = models
    rng = np.random.RandomState(11)
    prompts = [rng.randint(1, 128, size=6).tolist(),
               rng.randint(1, 128, size=9).tolist()]
    policies = [dict(temperature=0.7, top_p=1.0, seed=7),
                dict(temperature=1.3, top_p=0.9, top_k=20, seed=8)]

    cb = ContinuousBatcher(
        params, config, n_slots=2, max_len=64,
        draft_params=draft_params, draft_config=draft_config, n_draft=2,
    )
    rids = [
        cb.submit(p, max_new_tokens=8, **pol)
        for p, pol in zip(prompts, policies)
    ]
    results = cb.run_to_completion()

    for p, pol, rid in zip(prompts, policies, rids):
        gc = GenerationConfig(
            max_new_tokens=8, temperature=pol["temperature"],
            top_p=pol["top_p"], top_k=pol.get("top_k"),
            stop_tokens=(), pad_id=0,
        )
        P = len(p)
        buf, _ = generate_speculative(
            params, draft_params, jnp.asarray([p], jnp.int32),
            jnp.ones((1, P), bool), jax.random.PRNGKey(pol["seed"]),
            target_config=config, draft_config=draft_config,
            gen_config=gc, n_draft=2, mesh=None,
        )
        want = np.asarray(buf)[0, P:P + 8].tolist()
        assert results[rid] == want, f"slot {rid}"


# ---------------------------------------------------------------------------
# Fused R-round chunking (spec_rounds > 1): CPU parity matrix
# ---------------------------------------------------------------------------

def _spec_matrix(models, R, *, logprobs=False, stop=(), int8=False,
                 self_draft=False, **cb_kw):
    """The shared request mix — greedy finishing mid-chunk (max_new 5),
    greedy full-budget, two seeded sampled policies — 4 requests over
    2 slots, so R also ramps around queue-driven admissions.  Returns
    (per-request tokens, per-request logprobs, the acceptance pattern
    (proposed, accepted))."""
    params, config, draft_params, draft_config = models
    if self_draft:
        draft_params, draft_config = params, config
    if int8:
        config = dataclasses.replace(config, kv_cache_dtype="int8")
        draft_config = dataclasses.replace(
            draft_config, kv_cache_dtype="int8"
        )
        cb_kw.setdefault("block_size", 16)
    rng = np.random.RandomState(7)
    prompts = [rng.randint(1, 128, size=n).tolist() for n in (5, 9, 14, 6)]
    policies = [
        dict(max_new_tokens=5),
        dict(max_new_tokens=11),
        dict(max_new_tokens=9, temperature=0.9, seed=11),
        dict(max_new_tokens=12, temperature=0.7, top_p=0.8, seed=12),
    ]
    cb = ContinuousBatcher(
        params, config, n_slots=2, max_len=64, spec_rounds=R,
        draft_params=draft_params, draft_config=draft_config, n_draft=3,
        logprobs=logprobs, stop_tokens=stop, **cb_kw,
    )
    rids = [cb.submit(p, **pol) for p, pol in zip(prompts, policies)]
    toks, lps = {}, {}
    guard = 0
    while cb.pending():
        guard += 1
        assert guard < 500
        for ev in cb.step():
            toks.setdefault(ev[0], []).append(ev[1])
            if logprobs:
                lps.setdefault(ev[0], []).append(ev[3])
    return (
        [toks[r] for r in rids],
        [lps.get(r) for r in rids],
        (cb.drafts_proposed, cb.drafts_accepted),
    )


# Both cells ride the slow tier (r06 rebalanced R=2 out; r08 moved
# R=4 too — at ~30 s it was the single heaviest tier-1 test while the
# suite sat within 1% of its 870 s budget).  The R>1 ≡ classic
# identity class keeps tier-1 coverage through the stop-token /
# non-finite mid-chunk cells below and the perf-smoke spec matrix;
# the full greedy+sampled+acceptance-pattern matrix still runs in the
# unfiltered suite (plain `pytest tests/`, `make chaos`).
@pytest.mark.parametrize("R", [
    pytest.param(2, marks=pytest.mark.slow),
    pytest.param(4, marks=pytest.mark.slow),
])
def test_spec_rounds_token_identity_greedy_and_sampled(models, R):
    """R ∈ {2, 4} × {greedy, seeded-sampled} × max_new mid-chunk:
    tokens AND the acceptance pattern identical to the classic
    per-round loop (which the tests above pin against standalone
    engine/spec oracles)."""
    base, _, base_acc = _spec_matrix(models, 1)
    got, _, got_acc = _spec_matrix(models, R)
    assert got == base
    assert got_acc == base_acc


@pytest.mark.slow  # interpret-mode Pallas / long decode on CPU; out of the tier-1 budget (plain `pytest tests/` still runs it)
def test_spec_rounds_token_identity_logprobs(models):
    """logprobs ride the packed fetch bitcast: same values as the
    classic loop, token for token, for carried-tau, accepted-draft and
    replacement/bonus emissions alike."""
    base, base_lp, _ = _spec_matrix(models, 1, logprobs=True)
    got, got_lp, _ = _spec_matrix(models, 4, logprobs=True)
    assert got == base
    for a, b in zip(got_lp, base_lp):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


# slow (r17 budget rebalance, ~9 s): the two composing contracts keep
# tier-1 pins — the spec stop set via test_spec_batcher_stop_tokens,
# mid-chunk stop truncation via test_serving_chunked.py's stop cells —
# so the composed drill rides slow (unfiltered suite runs it).
@pytest.mark.slow
def test_spec_rounds_stop_token_mid_chunk(models):
    """A stop token landing INSIDE a round's accepted prefix, inside a
    fused chunk (self-draft => high acceptance => multi-token
    prefixes): the on-device accepted-prefix emit fold must end the
    request at exactly the token the host loop would."""
    params, config, _, _ = models
    prompt = [5, 17, 99, 3, 42]

    def run(R, stop=()):
        cb = ContinuousBatcher(
            params, config, n_slots=1, max_len=64, stop_tokens=stop,
            draft_params=params, draft_config=config, n_draft=3,
            spec_rounds=R,
        )
        rid = cb.submit(prompt, max_new_tokens=16)
        return cb.run_to_completion()[rid]

    free = run(1)
    j = next(i for i in range(1, len(free)) if free[i] not in free[:i])
    stop = free[j]
    want = run(1, stop=(stop,))
    got = run(4, stop=(stop,))
    assert want == free[:j + 1]
    assert got == want


@pytest.mark.slow  # interpret-mode Pallas / long decode on CPU; out of the tier-1 budget (plain `pytest tests/` still runs it)
def test_spec_rounds_int8_kv(models):
    """The int8 pools' quantized branches (per-round scale-plane writes
    for BOTH the target and draft pools inside the scan) must match
    their classic per-round emissions."""
    base, _, base_acc = _spec_matrix(models, 1, int8=True)
    got, _, got_acc = _spec_matrix(models, 4, int8=True)
    assert got == base
    assert got_acc == base_acc


def test_spec_rounds_nonfinite_mid_chunk(models):
    """NaN target logits under round fusion: the verify's -1 acceptance
    sentinel folds the row out mid-chunk, the round is never committed,
    and exactly that request fails — same contract as the classic
    loop's guard."""
    params, config, _, _ = models
    bad = dict(params)
    bad["lm_head"] = params["lm_head"] * float("nan")
    cb = ContinuousBatcher(
        bad, config, n_slots=1, max_len=64,
        draft_params=params, draft_config=config, n_draft=2,
        spec_rounds=4,
    )
    rid = cb.submit([5, 17, 99, 3], max_new_tokens=8)
    out = cb.run_to_completion()
    failed = cb.pop_failed()
    assert rid not in out
    assert failed and failed[0][0] == rid
    assert not cb.pending()
    assert sorted(cb.free_blocks) == list(range(cb.n_blocks))


# ---------------------------------------------------------------------------
# Fault-tolerance semantics with round fusion enabled
# ---------------------------------------------------------------------------

PROMPTS = [[5, 17, 99, 3], [7, 8, 9], [11, 12, 13]]
MAX_NEW = 12


def _post(url, payload, timeout=300):
    req = urllib.request.Request(
        url + "/generate", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def _stream_lines(url, payload, timeout=300):
    req = urllib.request.Request(
        url + "/generate", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        assert r.status == 200
        return [json.loads(line) for line in r.read().splitlines()]


@pytest.fixture(scope="module")
def reference(models):
    """Fault-free plain-greedy outputs (the identity oracle — the draft
    only ever changes speed)."""
    params, config, _, _ = models
    cb = ContinuousBatcher(params, config, n_slots=2, max_len=64)
    rids = [cb.submit(list(p), max_new_tokens=MAX_NEW) for p in PROMPTS]
    out = cb.run_to_completion()
    return [out[r] for r in rids]


@pytest.mark.faults
# slow (r06 budget rebalance, ~12 s): still in `make faults` / `make
# chaos`; the classic-path spec fault drills keep tier-1 coverage.
@pytest.mark.slow
def test_chunked_spec_fault_recovers_token_exact(models, reference):
    """A spec_decode-site fault mid-chunk (the site fires once per
    R-round dispatch): recovery rebuilds a fused-spec batcher and
    replays from delivered tokens — greedy outputs identical to the
    fault-free plain run, and a streaming client sees each token
    exactly once even though tokens now arrive in R-round bursts."""
    params, config, draft_params, draft_config = models
    inj = FaultInjector("spec_decode@2:error")
    cb = ContinuousBatcher(
        params, config, n_slots=2, max_len=64,
        draft_params=draft_params, draft_config=draft_config, n_draft=2,
        spec_rounds=4, fault_injector=inj,
    )
    results = {}
    # The spec_decode site is attributable: use a threshold ABOVE the
    # faults this drill injects so the drill exercises rebuild+replay,
    # not quarantine.
    with LLMServer(cb, quarantine_threshold=5) as srv:
        def call(i):
            try:
                if i == 0:  # one streaming client
                    results[i] = _stream_lines(
                        srv.address,
                        {"prompt": PROMPTS[i], "max_new_tokens": MAX_NEW,
                         "stream": True},
                    )
                else:
                    _, body = _post(
                        srv.address,
                        {"prompt": PROMPTS[i], "max_new_tokens": MAX_NEW},
                    )
                    results[i] = body["tokens"]
            except Exception as e:  # noqa: BLE001 — fail the test, not the thread
                results[i] = f"{type(e).__name__}: {e}"

        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(len(PROMPTS))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert not any(t.is_alive() for t in threads)

        lines = results[0]
        assert isinstance(lines, list), lines
        streamed = [ln["token"] for ln in lines[:-1]]
        assert streamed == reference[0]          # no dup, no gap
        assert lines[-1]["done"] is True
        assert lines[-1]["tokens"] == reference[0]
        for i in range(1, len(PROMPTS)):
            assert results[i] == reference[i], i
        assert inj.injected_total == 1
        assert srv.recoveries_total == 1
        # The rebuilt batcher still runs fused speculative serving.
        assert srv.batcher.spec and srv.batcher.spec_rounds == 4


@pytest.mark.faults
@pytest.mark.slow
def test_chunked_spec_nan_isolation_per_request(models, reference):
    """An armed nan poison under round fusion fails exactly one request
    with a clean 500 (its chunk tokens are discarded, never streamed);
    the neighbor slot completes token-identically.

    Slow tier (r14 budget rebalance, ~11 s server-backed drill; still
    in `make chaos`/`make faults` via its faults marker): the spec
    non-finite fold-out semantics stay tier-1-pinned by
    test_spec_rounds_nonfinite_mid_chunk, and per-request nan
    isolation at serving level by test_degrade's guard-isolation
    drills on the chunked path."""
    params, config, draft_params, draft_config = models
    inj = FaultInjector("step@1:nan")
    cb = ContinuousBatcher(
        params, config, n_slots=2, max_len=64,
        draft_params=draft_params, draft_config=draft_config, n_draft=2,
        spec_rounds=4, fault_injector=inj,
    )
    results = {}
    with LLMServer(cb) as srv:
        def call(i):
            try:
                results[i] = _post(
                    srv.address,
                    {"prompt": PROMPTS[i], "max_new_tokens": MAX_NEW},
                )[1]["tokens"]
            except urllib.error.HTTPError as e:
                results[i] = (e.code, json.loads(e.read())["error"])

        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert not any(t.is_alive() for t in threads)
    failed = [r for r in results.values() if isinstance(r, tuple)]
    ok = {i: r for i, r in results.items() if isinstance(r, list)}
    assert len(failed) == 1
    code, msg = failed[0]
    assert code == 500 and "non-finite" in msg
    assert len(ok) == 1
    (i, toks), = ok.items()
    assert toks == reference[i]
    assert inj.nans_armed_total == 1


@pytest.mark.faults
def test_chunked_spec_quarantine_falls_back_to_chunked_decode(
    models, reference
):
    """spec_decode faults past the threshold quarantine the feature and
    the batcher rebuilds WITHOUT the draft model but WITH the original
    decode_chunk / spec_rounds configuration — degraded speculative
    serving lands on plain CHUNKED decode, not the per-token loop, and
    requests replay token-identically."""
    params, config, draft_params, draft_config = models
    inj = FaultInjector("spec_decode~1.0:error")
    cb = ContinuousBatcher(
        params, config, n_slots=2, max_len=64, decode_chunk=4,
        draft_params=draft_params, draft_config=draft_config, n_draft=2,
        spec_rounds=4, fault_injector=inj,
    )
    with LLMServer(
        cb, quarantine_threshold=2, quarantine_cooldown_s=600.0
    ) as srv:
        _, body = _post(
            srv.address,
            {"prompt": PROMPTS[0], "max_new_tokens": MAX_NEW},
        )
        assert body["tokens"] == reference[0]
        assert srv.degrade.quarantined() == ("spec_decode",)
        # The fallback batcher is plain (no draft) but keeps the whole
        # chunk configuration for the day spec_decode probes healthy.
        assert not srv.batcher.spec
        assert srv.batcher.decode_chunk == 4
        assert srv.batcher.spec_rounds == 4
        # And keeps serving: a second request completes on the fallback.
        _, body2 = _post(
            srv.address,
            {"prompt": PROMPTS[1], "max_new_tokens": MAX_NEW},
        )
        assert body2["tokens"] == reference[1]


def test_spec_batcher_staggered_admission(models):
    """Requests entering mid-flight under overcommit must still match the
    plain batcher exactly."""
    params, config, draft_params, draft_config = models
    rng = np.random.RandomState(3)
    prompts = [rng.randint(1, 128, size=rng.randint(3, 10)).tolist()
               for _ in range(4)]
    prids, pres = _plain(params, config, prompts, 10)

    cb = ContinuousBatcher(
        params, config, n_slots=2, max_len=64, block_size=16, n_blocks=5,
        draft_params=draft_params, draft_config=draft_config, n_draft=2,
    )
    rids = {}
    results = {}
    rids[cb.submit(prompts[0], max_new_tokens=10)] = 0
    submitted = 1
    guard = 0
    while cb.pending():
        guard += 1
        assert guard < 300
        for rid, tok, done in cb.step():
            results.setdefault(rid, []).append(tok)
        if submitted < len(prompts):
            rids[cb.submit(prompts[submitted], max_new_tokens=10)] = submitted
            submitted += 1
    for rid, pi in rids.items():
        assert results[rid] == pres[prids[pi]], f"prompt {pi}"
