"""Independent PyTorch oracle for numerical-parity tests.

Plays the role Meta's ``llama`` repo plays for the reference test harness
(``/root/reference/jax_test.py:9-18`` imports it as the parity oracle): a
from-the-math torch implementation of the LLaMA architecture, written
independently of both the reference and the JAX framework under test, fp32
throughout.  It consumes the *same* param pytree layout as
``jax_llama_tpu.models.llama`` (numpy arrays) so tests load identical weights
into both sides.
"""

from __future__ import annotations

import math

import numpy as np
import torch


def rms_norm(x: torch.Tensor, scale: torch.Tensor, eps: float) -> torch.Tensor:
    ms = x.pow(2).mean(-1, keepdim=True)
    return x * torch.rsqrt(ms + eps) * scale


def rope_freqs_cis(head_dim: int, max_pos: int, theta: float) -> torch.Tensor:
    inv = 1.0 / (theta ** (torch.arange(0, head_dim, 2, dtype=torch.float64) / head_dim))
    t = torch.arange(max_pos, dtype=torch.float64)
    angles = torch.outer(t, inv)
    return torch.polar(torch.ones_like(angles), angles).to(torch.complex64)


def apply_rope(x: torch.Tensor, freqs_cis: torch.Tensor, positions: torch.Tensor) -> torch.Tensor:
    """x: [B, S, H, D]; interleaved-pair complex rotation (Meta convention)."""
    xc = torch.view_as_complex(x.float().reshape(*x.shape[:-1], -1, 2))
    fc = freqs_cis[positions]  # [B, S, D/2]
    out = torch.view_as_real(xc * fc[:, :, None, :]).flatten(-2)
    return out.type_as(x)


def _split_layers(lp):
    """Accept either the framework's fused layer layout (qkv [L, KVH,
    G+2, D, hd] + gate_up [L, 2, D, F]) or the separate one; return a
    dict with separate q/k/v/gate/up views in Meta interleaved-RoPE
    feature order, so the oracle math below stays an independent
    from-the-paper implementation of Meta's convention."""
    if "qkv" not in lp:
        return lp

    def unpermute(w):
        # Inverse of models.llama.rope_permute (numpy): runtime half-split
        # feature order -> Meta interleaved order.
        *lead, hd = w.shape
        return w.reshape(*lead, 2, hd // 2).swapaxes(-1, -2).reshape(w.shape)

    qkv = np.asarray(lp["qkv"])
    L, KVH, g2, D, hd = qkv.shape
    G = g2 - 2
    out = dict(lp)
    out["q"] = unpermute(
        np.moveaxis(qkv[:, :, :G], 3, 1).reshape(L, D, KVH * G, hd)
    )
    out["k"] = unpermute(qkv[:, :, G].swapaxes(1, 2))
    out["v"] = qkv[:, :, G + 1].swapaxes(1, 2)
    gu = np.asarray(lp["gate_up"])
    out["gate"], out["up"] = gu[:, 0], gu[:, 1]
    return out


def oracle_forward(params, tokens: np.ndarray, positions: np.ndarray, cfg) -> np.ndarray:
    """Full-model forward, no KV cache, fp32.  Returns [B, T, V] logits."""
    t = lambda a: torch.from_numpy(np.asarray(a)).float()
    tokens_t = torch.from_numpy(np.asarray(tokens)).long()
    pos = torch.from_numpy(np.asarray(positions)).long()
    mask_valid = pos >= 0
    pos_c = pos.clamp(min=0)

    B, T = tokens_t.shape
    H, KVH, hd = cfg.n_heads, cfg.kv_heads, cfg.head_dim
    freqs = rope_freqs_cis(hd, 2 * cfg.max_seq_len, cfg.rope_theta)

    x = t(params["embed"]["embedding"])[tokens_t]  # [B, T, D]

    # Additive mask: slot j attendable by query i iff valid[j] and
    # pos[j] <= pos[i] (matches the framework's position-based masking).
    slot_pos = torch.where(mask_valid, pos_c, torch.full_like(pos, -1))
    allowed = (slot_pos[:, None, :] >= 0) & (slot_pos[:, None, :] <= pos_c[:, :, None])
    bias = torch.where(allowed, 0.0, torch.finfo(torch.float32).min)[:, None, :, :]

    lp = _split_layers(params["layers"])
    for i in range(cfg.n_layers):
        h = rms_norm(x, t(lp["attn_norm"][i]), cfg.rms_norm_eps)
        q = torch.einsum("btd,dhk->bthk", h, t(lp["q"][i]))
        k = torch.einsum("btd,dhk->bthk", h, t(lp["k"][i]))
        v = torch.einsum("btd,dhk->bthk", h, t(lp["v"][i]))
        q = apply_rope(q, freqs, pos_c)
        k = apply_rope(k, freqs, pos_c)
        if KVH != H:
            rep = H // KVH
            k = k.repeat_interleave(rep, dim=2)
            v = v.repeat_interleave(rep, dim=2)
        scores = torch.einsum("bthk,bshk->bhts", q, k) / math.sqrt(hd)
        scores = scores + bias
        w = torch.softmax(scores, dim=-1)
        attn = torch.einsum("bhts,bshk->bthk", w, v)
        x = x + torch.einsum("bthk,hkd->btd", attn, t(lp["o"][i]))

        h = rms_norm(x, t(lp["mlp_norm"][i]), cfg.rms_norm_eps)
        gate = torch.nn.functional.silu(h @ t(lp["gate"][i]))
        up = h @ t(lp["up"][i])
        x = x + (gate * up) @ t(lp["down"][i])

    x = rms_norm(x, t(params["final_norm"]), cfg.rms_norm_eps)
    if cfg.tie_word_embeddings:
        kernel = t(params["embed"]["embedding"]).T
    else:
        kernel = t(params["lm_head"])
    return (x @ kernel).numpy()
