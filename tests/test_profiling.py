"""Profiling/observability utilities."""

import os

import jax
import jax.numpy as jnp
import numpy as np

from jax_llama_tpu.utils import DecodeStats, Timer, trace


def test_timer_measures_device_work():
    x = jnp.asarray(np.random.randn(256, 256), jnp.float32)
    with Timer() as t:
        y = x
        for _ in range(4):
            y = y @ x
        jax.block_until_ready(y)
    assert t.elapsed_s > 0


def test_decode_stats_math():
    s = DecodeStats(
        batch=8, prompt_len=128, new_tokens=100, prefill_s=0.5,
        decode_s=2.0, n_devices=4,
    )
    assert s.decode_tokens_per_s == 8 * 100 / 2.0
    assert s.decode_tokens_per_s_per_chip == 8 * 100 / 2.0 / 4
    assert s.per_token_latency_ms == 20.0
    assert "tok/s/chip" in s.summary()


def test_trace_writes_profile(tmp_path):
    d = str(tmp_path / "trace")
    with trace(d):
        jax.block_until_ready(jnp.ones((8, 8)) * 2)
    found = []
    for root, _, files in os.walk(d):
        found += [f for f in files if f.endswith(".xplane.pb")]
    assert found, f"no xplane files under {d}"


def test_normalize_program_name():
    """xplane event names map to serving-program names: host-plane
    PjitFunction frames and device-plane jit_ module names (with
    specialization suffixes) both normalize; HLO-op and host noise
    names return None."""
    from jax_llama_tpu.utils.profiling import normalize_program_name

    assert normalize_program_name(
        "PjitFunction(_paged_decode_chunk)"
    ) == "_paged_decode_chunk"
    assert normalize_program_name(
        "jit__fused_chunk"
    ) == "_fused_chunk"
    assert normalize_program_name("jit_myprog.3") == "myprog"
    assert normalize_program_name("%fusion.12") is None
    assert normalize_program_name("Thread dispatch") is None
    assert normalize_program_name("") is None
