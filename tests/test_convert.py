"""Converter + checkpoint tests: a synthetic 2-shard Meta-format checkpoint
(torch .pth, Megatron column/row splits) is converted and must reproduce the
oracle forward; Orbax roundtrip with and without mesh sharding."""

import json

import numpy as np
import jax
import jax.numpy as jnp
import pytest
import torch

from jax_llama_tpu import config as cfg_lib
from jax_llama_tpu.convert import (
    convert_meta_checkpoint,
    load_checkpoint,
    save_checkpoint,
)
from jax_llama_tpu.models import forward, init_params
from jax_llama_tpu.parallel import make_mesh, use_mesh
import torch_oracle as oracle

# Synthetic model geometry (full, unsharded).
DIM, LAYERS, HEADS, KVH, VOCAB, MULT = 16, 2, 4, 2, 64, 16
HD = DIM // HEADS
CFG_KW = dict(dim=DIM, n_layers=LAYERS, n_heads=HEADS)


def _make_meta_ckpt(tmp_path, n_shards=2, with_output=True):
    """Build full random Meta-layout tensors, split them Megatron-style
    across shards, and torch.save each shard."""
    rng = np.random.RandomState(0)
    ffn = cfg_lib.swiglu_hidden_size(DIM, MULT)
    full = {"tok_embeddings.weight": rng.randn(VOCAB, DIM).astype(np.float32),
            "norm.weight": rng.randn(DIM).astype(np.float32)}
    if with_output:
        full["output.weight"] = rng.randn(VOCAB, DIM).astype(np.float32)
    for l in range(LAYERS):
        p = f"layers.{l}."
        full[p + "attention.wq.weight"] = rng.randn(HEADS * HD, DIM).astype(np.float32)
        full[p + "attention.wk.weight"] = rng.randn(KVH * HD, DIM).astype(np.float32)
        full[p + "attention.wv.weight"] = rng.randn(KVH * HD, DIM).astype(np.float32)
        full[p + "attention.wo.weight"] = rng.randn(DIM, HEADS * HD).astype(np.float32)
        full[p + "feed_forward.w1.weight"] = rng.randn(ffn, DIM).astype(np.float32)
        full[p + "feed_forward.w2.weight"] = rng.randn(DIM, ffn).astype(np.float32)
        full[p + "feed_forward.w3.weight"] = rng.randn(ffn, DIM).astype(np.float32)
        full[p + "attention_norm.weight"] = rng.randn(DIM).astype(np.float32)
        full[p + "ffn_norm.weight"] = rng.randn(DIM).astype(np.float32)

    col_keys = ("wq", "wk", "wv", "w1", "w3", "output")
    row_keys = ("wo", "w2", "tok_embeddings")
    for s in range(n_shards):
        shard = {}
        for key, arr in full.items():
            if any(k in key for k in col_keys):
                shard[key] = torch.from_numpy(
                    np.split(arr, n_shards, axis=0)[s].copy())
            elif any(k in key for k in row_keys):
                shard[key] = torch.from_numpy(
                    np.split(arr, n_shards, axis=1)[s].copy())
            else:  # norms replicated
                shard[key] = torch.from_numpy(arr.copy())
        torch.save(shard, tmp_path / f"consolidated.{s:02d}.pth")

    (tmp_path / "params.json").write_text(json.dumps({
        "dim": DIM, "n_layers": LAYERS, "n_heads": HEADS, "n_kv_heads": KVH,
        "multiple_of": MULT, "norm_eps": 1e-5, "rope_theta": 10000.0,
        "vocab_size": -1,
    }))
    return full


class _FakeTok:
    def __len__(self):
        return VOCAB


def test_convert_matches_oracle_forward(tmp_path):
    _make_meta_ckpt(tmp_path)
    params, config = convert_meta_checkpoint(
        tmp_path, _FakeTok(), max_seq_len=64, dtype="float32"
    )
    assert config.dim == DIM and config.n_layers == LAYERS
    assert config.kv_heads == KVH and config.vocab_size == VOCAB
    assert not config.tie_word_embeddings

    cfg = config.replace(dtype="float32")
    tokens = np.random.RandomState(1).randint(0, VOCAB, (2, 8))
    positions = np.tile(np.arange(8), (2, 1))
    got, _ = forward(params, jnp.asarray(tokens), jnp.asarray(positions), cfg)
    want = oracle.oracle_forward(params, tokens, positions, cfg)
    np.testing.assert_allclose(np.asarray(got), want, atol=2e-4, rtol=1e-4)


def test_convert_shard_reassembly_exact(tmp_path):
    full = _make_meta_ckpt(tmp_path, n_shards=2)
    params, config = convert_meta_checkpoint(
        tmp_path, vocab_size=VOCAB, dtype="float32"
    )
    # wq of layer 0: concat shards on axis 0, transpose, reshape to heads
    # — recovered from the fused qkv layout via split_qkv (which inverts
    # both the slot packing and the half-split RoPE feature permutation).
    from jax_llama_tpu.models import split_qkv

    got_q, got_k, got_v = split_qkv(np.asarray(params["layers"]["qkv"][0]))
    want_q = full["layers.0.attention.wq.weight"].T.reshape(DIM, HEADS, HD)
    np.testing.assert_array_equal(got_q, want_q)
    want_k = full["layers.0.attention.wk.weight"].T.reshape(DIM, KVH, HD)
    np.testing.assert_array_equal(got_k, want_k)
    want_v = full["layers.0.attention.wv.weight"].T.reshape(DIM, KVH, HD)
    np.testing.assert_array_equal(got_v, want_v)
    want_up = full["layers.0.feed_forward.w3.weight"].T
    np.testing.assert_array_equal(
        params["layers"]["gate_up"][0][1], want_up
    )
    want_o = full["layers.0.attention.wo.weight"].T.reshape(HEADS, HD, DIM)
    np.testing.assert_array_equal(params["layers"]["o"][0], want_o)
    np.testing.assert_array_equal(
        params["embed"]["embedding"], full["tok_embeddings.weight"]
    )
    np.testing.assert_array_equal(
        params["lm_head"], full["output.weight"].T
    )


def test_convert_vocab_parallel_embedding(tmp_path):
    """Llama-3 layout: tok_embeddings split on the vocab axis."""
    full = _make_meta_ckpt(tmp_path, n_shards=2)
    # Rewrite shards with the embedding split on axis 0 instead of axis 1.
    for s in range(2):
        p = tmp_path / f"consolidated.{s:02d}.pth"
        sd = torch.load(p, weights_only=True)
        sd["tok_embeddings.weight"] = torch.from_numpy(
            np.split(full["tok_embeddings.weight"], 2, axis=0)[s].copy()
        )
        torch.save(sd, p)
    params, _ = convert_meta_checkpoint(
        tmp_path, vocab_size=VOCAB, dtype="float32"
    )
    np.testing.assert_array_equal(
        params["embed"]["embedding"], full["tok_embeddings.weight"]
    )


def test_convert_rejects_unknown_arch_keys(tmp_path):
    _make_meta_ckpt(tmp_path)
    pj = json.loads((tmp_path / "params.json").read_text())
    pj["quantization_scheme"] = "fp8"
    (tmp_path / "params.json").write_text(json.dumps(pj))
    with pytest.raises(ValueError, match="quantization_scheme"):
        convert_meta_checkpoint(tmp_path, vocab_size=VOCAB)


def test_convert_consumes_use_scaled_rope(tmp_path):
    _make_meta_ckpt(tmp_path)
    pj = json.loads((tmp_path / "params.json").read_text())
    pj["use_scaled_rope"] = True
    (tmp_path / "params.json").write_text(json.dumps(pj))
    _, config = convert_meta_checkpoint(tmp_path, vocab_size=VOCAB)
    assert config.use_scaled_rope


def test_convert_fp32_keeps_fp32_compute(tmp_path):
    _make_meta_ckpt(tmp_path)
    _, config = convert_meta_checkpoint(
        tmp_path, vocab_size=VOCAB, dtype="float32"
    )
    assert config.dtype == "float32" and config.param_dtype == "float32"


def test_convert_single_shard_and_tied(tmp_path):
    _make_meta_ckpt(tmp_path, n_shards=1, with_output=False)
    params, config = convert_meta_checkpoint(
        tmp_path, vocab_size=VOCAB, dtype="float32"
    )
    assert config.tie_word_embeddings
    assert "lm_head" not in params


def test_convert_bf16_dtype(tmp_path):
    _make_meta_ckpt(tmp_path)
    params, _ = convert_meta_checkpoint(tmp_path, vocab_size=VOCAB)
    assert params["layers"]["qkv"].dtype == jnp.bfloat16
    assert params["embed"]["embedding"].dtype == jnp.bfloat16


def test_orbax_roundtrip(tmp_path):
    cfg = cfg_lib.tiny()
    params = init_params(jax.random.PRNGKey(0), cfg)
    ckpt = tmp_path / "ckpt"
    save_checkpoint(ckpt, params, cfg)
    restored, rcfg = load_checkpoint(ckpt)
    assert rcfg == cfg
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        params, restored,
    )


def test_orbax_old_layout_checkpoint_migrates(tmp_path):
    """Rounds 1-2 checkpoints stored separate q/k/v + gate/up (Meta
    interleaved RoPE feature order): load_checkpoint must detect the old
    tree, restore it, and fuse_params-migrate — same forward after."""
    import orbax.checkpoint as ocp

    from jax_llama_tpu.models import split_qkv

    cfg = cfg_lib.tiny()
    params = init_params(jax.random.PRNGKey(0), cfg)
    # Construct the old on-disk layout from the fused tree (split_qkv
    # inverts both the packing and the rope permutation — exactly what an
    # old checkpoint held).
    lp = dict(params["layers"])
    q, k, v = split_qkv(lp.pop("qkv"))
    gate_up = lp.pop("gate_up")
    lp.update(q=q, k=k, v=v, gate=gate_up[:, 0], up=gate_up[:, 1])
    old = dict(params)
    old["layers"] = lp

    import dataclasses as _dc
    import json as _json

    ckpt = tmp_path / "old_ckpt"
    ckpt.mkdir()
    (ckpt / "config.json").write_text(
        _json.dumps(dict(_dc.asdict(cfg), _quantized=False))
    )
    ckptr = ocp.StandardCheckpointer()
    ckptr.save((ckpt / "params").absolute(), old, force=True)
    ckptr.wait_until_finished()

    restored, rcfg = load_checkpoint(ckpt)
    assert rcfg == cfg
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)
        ),
        restored, params,
    )


def test_orbax_sharded_restore(tmp_path):
    cfg = cfg_lib.tiny()
    params = init_params(jax.random.PRNGKey(0), cfg)
    ckpt = tmp_path / "ckpt"
    save_checkpoint(ckpt, params, cfg)
    mesh = make_mesh(tensor=2, data=4)
    restored, rcfg = load_checkpoint(ckpt, mesh=mesh)
    qkv = restored["layers"]["qkv"]
    shard_shapes = {s.data.shape for s in qkv.addressable_shards}
    G = cfg.n_heads // cfg.kv_heads
    assert shard_shapes == {
        (cfg.n_layers, cfg.kv_heads // 2, G + 2, cfg.dim, cfg.head_dim)
    }
    # Restored-sharded forward == original.
    tokens = jnp.asarray([[1, 2, 3, 4]])
    pos = jnp.arange(4)[None, :]
    with use_mesh(mesh):
        got = np.asarray(jax.jit(
            lambda p, t, q_: forward(p, t, q_, cfg)[0])(restored, tokens, pos))
    want, _ = forward(params, tokens, pos, cfg)
    np.testing.assert_allclose(got, np.asarray(want), atol=1e-5)


def test_train_state_resume_roundtrip(tmp_path):
    """Train 2 steps -> save -> restore (sharded) -> the next step must be
    bit-identical to training straight through (optimizer moments intact)."""
    import numpy as np
    from jax_llama_tpu import get_config, init_params, make_mesh
    from jax_llama_tpu.convert.checkpoint import (
        load_train_state,
        save_train_state,
    )
    from jax_llama_tpu.parallel import shard_params
    from jax_llama_tpu.train import (
        init_train_state,
        make_optimizer,
        train_step,
    )

    config = get_config(
        "tiny", vocab_size=64, dim=32, n_layers=2, n_heads=4, n_kv_heads=2,
        multiple_of=32, max_seq_len=16,
    )
    mesh = make_mesh(data=2, tensor=2, devices=jax.devices()[:4])
    opt = make_optimizer(1e-3)
    params = shard_params(init_params(jax.random.PRNGKey(0), config), mesh, config)
    state = init_train_state(params, opt)
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, 64, (4, 16)), jnp.int32
    )
    for _ in range(2):
        state, _ = train_step(state, tokens, config, opt, mesh=mesh)

    save_train_state(str(tmp_path / "tstate"), state, config)
    restored, rconfig = load_train_state(
        str(tmp_path / "tstate"), opt, mesh=mesh
    )
    assert rconfig == config
    assert int(restored.step) == 2
    # continue training from both and compare exactly
    cont_a, loss_a = train_step(state, tokens, config, opt, mesh=mesh)
    cont_b, loss_b = train_step(restored, tokens, config, opt, mesh=mesh)
    assert float(loss_a) == float(loss_b)
    for a, b in zip(jax.tree.leaves(cont_a.params), jax.tree.leaves(cont_b.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_checkpoint_kind_mismatch_errors():
    """Pointing the wrong loader at a checkpoint gives a clear error, not a
    TypeError from config parsing."""
    import pytest
    from jax_llama_tpu import get_config, init_params
    from jax_llama_tpu.convert.checkpoint import (
        load_checkpoint,
        load_train_state,
        save_checkpoint,
        save_train_state,
    )
    from jax_llama_tpu.train import init_train_state, make_optimizer

    config = get_config(
        "tiny", vocab_size=64, dim=32, n_layers=2, n_heads=4, n_kv_heads=2,
        multiple_of=32, max_seq_len=16,
    )
    params = init_params(jax.random.PRNGKey(0), config)
    opt = make_optimizer(1e-3)
    import tempfile
    with tempfile.TemporaryDirectory() as td:
        save_train_state(td + "/t", init_train_state(params, opt), config)
        with pytest.raises(ValueError, match="training checkpoint"):
            load_checkpoint(td + "/t")
        save_checkpoint(td + "/s", params, config)
        with pytest.raises(ValueError, match="serving checkpoint"):
            load_train_state(td + "/s", opt)


def test_orbax_d_first_layout_checkpoint_migrates(tmp_path):
    """r3 checkpoints stored the fused weights with the contracted D axis
    leading; load_checkpoint must detect the layout from metadata and
    migrate by axis permutation — exact for full-precision AND int8 trees
    (payload and scale permute together)."""
    import dataclasses as _dc
    import json as _json

    import orbax.checkpoint as ocp

    from jax_llama_tpu.convert.checkpoint import _to_d_first
    from jax_llama_tpu.ops.quant import QuantizedTensor, quantize_params

    cfg = cfg_lib.tiny()
    params = init_params(jax.random.PRNGKey(0), cfg)

    def save_as_d_first(tree, path, quantized):
        old = dict(tree)
        old["layers"] = _to_d_first(tree["layers"])
        path.mkdir()
        (path / "config.json").write_text(
            _json.dumps(dict(_dc.asdict(cfg), _quantized=quantized))
        )
        ckptr = ocp.StandardCheckpointer()
        ckptr.save((path / "params").absolute(), old, force=True)
        ckptr.wait_until_finished()

    save_as_d_first(params, tmp_path / "fp", quantized=False)
    restored, rcfg = load_checkpoint(tmp_path / "fp")
    assert rcfg == cfg
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)
        ),
        restored, params,
    )

    qp = quantize_params(params)
    save_as_d_first(qp, tmp_path / "q8", quantized=True)
    restored_q, _ = load_checkpoint(tmp_path / "q8")
    assert isinstance(restored_q["layers"]["qkv"], QuantizedTensor)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)
        ),
        restored_q, qp,
    )


def test_checkpoint_manifest_rejects_corrupt_shard(tmp_path):
    """The save-time sha256 manifest makes a flipped byte (or a
    truncated file) in any shard fail the restore loudly BEFORE serving
    starts — never silent garbage weights."""
    from jax_llama_tpu.convert.checkpoint import (
        MANIFEST_NAME,
        verify_manifest,
    )

    cfg = cfg_lib.tiny()
    params = init_params(jax.random.PRNGKey(0), cfg)
    ckpt = tmp_path / "ckpt"
    save_checkpoint(ckpt, params, cfg)
    manifest = json.loads((ckpt / MANIFEST_NAME).read_text())
    assert manifest["files"]  # every file hashed at save time
    assert verify_manifest(ckpt) is True

    # Flip one byte in the LARGEST shard (an actual array payload).
    rel = max(manifest["files"], key=lambda r: manifest["files"][r]["bytes"])
    shard = ckpt / rel
    blob = bytearray(shard.read_bytes())
    blob[len(blob) // 2] ^= 0xFF
    shard.write_bytes(bytes(blob))
    with pytest.raises(ValueError, match="sha256 mismatch"):
        load_checkpoint(ckpt)
    # verify=False opts out (storage-layer-integrity escape hatch).
    load_checkpoint(ckpt, verify=False)

    # Truncation is reported as truncation, checked before hashing.
    shard.write_bytes(bytes(blob[: len(blob) // 2]))
    with pytest.raises(ValueError, match="truncated"):
        load_checkpoint(ckpt)

    # A deleted shard is reported missing.
    shard.unlink()
    with pytest.raises(ValueError, match="missing"):
        load_checkpoint(ckpt)


def test_checkpoint_atomic_overwrite_keeps_manifest_consistent(tmp_path):
    """Re-saving over an existing checkpoint swaps the whole tree: the
    manifest always describes exactly the files on disk (no stale trash
    or temp siblings left behind)."""
    import os

    cfg = cfg_lib.tiny()
    ckpt = tmp_path / "ckpt"
    save_checkpoint(ckpt, init_params(jax.random.PRNGKey(0), cfg), cfg)
    save_checkpoint(ckpt, init_params(jax.random.PRNGKey(1), cfg), cfg)
    restored, _ = load_checkpoint(ckpt)
    want = init_params(jax.random.PRNGKey(1), cfg)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)
        ),
        want, restored,
    )
    # No .tmp-/.trash- siblings survive a completed save.
    leftovers = [n for n in os.listdir(tmp_path)
                 if ".tmp-" in n or ".trash-" in n]
    assert leftovers == []
