"""Fleet control-plane observability (ISSUE 15): the decision audit
log, the black-box flight recorder, synthetic canary probes, and the
per-replica health-score/anomaly sentinel.

Pure-host units (DecisionLog / EwmaDetector / HealthSentinel) run in
milliseconds; the server- and fleet-level drills reuse the exact
tiny-model geometry of tests/test_router.py (n_slots=2 / max_len=64)
so the jitted-program compiles are shared across files in one tier-1
process.
"""

import json
import urllib.request

import jax
import pytest

from jax_llama_tpu import get_config, init_params
from jax_llama_tpu.obs import DecisionLog, EwmaDetector
from jax_llama_tpu.router import (
    SENTINEL_SIGNALS,
    HealthSentinel,
    ReplicaRouter,
)
from jax_llama_tpu.server import LLMServer
from jax_llama_tpu.serving import ContinuousBatcher
from jax_llama_tpu.tokenizers.bytes import ByteTokenizer

CFG = dict(
    vocab_size=512, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
    multiple_of=32, max_seq_len=128, dtype="float32",
    param_dtype="float32",
)


@pytest.fixture(scope="module")
def model():
    config = get_config("tiny", **CFG)
    params = init_params(jax.random.PRNGKey(0), config)
    return params, config


def _post(url, payload, path="/generate", rid=None, timeout=300):
    headers = {"Content-Type": "application/json"}
    if rid is not None:
        headers["X-Request-Id"] = rid
    req = urllib.request.Request(
        url + path, data=json.dumps(payload).encode(), headers=headers,
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read()), dict(r.headers)


def _get_json(url, path, timeout=60):
    with urllib.request.urlopen(url + path, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def _metrics(url, timeout=60):
    """Unlabeled sample lines of a /metrics exposition as a dict."""
    with urllib.request.urlopen(url + "/metrics", timeout=timeout) as r:
        text = r.read().decode()
    out = {}
    for line in text.splitlines():
        if line.startswith("#") or " " not in line:
            continue
        name, val = line.split(" ", 1)
        try:
            out[name] = float(val)
        except ValueError:
            pass
    return out, text


# ---------------------------------------------------------------------------
# DecisionLog / EwmaDetector units (pure host)
# ---------------------------------------------------------------------------

def test_decision_log_records_filters_and_ring():
    log = DecisionLog(ring=4)
    for i in range(6):
        log.record("route", request_id=f"r{i % 2}", replica=i)
    log.record("reroute", request_id="r0", failed_replica=1)
    # seq survives ring eviction; counts are lifetime totals
    assert log.total() == 7
    assert log.counts_snapshot() == {"route": 6, "reroute": 1}
    doc = log.json()
    assert doc["events_total"] == 7 and doc["ring"] == 4
    assert len(doc["decisions"]) == 4  # ring bound
    seqs = [d["seq"] for d in doc["decisions"]]
    assert seqs == sorted(seqs) and seqs[-1] == 6
    # kind + request_id filters (the timeline join)
    only = log.json(kind="reroute")["decisions"]
    assert len(only) == 1 and only[0]["failed_replica"] == 1
    joined = log.for_request("r0")
    assert joined and all(d["request_id"] == "r0" for d in joined)
    # None-valued fields drop from the record
    log.record("canary", replica=0, error=None, ok=True)
    last = log.json(n=1)["decisions"][0]
    assert "error" not in last and last["ok"] is True


def test_ewma_detector_warmup_then_flags_spike():
    det = EwmaDetector(alpha=0.2, min_samples=5)
    zs = [det.update(10.0) for _ in range(5)]
    assert all(z is None for z in zs)  # warmup: no baseline, no verdict
    assert det.update(10.0) is not None  # baseline established
    z = det.update(1000.0)
    assert z is not None and z > 100.0  # spike vs near-constant baseline
    # scoring is against PRE-update stats: a healthy value right after
    # the spike still reads near the old baseline, not the spike
    z2 = det.update(10.0)
    assert z2 is not None and z2 < 0.0


# ---------------------------------------------------------------------------
# HealthSentinel units (pure host)
# ---------------------------------------------------------------------------

def test_sentinel_canary_failures_drop_score_and_flip_verdict():
    s = HealthSentinel()
    evs = s.observe_canary(0, ok=True, latency_ms=10.0)
    assert s.verdict(0) == "healthy" and s.score(0) == 1.0
    assert evs == []
    # one failed probe: counted anomaly edge + verdict drops to suspect
    evs = s.observe_canary(0, ok=False, error="connect refused")
    kinds = [e["kind"] for e in evs]
    assert "anomaly" in kinds and "verdict" in kinds
    assert s.anomalies_total["canary"] == 1
    assert s.verdict(0) == "suspect" and s.score(0) < 0.8
    # sustained failure: NO second anomaly event (edge-triggered),
    # verdict eventually critical
    evs = s.observe_canary(0, ok=False, error="connect refused")
    assert "anomaly" not in [e["kind"] for e in evs]
    assert s.anomalies_total["canary"] == 1
    s.observe_canary(0, ok=False, error="connect refused")
    assert s.verdict(0) == "critical" and s.score(0) < 0.5
    # recovery: successes clear the anomaly and restore the verdict
    cleared = False
    for _ in range(8):
        evs = s.observe_canary(0, ok=True, latency_ms=10.0)
        cleared = cleared or "anomaly_cleared" in [
            e["kind"] for e in evs
        ]
    assert cleared and s.verdict(0) == "healthy"
    assert s.anomalies_total["canary"] == 1  # incidents, not samples


def test_sentinel_token_mismatch_is_immediate_anomaly():
    s = HealthSentinel()
    s.observe_canary(1, ok=True, latency_ms=5.0)
    evs = s.observe_canary(1, ok=False, mismatch=True, latency_ms=5.0)
    assert any(
        e["kind"] == "anomaly" and e["signal"] == "canary"
        and e.get("mismatch") for e in evs
    )
    # a mismatch pins the canary subscore to 0 — worse than a flake
    assert s.score(1) < 0.8


def test_sentinel_latency_zscore_anomaly():
    s = HealthSentinel(min_samples=5)
    for _ in range(6):
        s.observe_canary(0, ok=True, latency_ms=10.0)
    before = s.anomalies_total["latency"]
    evs = s.observe_canary(0, ok=True, latency_ms=5000.0)
    assert s.anomalies_total["latency"] == before + 1
    assert any(
        e["kind"] == "anomaly" and e["signal"] == "latency"
        for e in evs
    )


def test_sentinel_zscore_floor_suppresses_ms_blips():
    """A near-zero healthy baseline must not turn a harmless
    single-digit-ms blip into a 500-sigma anomaly: the absolute
    z-divisor floor (z_floor_ms) bounds sensitivity in the signal's
    own units."""
    det = EwmaDetector(alpha=0.2, min_samples=5, floor=5.0)
    for _ in range(6):
        det.update(0.05)
    z = det.update(3.0)  # a GC-pause-sized blip over a 0.05 ms base
    assert z is not None and z < 3.0  # under the anomaly threshold
    s = HealthSentinel(min_samples=5)  # default z_floor_ms
    for _ in range(6):
        s.observe_health(0, reachable=True, queue_wait_ms=0.05,
                         age_s=0.0)
    s.observe_health(0, reachable=True, queue_wait_ms=3.0, age_s=0.0)
    assert s.anomalies_total["queue_wait"] == 0
    assert s.verdict(0) == "healthy"


def test_canary_oracle_majority_repin_and_reset():
    """A wrong-output replica probed first must not invert the fleet
    verdict: the oracle resolves against the WHOLE sweep, and a
    strict majority disagreeing with the pin re-pins it (counted);
    reset_canary_oracle() is the rollout hook."""
    router = ReplicaRouter(
        ["127.0.0.1:1", "127.0.0.1:2", "127.0.0.1:3"],
        health_interval_s=0, canary_interval_s=0,
    )
    reps = router._replicas
    # the corrupt replica 0 was probed first and pinned a bad oracle
    with router._lock:
        router._canary_oracle = [9, 9]
    results = [
        (reps[0], {"ok": True, "tokens": [9, 9], "latency_ms": 1.0,
                   "request_id": "c0"}),
        (reps[1], {"ok": True, "tokens": [1, 2], "latency_ms": 1.0,
                   "request_id": "c1"}),
        (reps[2], {"ok": True, "tokens": [1, 2], "latency_ms": 1.0,
                   "request_id": "c2"}),
    ]
    router._resolve_canary_oracle(results)
    with router._lock:
        assert router._canary_oracle == [1, 2]  # majority wins
    assert router.canary_oracle_repins_total == 1
    # ... and it is the CORRUPT replica that reads mismatched now
    assert results[0][1]["mismatch"] and not results[0][1]["ok"]
    assert results[1][1]["ok"] and results[2][1]["ok"]
    # a 1-vs-1 split keeps the pin (no majority — cannot tell who
    # is wrong, only that they disagree)
    split = [
        (reps[0], {"ok": True, "tokens": [1, 2], "latency_ms": 1.0}),
        (reps[1], {"ok": True, "tokens": [7, 7], "latency_ms": 1.0}),
    ]
    router._resolve_canary_oracle(split)
    with router._lock:
        assert router._canary_oracle == [1, 2]
    assert router.canary_oracle_repins_total == 1
    assert split[1][1]["mismatch"] and not split[1][1]["ok"]
    # the rollout hook forgets the pin; the next sweep re-establishes
    router.reset_canary_oracle()
    with router._lock:
        assert router._canary_oracle is None
    # with NO pin, a tie must not crown either side by probe order —
    # the oracle stays unset, NOBODY is mismatched, and the split is
    # recorded as a disagreement decision
    tie = [
        (reps[0], {"ok": True, "tokens": [8, 8], "latency_ms": 1.0}),
        (reps[1], {"ok": True, "tokens": [6, 6], "latency_ms": 1.0}),
    ]
    router._resolve_canary_oracle(tie)
    with router._lock:
        assert router._canary_oracle is None
    assert tie[0][1]["ok"] and tie[1][1]["ok"]
    router._resolve_canary_oracle([
        (reps[0], {"ok": True, "tokens": [4, 4], "latency_ms": 1.0}),
    ])
    with router._lock:
        assert router._canary_oracle == [4, 4]
    kinds = {
        d["kind"] for d in router.decisions.json(n=64)["decisions"]
    }
    assert {"canary_oracle_repin", "canary_oracle_reset",
            "canary_oracle_disagreement"} <= kinds


def test_sentinel_health_signals_attainment_and_staleness():
    s = HealthSentinel()
    # healthy scrapes keep everything at 1.0
    evs = s.observe_health(
        0, reachable=True, attainment=1.0, queue_wait_ms=5.0,
        itl_ms=20.0, age_s=0.0,
    )
    assert evs == [] and s.score(0) == 1.0
    # collapsing attainment smooths down into an anomaly
    for _ in range(12):
        evs = s.observe_health(0, reachable=True, attainment=0.0,
                               age_s=0.0)
    assert s.anomalies_total["attainment"] == 1
    assert s.verdict(0) != "healthy"
    # a replica gone unreachable: staleness decays with scrape age
    s2 = HealthSentinel(staleness_allowance_s=1.0)
    s2.observe_health(1, reachable=True, age_s=0.0)
    evs = s2.observe_health(1, reachable=False, age_s=10.0)
    assert s2.anomalies_total["staleness"] == 1
    assert any(e["kind"] == "anomaly" for e in evs)
    fleet = s2.fleet_json()
    assert fleet["replicas"][1]["verdict"] != "healthy"
    assert "staleness" in fleet["replicas"][1]["anomalous"]
    assert set(fleet["anomalies_total"]) == set(SENTINEL_SIGNALS)


# ---------------------------------------------------------------------------
# Server level: the reserved canary class + the flight recorder surface
# ---------------------------------------------------------------------------

def _mk_server(model, tok, **kw):
    params, config = model
    cb = ContinuousBatcher(
        params, config, n_slots=2, max_len=64,
        stop_tokens=tuple(tok.stop_tokens),
    )
    return LLMServer(cb, tokenizer=tok, **kw)


def test_canary_class_served_but_excluded_from_slo_and_ladder(model):
    """SATELLITE PIN: the reserved canary request class is served
    normally but excluded from SLO attainment, goodput, the latency
    histograms/EWMAs and the brownout ladder's signal windows."""
    tok = ByteTokenizer()
    with _mk_server(model, tok) as srv:
        status, body, _ = _post(srv.address, {
            "prompt": [1, 2, 3], "max_new_tokens": 4,
            "temperature": 0.0, "seed": 0, "priority": "canary",
        })
        assert status == 200 and body["tokens"]
        m, _ = _metrics(srv.address)
        assert m["llm_canary_requests_total"] == 1
        assert m["llm_requests_finished_total"] == 1  # served...
        assert m["llm_requests_slo_ok_total"] == 0    # ...never scored
        assert m["llm_goodput_tokens_total"] == 0
        assert m["llm_ttft_ms_count"] == 0            # histogram clean
        assert m["llm_itl_ms_count"] == 0
        # ladder signal windows untouched (no self-triggered brownouts)
        with srv.overload._lock:
            assert all(
                len(w) == 0
                for w in srv.overload._slo_windows.values()
            )
            assert len(srv.overload._wait_window) == 0
        # a NORMAL request scores everything the canary skipped
        status, body, _ = _post(srv.address, {
            "prompt": [1, 2, 3], "max_new_tokens": 4,
            "temperature": 0.0, "seed": 0,
        })
        assert status == 200
        m, _ = _metrics(srv.address)
        assert m["llm_requests_slo_ok_total"] == 1
        assert m["llm_goodput_tokens_total"] >= len(body["tokens"])
        assert m["llm_ttft_ms_count"] == 1
        # junk priority is still the client's defect
        req = urllib.request.Request(
            srv.address + "/generate",
            data=json.dumps({"prompt": [1], "priority": "vip"}).encode(),
            headers={"Content-Type": "application/json"},
        )
        try:
            urllib.request.urlopen(req, timeout=30)
            assert False, "junk priority must 400"
        except urllib.error.HTTPError as e:
            assert e.code == 400
            e.read()


def test_server_debug_decisions_and_bundle(model):
    """The replica-side decision log + flight-recorder artifact:
    decisions land with kinds and filters, and /debug/bundle
    round-trips one parseable postmortem JSON carrying config /
    health / metrics / snapshot ring / decisions / log tail /
    trace."""
    tok = ByteTokenizer()
    with _mk_server(model, tok, flight_interval_s=0.05) as srv:
        status, body, _ = _post(srv.address, {
            "prompt": [9, 8, 7], "max_new_tokens": 4,
            "temperature": 0.0,
        }, rid="ctl-1")
        assert status == 200
        srv.begin_drain(timeout_s=5.0)
        status, doc = _get_json(srv.address, "/debug/decisions")
        assert status == 200
        kinds = {d["kind"] for d in doc["decisions"]}
        assert "drain" in kinds
        assert doc["events_total"] >= 1 and doc["counts"]["drain"] == 1
        status, only = _get_json(
            srv.address, "/debug/decisions?kind=drain"
        )
        assert {d["kind"] for d in only["decisions"]} == {"drain"}
        status, bundle = _get_json(srv.address, "/debug/bundle")
        assert status == 200 and bundle["kind"] == "replica_bundle"
        for key in ("config", "health", "metrics", "metric_snapshots",
                    "decisions", "annotations", "log_tail",
                    "requests", "trace"):
            assert key in bundle, key
        assert bundle["config"]["batcher"]["n_slots"] == 2
        assert bundle["config"]["batcher"]["block_size"] > 0
        assert bundle["metrics"]["requests_finished_total"] == 1
        # the loop snapshots at least once (first iteration fires)
        assert len(bundle["metric_snapshots"]) >= 1
        snap = bundle["metric_snapshots"][-1]
        assert "emitted_tokens_total" in snap and "overload_rung" in snap
        assert isinstance(bundle["log_tail"], list)
        assert isinstance(bundle["trace"]["traceEvents"], list)
        # ?trace=0 slims the artifact
        status, slim = _get_json(srv.address, "/debug/bundle?trace=0")
        assert "trace" not in slim


# ---------------------------------------------------------------------------
# THE fleet drill (acceptance criteria)
# ---------------------------------------------------------------------------

@pytest.mark.mesh_serving
def test_fleet_drill_canary_flags_degraded_replica(model):
    """ACCEPTANCE PIN (ISSUE 15): inject a failure on one replica of a
    routed 2-replica fleet and show the whole control-plane
    observability story — the canary flags it, its health score drops
    and the /debug/fleet verdict flips, an anomaly counter fires,
    GET /debug/decisions explains the subsequent re-routes (candidate
    sets included, joinable by request id), and GET /debug/bundle
    round-trips one parseable postmortem artifact."""
    tok = ByteTokenizer()
    servers = [
        _mk_server(model, tok, replica_id=i).start() for i in range(2)
    ]
    router = ReplicaRouter(
        servers, policy="least-loaded",
        health_interval_s=0, canary_interval_s=0,  # manual drills
    ).start()
    try:
        router.check_health_now()
        router.run_canaries_now()
        with router._lock:
            oracle = router._canary_oracle
        assert oracle, "first successful probe pins the fleet oracle"
        assert router.canary_probes_total == 2
        assert router.canary_failures_total == 0
        status, fleet = _get_json(router.address, "/debug/fleet")
        assert status == 200 and fleet["verdict"] == "healthy"
        assert all(r["verdict"] == "healthy" for r in fleet["replicas"])

        # one real request (client-supplied id → decision join key)
        status, body, headers = _post(
            router.address,
            {"prompt": [5, 6, 7], "max_new_tokens": 4,
             "temperature": 0.0},
            rid="drill-1",
        )
        assert status == 200 and headers["X-Replica-Id"] == "0"

        # REPLICA 1 DEGRADES: its HTTP front door dies (loop alive —
        # the half-dead failure mode a liveness probe alone misses).
        servers[1].httpd.shutdown()
        servers[1].httpd.server_close()

        # the canary flags it: counted failures, health score drops,
        # anomaly fires, verdict flips
        for _ in range(3):
            router.run_canaries_now()
        assert router.canary_failures_total >= 3
        status, fleet = _get_json(router.address, "/debug/fleet")
        by_idx = {r["replica"]: r for r in fleet["replicas"]}
        assert by_idx[0]["verdict"] == "healthy"
        assert by_idx[1]["verdict"] in ("suspect", "critical")
        assert by_idx[1]["score"] < 0.8
        assert by_idx[1]["last_canary"]["ok"] is False
        assert "canary" in by_idx[1]["anomalous"]
        assert fleet["anomalies_total"]["canary"] >= 1
        assert fleet["verdict_index"] >= 1  # the autoscaler's signal
        assert fleet["canary"]["oracle_tokens"] == oracle

        # next request picks replica 1 (least routed), fails, and
        # re-routes LOSSLESSLY to replica 0
        status, body, headers = _post(
            router.address,
            {"prompt": [5, 6, 7], "max_new_tokens": 4,
             "temperature": 0.0},
            rid="drill-2",
        )
        assert status == 200 and headers["X-Replica-Id"] == "0"

        # /debug/decisions explains the story
        status, doc = _get_json(
            router.address, "/debug/decisions?n=256"
        )
        kinds = {d["kind"] for d in doc["decisions"]}
        assert {"route", "reroute", "canary", "anomaly",
                "verdict"} <= kinds
        # ... and joins by request id: route(1) -> reroute -> route(0)
        status, doc2 = _get_json(
            router.address, "/debug/decisions?request_id=drill-2"
        )
        evs = doc2["decisions"]
        routes = [d for d in evs if d["kind"] == "route"]
        assert [d["replica"] for d in routes] == [1, 0]
        assert all(d["candidates"] for d in routes)
        assert routes[1]["policy"] == "reroute"
        rr = [d for d in evs if d["kind"] == "reroute"]
        assert rr and rr[0]["failed_replica"] == 1
        # the fleet request lookup carries the same join
        status, tl = _get_json(
            router.address, "/debug/requests/drill-2"
        )
        assert status == 200 and tl["router_decisions"]

        # the postmortem artifact round-trips as one parseable doc
        status, bundle = _get_json(router.address, "/debug/bundle")
        assert status == 200 and bundle["kind"] == "router_bundle"
        assert bundle["fleet"]["verdict_index"] >= 1
        assert bundle["decisions"]["events_total"] >= 5
        assert isinstance(bundle["trace"]["traceEvents"], list)
        reps = bundle["replicas"]
        assert [b["replica"] for b in reps] == [0]  # 1 is unroutable
        assert reps[0]["kind"] == "replica_bundle"
        assert reps[0]["config"]["batcher"]["n_slots"] == 2
        # Replica bundles ship WITHOUT their own trace — the fleet-
        # merged trace above already carries replica-0's tracks, and
        # shipping them twice would double the heaviest section.
        assert "trace" not in reps[0]
        assert any(
            e.get("ph") == "M"
            and e.get("args", {}).get("name") == "replica-0"
            for e in bundle["trace"]["traceEvents"]
        )

        # the router exposition carries the new families
        m, text = _metrics(router.address)
        assert m["llm_router_canary_failures_total"] >= 3
        assert m["llm_router_fleet_verdict"] >= 1
        assert 'llm_router_replica_health_score{replica="1"}' in text
        assert 'llm_router_anomalies_total{signal="canary"}' in text
        assert 'llm_router_decisions_total{kind="route"}' in text

        # replica 0 served the canaries under the reserved class:
        # counted, never SLO-scored
        m0, _ = _metrics(servers[0].address)
        assert m0["llm_canary_requests_total"] >= 4
        assert m0["llm_requests_slo_ok_total"] == 2  # the 2 real ones
    finally:
        router.stop()
        for s in servers:
            s.stop()
