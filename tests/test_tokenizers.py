"""Tokenizer tests.  No proprietary vocab files ship with this repo, so the
LLaMA-3 tokenizer is exercised with a 256-byte identity rank table (every
single byte is a token) — the special-token layout, chat framing, and
oversized-input splitting are all independent of the rank table."""

import base64

import pytest

from jax_llama_tpu.tokenizers import ByteTokenizer, ChatFormat, LLaMA3Tokenizer
from jax_llama_tpu.tokenizers.llama3 import (
    NUM_RESERVED_SPECIAL_TOKENS,
    read_bpe_ranks,
    special_token_names,
    split_oversized,
)


@pytest.fixture(scope="module")
def tok():
    ranks = {bytes([i]): i for i in range(256)}
    return LLaMA3Tokenizer.from_ranks(ranks)


def test_special_token_layout():
    names = special_token_names()
    assert len(names) == NUM_RESERVED_SPECIAL_TOKENS
    assert names[0] == "<|begin_of_text|>"
    assert names[1] == "<|end_of_text|>"
    assert names[2] == "<|reserved_special_token_0|>"
    assert names[6] == "<|start_header_id|>"
    assert names[7] == "<|end_header_id|>"
    assert names[8] == "<|reserved_special_token_4|>"
    assert names[9] == "<|eot_id|>"
    assert names[10] == "<|reserved_special_token_5|>"
    assert names[255] == "<|reserved_special_token_250|>"


def test_vocab_and_ids(tok):
    assert len(tok) == 256 + 256
    assert tok.bos_id == 256
    assert tok.eos_id == 257
    assert tok.eot_id == 256 + 9
    assert tok.stop_tokens == {tok.eos_id, tok.eot_id}
    assert tok.pad_id == -1


def test_encode_decode_roundtrip(tok):
    for s in ["hello world", "a\n\nb", "  spaces  ", "123456", "don't"]:
        ids = tok.encode(s)
        assert tok.decode(ids) == s


def test_bos_eos_flags(tok):
    ids = tok.encode("hi", bos=True, eos=True)
    assert ids[0] == tok.bos_id and ids[-1] == tok.eos_id
    assert tok.decode(ids[1:-1]) == "hi"


def test_special_token_text_is_not_special_by_default(tok):
    # Parity with reference contract (llama3_tokenizer.py:121-127).
    ids = tok.encode("<|begin_of_text|>")
    assert tok.bos_id not in ids
    ids2 = tok.encode("<|begin_of_text|>", allowed_special="all")
    assert ids2 == [tok.bos_id]


def test_split_oversized_preserves_content():
    s = "x" * 60_001 + " " * 30_000 + "y z " + "w" * 25_001
    pieces = list(split_oversized(s, 25_000))
    assert "".join(pieces) == s
    for p in pieces:
        run = 1
        longest = 1 if p else 0
        for a, b in zip(p, p[1:]):
            run = run + 1 if a.isspace() == b.isspace() else 1
            longest = max(longest, run)
        assert longest <= 25_000


def test_split_oversized_empty():
    assert list(split_oversized("")) == []


def test_encode_huge_string(tok):
    s = "ab " * 20_000  # 60k chars, mixed classes
    assert tok.decode(tok.encode(s)) == s


def test_chat_format_framing(tok):
    cf = ChatFormat(tok)
    st = tok.special_tokens
    dialog = [
        {"role": "system", "content": "be brief"},
        {"role": "user", "content": "  hi  "},
    ]
    ids = cf.encode_dialog_prompt(dialog)
    assert ids[0] == tok.bos_id
    # First message frame: <|start_header_id|> "system" <|end_header_id|> \n\n
    assert ids[1] == st["<|start_header_id|>"]
    k = ids.index(st["<|end_header_id|>"])
    assert tok.decode(ids[2:k]) == "system"
    # Content is stripped and each message ends with <|eot_id|>.
    assert ids.count(tok.eot_id) == 2
    # Trailing open assistant header.
    tail = ids[-(len(cf.encode_header({"role": "assistant", "content": ""}))):]
    assert tail[0] == st["<|start_header_id|>"]
    assert tok.eot_id not in tail
    # Stripped content check: decode between header end and eot of message 2.
    second_eot = len(ids) - 1 - ids[::-1].index(tok.eot_id)
    hdr_end = [i for i, t in enumerate(ids) if t == st["<|end_header_id|>"]][1]
    assert tok.decode(ids[hdr_end + 1:second_eot]).lstrip("\n") == "hi"


def test_read_bpe_ranks(tmp_path):
    path = tmp_path / "ranks.model"
    lines = []
    for i, tok_bytes in enumerate([b"a", b"b", b"ab"]):
        lines.append(base64.b64encode(tok_bytes) + b" " + str(i).encode())
    path.write_bytes(b"\n".join(lines) + b"\n")
    ranks = read_bpe_ranks(str(path))
    assert ranks == {b"a": 0, b"b": 1, b"ab": 2}
    t = LLaMA3Tokenizer(str(path))
    assert t.encode("ab") == [2]
    assert t.decode([0, 1]) == "ab"


def test_llama2_tokenizer_gated(monkeypatch):
    # The gate must raise a clear ImportError whenever sentencepiece is
    # missing — force the missing state so the message is always verified.
    from jax_llama_tpu.tokenizers import LLaMA2Tokenizer
    from jax_llama_tpu.tokenizers import llama2 as llama2_mod

    monkeypatch.setattr(llama2_mod, "_HAVE_SENTENCEPIECE", False)
    with pytest.raises(ImportError, match="sentencepiece"):
        LLaMA2Tokenizer("/nonexistent/tokenizer.model")


def test_llama3_tokenizer_gated(monkeypatch):
    from jax_llama_tpu.tokenizers import llama3 as llama3_mod

    monkeypatch.setattr(llama3_mod, "_HAVE_TIKTOKEN", False)
    with pytest.raises(ImportError, match="tiktoken"):
        llama3_mod.Tokenizer.from_ranks({b"a": 0})


def test_byte_tokenizer_roundtrip():
    tok = ByteTokenizer()
    ids = tok.encode("héllo", bos=True, eos=True)
    assert ids[0] == tok.bos_id and ids[-1] == tok.eos_id
    assert tok.decode(ids) == "héllo"


# ---------------------------------------------------------------------------
# Non-identity rank table: real multi-byte BPE merges.
#
# The proprietary Llama-3 vocab cannot ship, but the identity table leaves a
# gap: nothing above validated the rank-file parser and the tokenizer stack
# against a table where merges actually fire.  Train a small but genuine BPE
# table (merge-order ranks, exactly how real tiktoken vocabs are built),
# round-trip it through the file format, and cross-check our Tokenizer
# against an INDEPENDENTLY constructed tiktoken.Encoding on varied text.
# ---------------------------------------------------------------------------

_CORPUS = (
    "the quick brown fox jumps over the lazy dog "
    "pack my box with five dozen liquor jugs "
    "sphinx of black quartz judge my vow "
    "tokenizers merge the most frequent pairs first "
    "the the the and and and of of to to in in "
) * 4


def _train_bpe_ranks(corpus: str, n_merges: int):
    """Classic BPE training: ranks ARE merge order (the invariant real
    tiktoken vocab files satisfy — every token splits into two
    lower-ranked tokens)."""
    ranks = {bytes([i]): i for i in range(256)}
    words = [
        [bytes([b]) for b in w.encode("utf-8")] for w in corpus.split()
    ]
    for step in range(n_merges):
        counts = {}
        for w in words:
            for a, b in zip(w, w[1:]):
                counts[(a, b)] = counts.get((a, b), 0) + 1
        if not counts:
            break
        # Deterministic: most frequent, ties broken lexicographically.
        (a, b), _ = max(counts.items(), key=lambda kv: (kv[1], kv[0]))
        merged = a + b
        ranks[merged] = 256 + step
        for w in words:
            i = 0
            while i < len(w) - 1:
                if w[i] == a and w[i + 1] == b:
                    w[i:i + 2] = [merged]
                else:
                    i += 1
    return ranks


@pytest.fixture(scope="module")
def trained_ranks():
    return _train_bpe_ranks(_CORPUS, n_merges=200)


def test_rank_file_roundtrip_trained_table(tmp_path_factory, trained_ranks):
    path = tmp_path_factory.mktemp("vocab") / "trained.model"
    path.write_text(
        "\n".join(
            f"{base64.b64encode(tok).decode()} {rank}"
            for tok, rank in trained_ranks.items()
        )
    )
    assert read_bpe_ranks(str(path)) == trained_ranks
    # Constructing from the file and from the in-memory table must be the
    # same tokenizer.
    t_file = LLaMA3Tokenizer(str(path))
    t_mem = LLaMA3Tokenizer.from_ranks(trained_ranks)
    for s in ("the quick brown fox", "unseen zebra text!"):
        assert t_file.encode(s, bos=True, eos=True) == t_mem.encode(
            s, bos=True, eos=True
        )


def test_trained_table_matches_independent_tiktoken(trained_ranks):
    """Our tokenizer must split + merge exactly like a tiktoken.Encoding
    built directly (no wrapper) from the same ranks and pattern — on text
    where multi-byte merges genuinely fire."""
    import tiktoken

    from jax_llama_tpu.tokenizers.llama3 import SPLIT_REGEX

    tok = LLaMA3Tokenizer.from_ranks(trained_ranks)
    ref = tiktoken.Encoding(
        name="ref", pat_str=SPLIT_REGEX,
        mergeable_ranks=trained_ranks, special_tokens={},
    )
    cases = [
        "the quick brown fox jumps over the lazy dog",
        "The Quick BROWN fox!  \n\n  jumps\t\tover",
        "unseen words zebra xylophone 12345 67 8",
        "punctuation, and 'contractions' don't split oddly...",
        "unicode: café 世界 \U0001f600 mixed in",
        "   leading and trailing   ",
    ]
    merged_seen = False
    for s in cases:
        got = tok.encode(s, bos=False, eos=False)
        want = ref.encode(s)
        assert got == want, s
        merged_seen |= any(t >= 256 for t in got)
        assert tok.decode(got) == s
    # The table must actually exercise merges, or this test proves nothing.
    assert merged_seen


def test_trained_table_special_layout_and_chat(trained_ranks):
    """Special-token ids sit immediately after the base vocab regardless
    of table size; chat framing and stop tokens follow them."""
    tok = LLaMA3Tokenizer.from_ranks(trained_ranks)
    base = len(trained_ranks)
    assert tok.bos_id == base + 0
    assert tok.eos_id == base + 1
    assert tok.eot_id == base + 9
    assert tok.stop_tokens == {base + 1, base + 9}
    ids = ChatFormat(tok).encode_dialog_prompt(
        [{"role": "user", "content": "the quick fox"}]
    )
    assert ids[0] == tok.bos_id
    assert tok.eot_id in ids
