"""HTTP serving front-end: concurrent requests through the real batcher
must match standalone batcher output, and /metrics must expose counters."""

import json
import threading
import urllib.request

import jax
import pytest

from jax_llama_tpu import get_config, init_params
from jax_llama_tpu.serving import ContinuousBatcher
from jax_llama_tpu.server import LLMServer
from jax_llama_tpu.tokenizers.bytes import ByteTokenizer

CFG = dict(
    vocab_size=512, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
    multiple_of=32, max_seq_len=128, dtype="float32", param_dtype="float32",
)


@pytest.fixture(scope="module")
def model():
    config = get_config("tiny", **CFG)
    params = init_params(jax.random.PRNGKey(0), config)
    return params, config


def _post(url, payload, timeout=300):
    req = urllib.request.Request(
        url + "/generate", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def _get(url, path, timeout=60):
    with urllib.request.urlopen(url + path, timeout=timeout) as r:
        return r.status, r.read().decode()


def test_http_concurrent_requests_match_standalone(model):
    params, config = model
    tok = ByteTokenizer()
    prompts = ["hello tpu", "paged kv"]
    token_prompts = [tok.encode(p, bos=True) for p in prompts]

    ref = ContinuousBatcher(params, config, n_slots=2, max_len=64,
                            stop_tokens=tuple(tok.stop_tokens))
    rids = [ref.submit(p, max_new_tokens=8) for p in token_prompts]
    want = ref.run_to_completion()

    cb = ContinuousBatcher(params, config, n_slots=2, max_len=64,
                           stop_tokens=tuple(tok.stop_tokens))
    with LLMServer(cb, tokenizer=tok) as srv:
        results = {}

        def call(i):
            status, body = _post(
                srv.address, {"text": prompts[i], "max_new_tokens": 8}
            )
            results[i] = (status, body)

        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(len(prompts))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert not any(t.is_alive() for t in threads)

        for i in range(len(prompts)):
            status, body = results[i]
            assert status == 200
            assert body["tokens"] == want[rids[i]], prompts[i]
            assert body["text"] == tok.decode(want[rids[i]])

        status, text = _get(srv.address, "/metrics")
        assert status == 200
        assert "llm_emitted_tokens_total" in text
        emitted = [
            line for line in text.splitlines()
            if line.startswith("llm_emitted_tokens_total")
        ][0]
        assert float(emitted.split()[1]) >= sum(
            len(want[r]) for r in rids
        )

        status, body = _get(srv.address, "/healthz")
        assert status == 200 and json.loads(body)["ok"] is True


def test_http_error_paths(model):
    params, config = model
    cb = ContinuousBatcher(params, config, n_slots=1, max_len=32)
    with LLMServer(cb) as srv:
        # no tokenizer -> text prompts rejected, token prompts fine
        try:
            _post(srv.address, {"text": "hi", "max_new_tokens": 4})
            assert False, "expected HTTP 400"
        except urllib.error.HTTPError as e:
            assert e.code == 400
            assert "tokenizer" in json.loads(e.read())["error"]
        # over-capacity request -> batcher ValueError surfaces as 400
        try:
            _post(srv.address,
                  {"prompt": list(range(1, 30)), "max_new_tokens": 30})
            assert False, "expected HTTP 400"
        except urllib.error.HTTPError as e:
            assert e.code == 400
        # a valid request still works afterwards
        status, body = _post(
            srv.address, {"prompt": [1, 2, 3], "max_new_tokens": 4}
        )
        assert status == 200
        assert len(body["tokens"]) == 4
