"""HTTP serving front-end: concurrent requests through the real batcher
must match standalone batcher output, and /metrics must expose counters."""

import json
import threading
import urllib.parse
import urllib.request

import jax
import pytest

from jax_llama_tpu import get_config, init_params
from jax_llama_tpu.serving import ContinuousBatcher
from jax_llama_tpu.server import LLMServer
from jax_llama_tpu.tokenizers.bytes import ByteTokenizer

CFG = dict(
    vocab_size=512, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
    multiple_of=32, max_seq_len=128, dtype="float32", param_dtype="float32",
)


@pytest.fixture(scope="module")
def model():
    config = get_config("tiny", **CFG)
    params = init_params(jax.random.PRNGKey(0), config)
    return params, config


def _post(url, payload, timeout=300):
    req = urllib.request.Request(
        url + "/generate", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def _get(url, path, timeout=60):
    with urllib.request.urlopen(url + path, timeout=timeout) as r:
        return r.status, r.read().decode()


def test_http_concurrent_requests_match_standalone(model):
    params, config = model
    tok = ByteTokenizer()
    prompts = ["hello tpu", "paged kv"]
    token_prompts = [tok.encode(p, bos=True) for p in prompts]

    ref = ContinuousBatcher(params, config, n_slots=2, max_len=64,
                            stop_tokens=tuple(tok.stop_tokens))
    rids = [ref.submit(p, max_new_tokens=8) for p in token_prompts]
    want = ref.run_to_completion()

    cb = ContinuousBatcher(params, config, n_slots=2, max_len=64,
                           stop_tokens=tuple(tok.stop_tokens))
    with LLMServer(cb, tokenizer=tok) as srv:
        results = {}

        def call(i):
            status, body = _post(
                srv.address, {"text": prompts[i], "max_new_tokens": 8}
            )
            results[i] = (status, body)

        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(len(prompts))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert not any(t.is_alive() for t in threads)

        for i in range(len(prompts)):
            status, body = results[i]
            assert status == 200
            assert body["tokens"] == want[rids[i]], prompts[i]
            assert body["text"] == tok.decode(want[rids[i]])

        status, text = _get(srv.address, "/metrics")
        assert status == 200
        assert "llm_emitted_tokens_total" in text
        emitted = [
            line for line in text.splitlines()
            if line.startswith("llm_emitted_tokens_total")
        ][0]
        assert float(emitted.split()[1]) >= sum(
            len(want[r]) for r in rids
        )

        status, body = _get(srv.address, "/healthz")
        assert status == 200 and json.loads(body)["ok"] is True


def test_http_error_paths(model):
    params, config = model
    cb = ContinuousBatcher(params, config, n_slots=1, max_len=32)
    with LLMServer(cb) as srv:
        # no tokenizer -> text prompts rejected, token prompts fine
        try:
            _post(srv.address, {"text": "hi", "max_new_tokens": 4})
            assert False, "expected HTTP 400"
        except urllib.error.HTTPError as e:
            assert e.code == 400
            assert "tokenizer" in json.loads(e.read())["error"]
        # over-capacity request -> batcher ValueError surfaces as 400
        try:
            _post(srv.address,
                  {"prompt": list(range(1, 30)), "max_new_tokens": 30})
            assert False, "expected HTTP 400"
        except urllib.error.HTTPError as e:
            assert e.code == 400
        # a valid request still works afterwards
        status, body = _post(
            srv.address, {"prompt": [1, 2, 3], "max_new_tokens": 4}
        )
        assert status == 200
        assert len(body["tokens"]) == 4


def _stream_lines(url, payload, timeout=300):
    """POST with stream=true; return the parsed NDJSON lines."""
    req = urllib.request.Request(
        url + "/generate", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        assert r.status == 200
        assert r.headers["Content-Type"] == "application/x-ndjson"
        return [json.loads(line) for line in r.read().splitlines()]


def test_http_streaming_matches_blocking(model):
    params, config = model
    cb = ContinuousBatcher(params, config, n_slots=2, max_len=64)
    with LLMServer(cb) as srv:
        status, body = _post(
            srv.address, {"prompt": [5, 9, 13], "max_new_tokens": 6}
        )
        assert status == 200
        lines = _stream_lines(
            srv.address,
            {"prompt": [5, 9, 13], "max_new_tokens": 6, "stream": True},
        )
        # one line per token, then the final summary line
        assert lines[-1]["done"] is True
        per_token = [ln["token"] for ln in lines[:-1]]
        assert per_token == body["tokens"]
        assert lines[-1]["tokens"] == body["tokens"]
        assert "timeout" not in lines[-1]


def test_http_timeout_cancels_and_frees_blocks(model):
    params, config = model
    cb = ContinuousBatcher(params, config, n_slots=1, max_len=64)
    total_blocks = cb.n_blocks
    with LLMServer(cb) as srv:
        # timeout_s=0: already expired when the loop pops the inbox —
        # rejected BEFORE admission (no slot was ever taken).
        try:
            _post(
                srv.address,
                {"prompt": [1, 2, 3], "max_new_tokens": 40,
                 "timeout_s": 0.0},
            )
            assert False, "expected HTTP 504"
        except urllib.error.HTTPError as e:
            assert e.code == 504
            assert "timed out" in json.loads(e.read())["error"]

        # Warm the compile caches so the next request's budget is spent
        # generating, not compiling.
        status, _ = _post(
            srv.address, {"prompt": [4, 5, 6], "max_new_tokens": 2}
        )
        assert status == 200

        # The cancelled request released its slot and blocks: a fresh
        # request gets full capacity and completes.
        status, body = _post(
            srv.address, {"prompt": [4, 5, 6], "max_new_tokens": 4}
        )
        assert status == 200 and len(body["tokens"]) == 4
        assert len(cb.free_blocks) == total_blocks
        assert all(s is None for s in cb.slots.values())


def test_http_mid_generation_timeout_reaps_active_request(model):
    """Exercise _reap's expired-ACTIVE branch (distinct from the
    pre-admission rejection above): the request must be admitted, emit
    some tokens, hit its deadline mid-generation, and be cancelled with
    partial tokens in the 504 body and its slot/blocks released."""
    params, config = model
    # A generation budget far larger than 2s of CPU steps can finish.
    cb = ContinuousBatcher(params, config, n_slots=1, max_len=4096)
    total_blocks = cb.n_blocks
    with LLMServer(cb) as srv:
        # Warm the compile caches so the timed request spends its budget
        # generating, not compiling.
        status, _ = _post(
            srv.address, {"prompt": [4, 5, 6], "max_new_tokens": 2}
        )
        assert status == 200
        try:
            _post(
                srv.address,
                {"prompt": [1, 2, 3], "max_new_tokens": 3000,
                 "timeout_s": 2.0},
            )
            assert False, "expected HTTP 504"
        except urllib.error.HTTPError as e:
            assert e.code == 504
            body = json.loads(e.read())
            assert "timed out" in body["error"]
            # It was admitted and generated until the reap.
            assert 0 < len(body["tokens"]) < 3000
        assert len(cb.free_blocks) == total_blocks
        assert all(s is None for s in cb.slots.values())
        assert not cb.pending()


def test_http_client_disconnect_cancels_stream(model):
    import socket
    import time as _time

    params, config = model
    cb = ContinuousBatcher(params, config, n_slots=1, max_len=64)
    total_blocks = cb.n_blocks
    with LLMServer(cb) as srv:
        host, port = srv.httpd.server_address[:2]
        # Small enough to be ADMITTED (the point is reaping an active,
        # generating request), big enough that the client disconnects
        # long before it finishes.
        payload = json.dumps(
            {"prompt": [7, 8, 9], "max_new_tokens": 40, "stream": True}
        ).encode()
        s = socket.create_connection((host, port), timeout=30)
        s.sendall(
            b"POST /generate HTTP/1.1\r\nHost: x\r\n"
            b"Content-Type: application/json\r\n"
            + f"Content-Length: {len(payload)}\r\n\r\n".encode() + payload
        )
        s.recv(1024)  # read the status line + first bytes, then vanish
        s.close()
        # The loop notices the dead socket at the next failed write and
        # frees the slot; other requests then proceed normally.
        deadline = _time.monotonic() + 120
        while _time.monotonic() < deadline:
            if (
                len(cb.free_blocks) == total_blocks
                and all(sl is None for sl in cb.slots.values())
                and not cb.queue
            ):
                break
            _time.sleep(0.2)
        else:
            assert False, "disconnected stream request was never reaped"
        status, body = _post(
            srv.address, {"prompt": [1, 2], "max_new_tokens": 3}
        )
        assert status == 200 and len(body["tokens"]) == 3


def test_http_client_disconnect_cancels_blocking(model):
    """A NON-streaming /generate whose client vanishes must also be
    reaped: nothing ever writes to the socket until completion, so the
    _blocking_reply wait loop's readable-EOF probe is the only signal."""
    import socket
    import time as _time

    params, config = model
    # A generation budget far larger than the reap window can finish, so
    # the only way the slot frees is the disconnect probe + _reap.
    cb = ContinuousBatcher(params, config, n_slots=1, max_len=4096)
    total_blocks = cb.n_blocks
    with LLMServer(cb) as srv:
        host, port = srv.httpd.server_address[:2]
        # Warm the compile caches first.
        status, _ = _post(
            srv.address, {"prompt": [4, 5, 6], "max_new_tokens": 2}
        )
        assert status == 200
        payload = json.dumps(
            {"prompt": [7, 8, 9], "max_new_tokens": 3000}
        ).encode()
        s = socket.create_connection((host, port), timeout=30)
        s.sendall(
            b"POST /generate HTTP/1.1\r\nHost: x\r\n"
            b"Content-Type: application/json\r\n"
            + f"Content-Length: {len(payload)}\r\n\r\n".encode() + payload
        )
        _time.sleep(0.5)  # let the handler enqueue + the loop admit it
        s.close()
        deadline = _time.monotonic() + 120
        while _time.monotonic() < deadline:
            if (
                len(cb.free_blocks) == total_blocks
                and all(sl is None for sl in cb.slots.values())
                and not cb.queue
            ):
                break
            _time.sleep(0.2)
        else:
            assert False, "disconnected blocking request was never reaped"
        # Reaped by cancellation, not by finishing the 3000 tokens.
        assert cb.emitted_total < 3000
        status, body = _post(
            srv.address, {"prompt": [1, 2], "max_new_tokens": 3}
        )
        assert status == 200 and len(body["tokens"]) == 3


def test_batcher_cancel_queued_and_active(model):
    params, config = model
    cb = ContinuousBatcher(params, config, n_slots=1, max_len=64)
    total = cb.n_blocks
    r1 = cb.submit([1, 2, 3], max_new_tokens=8)   # admitted to the slot
    r2 = cb.submit([4, 5, 6], max_new_tokens=8)   # waits in the queue
    assert cb.cancel(r2) is True                  # dequeue
    assert cb.cancel(r2) is False                 # already gone
    assert cb.cancel(r1) is True                  # frees the active slot
    assert not cb.pending()
    assert len(cb.free_blocks) == total


def test_http_non_finite_timeout_rejected(model):
    params, config = model
    cb = ContinuousBatcher(params, config, n_slots=1, max_len=32)
    with LLMServer(cb) as srv:
        for bad in ("NaN", "Infinity"):
            req = urllib.request.Request(
                srv.address + "/generate",
                # raw JSON so the non-finite literal reaches the server
                data=(
                    b'{"prompt": [1, 2], "max_new_tokens": 4, '
                    b'"timeout_s": ' + bad.encode() + b"}"
                ),
                headers={"Content-Type": "application/json"},
            )
            try:
                urllib.request.urlopen(req, timeout=60)
                assert False, f"expected HTTP 400 for timeout_s={bad}"
            except urllib.error.HTTPError as e:
                assert e.code == 400
                assert "finite" in json.loads(e.read())["error"]


def test_http_chat_endpoint(model):
    """/chat frames the dialog via the chat_format, defaults stop tokens
    to the tokenizer's stop set, strips stop ids from the decoded text,
    and rejects malformed dialogs and chat-less servers."""
    params, config = model
    tok = ByteTokenizer()

    class ByteChatFormat:
        """Minimal dialog framing over the byte tokenizer (the llama3
        ChatFormat needs a real tiktoken vocab; the server only relies on
        encode_dialog_prompt)."""

        def __init__(self, tokenizer):
            self.tokenizer = tokenizer

        def encode_dialog_prompt(self, dialog):
            ids = [self.tokenizer.bos_id]
            for m in dialog:
                ids += self.tokenizer.encode(f"[{m['role']}]")
                ids += self.tokenizer.encode(m["content"])
            ids += self.tokenizer.encode("[assistant]")
            return ids

    fmt = ByteChatFormat(tok)
    messages = [
        {"role": "system", "content": "terse"},
        {"role": "user", "content": "hi there"},
    ]

    # Reference: standalone batcher fed the same framed prompt with the
    # tokenizer's stop set (the endpoint's default).
    ref = ContinuousBatcher(params, config, n_slots=2, max_len=64)
    rid = ref.submit(
        fmt.encode_dialog_prompt(messages), max_new_tokens=8,
        stop_tokens=tuple(tok.stop_tokens),
    )
    want = ref.run_to_completion()[rid]

    cb = ContinuousBatcher(params, config, n_slots=2, max_len=64)
    with LLMServer(cb, tokenizer=tok, chat_format=fmt) as srv:
        req = urllib.request.Request(
            srv.address + "/chat",
            data=json.dumps(
                {"messages": messages, "max_new_tokens": 8}
            ).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=300) as r:
            status, body = r.status, json.loads(r.read())
        assert status == 200
        assert body["tokens"] == want
        stop_set = set(tok.stop_tokens)
        assert body["text"] == tok.decode(
            [t for t in want if t not in stop_set]
        )

        # Streaming /chat: NDJSON token lines; stop ids carry no text.
        req = urllib.request.Request(
            srv.address + "/chat",
            data=json.dumps(
                {"messages": messages, "max_new_tokens": 8, "stream": True}
            ).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=300) as r:
            lines = [json.loads(ln) for ln in r.read().splitlines()]
        assert lines[-1]["done"] is True
        assert lines[-1]["tokens"] == want
        toks_streamed = [ln["token"] for ln in lines[:-1]]
        assert toks_streamed == want
        for ln in lines[:-1]:
            if ln["token"] in stop_set:
                assert ln["text"] == ""  # protocol framing, not content

        # A /chat that sends its own "stop_tokens" decodes verbatim: the
        # tokenizer's stop set is not protocol framing for that request,
        # so a stop id the client generated past must survive in "text"
        # (it still appears in "tokens" either way).
        ref2 = ContinuousBatcher(params, config, n_slots=2, max_len=64)
        rid2 = ref2.submit(
            fmt.encode_dialog_prompt(messages), max_new_tokens=8,
            stop_tokens=(),
        )
        want2 = ref2.run_to_completion()[rid2]
        req = urllib.request.Request(
            srv.address + "/chat",
            data=json.dumps(
                {"messages": messages, "max_new_tokens": 8,
                 "stop_tokens": []}
            ).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=300) as r:
            body = json.loads(r.read())
        assert body["tokens"] == want2
        assert body["text"] == tok.decode(want2)  # verbatim, stop ids kept

        # Malformed dialogs are 400s, not loop crashes.
        for bad in (
            {},
            {"messages": []},
            {"messages": [{"role": "user"}]},
            {"messages": "hi"},
            # Wrong-TYPED values (OpenAI-style content parts, null, int):
            # ChatFormat would raise AttributeError on these, which is
            # outside the loop's caught-error set — they must be rejected
            # at validation, not allowed to kill the serving thread.
            {"messages": [{"role": "user",
                           "content": [{"type": "text", "text": "hi"}]}]},
            {"messages": [{"role": "user", "content": None}]},
            {"messages": [{"role": 3, "content": "hi"}]},
        ):
            req = urllib.request.Request(
                srv.address + "/chat", data=json.dumps(bad).encode(),
                headers={"Content-Type": "application/json"},
            )
            try:
                urllib.request.urlopen(req, timeout=60)
                assert False, bad
            except urllib.error.HTTPError as e:
                assert e.code == 400

    # A server without a chat_format refuses /chat.
    cb2 = ContinuousBatcher(params, config, n_slots=1, max_len=64)
    with LLMServer(cb2, tokenizer=tok) as srv:
        req = urllib.request.Request(
            srv.address + "/chat",
            data=json.dumps(
                {"messages": messages, "max_new_tokens": 4}
            ).encode(),
            headers={"Content-Type": "application/json"},
        )
        try:
            urllib.request.urlopen(req, timeout=60)
            assert False
        except urllib.error.HTTPError as e:
            assert e.code == 400
            assert "chat_format" in json.loads(e.read())["error"]


def test_http_logprobs(model):
    """"logprobs": true returns per-token model logprobs (blocking array
    + per-line streaming), and is a 400 when the batcher was not built
    with logprobs=True."""
    import math

    params, config = model
    tok = ByteTokenizer()
    cb = ContinuousBatcher(
        params, config, n_slots=2, max_len=64, logprobs=True
    )
    with LLMServer(cb, tokenizer=tok) as srv:
        status, body = _post(
            srv.address,
            {"text": "hello", "max_new_tokens": 6, "logprobs": True},
        )
        assert status == 200
        assert len(body["logprobs"]) == len(body["tokens"]) == 6
        assert all(
            isinstance(x, float) and x <= 0.0 and math.isfinite(x)
            for x in body["logprobs"]
        )

        # Streaming: each token line carries its logprob; the final line
        # repeats the full array.
        req = urllib.request.Request(
            srv.address + "/generate",
            data=json.dumps({"text": "hello", "max_new_tokens": 6,
                             "logprobs": True, "stream": True}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=300) as r:
            lines = [json.loads(ln) for ln in r.read().splitlines()]
        assert [ln["logprob"] for ln in lines[:-1]] == body["logprobs"]
        assert lines[-1]["logprobs"] == body["logprobs"]
        assert lines[-1]["tokens"] == body["tokens"]

        # Without logprobs the response omits the field.
        status, body2 = _post(
            srv.address, {"text": "hello", "max_new_tokens": 4}
        )
        assert status == 200 and "logprobs" not in body2

    cb2 = ContinuousBatcher(params, config, n_slots=1, max_len=32)
    with LLMServer(cb2, tokenizer=tok) as srv:
        try:
            _post(srv.address,
                  {"text": "x", "max_new_tokens": 2, "logprobs": True})
            assert False
        except urllib.error.HTTPError as e:
            assert e.code == 400
            assert "logprobs" in json.loads(e.read())["error"]


def test_http_logprobs_with_speculative_batcher(model):
    """"logprobs": true works over a speculative batcher (self-draft):
    the tokens match a plain batcher's and each gets a finite logprob —
    the verify pass supplies logprobs for multi-token emission."""
    import math

    params, config = model
    tok = ByteTokenizer()
    plain = ContinuousBatcher(params, config, n_slots=1, max_len=64)
    prid = plain.submit(tok.encode("hello", bos=True, eos=False),
                        max_new_tokens=8)
    want = plain.run_to_completion()[prid]

    cb = ContinuousBatcher(
        params, config, n_slots=2, max_len=64, logprobs=True,
        draft_params=params, draft_config=config, n_draft=3,
    )
    with LLMServer(cb, tokenizer=tok) as srv:
        status, body = _post(
            srv.address,
            {"text": "hello", "max_new_tokens": 8, "logprobs": True},
        )
        assert status == 200
        assert body["tokens"] == want
        assert len(body["logprobs"]) == 8
        assert all(
            isinstance(x, float) and x <= 0.0 and math.isfinite(x)
            for x in body["logprobs"]
        )


# slow (r17 budget rebalance, ~8 s): HTTP-layer concurrency stays
# tier-1-pinned by test_http_concurrent_requests_match_standalone and
# mixed-class load shedding by test_overload.py's drills (`make
# overload` runs its file unfiltered); the mixed-load soak rides slow
# (unfiltered suite runs it).
@pytest.mark.slow
def test_http_mixed_concurrent_load(model):
    """Soak: 12 concurrent clients mixing blocking, streaming, chat, and
    logprobs requests against a 3-slot batcher — every request completes
    with a consistent body and the pool drains clean."""
    params, config = model
    tok = ByteTokenizer()

    class ByteChatFormat:
        def __init__(self, t):
            self.tokenizer = t

        def encode_dialog_prompt(self, dialog):
            ids = [self.tokenizer.bos_id]
            for m in dialog:
                ids += self.tokenizer.encode(f"[{m['role']}]" + m["content"])
            ids += self.tokenizer.encode("[assistant]")
            return ids

    cb = ContinuousBatcher(
        params, config, n_slots=3, max_len=64, logprobs=True
    )
    total_blocks = cb.n_blocks
    with LLMServer(
        cb, tokenizer=tok, chat_format=ByteChatFormat(tok)
    ) as srv:
        results = {}

        def call(i):
            kind = i % 4
            try:
                if kind == 0:      # blocking /generate
                    status, body = _post(
                        srv.address,
                        {"text": f"req {i}", "max_new_tokens": 5},
                    )
                    ok = status == 200 and len(body["tokens"]) == 5
                elif kind == 1:    # streaming /generate + logprobs
                    lines = _stream_lines(
                        srv.address,
                        {"text": f"req {i}", "max_new_tokens": 5,
                         "stream": True, "logprobs": True},
                    )
                    ok = (
                        lines[-1]["done"] is True
                        and len(lines[-1]["tokens"]) == 5
                        and len(lines[-1]["logprobs"]) == 5
                        and [ln["token"] for ln in lines[:-1]]
                        == lines[-1]["tokens"]
                    )
                elif kind == 2:    # blocking /chat
                    req = urllib.request.Request(
                        srv.address + "/chat",
                        data=json.dumps({
                            "messages": [
                                {"role": "user", "content": f"hi {i}"}
                            ],
                            "max_new_tokens": 5,
                        }).encode(),
                        headers={"Content-Type": "application/json"},
                    )
                    with urllib.request.urlopen(req, timeout=300) as r:
                        body = json.loads(r.read())
                        ok = r.status == 200 and len(body["tokens"]) <= 5
                else:              # blocking /generate + logprobs
                    status, body = _post(
                        srv.address,
                        {"prompt": [2 + i, 7, 11], "max_new_tokens": 5,
                         "logprobs": True, "temperature": 0.6,
                         "seed": i},
                    )
                    ok = (
                        status == 200
                        and len(body["logprobs"]) == len(body["tokens"]) == 5
                    )
                results[i] = ok
            except Exception as e:  # noqa: BLE001 — fail the test, not the thread
                results[i] = f"{type(e).__name__}: {e}"

        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(12)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)
        assert not any(t.is_alive() for t in threads)
        assert all(v is True for v in results.values()), results

    # Everything released: the full pool is allocatable again — truly
    # free blocks plus prefix-cache-retained ones (r5: completed
    # requests RETAIN their keyed prompt blocks for reuse; retention is
    # capacity, not leakage) — and no occupied slots.
    assert (len(cb.free_blocks) + cb._store.cached_blocks()
            == total_blocks)
    assert all(s is None for s in cb.slots.values())
    assert not cb._block_refs  # no dangling refcounts


def test_http_body_size_cap(model):
    """Oversized or missing Content-Length is refused with 413 BEFORE
    any body read; a bad length is a 400; normal requests still work.
    urllib always sets the header, so drive http.client directly."""
    import http.client

    params, config = model
    cb = ContinuousBatcher(params, config, n_slots=1, max_len=32)
    with LLMServer(cb, max_body_bytes=1024) as srv:
        host, port = srv.httpd.server_address[:2]

        def raw_post(headers, body=b""):
            conn = http.client.HTTPConnection(host, port, timeout=30)
            try:
                conn.putrequest("POST", "/generate")
                for k, v in headers.items():
                    conn.putheader(k, v)
                conn.endheaders()
                if body:
                    conn.send(body)
                r = conn.getresponse()
                return r.status, json.loads(r.read())
            finally:
                conn.close()

        # claimed length over the cap: refused up front, body never read
        status, body = raw_post({"Content-Length": str(1 << 30)})
        assert status == 413
        assert "too large" in body["error"]
        # missing Content-Length: 413 too (the length is required)
        status, body = raw_post({})
        assert status == 413
        assert "Content-Length" in body["error"]
        # unparseable length: 400
        status, body = raw_post({"Content-Length": "banana"})
        assert status == 400
        # a normal request under the cap still works
        status, body = _post(
            srv.address, {"prompt": [1, 2, 3], "max_new_tokens": 4}
        )
        assert status == 200 and len(body["tokens"]) == 4


# ---------------------------------------------------------------------------
# Observability surface: /metrics exposition, end-to-end request ids,
# /debug endpoints, SLO gauges (obs.py)
# ---------------------------------------------------------------------------

_SAMPLE_RE = __import__("re").compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? -?[0-9]+(\.[0-9]+)?"
    r"([eE][+-][0-9]+)?$"
)


def _parse_exposition(text):
    """Minimal Prometheus text-format parser: returns
    ({family: type}, {family: help}, {sample_name_with_labels: value})
    and asserts every line is well-formed."""
    types, helps, samples = {}, {}, {}
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split(" ", 3)
            assert kind in ("counter", "gauge", "histogram"), line
            assert name not in types, f"duplicate TYPE for {name}"
            types[name] = kind
        elif line.startswith("# HELP "):
            _, _, name, help_text = line.split(" ", 3)
            assert help_text.strip(), line
            helps[name] = help_text
        else:
            assert _SAMPLE_RE.match(line), f"malformed sample: {line!r}"
            name, value = line.rsplit(" ", 1)
            samples[name] = float(value)
    return types, helps, samples


@pytest.mark.obs
def test_metrics_exposition_valid_prometheus(model):
    """Every /metrics line is valid Prometheus text format, every
    family carries an explicit # TYPE AND # HELP from the obs.METRICS
    registry (no heuristic, no unregistered stragglers), TYPE is
    consistent with semantics, and the histogram families obey the
    cumulative-bucket invariants."""
    params, config = model
    cb = ContinuousBatcher(
        params, config, n_slots=2, max_len=64, cost_models=True,
    )
    with LLMServer(cb, tokenizer=ByteTokenizer()) as srv:
        status, _ = _post(
            srv.address, {"prompt": [3, 4, 5], "max_new_tokens": 6}
        )
        assert status == 200
        status, text = _get(srv.address, "/metrics")
        assert status == 200
    types, helps, samples = _parse_exposition(text)
    # The legacy fallback marks unregistered scalars; none may ship.
    assert "UNREGISTERED" not in text
    # Every TYPE has a HELP and vice versa.
    assert set(types) == set(helps)
    # Every sample belongs to a typed family (histograms expose
    # _bucket/_sum/_count series under the family name).
    for name in samples:
        family = name.split("{")[0]
        for suffix in ("_bucket", "_sum", "_count"):
            if family.endswith(suffix) and (
                family[: -len(suffix)] in types
            ):
                family = family[: -len(suffix)]
                break
        assert family in types, f"untyped sample {name}"
    # TYPE consistent with semantics: *_total names counters — except
    # llm_radix_nodes_total, the documented resident-count exception.
    for family, kind in types.items():
        if kind == "histogram":
            continue
        if family.endswith("_total") and family != "llm_radix_nodes_total":
            assert kind == "counter", family
    assert types["llm_radix_nodes_total"] == "gauge"
    assert types["llm_active_slots"] == "gauge"
    # KV chain-digest scalar families (PR 13) are registered and
    # typed: versions as gauges, the event ledger as counters.
    assert types["llm_kv_digest_version"] == "gauge"
    assert types["llm_kv_digest_loss_version"] == "gauge"
    assert types["llm_kv_block_bytes"] == "gauge"
    for fam in ("llm_kv_publish_events_total",
                "llm_kv_evict_events_total",
                "llm_kv_demote_events_total",
                "llm_kv_restore_events_total",
                "llm_kv_host_evict_events_total",
                "llm_kv_export_events_total",
                "llm_kv_import_events_total"):
        assert types[fam] == "counter", fam
    assert samples["llm_kv_block_bytes"] > 0
    # The serving histograms are exposed and internally consistent —
    # including the two non-latency KV families (token/block buckets).
    for fam in ("llm_ttft_ms", "llm_itl_ms", "llm_queue_wait_ms",
                "llm_prefill_chunk_ms", "llm_swap_in_ms",
                "llm_compile_ms", "llm_prefix_hit_depth_tokens",
                "llm_session_kv_blocks"):
        assert types[fam] == "histogram"
        buckets = [
            (n, v) for n, v in samples.items()
            if n.startswith(fam + "_bucket{")
        ]
        assert buckets, fam
        counts = [v for _, v in buckets]
        assert counts == sorted(counts), f"{fam} buckets not cumulative"
        inf = [v for n, v in buckets if 'le="+Inf"' in n]
        assert len(inf) == 1
        assert inf[0] == samples[fam + "_count"]
        assert samples[fam + "_sum"] >= 0.0
    # dispatch_ms is a LABELED family: one series per dispatch kind,
    # each internally cumulative with its own _sum/_count.
    assert types["llm_dispatch_ms"] == "histogram"
    kind_re = __import__("re").compile(r'kind="([a-z_]+)"')
    kinds = {
        kind_re.search(n).group(1)
        for n in samples if n.startswith("llm_dispatch_ms_bucket{")
    }
    assert "decode" in kinds and "insert" in kinds, kinds
    for kind in kinds:
        buckets = [
            v for n, v in samples.items()
            if n.startswith("llm_dispatch_ms_bucket{")
            and f'kind="{kind}"' in n
        ]
        assert buckets == sorted(buckets), f"{kind} not cumulative"
        assert buckets[-1] == samples[
            f'llm_dispatch_ms_count{{kind="{kind}"}}'
        ]
        assert samples[f'llm_dispatch_ms_sum{{kind="{kind}"}}'] >= 0.0
    # The request actually fed TTFT and the per-kind dispatch series.
    assert samples["llm_ttft_ms_count"] >= 1
    assert samples['llm_dispatch_ms_count{kind="decode"}'] >= 1
    # Device-time attribution: per-kind utilization gauges (the
    # batcher above has cost models ON) and the jit-cache entry gauge
    # (one labeled sample per registered program).
    for fam in ("llm_mxu_utilization", "llm_hbm_utilization",
                "llm_host_overhead_ratio", "llm_jit_cache_entries",
                "llm_program_compiles_total"):
        assert fam in types, fam
    assert types["llm_mxu_utilization"] == "gauge"
    assert samples['llm_mxu_utilization{kind="decode"}'] >= 0.0
    assert samples['llm_host_overhead_ratio{kind="decode"}'] > 0.0
    cache_progs = {
        n for n in samples if n.startswith("llm_jit_cache_entries{")
    }
    assert (
        'llm_jit_cache_entries{program="_paged_decode_chunk"}'
        in cache_progs
    )
    assert len(cache_progs) == 10  # all registered serving programs
    assert samples["llm_compiles_total"] >= 0
    # SLO gauges present (unset deadlines -> 0 / attainment 1.0).
    assert samples["llm_slo_ttft_ms"] == 0.0
    assert samples["llm_slo_attainment"] == 1.0
    assert samples["llm_goodput_tokens_total"] >= 6


@pytest.mark.obs
def test_request_id_end_to_end(model):
    """A client-supplied X-Request-Id is honored and echoed in the
    blocking body, the response header, every stream line, and error
    bodies; absent the header, the server mints one."""
    params, config = model
    cb = ContinuousBatcher(params, config, n_slots=2, max_len=64)
    with LLMServer(cb, tokenizer=ByteTokenizer()) as srv:
        req = urllib.request.Request(
            srv.address + "/generate",
            data=json.dumps(
                {"prompt": [3, 4, 5], "max_new_tokens": 4}
            ).encode(),
            headers={"Content-Type": "application/json",
                     "X-Request-Id": "client-abc-123"},
        )
        with urllib.request.urlopen(req, timeout=300) as r:
            body = json.loads(r.read())
            assert body["request_id"] == "client-abc-123"
            assert r.headers["X-Request-Id"] == "client-abc-123"
        # Minted id when the client sends none.
        status, body = _post(
            srv.address, {"prompt": [3, 4, 5], "max_new_tokens": 4}
        )
        assert status == 200
        assert isinstance(body["request_id"], str) and body["request_id"]
        # Every stream event carries the id, and the final line agrees.
        req = urllib.request.Request(
            srv.address + "/generate",
            data=json.dumps(
                {"prompt": [5, 6], "max_new_tokens": 4, "stream": True}
            ).encode(),
            headers={"Content-Type": "application/json",
                     "X-Request-Id": "stream-id-9"},
        )
        with urllib.request.urlopen(req, timeout=300) as r:
            assert r.headers["X-Request-Id"] == "stream-id-9"
            lines = [json.loads(ln) for ln in r.read().splitlines()]
        assert all(ln["request_id"] == "stream-id-9" for ln in lines)
        assert lines[-1]["done"] is True
        # A well-formed JSON body that is not an object is refused
        # cleanly (an AttributeError traceback would close the socket
        # with no HTTP response at all).
        req = urllib.request.Request(
            srv.address + "/generate", data=b"[1, 2, 3]",
            headers={"Content-Type": "application/json"},
        )
        try:
            urllib.request.urlopen(req, timeout=60)
            assert False, "expected HTTP 400"
        except urllib.error.HTTPError as e:
            assert e.code == 400
            assert "JSON object" in json.loads(e.read())["error"]
        # Error bodies carry the id too (malformed payload -> 400).
        req = urllib.request.Request(
            srv.address + "/generate", data=b"{not json",
            headers={"Content-Type": "application/json",
                     "X-Request-Id": "err-id-7"},
        )
        try:
            urllib.request.urlopen(req, timeout=60)
            assert False, "expected HTTP 400"
        except urllib.error.HTTPError as e:
            assert e.code == 400
            # Body AND header: proxies correlate on the header.
            assert e.headers["X-Request-Id"] == "err-id-7"
            assert json.loads(e.read())["request_id"] == "err-id-7"


@pytest.mark.obs
def test_debug_endpoints_and_slo_gauges(model):
    """/debug/requests/<id> returns the request's span timeline (spans
    linked to real dispatch spans), /debug/dispatches the ring,
    /debug/trace Perfetto-loadable JSON; configured SLOs feed the
    attainment gauges and goodput counter."""
    from jax_llama_tpu.obs import Observability

    params, config = model
    obs = Observability(slo_ttft_ms=60_000.0, slo_itl_ms=60_000.0)
    cb = ContinuousBatcher(params, config, n_slots=2, max_len=64,
                           obs=obs)
    with LLMServer(cb, tokenizer=ByteTokenizer()) as srv:
        req = urllib.request.Request(
            srv.address + "/generate",
            data=json.dumps(
                {"prompt": [7, 8, 9], "max_new_tokens": 5}
            ).encode(),
            headers={"Content-Type": "application/json",
                     "X-Request-Id": "dbg-1"},
        )
        with urllib.request.urlopen(req, timeout=300) as r:
            assert json.loads(r.read())["request_id"] == "dbg-1"

        status, body = _get(srv.address, "/debug/requests/dbg-1")
        assert status == 200
        tl = json.loads(body)
        assert tl["request_id"] == "dbg-1"
        assert tl["outcome"] == "finished"
        states = [sp["state"] for sp in tl["spans"]]
        assert states[0] == "queued" and "decoding" in states
        ring = {d["seq"] for d in tl["dispatch_spans"]}
        linked = [s for sp in tl["spans"] for s in sp["dispatches"]]
        assert linked and set(linked) <= ring

        status, body = _get(srv.address, "/debug/requests?n=8")
        assert status == 200
        idx = json.loads(body)["requests"]
        assert any(r["request_id"] == "dbg-1" for r in idx)

        status, body = _get(srv.address, "/debug/dispatches?n=16")
        assert status == 200
        dispatches = json.loads(body)["dispatches"]
        assert dispatches and all("kind" in d for d in dispatches)

        status, body = _get(srv.address, "/debug/trace")
        assert status == 200
        doc = json.loads(body)
        assert doc["traceEvents"]
        assert any(e["ph"] == "X" for e in doc["traceEvents"])

        try:
            _get(srv.address, "/debug/requests/no-such-id")
            assert False, "expected HTTP 404"
        except urllib.error.HTTPError as e:
            assert e.code == 404

        status, text = _get(srv.address, "/metrics")
        _, _, samples = _parse_exposition(text)
        assert samples["llm_slo_ttft_ms"] == 60000.0
        assert samples["llm_slo_attainment"] == 1.0
        assert samples["llm_requests_slo_ok_total"] >= 1
        assert samples["llm_goodput_tokens_total"] >= 5


@pytest.mark.obs
def test_debug_kv_endpoint_and_healthz_digest(model):
    """GET /debug/kv (the chain-digest tree walk: summary + bounded
    node list, depth cap honored) and the /healthz kv.digest compact
    summary the router poller scrapes; /debug/requests/<id> carries
    the per-session kv accounting fields."""
    params, config = model
    cb = ContinuousBatcher(
        params, config, n_slots=2, max_len=64, block_size=16,
    )
    with LLMServer(cb, tokenizer=ByteTokenizer()) as srv:
        prompt = list(range(2, 40))  # 2 full keyed blocks
        req = urllib.request.Request(
            srv.address + "/generate",
            data=json.dumps(
                {"prompt": prompt, "max_new_tokens": 4}
            ).encode(),
            headers={"Content-Type": "application/json",
                     "X-Request-Id": "kv-dbg-1"},
        )
        with urllib.request.urlopen(req, timeout=300) as r:
            assert r.status == 200

        status, body = _get(srv.address, "/debug/kv")
        assert status == 200
        doc = json.loads(body)
        summ = doc["summary"]
        assert summ["prefix_index"] == "radix"
        assert summ["nodes"] == len(doc["nodes"]) == 2
        assert summ["version"] >= 2
        assert summ["block_bytes"] > 0
        assert summ["prompt_tokens_total"] == len(prompt)
        assert [n["depth"] for n in doc["nodes"]] == [1, 2]
        assert all(n["tier"] == "hbm" for n in doc["nodes"])
        # Finished request: chain retained idle -> refcount False.
        assert all(n["refcount"] is False for n in doc["nodes"])
        # Depth/node caps bound the payload.
        status, body = _get(srv.address, "/debug/kv?depth=1")
        assert json.loads(body)["nodes"][-1]["depth"] == 1
        status, body = _get(srv.address, "/debug/kv?n=1")
        capped = json.loads(body)
        assert len(capped["nodes"]) == 1 and capped["truncated"] == 1

        # /healthz piggybacks the compact digest summary.
        status, body = _get(srv.address, "/healthz")
        kv = json.loads(body)["kv"]
        assert kv["digest"]["version"] == summ["version"]
        assert kv["digest"]["hash"] == summ["hash"]
        assert kv["block_bytes"] == summ["block_bytes"]
        assert kv["total_blocks"] == cb.n_blocks
        assert kv["prompt_tokens_total"] == len(prompt)

        # Per-session KV accounting on the timeline.
        status, body = _get(srv.address, "/debug/requests/kv-dbg-1")
        tl = json.loads(body)
        assert tl["kv"]["blocks_held"] >= 3
        assert tl["kv"]["prefix_hit_tokens"] == 0
        # A revisit of the same prompt is a counted prefix hit.
        req = urllib.request.Request(
            srv.address + "/generate",
            data=json.dumps(
                {"prompt": prompt, "max_new_tokens": 4}
            ).encode(),
            headers={"Content-Type": "application/json",
                     "X-Request-Id": "kv-dbg-2"},
        )
        with urllib.request.urlopen(req, timeout=300) as r:
            assert r.status == 200
        status, body = _get(srv.address, "/debug/requests/kv-dbg-2")
        assert json.loads(body)["kv"]["prefix_hit_tokens"] == 32


@pytest.mark.obs
def test_debug_profiler_endpoint(model, tmp_path):
    """POST /debug/profiler brackets a jax.profiler session: start
    writes a trace under log_dir, double-start/stray-stop are 409s,
    bad actions 400."""
    params, config = model
    cb = ContinuousBatcher(params, config, n_slots=1, max_len=64)

    def post_prof(srv, payload):
        req = urllib.request.Request(
            srv.address + "/debug/profiler",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=60) as r:
                return r.status, json.loads(r.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    def get_json(srv, path):
        try:
            with urllib.request.urlopen(
                srv.address + path, timeout=60
            ) as r:
                return r.status, json.loads(r.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    log_dir = str(tmp_path / "xplane")
    with LLMServer(cb) as srv:
        status, body = post_prof(srv, {"action": "bogus"})
        assert status == 400
        status, body = post_prof(srv, {"action": "stop"})
        assert status == 409  # nothing active
        # No completed session yet: the summary endpoint 404s cleanly
        # (before any xplane parsing machinery is touched).
        status, body = get_json(srv, "/debug/profile/summary")
        assert status == 404 and "profiler" in body["error"]
        status, body = post_prof(
            srv, {"action": "start", "log_dir": log_dir}
        )
        assert status == 200 and body["ok"] is True
        status, body = post_prof(
            srv, {"action": "start", "log_dir": log_dir}
        )
        assert status == 409  # already tracing
        # Summarizing the ACTIVE session's dir is refused too.
        status, body = get_json(
            srv, "/debug/profile/summary?log_dir="
            + urllib.parse.quote(log_dir)
        )
        assert status == 409
        status, _ = _post(
            srv.address, {"prompt": [3, 4], "max_new_tokens": 3}
        )
        assert status == 200
        status, body = post_prof(srv, {"action": "stop"})
        assert status == 200 and body["log_dir"] == log_dir
    import os

    assert any(
        f for _, _, fs in os.walk(log_dir) for f in fs
    ), "profiler session wrote no trace files"


@pytest.mark.obs
@pytest.mark.slow
def test_debug_profile_summary_attributes_programs(model, tmp_path):
    """GET /debug/profile/summary parses the completed xplane session
    into per-program time attribution: the serving programs the
    bracketed traffic dispatched appear with nonzero host/device ms.
    Slow-marked: the xplane proto import (tensorflow.tsl) costs
    seconds; self-skips where the protos are unavailable."""
    pytest.importorskip("tensorflow.tsl.profiler.protobuf.xplane_pb2")
    params, config = model
    cb = ContinuousBatcher(params, config, n_slots=1, max_len=64)

    def post_prof(srv, payload):
        req = urllib.request.Request(
            srv.address + "/debug/profiler",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=60) as r:
            return r.status, json.loads(r.read())

    log_dir = str(tmp_path / "xplane")
    with LLMServer(cb) as srv:
        status, _ = post_prof(
            srv, {"action": "start", "log_dir": log_dir}
        )
        assert status == 200
        status, _ = _post(
            srv.address, {"prompt": [5, 6, 7], "max_new_tokens": 4}
        )
        assert status == 200
        status, _ = post_prof(srv, {"action": "stop"})
        assert status == 200
        with urllib.request.urlopen(
            srv.address + "/debug/profile/summary", timeout=120
        ) as r:
            assert r.status == 200
            summary = json.loads(r.read())
    assert summary["log_dir"] == log_dir
    progs = summary["programs"]
    # The bracketed request dispatched decode chunks: attributed.
    assert "_paged_decode_chunk" in progs
    attributed = (
        progs["_paged_decode_chunk"]["host_ms"]
        + progs["_paged_decode_chunk"]["device_ms"]
    )
    assert attributed > 0
    assert summary["total_host_ms"] + summary["total_device_ms"] > 0


def test_http_overload_refusal_503_carries_retry_after(model):
    """The queue-depth overload 503 (ISSUE 9 satellite): it used to be
    a bare 503 while the drain-mode 503 carried Retry-After — now both
    do, load-derived, so retry layers back off instead of hammering."""
    import time

    from jax_llama_tpu.faults import FaultInjector

    params, config = model
    # A 20 ms injected step delay pins the resident in its slot long
    # enough to observe the depth-1 refusal deterministically.
    cb = ContinuousBatcher(
        params, config, n_slots=1, max_len=256,
        fault_injector=FaultInjector("step~1.0:delay=0.02"),
    )
    with LLMServer(cb, max_queue=1) as srv:
        status, _ = _post(srv.address,
                          {"prompt": [1, 2], "max_new_tokens": 2})
        assert status == 200  # warm the compile caches
        done = {}

        def run():
            done["resident"] = _post(
                srv.address, {"prompt": [3, 4], "max_new_tokens": 60},
                timeout=300,
            )

        t = threading.Thread(target=run)
        t.start()
        time.sleep(0.4)  # resident admitted: depth budget consumed
        try:
            _post(srv.address, {"prompt": [5, 6], "max_new_tokens": 2})
            assert False, "expected HTTP 503"
        except urllib.error.HTTPError as e:
            assert e.code == 503
            assert int(e.headers["Retry-After"]) >= 1
            body = json.loads(e.read())
            assert "overloaded" in body["error"]
            assert body["request_id"]  # refusals stay traceable
        t.join(timeout=300)
        assert not t.is_alive()
        assert done["resident"][0] == 200  # the resident was untouched


def test_healthz_overload_section(model):
    """/healthz carries the overload controller's state (schema in the
    server.py module docstring) next to the kv and features sections."""
    params, config = model
    cb = ContinuousBatcher(params, config, n_slots=1, max_len=32)
    with LLMServer(cb) as srv:
        status, body = _get(srv.address, "/healthz")
        assert status == 200
        ov = json.loads(body)["overload"]
        assert ov["enabled"] is True
        assert ov["rung"] == "normal"
        assert set(ov["queued"]) == {"interactive", "batch"}
        assert ov["refused"] == {
            "backlog": 0, "deadline": 0, "batch": 0,
        }
        assert ov["transitions_total"] == 0
        # priority_classes=False keeps the FIFO/backstop-only mode and
        # says so in the same section.
    cb2 = ContinuousBatcher(params, config, n_slots=1, max_len=32)
    with LLMServer(cb2, priority_classes=False) as srv:
        _, body = _get(srv.address, "/healthz")
        assert json.loads(body)["overload"]["enabled"] is False
