"""KV chain-digest correctness (kvcache.KvDigest — PR 13 fleet cache
telemetry): determinism for identical published content, version /
loss-version semantics at every mutation class, the bounded /debug/kv
walk, and the per-event ledger.  Pure host-side store manipulation —
no model, no device dispatches — so the whole module is tier-1 cheap."""

import json

import pytest

from jax_llama_tpu.kvcache import (
    ExactPrefixStore,
    KvDigest,
    NullPrefixStore,
    RadixPrefixStore,
)

pytestmark = pytest.mark.kvcache


def _key(i: int) -> bytes:
    return b"chain-%04d" % i


def _chain(prefix: int, n: int):
    """n chain keys sharing a per-prefix namespace (divergent chains
    share nothing here; radix sharing is exercised via shared keys)."""
    return [_key(prefix * 100 + j) for j in range(n)]


def test_digest_deterministic_for_same_published_chains():
    """Same published content (two divergent chains sharing a common
    prefix), different publish/evict interleavings -> identical hash
    and identical sorted node list (the XOR set-hash is order-free)."""
    shared = [_key(1), _key(2)]
    a_tail = [_key(10)]
    b_tail = [_key(20)]

    s1 = RadixPrefixStore()
    s1.publish(shared + a_tail, [0, 1, 2])
    s1.publish(shared + b_tail, [0, 1, 3])

    s2 = RadixPrefixStore()
    # Reverse order, plus a publish/evict detour that cancels out.
    s2.publish(shared + b_tail, [5, 6, 7])
    s2.publish([_key(99)], [4])
    s2.retain([4])
    s2.pop_evictable()  # drops the detour chain again
    s2.publish(shared + a_tail, [5, 6, 8])

    d1, d2 = s1.digest.summary(), s2.digest.summary()
    assert d1["hash"] == d2["hash"]
    assert d1["nodes"] == d2["nodes"] == 4
    n1 = s1.digest.nodes_json()["nodes"]
    n2 = s2.digest.nodes_json()["nodes"]
    strip = lambda ns: [  # noqa: E731 - local shorthand
        {k: n[k] for k in ("key", "depth", "tier")} for n in ns
    ]
    assert strip(n1) == strip(n2)
    # Versions tell the EDIT history apart even when content matches.
    assert d2["version"] > d1["version"]


def test_version_bumps_on_publish_evict_demote_restore():
    store = RadixPrefixStore(host_blocks=4)
    dg = store.digest
    assert dg.summary()["version"] == 0

    store.publish(_chain(0, 2), [0, 1])
    v1 = dg.summary()["version"]
    assert v1 == 2  # one bump per published block
    assert dg.summary()["loss_version"] == 0

    # Demote: version AND loss_version move (HBM residency lost).
    store.retain([0, 1])
    blk, extra = store.pop_evictable(lambda b: {"pos": None})
    assert blk == 1 and not extra  # leaves-first: deepest idle first
    s = dg.summary()
    assert s["version"] > v1
    assert s["loss_version"] == 1
    assert s["demotions_total"] == 1
    assert (s["hbm_blocks"], s["host_blocks"]) == (1, 1)

    # Restore flips it back; version moves, loss_version does not.
    node = store.match(_chain(0, 2)).restore[0]
    store.pin_restoring([node])
    v2, l2 = s["version"], s["loss_version"]
    store.complete_restore([node], [5])
    s = dg.summary()
    assert s["version"] > v2 and s["loss_version"] == l2
    assert s["restores_total"] == 1

    # Unpublish (the non-finite guard): nodes leave, losses count.
    store.unpublish(0)
    s = dg.summary()
    assert s["nodes"] == 0
    assert s["evictions_total"] == 2
    assert s["loss_version"] > l2


def test_idle_flag_tracks_refcount_boundary_without_version_noise():
    store = RadixPrefixStore()
    store.publish(_chain(0, 2), [0, 1])
    v = store.digest.summary()["version"]
    store.retain([0, 1])
    s = store.digest.summary()
    assert s["idle_blocks"] == 2
    assert s["version"] == v  # claims/retains are not content edits
    store.on_claim([0])
    s = store.digest.summary()
    assert s["idle_blocks"] == 1
    by_key = {
        n["key"]: n for n in store.digest.nodes_json()["nodes"]
    }
    assert by_key[_key(0).hex()]["refcount"] is True
    assert by_key[_key(1).hex()]["refcount"] is False


def test_host_lru_eviction_counts_and_removes():
    """A host-tier LRU victim bumps host_evictions_total and its
    (unreachable) node leaves the digest."""
    store = RadixPrefixStore(host_blocks=1)
    store.publish([_key(1)], [0])
    store.publish([_key(2)], [1])
    store.retain([0])
    store.retain([1])
    store.pop_evictable(lambda b: {"pos": None})  # key1 -> host
    store.pop_evictable(lambda b: {"pos": None})  # key2 evicts key1
    s = store.digest.summary()
    assert s["host_evictions_total"] == 1
    assert s["host_blocks"] == 1 and s["hbm_blocks"] == 0
    assert s["nodes"] == 1
    tiers = {
        n["key"]: n["tier"] for n in store.digest.nodes_json()["nodes"]
    }
    assert tiers == {_key(2).hex(): "host"}


def test_nodes_json_bounded_at_max_occupancy():
    """The /debug/kv walk stays under its size bound at max radix
    occupancy: node cap enforced (shallowest-first, deterministic),
    truncation reported, depth cap honored."""
    store = RadixPrefixStore()
    n = 512  # a full pool's worth of keyed blocks
    store.publish([_key(i) for i in range(n)], list(range(n)))
    walk = store.digest.nodes_json(max_nodes=64)
    assert len(walk["nodes"]) == 64
    assert walk["truncated"] == n - 64
    assert [e["depth"] for e in walk["nodes"]] == list(range(1, 65))
    # Bounded payload: the serialized cap stays small even though the
    # tree holds 8x more nodes.
    assert len(json.dumps(walk)) < 64 * 120 + 512
    # Depth cap composes with the node cap.
    shallow = store.digest.nodes_json(depth=8, max_nodes=64)
    assert len(shallow["nodes"]) == 8
    assert shallow["truncated"] == 0
    assert all(e["depth"] <= 8 for e in shallow["nodes"])


def test_exact_store_digest_parity_surface():
    """The legacy flat map exposes the same digest surface: versioned
    publishes, supersede keeps the key, unpublish removes it."""
    store = ExactPrefixStore()
    store.publish(_chain(0, 3), [0, 1, 2])
    s = store.digest.summary()
    assert s["nodes"] == 3 and s["publishes_total"] == 3
    # Supersede: same keys, new blocks — content keys unchanged.
    h0 = s["hash"]
    store.retain([0, 1, 2])
    store.publish(_chain(0, 3), [4, 5, 6])
    s = store.digest.summary()
    assert s["nodes"] == 3 and s["hash"] == h0
    assert s["version"] > 3
    store.unpublish(4)
    assert store.digest.summary()["nodes"] == 2
    # Supersede of an IDLE old block by a freshly claimed one clears
    # the digest's idle flag (review fix: the store's truth is
    # claimed, and the gauge must not call a live block evictable).
    s2 = ExactPrefixStore()
    s2.publish([_key(9)], [0])
    s2.retain([0])
    assert s2.digest.summary()["idle_blocks"] == 1
    s2.publish([_key(9)], [5])  # supersede with a claimed block
    assert s2.evictable() == 0
    assert s2.digest.summary()["idle_blocks"] == 0


def test_null_store_digest_stays_empty():
    store = NullPrefixStore()
    store.publish([_key(1)], [0])
    store.retain([0])
    assert store.digest.summary()["version"] == 0
    assert store.digest.summary()["nodes"] == 0
    assert store.digest.nodes_json()["nodes"] == []


def test_digest_hash_xor_cancellation_is_tier_aware():
    """The set-hash distinguishes residency tier: the same key on HBM
    vs host hashes differently (a fleet diff must not call a demoted
    replica 'identical' to a resident one)."""
    d1, d2 = KvDigest(), KvDigest()
    d1.on_publish(b"k", 1)
    d2.on_publish(b"k", 1)
    assert d1.summary()["hash"] == d2.summary()["hash"]
    d2.on_demote(b"k")
    assert d1.summary()["hash"] != d2.summary()["hash"]
    d2.on_restore(b"k")
    assert d1.summary()["hash"] == d2.summary()["hash"]
