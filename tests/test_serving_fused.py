"""Fused prefill-decode scheduling (``prefill_budget`` > 0) must be
TOKEN- and logprob-IDENTICAL to the classic admit-then-decode path —
the acceptance matrix of the fused scheduler: prefill_budget ∈
{1 block, 2 blocks, ∞} × {greedy, seeded-sampled} × {prefix-cache
hit/miss} × {int8-KV}, including a row whose first sampled token is
emitted by the SAME dispatch that finished its prefill, and the
stall-free property itself (decode rows keep emitting while a long
prompt is mid-prefill).

The scenario intentionally admits the probe request MID-DECODE — the
only regime where the fused path engages (a cold pool still admits
through the classic batched insert; there is nobody to stall)."""

import dataclasses

import jax
import numpy as np
import pytest

from jax_llama_tpu import get_config, init_params
from jax_llama_tpu.serving import ContinuousBatcher

CFG = dict(
    vocab_size=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
    multiple_of=32, max_seq_len=128, dtype="float32", param_dtype="float32",
)
BLOCK = 16


@pytest.fixture(scope="module")
def model():
    config = get_config("tiny", **CFG)
    params = init_params(jax.random.PRNGKey(0), config)
    return params, config


def _scenario(
    params, config, budget, *, sampled=False, prefix=False,
    logprobs=True, oracle_prefill_chunk=None, **cb_kw,
):
    """The shared request shape: r0 decodes (admitted cold -> classic
    path either way), then r1 — a 2.5-block prompt — submits mid-decode
    and, with ``budget`` > 0, rides the fused prefill.  ``prefix=True``
    first runs a sharer to warm the prefix cache so r1's chunk walk
    starts at fill0.  Returns ((r0, r1) token lists, (r0, r1) logprob
    lists, batcher)."""
    cb_kw.setdefault("block_size", BLOCK)
    cb = ContinuousBatcher(
        params, config, n_slots=2, max_len=64, decode_chunk=4,
        prefill_budget=budget, logprobs=logprobs,
        prefill_chunk=oracle_prefill_chunk, **cb_kw,
    )
    toks, lps = {}, {}

    def pump(n=None):
        guard = 0
        while True:
            guard += 1
            assert guard < 500
            for ev in cb.step():
                toks.setdefault(ev[0], []).append(ev[1])
                if logprobs:
                    lps.setdefault(ev[0], []).append(ev[3])
            if n is not None and guard >= n:
                return
            if n is None and not cb.pending():
                return

    rng = np.random.RandomState(3)
    shared = rng.randint(1, 128, size=34).tolist()  # 2 full keyed blocks
    if prefix:
        cb.submit(shared + [7], max_new_tokens=2)
        pump()
    pol0 = (
        dict(max_new_tokens=9, temperature=0.8, seed=7)
        if sampled else dict(max_new_tokens=9)
    )
    pol1 = (
        dict(max_new_tokens=6, temperature=0.7, top_p=0.9, seed=12)
        if sampled else dict(max_new_tokens=6)
    )
    r0 = cb.submit([5, 17, 99, 3], **pol0)
    pump(2)  # r0 admitted and mid-decode
    r1 = cb.submit(shared + [9, 11], **pol1)
    pump()
    return (toks[r0], toks[r1]), (lps.get(r0), lps.get(r1)), cb


@pytest.fixture(scope="module")
def classic_oracle(model):
    """Memoized classic-path (budget 0) runs: each (sampled, prefix)
    cell of the matrix shares ONE oracle run across the three budget
    parametrizations instead of recomputing it per test."""
    params, config = model
    cache = {}

    def get(sampled, prefix):
        key = (sampled, prefix)
        if key not in cache:
            t, l, cb0 = _scenario(
                params, config, 0, sampled=sampled, prefix=prefix,
            )
            assert cb0.fused_admissions_total == 0
            cache[key] = (t, l)
        return cache[key]

    return get


_SLOW = pytest.mark.slow
@pytest.mark.parametrize(
    "budget,sampled,prefix",
    [
        # Tier-1 slice (r14 budget rebalance, narrowed again in r17 with
        # the suite back AT its 870 s ceiling): the block-budget greedy
        # cell stays as THE tier-1 fused-identity pin.  The ∞-budget
        # sampled cell joined the slow tier in r17 (~16 s): sampled-
        # policy chunked identity stays tier-1-pinned by
        # test_serving_chunked's sampled cells and test_kvcache's
        # sampled radix smoke, and the fused scheduling contract by
        # test_first_token_emitted_by_prefill_completion_dispatch below.
        # The prefix-hit fused cells ride the slow tier because
        # fused×prefix-hit token identity is ALREADY tier-1-pinned by
        # test_kvcache's {fused, classic} × hit-depth parity matrix
        # (PR 6) — this file's hit cells re-proved the same contract at
        # ~18 s of compile-bound cost.  The FULL
        # {block, 2·block, ∞} × {greedy, sampled} × {hit, miss} cross
        # runs in the unfiltered suite (slow marks).
        (BLOCK, False, False),
        pytest.param(4096, True, False, marks=_SLOW),
        pytest.param(BLOCK, True, True, marks=_SLOW),
        pytest.param(4096, False, True, marks=_SLOW),
        pytest.param(BLOCK, True, False, marks=_SLOW),
        pytest.param(BLOCK, False, True, marks=_SLOW),
        pytest.param(4096, False, False, marks=_SLOW),
        pytest.param(4096, True, True, marks=_SLOW),
        pytest.param(2 * BLOCK, False, False, marks=_SLOW),
        pytest.param(2 * BLOCK, True, False, marks=_SLOW),
        pytest.param(2 * BLOCK, False, True, marks=_SLOW),
        pytest.param(2 * BLOCK, True, True, marks=_SLOW),
    ],
)
def test_fused_token_and_logprob_identity(
    model, classic_oracle, budget, sampled, prefix,
):
    """The core matrix: every budget (one block per dispatch, two, the
    whole prompt in one chunk) emits exactly what the classic
    admit-then-decode path emits — tokens exact, logprobs to fp32
    noise — for greedy and seeded-sampled policies, cold and
    prefix-cache-hit admissions."""
    params, config = model
    base_t, base_l = classic_oracle(sampled, prefix)
    got_t, got_l, cb1 = _scenario(
        params, config, budget, sampled=sampled, prefix=prefix,
    )
    assert cb1.fused_admissions_total >= 1  # r1 rode the fused path
    assert cb1.prefill_chunks_total >= 1
    assert got_t == base_t
    for a, b in zip(got_l, base_l):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)
    if prefix:
        # The fused admission reused the warmed chain (fill0 walk).
        assert cb1.prefix_requests_hit >= 1


# slow (r06 budget rebalance, ~23 s): int8 chunked identity stays in
# tier-1 via test_serving_chunked's int8 cell; the fused int8 cell
# runs in the full suite / pytest -m slow.
@pytest.mark.slow
def test_fused_token_identity_int8_kv(model):
    """int8-KV pools quantize a chunk's KV when it lands, so WHERE the
    chunk boundaries fall is part of the numerics: the oracle is the
    classic path with the SAME prefill chunking
    (``prefill_chunk=budget``), against which the fused path is
    token-exact and logprob-identical to fp32 noise.  Seeded-sampled
    policies (the stricter cell: they consume the key chains greedy
    never touches)."""
    params, config = model
    qconfig = dataclasses.replace(config, kv_cache_dtype="int8")
    budget = 2 * BLOCK
    base_t, base_l, _ = _scenario(
        params, qconfig, 0, sampled=True,
        oracle_prefill_chunk=budget,
    )
    got_t, got_l, cb1 = _scenario(
        params, qconfig, budget, sampled=True,
    )
    assert cb1.fused_admissions_total >= 1
    assert got_t == base_t
    for a, b in zip(got_l, base_l):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


@pytest.mark.slow
def test_fused_token_identity_flash_prefill(model):
    """attn_impl='auto' with a >8-token budget runs the PREFILL half of
    the fused program through the flash kernel (the view's scalar write
    index keeps it off the must-xla path) — still token-identical to
    the classic xla admit-then-decode path.  block_size=8 keeps the
    cold classic admissions on xla, so flash only ever runs inside
    ``_fused_chunk`` here.  slow: the interpret-mode flash compile is
    ~26 s of pure trace time (tier-1 budget); the fused flash PATH
    still runs in tier-1 via test_degrade's quarantine drill."""
    params, config = model
    auto_cfg = config.replace(attn_impl="auto")
    base_t, _, _ = _scenario(
        params, config, 0, sampled=True, logprobs=False, block_size=8,
    )
    got_t, _, cb1 = _scenario(
        params, auto_cfg, 16, sampled=True, logprobs=False,
        block_size=8,
    )
    assert cb1.fused_admissions_total >= 1
    assert cb1.prefill_chunks_total >= 2  # 36-token prompt, 16/chunk
    assert got_t == base_t


@pytest.mark.slow
def test_fused_token_identity_gathered_fallback(model):
    """use_pallas_kernel=False: the decode half of the fused program
    runs the gathered-view scan and the prefill half is unchanged —
    still identical to the classic path on the same fallback.  slow:
    the gathered decode scan is covered per-iteration by
    tests/test_serving_chunked.py and the quarantine drills; this cell
    pins the fused-prefill × gathered-decode CROSS in the unfiltered
    suite."""
    params, config = model
    base_t, _, _ = _scenario(
        params, config, 0, use_pallas_kernel=False, logprobs=False,
    )
    got_t, _, cb1 = _scenario(
        params, config, 2 * BLOCK, use_pallas_kernel=False,
        logprobs=False,
    )
    assert cb1.fused_admissions_total >= 1
    assert got_t == base_t


def test_first_token_emitted_by_prefill_completion_dispatch(model):
    """The tentpole's latency contract: the dispatch whose prefill
    chunk lands the LAST prompt token also emits the row's first
    sampled token (the row folds into the decode mask mid-dispatch) —
    and while the prompt is mid-prefill, the resident decode row keeps
    emitting every dispatch (zero full-prefill stalls) at a chunk size
    that did NOT collapse to 1."""
    params, config = model
    cb = ContinuousBatcher(
        params, config, n_slots=2, max_len=64, decode_chunk=4,
        block_size=BLOCK, prefill_budget=BLOCK,
    )
    r0 = cb.submit([5, 17, 99, 3], max_new_tokens=40)
    cb.step()
    cb.step()
    rng = np.random.RandomState(3)
    r1 = cb.submit(rng.randint(1, 128, size=40).tolist(), max_new_tokens=6)
    completion_events = None
    mid_prefill_steps = 0
    guard = 0
    while cb.pending():
        guard += 1
        assert guard < 300
        mid_before = cb._pf is not None
        if mid_before:
            assert cb.stats()["prefill_tokens_inflight"] > 0
        evs = cb.step()
        if mid_before and cb._pf is None and completion_events is None:
            completion_events = evs
        elif mid_before and cb._pf is not None:
            mid_prefill_steps += 1
            # Stall-free: the decode row emitted THIS dispatch, at an
            # un-collapsed chunk size, and r1 (mid-prefill) did not.
            assert any(ev[0] == r0 for ev in evs)
            assert not any(ev[0] == r1 for ev in evs)
            assert cb.decode_chunk_last > 1
    # 40 tokens at a 16-token budget: at least one genuinely
    # mid-prefill dispatch before the completing one.
    assert mid_prefill_steps >= 1
    assert completion_events is not None
    assert any(ev[0] == r1 for ev in completion_events)


def test_cancel_mid_prefill_frees_admission(model):
    """Cancelling the in-flight admission mid-prefill drops it cleanly:
    its blocks free, no fused dispatches reference it afterwards, and
    the next queued request admits."""
    params, config = model
    cb = ContinuousBatcher(
        params, config, n_slots=2, max_len=64, decode_chunk=4,
        block_size=BLOCK, prefill_budget=BLOCK,
    )
    toks: dict = {}

    def pump(n):
        for _ in range(n):
            for ev in cb.step():
                toks.setdefault(ev[0], []).append(ev[1])

    r0 = cb.submit([5, 17, 99, 3], max_new_tokens=16)
    pump(2)
    rng = np.random.RandomState(3)
    r1 = cb.submit(rng.randint(1, 128, size=40).tolist(), max_new_tokens=6)
    r2 = cb.submit([7, 8, 9], max_new_tokens=4)
    pump(1)  # r1's prefill starts (40 tokens > one 16-token chunk)
    assert cb._pf is not None and cb._pf.req.rid == r1
    free_before = len(cb.free_blocks)
    assert cb.cancel(r1)
    assert cb._pf is None
    assert len(cb.free_blocks) > free_before
    guard = 0
    while cb.pending():
        guard += 1
        assert guard < 300
        pump(1)
    assert r1 not in toks
    assert len(toks[r2]) == 4  # the next queued request admitted fine
    assert len(toks[r0]) == 16


def test_rebuild_drops_inflight_prefill(model):
    """Crash-recovery rebuild: the fresh batcher has no prefill in
    flight; resubmitting the mid-prefill request (the server's replay
    contract) regenerates it token-identically."""
    params, config = model
    cb = ContinuousBatcher(
        params, config, n_slots=2, max_len=64, decode_chunk=4,
        block_size=BLOCK, prefill_budget=BLOCK,
    )
    oracle = ContinuousBatcher(
        params, config, n_slots=2, max_len=64, decode_chunk=4,
        block_size=BLOCK,
    )
    prompt = np.random.RandomState(3).randint(1, 128, size=40).tolist()
    ro = oracle.submit(list(prompt), max_new_tokens=6)
    want = oracle.run_to_completion()[ro]

    cb.submit([5, 17, 99, 3], max_new_tokens=12)
    cb.step()
    cb.step()
    cb.submit(list(prompt), max_new_tokens=6)
    cb.step()
    assert cb._pf is not None  # mid-prefill "crash" point
    cb2 = cb.rebuild()
    assert cb2._pf is None and cb2.prefill_budget == cb.prefill_budget
    r = cb2.submit(list(prompt), max_new_tokens=6)
    assert cb2.run_to_completion()[r] == want
