"""Training tests: gradient step mechanics, overfit sanity, sharded step."""

import numpy as np
import jax
import jax.numpy as jnp

from jax_llama_tpu import config as cfg_lib
from jax_llama_tpu.models import init_params
from jax_llama_tpu.parallel import make_mesh, shard_params, use_mesh
from jax_llama_tpu.train import (
    init_train_state,
    lm_loss,
    make_optimizer,
    train_step,
)

CFG = cfg_lib.tiny(max_seq_len=32)
OPT = make_optimizer(learning_rate=1e-2, warmup_steps=0)


def test_loss_is_finite_and_near_uniform_at_init():
    params = init_params(jax.random.PRNGKey(0), CFG)
    tokens = jnp.asarray(np.random.RandomState(0).randint(0, CFG.vocab_size, (2, 16)))
    loss = lm_loss(params, tokens, CFG)
    assert np.isfinite(float(loss))
    # Random init ≈ uniform over vocab.
    assert abs(float(loss) - np.log(CFG.vocab_size)) < 1.0


def test_overfit_single_batch():
    params = init_params(jax.random.PRNGKey(0), CFG)
    state = init_train_state(params, OPT)
    tokens = jnp.asarray(np.random.RandomState(1).randint(0, CFG.vocab_size, (2, 16)))
    losses = []
    for _ in range(30):
        state, loss = train_step(state, tokens, CFG, OPT)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5, losses[::10]
    assert int(state.step) == 30


def test_loss_mask_excludes_positions():
    from jax_llama_tpu.models import forward

    params = init_params(jax.random.PRNGKey(0), CFG)
    tokens = jnp.asarray([[1, 2, 3, 4, 5, 6]])
    mask = jnp.asarray([[True, True, True, False, False, False]])
    got = float(lm_loss(params, tokens, CFG, loss_mask=mask))

    # Query-indexed convention: mask[:, t] gates the loss predicting token
    # t+1 from position t, so mask [T,T,T,F,F,F] keeps the loss terms at
    # query positions 0,1,2 (targets 2,3,4) — lm_loss drops mask[:, -1].
    logits, _ = forward(
        params, tokens[:, :-1],
        jnp.arange(5)[None, :], CFG,
    )
    logp = jax.nn.log_softmax(np.asarray(logits, np.float64), axis=-1)
    targets = np.asarray(tokens)[0, 1:]
    nll = -logp[0, np.arange(5), targets]
    want = nll[:3].mean()  # query positions 0,1,2 are unmasked
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_sharded_train_step_matches_single_device():
    # train_step donates its state, so each path gets its own params copy
    # (same seed -> identical values).
    tokens = jnp.asarray(np.random.RandomState(2).randint(0, CFG.vocab_size, (4, 16)))

    state = init_train_state(init_params(jax.random.PRNGKey(0), CFG), OPT)
    _, loss_single = train_step(state, tokens, CFG, OPT)

    mesh = make_mesh(data=2, fsdp=2, tensor=2)
    sharded = shard_params(
        init_params(jax.random.PRNGKey(0), CFG), mesh, CFG, fsdp=True
    )
    sstate = init_train_state(sharded, OPT)
    sstate, loss_sharded = train_step(sstate, tokens, CFG, OPT, mesh=mesh)
    np.testing.assert_allclose(
        float(loss_sharded), float(loss_single), rtol=1e-5
    )
    # Params actually changed and stayed finite.
    q = np.asarray(sstate.params["layers"]["q"])
    assert np.isfinite(q).all()
