"""Training tests: gradient step mechanics, overfit sanity, sharded step."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from conftest import xfail_if_remat_ulp_skew

from jax_llama_tpu import config as cfg_lib
from jax_llama_tpu.models import init_params
from jax_llama_tpu.parallel import make_mesh, shard_params, use_mesh
from jax_llama_tpu.train import (
    init_train_state,
    lm_loss,
    make_optimizer,
    train_step,
)

CFG = cfg_lib.tiny(max_seq_len=32)
OPT = make_optimizer(learning_rate=1e-2, warmup_steps=0)


def test_loss_is_finite_and_near_uniform_at_init():
    params = init_params(jax.random.PRNGKey(0), CFG)
    tokens = jnp.asarray(np.random.RandomState(0).randint(0, CFG.vocab_size, (2, 16)))
    loss = lm_loss(params, tokens, CFG)
    assert np.isfinite(float(loss))
    # Random init ≈ uniform over vocab.
    assert abs(float(loss) - np.log(CFG.vocab_size)) < 1.0


def test_overfit_single_batch():
    params = init_params(jax.random.PRNGKey(0), CFG)
    state = init_train_state(params, OPT)
    tokens = jnp.asarray(np.random.RandomState(1).randint(0, CFG.vocab_size, (2, 16)))
    losses = []
    for _ in range(30):
        state, loss = train_step(state, tokens, CFG, OPT)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5, losses[::10]
    assert int(state.step) == 30


def test_loss_mask_excludes_positions():
    from jax_llama_tpu.models import forward

    params = init_params(jax.random.PRNGKey(0), CFG)
    tokens = jnp.asarray([[1, 2, 3, 4, 5, 6]])
    mask = jnp.asarray([[True, True, True, False, False, False]])
    got = float(lm_loss(params, tokens, CFG, loss_mask=mask))

    # Query-indexed convention: mask[:, t] gates the loss predicting token
    # t+1 from position t, so mask [T,T,T,F,F,F] keeps the loss terms at
    # query positions 0,1,2 (targets 2,3,4) — lm_loss drops mask[:, -1].
    logits, _ = forward(
        params, tokens[:, :-1],
        jnp.arange(5)[None, :], CFG,
    )
    logp = jax.nn.log_softmax(np.asarray(logits, np.float64), axis=-1)
    targets = np.asarray(tokens)[0, 1:]
    nll = -logp[0, np.arange(5), targets]
    want = nll[:3].mean()  # query positions 0,1,2 are unmasked
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_fused_loss_matches_dense_value_and_grads():
    """The chunked LM-head cross-entropy (ops.loss) must reproduce the
    dense log-softmax path: value to 1e-6 rel and every parameter
    gradient to 1e-5 rel (fp32 CPU) — masked, with a non-chunk-multiple
    row count exercising the weight-0 padding."""
    params = init_params(jax.random.PRNGKey(3), CFG)
    rng = np.random.RandomState(7)
    tokens = jnp.asarray(rng.randint(0, CFG.vocab_size, (2, 13)))
    mask = jnp.asarray(rng.rand(2, 13) > 0.3)

    vf, gf = jax.value_and_grad(
        lambda p: lm_loss(p, tokens, CFG, loss_mask=mask, fused=True)
    )(params)
    vd, gd = jax.value_and_grad(
        lambda p: lm_loss(p, tokens, CFG, loss_mask=mask, fused=False)
    )(params)
    np.testing.assert_allclose(float(vf), float(vd), rtol=1e-6)
    flat_f = jax.tree_util.tree_leaves_with_path(gf)
    flat_d = jax.tree_util.tree_leaves_with_path(gd)
    for (path, lf), (_, ld) in zip(flat_f, flat_d):
        denom = max(np.abs(np.asarray(ld)).max(), 1e-8)
        rel = np.abs(np.asarray(lf) - np.asarray(ld)).max() / denom
        assert rel < 1e-5, (jax.tree_util.keystr(path), rel)


def test_fused_loss_tied_embeddings_and_multichunk():
    """Tied-embedding head (the [V, D] layout is folded into the einsum,
    never transposed) and a multi-chunk row count agree with the dense
    path; chunk-size invariance via a direct chunked_softmax_xent call."""
    from jax_llama_tpu.ops.loss import chunked_softmax_xent

    tied = cfg_lib.tiny(max_seq_len=32, tie_word_embeddings=True)
    params = init_params(jax.random.PRNGKey(4), tied)
    tokens = jnp.asarray(
        np.random.RandomState(8).randint(0, tied.vocab_size, (2, 16))
    )
    vf = float(lm_loss(params, tokens, tied, fused=True))
    vd = float(lm_loss(params, tokens, tied, fused=False))
    np.testing.assert_allclose(vf, vd, rtol=1e-6)

    rng = np.random.RandomState(9)
    N, D, V = 37, 16, 24
    h = jnp.asarray(rng.randn(N, D), jnp.float32)
    head = jnp.asarray(rng.randn(D, V), jnp.float32)
    tgt = jnp.asarray(rng.randint(0, V, N))
    w = jnp.asarray(rng.rand(N) > 0.2, jnp.float32)
    outs = [
        chunked_softmax_xent(h, head, tgt, w, chunk=c) for c in (8, 16, 64)
    ]
    for tot, wsum in outs[1:]:
        np.testing.assert_allclose(float(tot), float(outs[0][0]), rtol=1e-6)
        np.testing.assert_allclose(float(wsum), float(outs[0][1]))


def test_sharded_train_step_matches_single_device():
    # train_step donates its state, so each path gets its own params copy
    # (same seed -> identical values).
    tokens = jnp.asarray(np.random.RandomState(2).randint(0, CFG.vocab_size, (4, 16)))

    state = init_train_state(init_params(jax.random.PRNGKey(0), CFG), OPT)
    _, loss_single = train_step(state, tokens, CFG, OPT)

    mesh = make_mesh(data=2, fsdp=2, tensor=2)
    sharded = shard_params(
        init_params(jax.random.PRNGKey(0), CFG), mesh, CFG, fsdp=True
    )
    sstate = init_train_state(sharded, OPT)
    sstate, loss_sharded = train_step(sstate, tokens, CFG, OPT, mesh=mesh)
    np.testing.assert_allclose(
        float(loss_sharded), float(loss_single), rtol=1e-5
    )
    # Params actually changed and stayed finite.
    qkv = np.asarray(sstate.params["layers"]["qkv"])
    assert np.isfinite(qkv).all()


# ---------------------------------------------------------------------------
# Dropout (reference capability: config.py:85-87, model.py:166-168,296-299)
# ---------------------------------------------------------------------------

DROP_CFG = cfg_lib.tiny(
    max_seq_len=32, resid_pdrop=0.2, embd_pdrop=0.1, attn_pdrop=0.1,
    # Pin the statistical tests to the xla path: since attn_pdrop composes
    # with flash, "auto" would route these T=16 forwards through the
    # interpret-mode Pallas kernel (slow on CPU); flash-dropout semantics
    # are covered by test_flash_attention and test_dropout_refusals.
    attn_impl="xla",
)


def test_dropout_perturbs_loss_deterministically():
    params = init_params(jax.random.PRNGKey(0), DROP_CFG)
    tokens = jnp.asarray(
        np.random.RandomState(3).randint(0, DROP_CFG.vocab_size, (2, 16))
    )
    base = float(lm_loss(params, tokens, DROP_CFG))
    a = float(lm_loss(params, tokens, DROP_CFG, dropout_rng=jax.random.PRNGKey(1)))
    a2 = float(lm_loss(params, tokens, DROP_CFG, dropout_rng=jax.random.PRNGKey(1)))
    b = float(lm_loss(params, tokens, DROP_CFG, dropout_rng=jax.random.PRNGKey(2)))
    assert a == a2                      # same key -> same masks
    assert a != base and b != base and a != b
    # All-zero rates with a key is exactly the deterministic path.
    zero = cfg_lib.tiny(max_seq_len=32)
    z = float(lm_loss(params, tokens, zero, dropout_rng=jax.random.PRNGKey(1)))
    np.testing.assert_allclose(z, float(lm_loss(params, tokens, zero)), rtol=1e-6)


@pytest.mark.slow  # ~18 s of statistical averaging; tier-1 headroom
def test_dropout_mean_approximates_deterministic_loss():
    """Inverted dropout preserves expectations: averaging over many masks
    should land near the no-dropout loss (loose tolerance, tiny model)."""
    params = init_params(jax.random.PRNGKey(0), DROP_CFG)
    tokens = jnp.asarray(
        np.random.RandomState(4).randint(0, DROP_CFG.vocab_size, (2, 16))
    )
    base = float(lm_loss(params, tokens, DROP_CFG))
    ls = [
        float(lm_loss(params, tokens, DROP_CFG, dropout_rng=jax.random.PRNGKey(i)))
        for i in range(24)
    ]
    assert abs(np.mean(ls) - base) < 0.35, (np.mean(ls), base)


def test_train_step_with_dropout_rng_learns():
    params = init_params(jax.random.PRNGKey(0), DROP_CFG)
    state = init_train_state(params, OPT)
    tokens = jnp.asarray(
        np.random.RandomState(5).randint(0, DROP_CFG.vocab_size, (2, 16))
    )
    rng = jax.random.PRNGKey(7)
    losses = []
    for _ in range(30):
        state, loss = train_step(
            state, tokens, DROP_CFG, OPT, dropout_rng=rng
        )
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0] * 0.7, losses[::10]
    # The per-step fold_in gives different masks per step: consecutive
    # losses on the same batch are not byte-identical.
    assert len(set(losses)) > 25


def test_dropout_refusals():
    import pytest

    from jax_llama_tpu.models import forward, init_cache

    params = init_params(jax.random.PRNGKey(0), DROP_CFG)
    tokens = jnp.asarray([[1, 2, 3, 4]])
    pos = jnp.arange(4)[None, :]
    cache = init_cache(DROP_CFG, 1, max_len=8)
    with pytest.raises(ValueError, match="training-only"):
        forward(params, tokens, pos, DROP_CFG, cache=cache,
                dropout_rng=jax.random.PRNGKey(0))
    # attn_pdrop composes with every attention path: flash generates its
    # mask in-kernel, ring hashes absolute positions chunkwise (tested on
    # a seq=2 mesh in test_ring.py); off-mesh "ring" falls back to sdpa
    # and must run, stay finite, and be deterministic per key.
    ring_cfg = DROP_CFG.replace(attn_impl="ring")
    lr1, _ = forward(params, tokens, pos, ring_cfg,
                     dropout_rng=jax.random.PRNGKey(0))
    lr2, _ = forward(params, tokens, pos, ring_cfg,
                     dropout_rng=jax.random.PRNGKey(0))
    assert np.isfinite(np.asarray(lr1, np.float32)).all()
    np.testing.assert_array_equal(np.asarray(lr1), np.asarray(lr2))
    # "auto" resolves to flash at prefill lengths even under attn_pdrop
    # (the kernel generates its own mask); both impls stay finite,
    # deterministic per key, and distinct across keys.
    t16 = jnp.asarray([list(range(1, 17))])
    p16 = jnp.arange(16)[None, :]
    for impl in ("auto", "flash"):
        icfg = DROP_CFG.replace(attn_impl=impl)
        la, _ = forward(params, t16, p16, icfg,
                        dropout_rng=jax.random.PRNGKey(0))
        la2, _ = forward(params, t16, p16, icfg,
                         dropout_rng=jax.random.PRNGKey(0))
        lb, _ = forward(params, t16, p16, icfg,
                        dropout_rng=jax.random.PRNGKey(1))
        assert np.isfinite(np.asarray(la, np.float32)).all()
        np.testing.assert_array_equal(np.asarray(la), np.asarray(la2))
        assert np.abs(np.asarray(la) - np.asarray(lb)).max() > 0
    # Embedding-only dropout needs no layer rng threading; full per-layer
    # dropout on a stage > 1 mesh is covered by
    # test_pipeline.test_pipeline_dropout_training.
    emb_only = cfg_lib.tiny(max_seq_len=32, embd_pdrop=0.5)
    mesh = make_mesh(stage=2, devices=jax.devices()[:2])
    sp = shard_params(init_params(jax.random.PRNGKey(0), emb_only), mesh, emb_only)
    tb = jnp.tile(t16, (2, 1))

    @jax.jit  # the pipeline path runs under jit (like engine/train do)
    def run(p, t, q, rng):
        with use_mesh(mesh):
            return forward(p, t, q, emb_only, dropout_rng=rng)[0]

    logits = run(sp, tb, jnp.tile(p16, (2, 1)), jax.random.PRNGKey(0))
    assert np.isfinite(np.asarray(logits, np.float32)).all()


def test_remat_policies_identical_gradients():
    """remat_policy changes WHAT is recomputed, never the math: loss and
    gradients must be bit-identical across "dots" / "full" / no remat on
    the fp32 CPU path."""
    results = {}
    for label, kw in (
        ("none", dict(remat=False)),
        ("full", dict(remat=True, remat_policy="full")),
        ("dots", dict(remat=True, remat_policy="dots")),
    ):
        config = cfg_lib.get_config(
            "tiny", dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
            vocab_size=128, max_seq_len=32, **kw,
        )
        params = init_params(jax.random.PRNGKey(0), config)
        toks = jnp.asarray(
            np.random.RandomState(0).randint(0, 128, (2, 32)), jnp.int32
        )
        loss, grads = jax.value_and_grad(lm_loss)(params, toks, config)
        results[label] = (float(loss), jax.tree_util.tree_leaves(grads))
    base_loss, base_grads = results["none"]
    skewed = False
    for label in ("full", "dots"):
        loss, grads = results[label]
        assert loss == base_loss, (label, loss, base_loss)
        for a, b in zip(grads, base_grads):
            skewed |= xfail_if_remat_ulp_skew(
                np.asarray(a), np.asarray(b), label
            )
    if skewed:
        pytest.xfail(
            "environment XLA:CPU skew (detected): rematerialized "
            "backward gradients differ from the unrematted ones at "
            "rounding scale on this jaxlib (every diff passed the "
            "tight allclose above; bit-identical on current jax/XLA, "
            "pre-existing at the seed of this image)"
        )
