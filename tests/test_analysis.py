"""Invariant auditor (jax_llama_tpu.analysis) — ``pytest -m analysis``.

Two halves:

  * **Fixture tests**: synthetic modules that deliberately violate each
    rule class (stray device->host sync, undonated pool arg, full-pool
    copy via a non-donated carry, unguarded field write, cross-thread
    holder access, upload-in-loop, device control flow) assert each
    checker catches its class — and that the matching ``# audit:``
    pragma sanctions it.
  * **Package-cleanliness gates** (tier-1): the REAL package must be
    clean under every static layer, and every jitted program the
    batcher dispatches must hold a registered lowering contract.  The
    abstract-trace layer (lowers all ten programs at a tiny geometry)
    is ``slow``-marked — ``make lint-invariants`` runs it on every
    lint invocation; tier-1 keeps the fast static gates.
"""

import subprocess
import sys

import pytest

from jax_llama_tpu.analysis import run_all
from jax_llama_tpu.analysis.common import Pragmas
from jax_llama_tpu.analysis.hostsync import HostBoundaryChecker
from jax_llama_tpu.analysis.lockcheck import (
    CONFINEMENTS, LOCK_GUARDS, LockDisciplineChecker, LockGuard,
    ThreadConfinement,
)
from jax_llama_tpu.analysis.lowering import (
    check_lowering, check_static, check_traces,
)
from jax_llama_tpu.analysis.contracts import (
    REGISTRY, ProgramContract, clear_examples,
)
from jax_llama_tpu.analysis.__main__ import main as cli_main

pytestmark = pytest.mark.analysis


def rules(findings):
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------------------------
# Pragma grammar
# ---------------------------------------------------------------------------

class TestPragmas:
    def test_single_line(self):
        p = Pragmas.scan("x = 1  # audit: host-fetch(the one fetch)\n")
        assert p.allows("host-fetch", (1, 1))
        assert not p.allows("host-upload", (1, 1))
        assert not p.bad_lines

    def test_multi_line_reason(self):
        src = (
            "# audit: racy-read(a reason that wraps\n"
            "# across two comment lines)\n"
            "x = 1\n"
        )
        p = Pragmas.scan(src)
        assert p.allows("racy-read", (3, 3))  # preceding-line rule
        assert not p.bad_lines

    def test_unknown_kind_is_bad(self):
        p = Pragmas.scan("# audit: host-fetchh(typo)\nx = 1\n")
        assert p.bad_lines
        assert not p.allows("host-fetch", (2, 2))

    def test_missing_reason_is_bad(self):
        p = Pragmas.scan("# audit: host-fetch()\nx = 1\n")
        assert p.bad_lines

    def test_bad_pragma_is_a_finding(self):
        fs = HostBoundaryChecker().check_source(
            "serving.py", "# audit: host-fetchh(typo)\nx = 1\n"
        )
        assert rules(fs) == ["bad-pragma"]


# ---------------------------------------------------------------------------
# Host-boundary lint fixtures
# ---------------------------------------------------------------------------

FETCH_FIXTURE = """
import numpy as np
import jax.numpy as jnp

class B:
    def step(self):
        packed = jnp.zeros((4,))
        return np.asarray(packed)
"""

FETCH_PRAGMA_FIXTURE = """
import numpy as np
import jax.numpy as jnp

class B:
    def step(self):
        packed = jnp.zeros((4,))
        # audit: host-fetch(the one packed fetch per chunk)
        return np.asarray(packed)
"""

SCALAR_FIXTURE = """
class B:
    def peek(self):
        return float(self.tau[0]), self.tau.item()
"""

FLOW_FIXTURE = """
class B:
    def step(self):
        if self.d_active.any():
            return 1
        while self.tau > 0:
            pass
"""

UPLOAD_FIXTURE = """
import jax.numpy as jnp

class B:
    def admit(self, rows):
        for r in rows:
            self.d_table = jnp.asarray(r)
"""

TRACE_TIME_FIXTURE = """
import functools
import jax
import jax.numpy as jnp

def helper(n):
    out = []
    for i in range(n):
        out.append(jnp.zeros((4,)))
    return out

@functools.partial(jax.jit, static_argnames=("n",))
def program(x, *, n):
    return sum(helper(n)) + x
"""

BLOCKING_FIXTURE = """
import jax

class B:
    def wait(self, staged):
        jax.block_until_ready(staged)
        jax.device_get(staged)
"""


class TestHostBoundary:
    def check(self, src, module="serving"):
        return HostBoundaryChecker().check_source(
            f"{module}.py", src, module=module
        )

    def test_stray_fetch_caught(self):
        assert rules(self.check(FETCH_FIXTURE)) == ["host-fetch"]

    def test_pragma_sanctions_fetch(self):
        assert self.check(FETCH_PRAGMA_FIXTURE) == []

    def test_scalar_fetches_caught(self):
        fs = self.check(SCALAR_FIXTURE)
        assert rules(fs) == ["host-fetch"] and len(fs) == 2

    def test_device_control_flow_caught(self):
        fs = self.check(FLOW_FIXTURE)
        assert rules(fs) == ["device-flow"] and len(fs) == 2

    def test_upload_in_loop_caught(self):
        assert rules(self.check(UPLOAD_FIXTURE)) == ["host-upload"]

    def test_trace_time_unrolling_not_flagged(self):
        # jnp-in-a-loop inside a helper reachable ONLY from a jitted
        # program is loop unrolling, not a runtime upload.
        assert self.check(TRACE_TIME_FIXTURE) == []

    def test_unconditional_syncs_caught(self):
        fs = self.check(BLOCKING_FIXTURE)
        assert rules(fs) == ["host-fetch"] and len(fs) == 2

    def test_numpy_mirror_not_flagged(self):
        # self.tau_lp is the numpy mirror: np.asarray on it is free.
        src = (
            "import numpy as np\n"
            "class B:\n"
            "    def f(self):\n"
            "        return np.asarray(self.tau_lp)\n"
        )
        assert self.check(src) == []

    def test_is_none_test_not_flagged(self):
        src = (
            "class B:\n"
            "    def f(self):\n"
            "        if self.pool is not None:\n"
            "            return 1\n"
        )
        assert self.check(src) == []

    def test_package_clean(self):
        assert HostBoundaryChecker().check_package() == []


# ---------------------------------------------------------------------------
# Lock-discipline fixtures
# ---------------------------------------------------------------------------

LOCK_FIXTURE = """
import threading

class Obs:
    def __init__(self):
        self._lock = threading.Lock()
        self.ring = []

    def good(self):
        with self._lock:
            self.ring.append(1)

    def bad(self):
        self.ring.append(2)

    def _drain_locked(self):
        self.ring.clear()

    def annotated(self):
        # audit: locked(caller holds self._lock)
        self.ring.append(3)
"""

CONFINED_FIXTURE = """
class Batcher:
    def step(self):
        self.table[0] = 1  # owner method: fine

    def stats(self):
        return len(self.table)  # foreign method, no pragma

class Server:
    def handler(self):
        return server.batcher.table  # holder access, no pragma
"""


def fixture_lock_registry():
    return LockDisciplineChecker(
        lock_guards=(LockGuard(
            module="fix", cls="Obs", lock="_lock",
            fields=frozenset({"ring"}),
        ),),
        confinements=(ThreadConfinement(
            module="fix", cls="Batcher", owner="the loop thread",
            fields=frozenset({"table"}),
            foreign_methods=frozenset({"stats"}),
            holders=frozenset({"batcher"}),
        ),),
    )


class TestLockDiscipline:
    def test_unguarded_write_caught_conventions_respected(self):
        fs = fixture_lock_registry().check_source(
            "fix.py", LOCK_FIXTURE, module="fix"
        )
        # exactly ONE finding: bad(); good()/_drain_locked()/annotated()
        # are sanctioned by with-block, naming convention, and pragma.
        assert rules(fs) == ["unlocked-access"]
        assert len(fs) == 1 and fs[0].line == 14

    def test_confinement_and_holder_caught(self):
        fs = fixture_lock_registry().check_source(
            "fix.py", CONFINED_FIXTURE, module="fix"
        )
        assert rules(fs) == ["foreign-thread-access"]
        assert len(fs) == 2  # stats() read + holder access; step() fine

    def test_stale_foreign_method_is_a_finding(self):
        checker = LockDisciplineChecker(
            lock_guards=(),
            confinements=(ThreadConfinement(
                module="fix", cls="Batcher", owner="loop",
                fields=frozenset({"table"}),
                foreign_methods=frozenset({"gone"}),
            ),),
        )
        fs = checker.check_source("fix.py", CONFINED_FIXTURE,
                                  module="fix")
        assert "stale-registry" in rules(fs)

    def test_registry_covers_the_stack(self):
        guarded = {(g.module, g.cls) for g in LOCK_GUARDS}
        confined = {(c.module, c.cls) for c in CONFINEMENTS}
        assert ("obs", "Observability") in guarded
        assert ("degrade", "DegradeManager") in guarded
        assert ("serving", "ContinuousBatcher") in confined
        assert ("server", "LLMServer") in confined

    def test_package_clean(self):
        assert LockDisciplineChecker().check_package() == []


# ---------------------------------------------------------------------------
# Lowering auditor
# ---------------------------------------------------------------------------

class TestLoweringStatic:
    def test_package_static_clean(self):
        assert check_static() == []

    def test_every_dispatched_program_registered(self):
        # The acceptance bar: every jitted program the batcher
        # dispatches holds a contract.  check_static() fails on any
        # unregistered jit-decorated function in serving/kvcache; the
        # dispatch sites are a subset of those.
        for name in (
            "_paged_decode_step", "_paged_decode_chunk", "_fused_chunk",
            "_spec_round", "_spec_rounds_chunk", "_paged_insert",
            "_paged_suffix_insert", "_scatter_rows", "_release_blocks",
            "_adopt_jit",
        ):
            assert name in REGISTRY, f"{name} lost its contract"

    def test_unregistered_program_caught(self):
        registry = {
            k: v for k, v in REGISTRY.items() if k != "_fused_chunk"
        }
        fs = check_static(registry=registry)
        assert rules(fs) == ["unregistered-program"]
        assert "_fused_chunk" in fs[0].message

    def test_stale_contract_caught(self):
        import dataclasses as dc

        registry = dict(REGISTRY)
        registry["_ghost_program"] = dc.replace(
            REGISTRY["_paged_insert"], name="_ghost_program"
        )
        assert "stale-contract" in rules(check_static(registry=registry))

    def test_aliased_jit_decorator_recognized(self):
        # `from jax import jit; @partial(jit, ...)` must not bypass
        # the coverage gate (or the host lint's trace-time exemption).
        from jax_llama_tpu.analysis.common import jit_decorations
        import ast as _ast

        src = (
            "import functools\n"
            "from jax import jit\n"
            "@functools.partial(jit, donate_argnames=('pool',))\n"
            "def sneaky(pool, x):\n"
            "    return pool, x\n"
            "@jit\n"
            "def bare(x):\n"
            "    return x\n"
        )
        assert set(jit_decorations(_ast.parse(src))) == {
            "sneaky", "bare",
        }

    def test_cli_lowering_with_paths_is_usage_error(self, capsys):
        assert cli_main(
            ["--checker", "lowering", "tests/test_analysis.py"]
        ) == 2
        assert "does not take file paths" in capsys.readouterr().err

    def test_donation_decorator_mismatch_caught(self):
        import dataclasses as dc

        registry = dict(REGISTRY)
        registry["_paged_insert"] = dc.replace(
            REGISTRY["_paged_insert"], donated=("pool", "keys")
        )
        fs = check_static(registry=registry)
        assert rules(fs) == ["donation-mismatch"]


# -- trace-layer fixtures (tiny standalone programs; no model) --------------

def _fixture_contract(fn_name, module, donated, live, bpr, build,
                      forbid_pool_shapes=False):
    # fixture contracts default the pool-shape rule OFF (their args are
    # bare arrays; a contract with it on and no derivable shapes is
    # itself a finding — see test_vacuous_shape_set_is_a_finding)
    return ProgramContract(
        name=fn_name, module=module, donated=donated,
        max_live_outputs=live, max_fetch_bytes_per_row=bpr,
        build=build, forbid_pool_shapes=forbid_pool_shapes,
    )


@pytest.fixture(scope="module")
def fixture_programs():
    """A module-like namespace with tiny jitted programs: one donates
    its pool correctly, one forgot, one materializes a full-pool copy
    through a non-donated carry."""
    import functools
    import sys
    import types

    import jax
    import jax.numpy as jnp

    mod = types.ModuleType("_analysis_fixture_programs")

    @functools.partial(jax.jit, donate_argnames=("pool",))
    def good(pool, x):
        return pool.at[0, 0].add(x.sum()), x * 2

    @jax.jit
    def undonated(pool, x):  # forgot donate_argnames
        return pool.at[0, 0].add(x.sum()), x * 2

    @functools.partial(jax.jit, donate_argnames=("pool",))
    def leaky(pool, x):
        # the classic regression: a pool-sized broadcast materializes
        # a full-pool copy (and an extra live pool-sized output)
        ghost = jnp.broadcast_to(x[0], pool.shape) + pool
        return pool.at[0, 0].add(x.sum()), ghost

    mod.good, mod.undonated, mod.leaky = good, undonated, leaky
    sys.modules[mod.__name__] = mod
    yield mod
    del sys.modules[mod.__name__]


def _args_builder():
    import jax.numpy as jnp

    pool = jnp.zeros((2, 2, 4, 8, 4), jnp.float32)
    x = jnp.ones((2,), jnp.float32)
    return ("pool", "x"), (pool, x), {}


def _pooled_args_builder():
    # wrap the pool in a BlockPool-shaped carrier so pool_shapes()
    # derives the forbidden shapes (registered as a pytree so jit can
    # flatten it)
    import dataclasses as dc

    import jax
    import jax.numpy as jnp

    @dc.dataclass(frozen=True)
    class MiniPool:
        k: object
        v: object
        pos: object
        k_scale: object = None
        v_scale: object = None

        @property
        def block_size(self):
            return 8

    jax.tree_util.register_pytree_node(
        MiniPool,
        lambda p: ((p.k, p.v, p.pos), None),
        lambda aux, ch: MiniPool(k=ch[0], v=ch[1], pos=ch[2]),
    )
    k = jnp.zeros((2, 2, 4, 8, 4), jnp.float32)
    pool = MiniPool(k=k, v=k, pos=jnp.zeros((4, 8), jnp.int32))
    x = jnp.ones((2,), jnp.float32)
    return ("pool", "x"), (pool, x), {}


@pytest.mark.slow
class TestLoweringTraceFixtures:
    def test_good_program_clean(self, fixture_programs):
        c = _fixture_contract(
            "good", fixture_programs.__name__, ("pool",), 1, 8,
            _args_builder,
        )
        assert check_lowering(c) == []

    def test_forgotten_donation_caught(self, fixture_programs):
        c = _fixture_contract(
            "undonated", fixture_programs.__name__, ("pool",), 1, 8,
            _args_builder,
        )
        fs = check_lowering(c)
        assert "donation-not-applied" in rules(fs)

    def test_full_pool_copy_and_fetch_surface_caught(
        self, fixture_programs
    ):
        import functools
        import jax
        import jax.numpy as jnp
        import sys
        import types

        mod = types.ModuleType("_analysis_fixture_pool_copy")

        @functools.partial(jax.jit, donate_argnames=())
        def copying(pool, x):
            # non-donated carry: returning pool broadcast-shaped
            plane = jnp.broadcast_to(x.sum(), tuple(pool.k.shape))
            return plane + pool.k, x * 2

        mod.copying = copying
        sys.modules[mod.__name__] = mod
        try:
            c = _fixture_contract(
                "copying", mod.__name__, (), 2, 8,
                _pooled_args_builder, forbid_pool_shapes=True,
            )
            fs = check_lowering(c)
            assert "full-pool-copy" in rules(fs)
            # the pool-sized live output also blows the byte budget
            assert "fetch-bytes" in rules(fs)
        finally:
            del sys.modules[mod.__name__]

    def test_vacuous_shape_set_is_a_finding(self, fixture_programs):
        # forbid_pool_shapes with nothing derivable must NOT pass
        # silently (the silent-cap failure mode).
        c = _fixture_contract(
            "good", fixture_programs.__name__, ("pool",), 1, 8,
            _args_builder, forbid_pool_shapes=True,
        )
        assert "no-forbidden-shapes" in rules(check_lowering(c))

    def test_live_output_count_enforced(self, fixture_programs):
        c = _fixture_contract(
            "good", fixture_programs.__name__, ("pool",), 0, 8,
            _args_builder,
        )
        fs = check_lowering(c)
        assert "fetch-count" in rules(fs)


@pytest.mark.slow
class TestLoweringTracePackage:
    def test_all_contracts_trace_clean(self):
        # Lowers all ten registered programs at the tiny example
        # geometry: donation resolves, fetch surface within budget,
        # no pool-shaped copy-class equations.  ~30 s cold.
        clear_examples()
        assert check_traces() == []

    def test_mesh_contracts_trace_clean(self):
        # The serving-mesh pass: every contract with a mesh_build
        # lowers its SHARDED variant (donor attributes present for all
        # donated leaves) and runs it once proving sharding stability
        # (donated inputs leave with the sharding they entered with).
        from jax_llama_tpu.analysis.lowering import check_mesh_traces

        clear_examples()
        assert check_mesh_traces() == []


def test_mesh_contract_registry_consistent():
    """Cheap (tier-1) registry hygiene for the mesh pass: the two
    chunk programs carry mesh variants, every mesh_aliases key is a
    declared donated arg, and alias positions are unique."""
    from jax_llama_tpu.analysis.contracts import REGISTRY

    with_mesh = {
        n: c for n, c in REGISTRY.items() if c.mesh_build is not None
    }
    assert {"_paged_decode_chunk", "_fused_chunk"} <= set(with_mesh)
    for name, c in with_mesh.items():
        assert c.mesh_aliases, name
        assert set(c.mesh_aliases) <= set(c.donated), name
        positions = list(c.mesh_aliases.values())
        assert len(positions) == len(set(positions)), name


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

class TestCLI:
    def test_clean_package_exits_zero(self, capsys):
        assert cli_main(["--no-trace"]) == 0
        assert "clean" in capsys.readouterr().out

    def test_violating_file_exits_nonzero(self, tmp_path, capsys):
        bad = tmp_path / "bad.py"
        bad.write_text(
            "import numpy as np\nimport jax.numpy as jnp\n"
            "class B:\n"
            "    def f(self):\n"
            "        v = jnp.zeros((2,))\n"
            "        return np.asarray(v)\n"
        )
        assert cli_main([str(bad)]) == 1
        assert "host-fetch" in capsys.readouterr().out

    def test_lock_fixture_exits_nonzero(self, tmp_path, capsys):
        # the generic d_-twin rule needs no registry: an obs-module
        # fixture exercising the serving registry instead
        bad = tmp_path / "serving.py"
        bad.write_text(
            "class ContinuousBatcher:\n"
            "    def stats(self):\n"
            "        return len(self.queue)\n"
        )
        assert cli_main([str(bad)]) == 1
        assert "foreign-thread-access" in capsys.readouterr().out

    def test_json_output(self, tmp_path, capsys):
        import json as _json

        bad = tmp_path / "bad.py"
        bad.write_text(
            "import jax\nclass B:\n"
            "    def f(self, staged):\n"
            "        jax.block_until_ready(staged)\n"
        )
        # --json uses the per-pass stable exit codes (host-boundary =
        # 10); findings objects carry the machine-readable fields.
        assert cli_main(["--json", str(bad)]) == 10
        payload = _json.loads(capsys.readouterr().out)
        assert payload and payload[0]["rule"] == "host-fetch"
        assert payload[0]["checker"] == "host-boundary"
        assert payload[0]["severity"] == "error"
        assert payload[0]["sanctionable"] in (True, False)

    @pytest.mark.slow
    def test_cli_contracts_hook_donation_and_pool_copy(
        self, fixture_programs, capsys
    ):
        """The acceptance-criteria fixture classes through the CLI:
        a forgotten donation and a full-pool copy each exit non-zero
        via ``--contracts`` (an external fixture REGISTRY)."""
        import sys as _sys
        import types

        reg = types.ModuleType("_analysis_fixture_registry")
        reg.REGISTRY = {
            "undonated": _fixture_contract(
                "undonated", fixture_programs.__name__, ("pool",), 1,
                8, _args_builder,
            ),
        }
        _sys.modules[reg.__name__] = reg
        try:
            rc = cli_main(
                ["--checker", "lowering", "--contracts", reg.__name__]
            )
            out = capsys.readouterr().out
            assert rc == 1 and "donation-not-applied" in out
        finally:
            del _sys.modules[reg.__name__]

    @pytest.mark.slow
    def test_module_entrypoint_subprocess(self):
        # the acceptance-criteria invocation, end to end
        proc = subprocess.run(
            [sys.executable, "-m", "jax_llama_tpu.analysis",
             "--no-trace"],
            capture_output=True, text=True, timeout=300,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr


# ---------------------------------------------------------------------------
# run_all: the tier-1 cleanliness gate
# ---------------------------------------------------------------------------

def test_package_clean_static_gate():
    """The PR gate: every checker's static layer is clean on the
    package — a stray sync / unguarded access / contract drift fails
    tier-1 here before any bench round notices."""
    findings = run_all(trace=False)
    assert findings == [], "\n".join(f.render() for f in findings)


# ---------------------------------------------------------------------------
# Retrace auditor (analysis/retrace.py)
# ---------------------------------------------------------------------------

_RETRACE_FIXTURE = '''
import functools, jax
import numpy as np
import jax.numpy as jnp
from jax_llama_tpu.engine import pow2_bucket

@functools.partial(jax.jit, static_argnames=("width",))
def _prog(x, *, width):
    return x[:width]

class Batcher:
    def __init__(self):
        self.cap = 8
    def good(self, req):
        w = pow2_bucket(len(req))
        buf = np.zeros((w,), np.int32)
        return _prog(jnp.asarray(buf), width=min(len(req), self.cap))
    def bad_static(self, req):
        buf = np.zeros((self.cap,), np.int32)
        return _prog(jnp.asarray(buf), width=len(req))
    def bad_shape(self, req):
        buf = np.zeros((len(req),), np.int32)
        return _prog(jnp.asarray(buf), width=self.cap)
    def sanctioned(self, req):
        buf = np.zeros((len(req),), np.int32)  # audit: trace-domain(fixture: caller guarantees <= 4 lengths)
        # audit: trace-domain(fixture: caller-bounded)
        return _prog(jnp.asarray(buf), width=len(req))
'''


class TestRetraceStatic:
    def _registry(self, max_cache_keys=4):
        return {"_prog": ProgramContract(
            name="_prog", module="retrace_fixture", donated=(),
            max_live_outputs=1, max_fetch_bytes_per_row=1 << 20,
            max_cache_keys=max_cache_keys,
        )}

    def _check(self):
        from jax_llama_tpu.analysis.retrace import check_module_source

        return check_module_source(
            "retrace_fixture.py", _RETRACE_FIXTURE,
            registry=self._registry(),
        )

    def test_unbounded_static_arg_caught(self):
        fs = self._check()
        assert any(
            f.rule == "unbounded-trace-domain" and "bad_static" in
            f.message and "static arg" in f.message for f in fs
        ), [f.render() for f in fs]

    def test_unbounded_array_dim_caught(self):
        fs = self._check()
        assert any(
            f.rule == "unbounded-trace-domain" and "bad_shape" in
            f.message for f in fs
        ), [f.render() for f in fs]

    def test_bounded_and_sanctioned_paths_clean(self):
        fs = self._check()
        assert not any(
            "good" in f.message or "sanctioned" in f.message
            for f in fs
        ), [f.render() for f in fs]
        # the findings are pragma-sanctionable and say so
        assert all(f.sanctionable for f in fs)

    def test_missing_cache_key_budget_is_finding(self):
        from jax_llama_tpu.analysis.retrace import check_static

        fs = check_static(registry=self._registry(max_cache_keys=None))
        assert any(f.rule == "no-cache-key-budget" for f in fs)

    def test_every_contract_declares_cache_key_budget(self):
        assert all(
            c.max_cache_keys is not None for c in REGISTRY.values()
        ), "registered programs must bound their jit-cache domains"

    def test_package_retrace_static_clean(self):
        from jax_llama_tpu.analysis.retrace import check_static

        fs = check_static()
        assert fs == [], "\n".join(f.render() for f in fs)


@pytest.mark.slow
def test_retrace_runtime_drill_within_contract():
    """The jit-cache drill: a real admission sweep must stay within
    every contract's max_cache_keys (the runtime half of the retrace
    contract; ~60 s of tiny-model compiles)."""
    from jax_llama_tpu.analysis.retrace import check_runtime

    fs = check_runtime()
    assert fs == [], "\n".join(f.render() for f in fs)


@pytest.mark.slow
def test_classic_insert_width_is_bucketed():
    """Regression pin for the over-wide _paged_insert trace-key domain
    the retrace pass surfaced: whole-prompt admissions in DIFFERENT
    raw block counts but the same pow2 bucket must share ONE compiled
    executable (pre-fix: P was only block-rounded, one cache entry per
    distinct prompt block count)."""
    import numpy as np

    from jax_llama_tpu import serving
    from jax_llama_tpu.analysis.contracts import (
        _MAXLEN, _VOCAB, _tiny_config_params,
    )
    from jax_llama_tpu.serving import ContinuousBatcher

    cfg, params = _tiny_config_params()
    cb = ContinuousBatcher(
        params, cfg, n_slots=2, max_len=_MAXLEN, block_size=8,
        prefix_cache=False,
    )
    rng = np.random.RandomState(3)
    before = serving.jit_cache_entries()["_paged_insert"]
    if before < 0:
        pytest.skip("jax hides the executable cache")
    # 20 tokens = 3 blocks and 28 tokens = 4 blocks, both bucket to 4
    for n in (20, 28):
        cb.submit(list(rng.randint(1, _VOCAB, n)), max_new_tokens=2)
        cb.run_to_completion()
    after = serving.jit_cache_entries()["_paged_insert"]
    assert after - before == 1, (
        f"two same-bucket admissions compiled {after - before} "
        "_paged_insert variants (want 1: the pow2 group width)"
    )


# ---------------------------------------------------------------------------
# Schedule explorer (analysis/schedules.py)
# ---------------------------------------------------------------------------

class TestSchedules:
    def _toctou_model(self, safe):
        from jax_llama_tpu.analysis.schedules import Op, ScheduleModel

        def make():
            class PF:
                remaining = 7

            class S:
                pass

            s = S()
            s.pf = PF()
            return s

        def racy_reader(s):
            if s.pf is not None:
                return s.pf.remaining
            return 0

        def safe_reader(s):
            pf = s.pf
            if pf is not None:
                return pf.remaining
            return 0

        return ScheduleModel(
            name="fixture-toctou", module="x", func="reader",
            claim="snapshot", make=make,
            writers={"loop": (
                Op("null", lambda s, c: setattr(s, "pf", None),
                   frozenset({"pf"})),
            )},
            reader=safe_reader if safe else racy_reader,
            trace_fn="safe_reader" if safe else "racy_reader",
        )

    def test_toctou_reader_fails_with_counterexample(self):
        from jax_llama_tpu.analysis.schedules import explore

        fails = explore(self._toctou_model(safe=False))
        assert fails and "AttributeError" in fails[0], fails

    def test_snapshot_safe_reader_passes(self):
        from jax_llama_tpu.analysis.schedules import explore

        assert explore(self._toctou_model(safe=True)) == []

    def test_single_writer_violation_is_structural(self):
        from jax_llama_tpu.analysis.schedules import (
            Op, ScheduleModel, explore,
        )

        m = ScheduleModel(
            name="two-writers", module="x", func="f",
            claim="single-writer",
            make=lambda: type("S", (), {"n": 0})(),
            writers={
                "a": (Op("wa", lambda s, c: setattr(s, "n", 1),
                         frozenset({"n"})),),
                "b": (Op("wb", lambda s, c: setattr(s, "n", 2),
                         frozenset({"n"})),),
            },
        )
        fails = explore(m)
        assert fails and "single-writer claim is structurally void" in \
            fails[0]

    def test_happens_before_edge_enforced(self):
        from jax_llama_tpu.analysis.schedules import (
            Op, ScheduleModel, explore,
        )

        def make():
            s = type("S", (), {})()
            s.x = None
            return s

        def read(s, c):
            assert s.x is not None, "read before write"

        write = Op("write", lambda s, c: setattr(s, "x", c),
                   frozenset({"x"}))
        base = dict(
            name="hb", module="x", func="f", claim="happens-before",
            make=make,
            writers={"main": (write,), "loop": (Op("read", read),)},
        )
        # without the edge some interleaving reads first...
        assert explore(ScheduleModel(**base)) != []
        # ...the declared edge makes every schedule safe
        assert explore(ScheduleModel(
            **base, after={"loop": ("main", "write")}
        )) == []

    def test_unmodeled_pragma_is_finding(self):
        from jax_llama_tpu.analysis.schedules import check_package

        src = (
            "class C:\n"
            "    def f(self):\n"
            "        # audit: racy-read(nobody modeled this)\n"
            "        return self.x\n"
        )
        fs = check_package(models=[], sources=[("fixmod.py", src)])
        assert [f.rule for f in fs] == ["unmodeled-pragma"]

    def test_stale_model_is_finding(self):
        from jax_llama_tpu.analysis.schedules import (
            ScheduleModel, check_package,
        )

        ghost = ScheduleModel(
            name="ghost", module="serving", func="no_such_method",
            claim="owner-thread", make=lambda: object(), writers={},
        )
        fs = check_package(models=[ghost])
        assert any(f.rule == "stale-model" for f in fs)

    def test_every_pragma_site_has_a_passing_model(self):
        """The tier-1 gate: every racy-read/unguarded pragma in the
        package resolves to a schedule model and every model's
        exploration passes (sub-second: the explorers preempt real
        stats()/_health() readers line-by-line)."""
        from jax_llama_tpu.analysis.schedules import check_package

        fs = check_package()
        assert fs == [], "\n".join(f.render() for f in fs)

    def test_pragma_sites_found(self):
        from jax_llama_tpu.analysis.schedules import pragma_sites

        keys = {(s.module, s.func) for s in pragma_sites()}
        # the load-bearing cross-thread surfaces must be in the scan
        assert ("serving", "stats") in keys
        assert ("serving", "_window_acceptance") in keys
        assert ("server", "_health") in keys
        assert ("server", "_watchdog") in keys


# ---------------------------------------------------------------------------
# Metrics-registry lint (analysis/metricscheck.py)
# ---------------------------------------------------------------------------

class TestMetricsLint:
    def test_package_metrics_clean(self):
        from jax_llama_tpu.analysis.metricscheck import check_package

        fs = check_package()
        assert fs == [], "\n".join(f.render() for f in fs)

    def test_ghost_registration_caught(self):
        from jax_llama_tpu import obs
        from jax_llama_tpu.analysis.metricscheck import check_package

        reg = dict(obs.METRICS)
        reg["ghost_gauge_total"] = ("counter", "never emitted")
        fs = check_package(registry=reg)
        assert any(
            f.rule == "unemitted-metric" and "ghost_gauge_total" in
            f.message for f in fs
        )

    def test_unregistered_emission_caught(self):
        from jax_llama_tpu.analysis.metricscheck import check_package

        src = (
            "class P:\n"
            "    def stats(self):\n"
            "        return {'rogue_scalar': 1}\n"
        )
        fs = check_package(
            registry={"known": ("gauge", "k")},
            sources=[("provider_mod.py", src)],
            providers=(("provider_mod", "P", "stats"),),
        )
        assert any(
            f.rule == "unregistered-metric" and "rogue_scalar" in
            f.message for f in fs
        )

    def test_templated_family_matches_registration(self):
        from jax_llama_tpu.analysis.metricscheck import check_package

        src = (
            "SITES = ('a',)\n"
            "class P:\n"
            "    def stats(self):\n"
            "        out = {}\n"
            "        for s in SITES:\n"
            "            out[f'faults_injected_{s}_total'] = 1\n"
            "        return out\n"
        )
        fs = check_package(
            registry={"faults_injected_step_total": ("counter", "x")},
            sources=[("provider_mod.py", src)],
            providers=(("provider_mod", "P", "stats"),),
        )
        assert not any(f.rule == "unregistered-metric" for f in fs), \
            [f.render() for f in fs]

    def test_router_registry_package_clean(self):
        from jax_llama_tpu.analysis.metricscheck import (
            check_router_registry,
        )

        fs = check_router_registry()
        assert fs == [], "\n".join(f.render() for f in fs)

    def test_router_registry_drift_fixtures(self):
        """Both router-audit directions bite: a registered family
        nothing emits, a fam() header with no registration, and a raw
        sample line minting an unregistered family — while the clean
        family passes and docstring/registry mentions are NOT
        evidence."""
        from jax_llama_tpu.analysis.metricscheck import (
            check_router_registry,
        )

        src = (
            '"""Docstring naming llm_router_doc_only_total is not '
            'emission evidence."""\n'
            'ROUTER_METRICS = {\n'
            '    "llm_router_emitted_total": ("counter", "ok"),\n'
            '    "llm_router_ghost_total": ("counter", "never"),\n'
            '}\n'
            'def fam(name):\n'
            '    pass\n'
            'def render(lines, n):\n'
            '    fam("llm_router_emitted_total")\n'
            '    fam("llm_router_undeclared_total")\n'
            '    lines.append(f"llm_router_emitted_total {n}")\n'
            '    lines.append(f"llm_fleet_raw_gauge {n}")\n'
        )
        registry = {
            "llm_router_emitted_total": ("counter", "ok"),
            "llm_router_ghost_total": ("counter", "never"),
        }
        fs = check_router_registry(
            registry=registry, source=src, path="fixture_router.py"
        )
        unemitted = [
            f for f in fs if f.rule == "router-unemitted-metric"
        ]
        unregistered = [
            f for f in fs if f.rule == "router-unregistered-metric"
        ]
        assert len(unemitted) == 1
        assert "llm_router_ghost_total" in unemitted[0].message
        names = {
            n for f in unregistered
            for n in ("llm_router_undeclared_total",
                      "llm_fleet_raw_gauge")
            if n in f.message
        }
        assert names == {
            "llm_router_undeclared_total", "llm_fleet_raw_gauge",
        }
        assert not any(
            "llm_router_emitted_total" in f.message
            or "llm_router_doc_only_total" in f.message
            for f in fs
        )


# ---------------------------------------------------------------------------
# Comms-budget contracts (analysis/comms.py)
# ---------------------------------------------------------------------------

def _mesh4():
    import jax

    from jax_llama_tpu.parallel.serve_mesh import (
        ServeMeshSpec, build_serve_mesh,
    )

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 forced host devices")
    return build_serve_mesh(
        ServeMeshSpec(data=2, tensor=2), devices=jax.devices()[:4]
    )


@pytest.mark.slow
class TestComms:
    """Sharded-lowering comms matrix: compiles tiny mesh programs."""

    def _fixture_contract(self, body_kind, budget):
        """A contract whose program runs ``body_kind`` inside a scan
        body over a pool-shaped sharded operand."""
        import sys as _sys
        import types as _types

        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax.sharding import NamedSharding, PartitionSpec as P

        from jax_llama_tpu.analysis.contracts import ProgramContract

        mesh = _mesh4()
        mod = _types.ModuleType("comms_fixture_mod")

        @jax.jit
        def _fx(pool, x):
            def body(carry, _):
                if body_kind == "pool-gather":
                    full = jax.lax.with_sharding_constraint(
                        pool, NamedSharding(mesh, P())
                    )
                    return carry + full.sum(), None
                row = jax.lax.with_sharding_constraint(
                    pool[0, :, 0, 0], NamedSharding(mesh, P())
                )
                return carry + row.sum(), None

            out, _ = jax.lax.scan(body, x, None, length=2)
            return out

        mod._fx = _fx
        _sys.modules["comms_fixture_mod"] = mod

        def build():
            pool = jax.device_put(
                np.ones((2, 2, 8, 16, 16), np.float32),
                NamedSharding(mesh, P(None, "tensor")),
            )
            return ("pool", "x"), (pool, jnp.zeros(())), {}

        return ProgramContract(
            name="_fx", module="comms_fixture_mod", donated=(),
            max_live_outputs=1, max_fetch_bytes_per_row=1 << 20,
            mesh_build=build, max_cache_keys=4, comms=budget,
            forbidden_shapes=lambda args: [
                tuple(args[0].shape), tuple(args[0].shape[1:]),
            ],
        )

    def test_full_pool_all_gather_in_scan_body_is_hard_finding(self):
        from jax_llama_tpu.analysis.comms import check_comms
        from jax_llama_tpu.analysis.contracts import CommsBudget

        # even a budget that ALLOWS big all-gathers cannot sanction a
        # pool-shaped one
        c = self._fixture_contract("pool-gather", CommsBudget(
            max_count={"all-gather": 99, "all-reduce": 99,
                       "collective-permute": 99},
            max_bytes=1 << 30,
        ))
        fs = check_comms(c)
        assert any(f.rule == "pool-collective" for f in fs), \
            [f.render() for f in fs]

    def test_count_and_byte_budgets_enforced_and_sanctionable(self):
        from jax_llama_tpu.analysis.comms import check_comms
        from jax_llama_tpu.analysis.contracts import CommsBudget

        # a small row gather: not pool-shaped, so the BUDGET decides
        tight = self._fixture_contract("row-gather", CommsBudget(
            max_count={}, max_bytes=1,
        ))
        fs = check_comms(tight)
        assert any(f.rule == "comms-count" for f in fs)
        loose = self._fixture_contract("row-gather", CommsBudget(
            max_count={"all-gather": 8, "all-reduce": 8,
                       "collective-permute": 8},
            max_bytes=65536,
        ))
        assert not [
            f for f in check_comms(loose)
            if f.rule in ("comms-count", "comms-bytes",
                          "pool-collective")
        ]

    def test_mesh_program_without_budget_is_finding(self):
        from jax_llama_tpu.analysis.comms import check_comms

        c = self._fixture_contract("row-gather", None)
        fs = check_comms(c)
        assert [f.rule for f in fs] == ["no-comms-budget"]

    def test_package_comms_clean(self):
        """The regression pin for the full-pool reshard this PR fixed:
        the sharded _paged_decode_chunk / _fused_chunk lowerings hold
        their comms budgets and contain NO pool-shaped collective
        (pre-fix: 4 and 36 full-pool all-gathers per scan body)."""
        from jax_llama_tpu.analysis.comms import check_package

        fs = check_package()
        assert fs == [], "\n".join(f.render() for f in fs)

    def test_every_mesh_contract_declares_budget(self):
        for c in REGISTRY.values():
            if c.mesh_build is not None:
                assert c.comms is not None, c.name


def test_constrain_view_pins_kv_heads():
    """Fast pin for the gathered-view sharding fix: under a serving
    mesh, constrain_view forces the view's KV-head axis onto the
    ``tensor`` axis (the pin that stops GSPMD replicating the pool)."""
    import jax
    import jax.numpy as jnp

    from jax_llama_tpu.models.llama import KVCache
    from jax_llama_tpu.parallel import mesh as pmesh
    from jax_llama_tpu.parallel import serve_mesh as smesh

    mesh = _mesh4()

    @jax.jit
    def f(k, v, pos):
        view = KVCache(
            k=k, v=v, pos=pos, index=jnp.zeros((2,), jnp.int32)
        )
        with pmesh.use_mesh(mesh):
            return smesh.constrain_view(view).k

    k = jnp.zeros((2, 2, 32, 2, 16), jnp.float32)
    out = f(k, k, jnp.zeros((2, 32), jnp.int32))
    spec = out.sharding.spec
    assert tuple(spec)[3] == "tensor", spec


# ---------------------------------------------------------------------------
# Review-hardening pins for the new passes themselves
# ---------------------------------------------------------------------------

class TestPassRobustness:
    def test_unsatisfiable_happens_before_edge_is_not_vacuous(self):
        from jax_llama_tpu.analysis.schedules import (
            Op, ScheduleModel, explore,
        )

        m = ScheduleModel(
            name="vac", module="x", func="f", claim="happens-before",
            make=lambda: object(),
            writers={"main": (Op("w", lambda s, c: None),),
                     "loop": (Op("r", lambda s, c: None),)},
            after={"loop": ("main", "TYPO_no_such_op")},
        )
        fails = explore(m)
        assert fails and "no complete schedule" in fails[0]

    def test_shape_of_parameter_is_not_bounded(self):
        from jax_llama_tpu.analysis.retrace import check_module_source

        src = (
            "import functools, jax\n"
            "import jax.numpy as jnp\n"
            '@functools.partial(jax.jit, static_argnames=("width",))\n'
            "def _prog(x, *, width):\n"
            "    return x[:width]\n"
            "class B:\n"
            "    def f(self, toks):\n"
            "        return _prog(jnp.asarray(toks), "
            "width=toks.shape[0])\n"
        )
        reg = {"_prog": ProgramContract(
            name="_prog", module="fixture_mod", donated=(),
            max_live_outputs=1, max_fetch_bytes_per_row=1 << 20,
            max_cache_keys=4,
        )}
        fs = check_module_source("fixture_mod.py", src, registry=reg)
        assert any("request-shaped" in f.message for f in fs), \
            [f.render() for f in fs]

    def test_tuple_result_collectives_parsed(self):
        from jax_llama_tpu.analysis.comms import collectives_in_text

        text = (
            "%ag = (f32[2,2,8,16,16]{4,3,2,0,1}, s32[4]{0}) "
            "all-gather(f32[2,1,8,16,16] %a, s32[2] %b), dims={1}\n"
            "%ar = f32[1,64]{1,0} all-reduce(f32[1,64] %c)\n"
            "%done = (f32[8]{0}) all-gather-done(%x)\n"
        )
        got = collectives_in_text(text)
        kinds = [k for k, _ in got]
        assert kinds == ["all-gather", "all-reduce"]  # -done skipped
        shapes = [s for _, rs in got for s, _ in rs]
        assert (2, 2, 8, 16, 16) in shapes and (4,) in shapes

    def test_docstring_mention_is_not_emission_evidence(self):
        from jax_llama_tpu.analysis.metricscheck import check_package

        src = (
            '"""Module docs mention ghost_gauge by name."""\n'
            "class P:\n"
            '    """Docs: ghost_gauge again."""\n'
            "    def stats(self):\n"
            "        return {}\n"
        )
        fs = check_package(
            registry={"ghost_gauge": ("gauge", "x")},
            sources=[("provider_mod.py", src)],
            providers=(),
        )
        assert any(
            f.rule == "unemitted-metric" and "ghost_gauge" in f.message
            for f in fs
        ), [f.render() for f in fs]

    def test_cli_comms_no_trace_is_usage_error(self, capsys):
        assert cli_main(["--checker", "comms", "--no-trace"]) == 2
        assert "vacuous" in capsys.readouterr().err

    def test_cli_retrace_with_contracts_is_usage_error(self, capsys):
        assert cli_main(
            ["--checker", "retrace", "--contracts", "anything"]
        ) == 2
        assert "cannot audit an external" in capsys.readouterr().err
