"""Invariant auditor (jax_llama_tpu.analysis) — ``pytest -m analysis``.

Two halves:

  * **Fixture tests**: synthetic modules that deliberately violate each
    rule class (stray device->host sync, undonated pool arg, full-pool
    copy via a non-donated carry, unguarded field write, cross-thread
    holder access, upload-in-loop, device control flow) assert each
    checker catches its class — and that the matching ``# audit:``
    pragma sanctions it.
  * **Package-cleanliness gates** (tier-1): the REAL package must be
    clean under every static layer, and every jitted program the
    batcher dispatches must hold a registered lowering contract.  The
    abstract-trace layer (lowers all ten programs at a tiny geometry)
    is ``slow``-marked — ``make lint-invariants`` runs it on every
    lint invocation; tier-1 keeps the fast static gates.
"""

import subprocess
import sys

import pytest

from jax_llama_tpu.analysis import run_all
from jax_llama_tpu.analysis.common import Pragmas
from jax_llama_tpu.analysis.hostsync import HostBoundaryChecker
from jax_llama_tpu.analysis.lockcheck import (
    CONFINEMENTS, LOCK_GUARDS, LockDisciplineChecker, LockGuard,
    ThreadConfinement,
)
from jax_llama_tpu.analysis.lowering import (
    check_lowering, check_static, check_traces,
)
from jax_llama_tpu.analysis.contracts import (
    REGISTRY, ProgramContract, clear_examples,
)
from jax_llama_tpu.analysis.__main__ import main as cli_main

pytestmark = pytest.mark.analysis


def rules(findings):
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------------------------
# Pragma grammar
# ---------------------------------------------------------------------------

class TestPragmas:
    def test_single_line(self):
        p = Pragmas.scan("x = 1  # audit: host-fetch(the one fetch)\n")
        assert p.allows("host-fetch", (1, 1))
        assert not p.allows("host-upload", (1, 1))
        assert not p.bad_lines

    def test_multi_line_reason(self):
        src = (
            "# audit: racy-read(a reason that wraps\n"
            "# across two comment lines)\n"
            "x = 1\n"
        )
        p = Pragmas.scan(src)
        assert p.allows("racy-read", (3, 3))  # preceding-line rule
        assert not p.bad_lines

    def test_unknown_kind_is_bad(self):
        p = Pragmas.scan("# audit: host-fetchh(typo)\nx = 1\n")
        assert p.bad_lines
        assert not p.allows("host-fetch", (2, 2))

    def test_missing_reason_is_bad(self):
        p = Pragmas.scan("# audit: host-fetch()\nx = 1\n")
        assert p.bad_lines

    def test_bad_pragma_is_a_finding(self):
        fs = HostBoundaryChecker().check_source(
            "serving.py", "# audit: host-fetchh(typo)\nx = 1\n"
        )
        assert rules(fs) == ["bad-pragma"]


# ---------------------------------------------------------------------------
# Host-boundary lint fixtures
# ---------------------------------------------------------------------------

FETCH_FIXTURE = """
import numpy as np
import jax.numpy as jnp

class B:
    def step(self):
        packed = jnp.zeros((4,))
        return np.asarray(packed)
"""

FETCH_PRAGMA_FIXTURE = """
import numpy as np
import jax.numpy as jnp

class B:
    def step(self):
        packed = jnp.zeros((4,))
        # audit: host-fetch(the one packed fetch per chunk)
        return np.asarray(packed)
"""

SCALAR_FIXTURE = """
class B:
    def peek(self):
        return float(self.tau[0]), self.tau.item()
"""

FLOW_FIXTURE = """
class B:
    def step(self):
        if self.d_active.any():
            return 1
        while self.tau > 0:
            pass
"""

UPLOAD_FIXTURE = """
import jax.numpy as jnp

class B:
    def admit(self, rows):
        for r in rows:
            self.d_table = jnp.asarray(r)
"""

TRACE_TIME_FIXTURE = """
import functools
import jax
import jax.numpy as jnp

def helper(n):
    out = []
    for i in range(n):
        out.append(jnp.zeros((4,)))
    return out

@functools.partial(jax.jit, static_argnames=("n",))
def program(x, *, n):
    return sum(helper(n)) + x
"""

BLOCKING_FIXTURE = """
import jax

class B:
    def wait(self, staged):
        jax.block_until_ready(staged)
        jax.device_get(staged)
"""


class TestHostBoundary:
    def check(self, src, module="serving"):
        return HostBoundaryChecker().check_source(
            f"{module}.py", src, module=module
        )

    def test_stray_fetch_caught(self):
        assert rules(self.check(FETCH_FIXTURE)) == ["host-fetch"]

    def test_pragma_sanctions_fetch(self):
        assert self.check(FETCH_PRAGMA_FIXTURE) == []

    def test_scalar_fetches_caught(self):
        fs = self.check(SCALAR_FIXTURE)
        assert rules(fs) == ["host-fetch"] and len(fs) == 2

    def test_device_control_flow_caught(self):
        fs = self.check(FLOW_FIXTURE)
        assert rules(fs) == ["device-flow"] and len(fs) == 2

    def test_upload_in_loop_caught(self):
        assert rules(self.check(UPLOAD_FIXTURE)) == ["host-upload"]

    def test_trace_time_unrolling_not_flagged(self):
        # jnp-in-a-loop inside a helper reachable ONLY from a jitted
        # program is loop unrolling, not a runtime upload.
        assert self.check(TRACE_TIME_FIXTURE) == []

    def test_unconditional_syncs_caught(self):
        fs = self.check(BLOCKING_FIXTURE)
        assert rules(fs) == ["host-fetch"] and len(fs) == 2

    def test_numpy_mirror_not_flagged(self):
        # self.tau_lp is the numpy mirror: np.asarray on it is free.
        src = (
            "import numpy as np\n"
            "class B:\n"
            "    def f(self):\n"
            "        return np.asarray(self.tau_lp)\n"
        )
        assert self.check(src) == []

    def test_is_none_test_not_flagged(self):
        src = (
            "class B:\n"
            "    def f(self):\n"
            "        if self.pool is not None:\n"
            "            return 1\n"
        )
        assert self.check(src) == []

    def test_package_clean(self):
        assert HostBoundaryChecker().check_package() == []


# ---------------------------------------------------------------------------
# Lock-discipline fixtures
# ---------------------------------------------------------------------------

LOCK_FIXTURE = """
import threading

class Obs:
    def __init__(self):
        self._lock = threading.Lock()
        self.ring = []

    def good(self):
        with self._lock:
            self.ring.append(1)

    def bad(self):
        self.ring.append(2)

    def _drain_locked(self):
        self.ring.clear()

    def annotated(self):
        # audit: locked(caller holds self._lock)
        self.ring.append(3)
"""

CONFINED_FIXTURE = """
class Batcher:
    def step(self):
        self.table[0] = 1  # owner method: fine

    def stats(self):
        return len(self.table)  # foreign method, no pragma

class Server:
    def handler(self):
        return server.batcher.table  # holder access, no pragma
"""


def fixture_lock_registry():
    return LockDisciplineChecker(
        lock_guards=(LockGuard(
            module="fix", cls="Obs", lock="_lock",
            fields=frozenset({"ring"}),
        ),),
        confinements=(ThreadConfinement(
            module="fix", cls="Batcher", owner="the loop thread",
            fields=frozenset({"table"}),
            foreign_methods=frozenset({"stats"}),
            holders=frozenset({"batcher"}),
        ),),
    )


class TestLockDiscipline:
    def test_unguarded_write_caught_conventions_respected(self):
        fs = fixture_lock_registry().check_source(
            "fix.py", LOCK_FIXTURE, module="fix"
        )
        # exactly ONE finding: bad(); good()/_drain_locked()/annotated()
        # are sanctioned by with-block, naming convention, and pragma.
        assert rules(fs) == ["unlocked-access"]
        assert len(fs) == 1 and fs[0].line == 14

    def test_confinement_and_holder_caught(self):
        fs = fixture_lock_registry().check_source(
            "fix.py", CONFINED_FIXTURE, module="fix"
        )
        assert rules(fs) == ["foreign-thread-access"]
        assert len(fs) == 2  # stats() read + holder access; step() fine

    def test_stale_foreign_method_is_a_finding(self):
        checker = LockDisciplineChecker(
            lock_guards=(),
            confinements=(ThreadConfinement(
                module="fix", cls="Batcher", owner="loop",
                fields=frozenset({"table"}),
                foreign_methods=frozenset({"gone"}),
            ),),
        )
        fs = checker.check_source("fix.py", CONFINED_FIXTURE,
                                  module="fix")
        assert "stale-registry" in rules(fs)

    def test_registry_covers_the_stack(self):
        guarded = {(g.module, g.cls) for g in LOCK_GUARDS}
        confined = {(c.module, c.cls) for c in CONFINEMENTS}
        assert ("obs", "Observability") in guarded
        assert ("degrade", "DegradeManager") in guarded
        assert ("serving", "ContinuousBatcher") in confined
        assert ("server", "LLMServer") in confined

    def test_package_clean(self):
        assert LockDisciplineChecker().check_package() == []


# ---------------------------------------------------------------------------
# Lowering auditor
# ---------------------------------------------------------------------------

class TestLoweringStatic:
    def test_package_static_clean(self):
        assert check_static() == []

    def test_every_dispatched_program_registered(self):
        # The acceptance bar: every jitted program the batcher
        # dispatches holds a contract.  check_static() fails on any
        # unregistered jit-decorated function in serving/kvcache; the
        # dispatch sites are a subset of those.
        for name in (
            "_paged_decode_step", "_paged_decode_chunk", "_fused_chunk",
            "_spec_round", "_spec_rounds_chunk", "_paged_insert",
            "_paged_suffix_insert", "_scatter_rows", "_release_blocks",
            "_adopt_jit",
        ):
            assert name in REGISTRY, f"{name} lost its contract"

    def test_unregistered_program_caught(self):
        registry = {
            k: v for k, v in REGISTRY.items() if k != "_fused_chunk"
        }
        fs = check_static(registry=registry)
        assert rules(fs) == ["unregistered-program"]
        assert "_fused_chunk" in fs[0].message

    def test_stale_contract_caught(self):
        import dataclasses as dc

        registry = dict(REGISTRY)
        registry["_ghost_program"] = dc.replace(
            REGISTRY["_paged_insert"], name="_ghost_program"
        )
        assert "stale-contract" in rules(check_static(registry=registry))

    def test_aliased_jit_decorator_recognized(self):
        # `from jax import jit; @partial(jit, ...)` must not bypass
        # the coverage gate (or the host lint's trace-time exemption).
        from jax_llama_tpu.analysis.common import jit_decorations
        import ast as _ast

        src = (
            "import functools\n"
            "from jax import jit\n"
            "@functools.partial(jit, donate_argnames=('pool',))\n"
            "def sneaky(pool, x):\n"
            "    return pool, x\n"
            "@jit\n"
            "def bare(x):\n"
            "    return x\n"
        )
        assert set(jit_decorations(_ast.parse(src))) == {
            "sneaky", "bare",
        }

    def test_cli_lowering_with_paths_is_usage_error(self, capsys):
        assert cli_main(
            ["--checker", "lowering", "tests/test_analysis.py"]
        ) == 2
        assert "does not take file paths" in capsys.readouterr().err

    def test_donation_decorator_mismatch_caught(self):
        import dataclasses as dc

        registry = dict(REGISTRY)
        registry["_paged_insert"] = dc.replace(
            REGISTRY["_paged_insert"], donated=("pool", "keys")
        )
        fs = check_static(registry=registry)
        assert rules(fs) == ["donation-mismatch"]


# -- trace-layer fixtures (tiny standalone programs; no model) --------------

def _fixture_contract(fn_name, module, donated, live, bpr, build,
                      forbid_pool_shapes=False):
    # fixture contracts default the pool-shape rule OFF (their args are
    # bare arrays; a contract with it on and no derivable shapes is
    # itself a finding — see test_vacuous_shape_set_is_a_finding)
    return ProgramContract(
        name=fn_name, module=module, donated=donated,
        max_live_outputs=live, max_fetch_bytes_per_row=bpr,
        build=build, forbid_pool_shapes=forbid_pool_shapes,
    )


@pytest.fixture(scope="module")
def fixture_programs():
    """A module-like namespace with tiny jitted programs: one donates
    its pool correctly, one forgot, one materializes a full-pool copy
    through a non-donated carry."""
    import functools
    import sys
    import types

    import jax
    import jax.numpy as jnp

    mod = types.ModuleType("_analysis_fixture_programs")

    @functools.partial(jax.jit, donate_argnames=("pool",))
    def good(pool, x):
        return pool.at[0, 0].add(x.sum()), x * 2

    @jax.jit
    def undonated(pool, x):  # forgot donate_argnames
        return pool.at[0, 0].add(x.sum()), x * 2

    @functools.partial(jax.jit, donate_argnames=("pool",))
    def leaky(pool, x):
        # the classic regression: a pool-sized broadcast materializes
        # a full-pool copy (and an extra live pool-sized output)
        ghost = jnp.broadcast_to(x[0], pool.shape) + pool
        return pool.at[0, 0].add(x.sum()), ghost

    mod.good, mod.undonated, mod.leaky = good, undonated, leaky
    sys.modules[mod.__name__] = mod
    yield mod
    del sys.modules[mod.__name__]


def _args_builder():
    import jax.numpy as jnp

    pool = jnp.zeros((2, 2, 4, 8, 4), jnp.float32)
    x = jnp.ones((2,), jnp.float32)
    return ("pool", "x"), (pool, x), {}


def _pooled_args_builder():
    # wrap the pool in a BlockPool-shaped carrier so pool_shapes()
    # derives the forbidden shapes (registered as a pytree so jit can
    # flatten it)
    import dataclasses as dc

    import jax
    import jax.numpy as jnp

    @dc.dataclass(frozen=True)
    class MiniPool:
        k: object
        v: object
        pos: object
        k_scale: object = None
        v_scale: object = None

        @property
        def block_size(self):
            return 8

    jax.tree_util.register_pytree_node(
        MiniPool,
        lambda p: ((p.k, p.v, p.pos), None),
        lambda aux, ch: MiniPool(k=ch[0], v=ch[1], pos=ch[2]),
    )
    k = jnp.zeros((2, 2, 4, 8, 4), jnp.float32)
    pool = MiniPool(k=k, v=k, pos=jnp.zeros((4, 8), jnp.int32))
    x = jnp.ones((2,), jnp.float32)
    return ("pool", "x"), (pool, x), {}


@pytest.mark.slow
class TestLoweringTraceFixtures:
    def test_good_program_clean(self, fixture_programs):
        c = _fixture_contract(
            "good", fixture_programs.__name__, ("pool",), 1, 8,
            _args_builder,
        )
        assert check_lowering(c) == []

    def test_forgotten_donation_caught(self, fixture_programs):
        c = _fixture_contract(
            "undonated", fixture_programs.__name__, ("pool",), 1, 8,
            _args_builder,
        )
        fs = check_lowering(c)
        assert "donation-not-applied" in rules(fs)

    def test_full_pool_copy_and_fetch_surface_caught(
        self, fixture_programs
    ):
        import functools
        import jax
        import jax.numpy as jnp
        import sys
        import types

        mod = types.ModuleType("_analysis_fixture_pool_copy")

        @functools.partial(jax.jit, donate_argnames=())
        def copying(pool, x):
            # non-donated carry: returning pool broadcast-shaped
            plane = jnp.broadcast_to(x.sum(), tuple(pool.k.shape))
            return plane + pool.k, x * 2

        mod.copying = copying
        sys.modules[mod.__name__] = mod
        try:
            c = _fixture_contract(
                "copying", mod.__name__, (), 2, 8,
                _pooled_args_builder, forbid_pool_shapes=True,
            )
            fs = check_lowering(c)
            assert "full-pool-copy" in rules(fs)
            # the pool-sized live output also blows the byte budget
            assert "fetch-bytes" in rules(fs)
        finally:
            del sys.modules[mod.__name__]

    def test_vacuous_shape_set_is_a_finding(self, fixture_programs):
        # forbid_pool_shapes with nothing derivable must NOT pass
        # silently (the silent-cap failure mode).
        c = _fixture_contract(
            "good", fixture_programs.__name__, ("pool",), 1, 8,
            _args_builder, forbid_pool_shapes=True,
        )
        assert "no-forbidden-shapes" in rules(check_lowering(c))

    def test_live_output_count_enforced(self, fixture_programs):
        c = _fixture_contract(
            "good", fixture_programs.__name__, ("pool",), 0, 8,
            _args_builder,
        )
        fs = check_lowering(c)
        assert "fetch-count" in rules(fs)


@pytest.mark.slow
class TestLoweringTracePackage:
    def test_all_contracts_trace_clean(self):
        # Lowers all ten registered programs at the tiny example
        # geometry: donation resolves, fetch surface within budget,
        # no pool-shaped copy-class equations.  ~30 s cold.
        clear_examples()
        assert check_traces() == []

    def test_mesh_contracts_trace_clean(self):
        # The serving-mesh pass: every contract with a mesh_build
        # lowers its SHARDED variant (donor attributes present for all
        # donated leaves) and runs it once proving sharding stability
        # (donated inputs leave with the sharding they entered with).
        from jax_llama_tpu.analysis.lowering import check_mesh_traces

        clear_examples()
        assert check_mesh_traces() == []


def test_mesh_contract_registry_consistent():
    """Cheap (tier-1) registry hygiene for the mesh pass: the two
    chunk programs carry mesh variants, every mesh_aliases key is a
    declared donated arg, and alias positions are unique."""
    from jax_llama_tpu.analysis.contracts import REGISTRY

    with_mesh = {
        n: c for n, c in REGISTRY.items() if c.mesh_build is not None
    }
    assert {"_paged_decode_chunk", "_fused_chunk"} <= set(with_mesh)
    for name, c in with_mesh.items():
        assert c.mesh_aliases, name
        assert set(c.mesh_aliases) <= set(c.donated), name
        positions = list(c.mesh_aliases.values())
        assert len(positions) == len(set(positions)), name


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

class TestCLI:
    def test_clean_package_exits_zero(self, capsys):
        assert cli_main(["--no-trace"]) == 0
        assert "clean" in capsys.readouterr().out

    def test_violating_file_exits_nonzero(self, tmp_path, capsys):
        bad = tmp_path / "bad.py"
        bad.write_text(
            "import numpy as np\nimport jax.numpy as jnp\n"
            "class B:\n"
            "    def f(self):\n"
            "        v = jnp.zeros((2,))\n"
            "        return np.asarray(v)\n"
        )
        assert cli_main([str(bad)]) == 1
        assert "host-fetch" in capsys.readouterr().out

    def test_lock_fixture_exits_nonzero(self, tmp_path, capsys):
        # the generic d_-twin rule needs no registry: an obs-module
        # fixture exercising the serving registry instead
        bad = tmp_path / "serving.py"
        bad.write_text(
            "class ContinuousBatcher:\n"
            "    def stats(self):\n"
            "        return len(self.queue)\n"
        )
        assert cli_main([str(bad)]) == 1
        assert "foreign-thread-access" in capsys.readouterr().out

    def test_json_output(self, tmp_path, capsys):
        import json as _json

        bad = tmp_path / "bad.py"
        bad.write_text(
            "import jax\nclass B:\n"
            "    def f(self, staged):\n"
            "        jax.block_until_ready(staged)\n"
        )
        assert cli_main(["--json", str(bad)]) == 1
        payload = _json.loads(capsys.readouterr().out)
        assert payload and payload[0]["rule"] == "host-fetch"

    @pytest.mark.slow
    def test_cli_contracts_hook_donation_and_pool_copy(
        self, fixture_programs, capsys
    ):
        """The acceptance-criteria fixture classes through the CLI:
        a forgotten donation and a full-pool copy each exit non-zero
        via ``--contracts`` (an external fixture REGISTRY)."""
        import sys as _sys
        import types

        reg = types.ModuleType("_analysis_fixture_registry")
        reg.REGISTRY = {
            "undonated": _fixture_contract(
                "undonated", fixture_programs.__name__, ("pool",), 1,
                8, _args_builder,
            ),
        }
        _sys.modules[reg.__name__] = reg
        try:
            rc = cli_main(
                ["--checker", "lowering", "--contracts", reg.__name__]
            )
            out = capsys.readouterr().out
            assert rc == 1 and "donation-not-applied" in out
        finally:
            del _sys.modules[reg.__name__]

    @pytest.mark.slow
    def test_module_entrypoint_subprocess(self):
        # the acceptance-criteria invocation, end to end
        proc = subprocess.run(
            [sys.executable, "-m", "jax_llama_tpu.analysis",
             "--no-trace"],
            capture_output=True, text=True, timeout=300,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr


# ---------------------------------------------------------------------------
# run_all: the tier-1 cleanliness gate
# ---------------------------------------------------------------------------

def test_package_clean_static_gate():
    """The PR gate: every checker's static layer is clean on the
    package — a stray sync / unguarded access / contract drift fails
    tier-1 here before any bench round notices."""
    findings = run_all(trace=False)
    assert findings == [], "\n".join(f.render() for f in findings)
