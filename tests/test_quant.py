"""int8 weight-only quantization: algebra, forward accuracy, decode, and
sharded execution.  (No reference counterpart — the reference serves full-
precision weights only; quantization is a TPU-serving addition.)"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from jax_llama_tpu import config as cfg_lib
from jax_llama_tpu.engine import GenerationConfig, generate
from jax_llama_tpu.models import forward, init_params
from jax_llama_tpu.ops.quant import (
    QuantizedTensor,
    is_quantized,
    quantize,
    quantize_params,
)
from jax_llama_tpu.parallel import make_mesh, shard_params

CFG = cfg_lib.tiny(max_seq_len=64)


def _params():
    return init_params(jax.random.PRNGKey(0), CFG)


def _dequantize_tree(qparams):
    return jax.tree.map(
        lambda x: x.dequantize() if isinstance(x, QuantizedTensor) else x,
        qparams,
        is_leaf=lambda x: isinstance(x, QuantizedTensor),
    )


def test_quantize_roundtrip_error_bounded():
    w = jax.random.normal(jax.random.PRNGKey(1), (64, 32))
    qt = quantize(w, contract_axes=(0,))
    assert qt.q.dtype == jnp.int8
    assert qt.scale.shape == (1, 32)
    # Per-channel symmetric int8: error <= scale/2 per element.
    err = np.abs(np.asarray(qt.dequantize() - w))
    bound = np.asarray(qt.scale) / 2 + 1e-7
    assert (err <= bound).all()


def test_quantized_tree_marks_projections_only():
    qp = quantize_params(_params())
    assert is_quantized(qp)
    assert isinstance(qp["layers"]["qkv"], QuantizedTensor)
    assert isinstance(qp["layers"]["gate_up"], QuantizedTensor)
    assert isinstance(qp["layers"]["down"], QuantizedTensor)
    assert isinstance(qp["lm_head"], QuantizedTensor)
    assert not isinstance(qp["layers"]["attn_norm"], QuantizedTensor)
    assert not isinstance(qp["embed"]["embedding"], QuantizedTensor)


def test_quantized_forward_matches_dequantized_forward():
    """(x @ Wq) * scale == x @ (Wq * scale): the quantized execution path
    must match running the dequantized weights densely, up to float
    reassociation — this isolates the kernel path from quantization error."""
    params = _params()
    qp = quantize_params(params)
    tokens = jnp.asarray(np.random.randint(0, CFG.vocab_size, (2, 12)))
    positions = jnp.tile(jnp.arange(12)[None, :], (2, 1))
    got, _ = forward(qp, tokens, positions, CFG)
    want, _ = forward(_dequantize_tree(qp), tokens, positions, CFG)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-4, rtol=2e-4
    )


def test_quantized_forward_close_to_full_precision():
    params = _params()
    qp = quantize_params(params)
    tokens = jnp.asarray(np.random.randint(0, CFG.vocab_size, (2, 12)))
    positions = jnp.tile(jnp.arange(12)[None, :], (2, 1))
    got, _ = forward(qp, tokens, positions, CFG)
    want, _ = forward(params, tokens, positions, CFG)
    # Quantization error at tiny width (dim=32: per-channel int8 noise is
    # proportionally huge and near-tie logits flip easily — the bound is a
    # sanity floor, not a quality claim; real-width quality rides the
    # bounded logit diff + the roundtrip error bound above).
    diff = np.abs(np.asarray(got) - np.asarray(want))
    assert diff.max() < 0.5, diff.max()
    agree = (np.argmax(got, -1) == np.argmax(want, -1)).mean()
    assert agree > 0.7, agree


def test_quantized_greedy_decode_runs():
    qp = quantize_params(_params())
    B, P = 2, 8
    tokens = jnp.asarray(np.random.randint(0, CFG.vocab_size, (B, P)))
    mask = jnp.ones((B, P), dtype=bool)
    gc = GenerationConfig(max_new_tokens=6, temperature=0.0, stop_tokens=())
    out = generate(
        qp, tokens, mask, jax.random.PRNGKey(0), config=CFG, gen_config=gc
    )
    assert out.shape == (B, P + 6)
    assert (np.asarray(out[:, :P]) == np.asarray(tokens)).all()


def test_quantized_checkpoint_roundtrip(tmp_path):
    from jax_llama_tpu.convert.checkpoint import load_checkpoint, save_checkpoint

    qp = quantize_params(_params())
    save_checkpoint(str(tmp_path / "ckpt"), qp, CFG)
    restored, rcfg = load_checkpoint(str(tmp_path / "ckpt"))
    assert rcfg == CFG
    assert isinstance(restored["layers"]["qkv"], QuantizedTensor)
    np.testing.assert_array_equal(
        np.asarray(restored["layers"]["qkv"].q),
        np.asarray(qp["layers"]["qkv"].q),
    )
    # Sharded restore of a quantized tree.
    G = CFG.n_heads // CFG.kv_heads
    mesh = make_mesh(tensor=2, data=4)
    sharded, _ = load_checkpoint(str(tmp_path / "ckpt"), mesh=mesh)
    assert {
        s.data.shape
        for s in sharded["layers"]["qkv"].q.addressable_shards
    } == {(CFG.n_layers, CFG.kv_heads // 2, G + 2, CFG.dim, CFG.head_dim)}


def test_quantized_sharded_forward_matches_single_device():
    params = _params()
    qp = quantize_params(params)
    tokens = jnp.asarray(np.random.randint(0, CFG.vocab_size, (2, 10)))
    positions = jnp.tile(jnp.arange(10)[None, :], (2, 1))
    want, _ = forward(qp, tokens, positions, CFG)

    mesh = make_mesh(tensor=2, data=4)
    sharded = shard_params(qp, mesh, CFG)
    qkv = sharded["layers"]["qkv"]
    G = CFG.n_heads // CFG.kv_heads
    # int8 payload sharded over KV heads; per-channel scale sharded
    # identically on the dims it has.
    assert {s.data.shape for s in qkv.q.addressable_shards} == {
        (CFG.n_layers, CFG.kv_heads // 2, G + 2, CFG.dim, CFG.head_dim)
    }
    assert {s.data.shape for s in qkv.scale.addressable_shards} == {
        (CFG.n_layers, CFG.kv_heads // 2, G + 2, 1, CFG.head_dim)
    }
    got, _ = forward(sharded, tokens, positions, CFG)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-4, rtol=2e-4
    )


# slow (r17 budget rebalance, ~8 s): the int8-KV-vs-fp32 numeric bound
# stays tier-1-pinned by test_int8_kv_flash_prefill_matches_xla (tracks
# the fp32 forward within int8-rounding error) and the int8 decode
# path's token behavior by test_int8_kv_auto_chunked_prefill_greedy_
# matches_xla plus test_serving.py::test_int8_kv_paged_batcher; the
# incremental-decode bound drill rides slow (unfiltered suite runs it).
@pytest.mark.slow
def test_int8_kv_cache_decode_close_to_fp():
    """Incremental decode over an int8 cache must track the fp32 full
    forward closely (per-slot-per-head scales keep error ~0.5%)."""
    from jax_llama_tpu import get_config, init_params
    from jax_llama_tpu.models import forward
    from jax_llama_tpu.models.llama import init_cache

    config = get_config(
        "tiny", vocab_size=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
        multiple_of=32, max_seq_len=32, kv_cache_dtype="int8",
    )
    params = init_params(jax.random.PRNGKey(0), config)
    B, T = 2, 16
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, 128, (B, T)), jnp.int32
    )
    pos = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
    want = np.asarray(forward(params, tokens, pos, config)[0])

    cache = init_cache(config, B, max_len=T)
    assert cache.k.dtype == jnp.int8 and cache.quantized
    lg, cache = forward(params, tokens[:, :8], pos[:, :8], config, cache=cache)
    outs = [np.asarray(lg)]
    for i in range(8, T):
        lg, cache = forward(
            params, tokens[:, i:i + 1], pos[:, i:i + 1], config, cache=cache
        )
        outs.append(np.asarray(lg))
    got = np.concatenate(outs, axis=1)
    rel = np.abs(got - want).max() / np.abs(want).max()
    assert rel < 0.02, rel


def test_int8_kv_cache_generate_end_to_end():
    from jax_llama_tpu import get_config, init_params
    from jax_llama_tpu.engine import GenerationConfig, generate

    config = get_config(
        "tiny", vocab_size=128, dim=32, n_layers=2, n_heads=4, n_kv_heads=2,
        multiple_of=32, max_seq_len=64, kv_cache_dtype="int8",
    )
    params = init_params(jax.random.PRNGKey(0), config)
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(1, 128, (2, 8)), jnp.int32
    )
    mask = jnp.ones((2, 8), bool)
    gc = GenerationConfig(max_new_tokens=12, temperature=0.0, stop_tokens=())
    out = generate(params, tokens, mask, jax.random.PRNGKey(0),
                   config=config, gen_config=gc)
    o = np.asarray(out)
    assert o.shape == (2, 20) and (o[:, 8:] < 128).all()


def test_int8_kv_flash_prefill_matches_xla():
    """int8 cache on the flash path (in-kernel scale folding) must land a
    bit-identical cache to the xla int8 path (same quantization math) and
    track the fp32 forward within int8-rounding error.

    Logits differ from the xla path at the quantization-noise level by
    design: flash quantizes on WRITE (the chunk's own tokens attend their
    int8 values), while sdpa_cached attends the current chunk at full
    precision and only reads the cache quantized."""
    from jax_llama_tpu import get_config, init_params
    from jax_llama_tpu.models import forward
    from jax_llama_tpu.models.llama import init_cache

    config = get_config(
        "tiny", vocab_size=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
        multiple_of=32, max_seq_len=32, kv_cache_dtype="int8",
    )
    params = init_params(jax.random.PRNGKey(0), config)
    B, T = 2, 16
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, 128, (B, T)), jnp.int32
    )
    pos = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))

    fp, _ = forward(params, tokens, pos, config.replace(kv_cache_dtype="auto"))
    cx = init_cache(config, B, max_len=32)
    want, cx = forward(params, tokens, pos, config, cache=cx)
    cf = init_cache(config, B, max_len=32)
    got, cf = forward(
        params, tokens, pos, config.replace(attn_impl="flash"), cache=cf
    )
    # Layer 0 sees identical inputs on both paths, so its payload + scales
    # are bit-equal.  (Later layers' inputs already differ at quantization-
    # noise level — layer 0's attention output feeds them — so only the
    # dequantized values stay close.)
    np.testing.assert_array_equal(np.asarray(cf.k[0]), np.asarray(cx.k[0]))
    np.testing.assert_array_equal(np.asarray(cf.v[0]), np.asarray(cx.v[0]))
    np.testing.assert_allclose(
        np.asarray(cf.k_scale[0]), np.asarray(cx.k_scale[0]), rtol=1e-6
    )
    deq_f = np.asarray(cf.k, np.float32) * np.asarray(cf.k_scale)[..., None]
    deq_x = np.asarray(cx.k, np.float32) * np.asarray(cx.k_scale)[..., None]
    assert np.abs(deq_f - deq_x).max() < 0.05
    np.testing.assert_array_equal(np.asarray(cf.pos), np.asarray(cx.pos))
    # Both int8 paths track the fp32 forward at quantization-noise level.
    fp = np.asarray(fp)
    for lg in (np.asarray(got), np.asarray(want)):
        rel = np.abs(lg - fp).max() / np.abs(fp).max()
        assert rel < 0.02, rel


def test_int8_kv_auto_chunked_prefill_greedy_matches_xla():
    """attn_impl='auto' + int8 cache prefills via the quantized flash
    kernel (T > 8) and decodes via the xla path; greedy output must be
    token-identical to forcing xla everywhere."""
    from jax_llama_tpu import get_config, init_params
    from jax_llama_tpu.engine import GenerationConfig, generate

    kw = dict(
        vocab_size=128, dim=32, n_layers=2, n_heads=4, n_kv_heads=2,
        multiple_of=32, max_seq_len=64, kv_cache_dtype="int8",
    )
    params = init_params(
        jax.random.PRNGKey(0), get_config("tiny", **kw)
    )
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(1, 128, (2, 16)), jnp.int32
    )
    mask = jnp.ones((2, 16), bool)
    gc = GenerationConfig(max_new_tokens=8, temperature=0.0, stop_tokens=())
    out_auto = generate(
        params, tokens, mask, jax.random.PRNGKey(0),
        config=get_config("tiny", attn_impl="auto", **kw), gen_config=gc,
    )
    out_xla = generate(
        params, tokens, mask, jax.random.PRNGKey(0),
        config=get_config("tiny", attn_impl="xla", **kw), gen_config=gc,
    )
    np.testing.assert_array_equal(np.asarray(out_auto), np.asarray(out_xla))


def test_bad_kv_cache_dtype_rejected():
    import pytest
    from jax_llama_tpu import get_config

    with pytest.raises(ValueError, match="kv_cache_dtype"):
        get_config("tiny", kv_cache_dtype="fp8").validate()
