"""int8 weight-only quantization: algebra, forward accuracy, decode, and
sharded execution.  (No reference counterpart — the reference serves full-
precision weights only; quantization is a TPU-serving addition.)"""

import numpy as np
import jax
import jax.numpy as jnp

from jax_llama_tpu import config as cfg_lib
from jax_llama_tpu.engine import GenerationConfig, generate
from jax_llama_tpu.models import forward, init_params
from jax_llama_tpu.ops.quant import (
    QuantizedTensor,
    is_quantized,
    quantize,
    quantize_params,
)
from jax_llama_tpu.parallel import make_mesh, shard_params

CFG = cfg_lib.tiny(max_seq_len=64)


def _params():
    return init_params(jax.random.PRNGKey(0), CFG)


def _dequantize_tree(qparams):
    return jax.tree.map(
        lambda x: x.dequantize() if isinstance(x, QuantizedTensor) else x,
        qparams,
        is_leaf=lambda x: isinstance(x, QuantizedTensor),
    )


def test_quantize_roundtrip_error_bounded():
    w = jax.random.normal(jax.random.PRNGKey(1), (64, 32))
    qt = quantize(w, contract_axes=(0,))
    assert qt.q.dtype == jnp.int8
    assert qt.scale.shape == (1, 32)
    # Per-channel symmetric int8: error <= scale/2 per element.
    err = np.abs(np.asarray(qt.dequantize() - w))
    bound = np.asarray(qt.scale) / 2 + 1e-7
    assert (err <= bound).all()


def test_quantized_tree_marks_projections_only():
    qp = quantize_params(_params())
    assert is_quantized(qp)
    assert isinstance(qp["layers"]["q"], QuantizedTensor)
    assert isinstance(qp["layers"]["down"], QuantizedTensor)
    assert isinstance(qp["lm_head"], QuantizedTensor)
    assert not isinstance(qp["layers"]["attn_norm"], QuantizedTensor)
    assert not isinstance(qp["embed"]["embedding"], QuantizedTensor)


def test_quantized_forward_matches_dequantized_forward():
    """(x @ Wq) * scale == x @ (Wq * scale): the quantized execution path
    must match running the dequantized weights densely, up to float
    reassociation — this isolates the kernel path from quantization error."""
    params = _params()
    qp = quantize_params(params)
    tokens = jnp.asarray(np.random.randint(0, CFG.vocab_size, (2, 12)))
    positions = jnp.tile(jnp.arange(12)[None, :], (2, 1))
    got, _ = forward(qp, tokens, positions, CFG)
    want, _ = forward(_dequantize_tree(qp), tokens, positions, CFG)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-4, rtol=2e-4
    )


def test_quantized_forward_close_to_full_precision():
    params = _params()
    qp = quantize_params(params)
    tokens = jnp.asarray(np.random.randint(0, CFG.vocab_size, (2, 12)))
    positions = jnp.tile(jnp.arange(12)[None, :], (2, 1))
    got, _ = forward(qp, tokens, positions, CFG)
    want, _ = forward(params, tokens, positions, CFG)
    # Quantization error at tiny width: logits stay close and argmax agrees
    # nearly everywhere.
    diff = np.abs(np.asarray(got) - np.asarray(want))
    assert diff.max() < 0.5, diff.max()
    agree = (np.argmax(got, -1) == np.argmax(want, -1)).mean()
    assert agree > 0.9, agree


def test_quantized_greedy_decode_runs():
    qp = quantize_params(_params())
    B, P = 2, 8
    tokens = jnp.asarray(np.random.randint(0, CFG.vocab_size, (B, P)))
    mask = jnp.ones((B, P), dtype=bool)
    gc = GenerationConfig(max_new_tokens=6, temperature=0.0, stop_tokens=())
    out = generate(
        qp, tokens, mask, jax.random.PRNGKey(0), config=CFG, gen_config=gc
    )
    assert out.shape == (B, P + 6)
    assert (np.asarray(out[:, :P]) == np.asarray(tokens)).all()


def test_quantized_checkpoint_roundtrip(tmp_path):
    from jax_llama_tpu.convert.checkpoint import load_checkpoint, save_checkpoint

    qp = quantize_params(_params())
    save_checkpoint(str(tmp_path / "ckpt"), qp, CFG)
    restored, rcfg = load_checkpoint(str(tmp_path / "ckpt"))
    assert rcfg == CFG
    assert isinstance(restored["layers"]["q"], QuantizedTensor)
    np.testing.assert_array_equal(
        np.asarray(restored["layers"]["q"].q), np.asarray(qp["layers"]["q"].q)
    )
    # Sharded restore of a quantized tree.
    mesh = make_mesh(tensor=2, data=4)
    sharded, _ = load_checkpoint(str(tmp_path / "ckpt"), mesh=mesh)
    assert {s.data.shape for s in sharded["layers"]["q"].q.addressable_shards} == {
        (CFG.n_layers, CFG.dim, CFG.n_heads // 2, CFG.head_dim)
    }


def test_quantized_sharded_forward_matches_single_device():
    params = _params()
    qp = quantize_params(params)
    tokens = jnp.asarray(np.random.randint(0, CFG.vocab_size, (2, 10)))
    positions = jnp.tile(jnp.arange(10)[None, :], (2, 1))
    want, _ = forward(qp, tokens, positions, CFG)

    mesh = make_mesh(tensor=2, data=4)
    sharded = shard_params(qp, mesh, CFG)
    q = sharded["layers"]["q"]
    # int8 payload sharded over heads; per-channel scale sharded identically
    # on the dims it has.
    assert {s.data.shape for s in q.q.addressable_shards} == {
        (CFG.n_layers, CFG.dim, CFG.n_heads // 2, CFG.head_dim)
    }
    assert {s.data.shape for s in q.scale.addressable_shards} == {
        (CFG.n_layers, 1, CFG.n_heads // 2, CFG.head_dim)
    }
    got, _ = forward(sharded, tokens, positions, CFG)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-4, rtol=2e-4
    )
